"""Block-sparse attention — SparsityConfig layouts over the flash kernel.

Analog of the reference's sparse-attention stack
(``deepspeed/ops/sparse_attention/``: Triton block-sparse matmul/softmax +
``sparsity_config.py`` layout family + ``SparseSelfAttention``). TPU-native
shape: the layouts are the SAME contract — a ``[Hl, nb, nb]`` 0/1 block mask
— but instead of dedicated block-sparse matmul kernels, the mask rides the
flash kernel's static tile-skip (``ops/flash_attention.py block_layout``):
dead blocks are skipped on the MXU while the streaming softmax handles the
live ones, so sparsity translates directly into compute savings.

Config surface mirrors the reference classes (``sparsity_config.py:15-700``):
Dense, LocalSlidingWindow, Fixed, BigBird, BSLongformer.
"""
from typing import List, Optional

import numpy as np

import jax.numpy as jnp

__all__ = ["SparsityConfig", "DenseSparsityConfig",
           "LocalSlidingWindowSparsityConfig", "FixedSparsityConfig",
           "BigBirdSparsityConfig", "BSLongformerSparsityConfig",
           "sparse_attention"]


class SparsityConfig:
    """Base: ``make_layout(seq_len)`` → int32 ``[Hl, nb, nb]`` block mask
    (reference ``SparsityConfig.setup_layout``)."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    @property
    def layout_heads(self) -> int:
        return self.num_heads if self.different_layout_per_head else 1

    def _empty(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block:
            raise ValueError(f"seq_len {seq_len} not a multiple of "
                             f"block {self.block}")
        nb = seq_len // self.block
        return np.zeros((self.layout_heads, nb, nb), np.int32)

    def _finish(self, layout: np.ndarray, causal: bool) -> np.ndarray:
        if causal:
            layout = layout * np.tril(
                np.ones(layout.shape[1:], np.int32))[None]
        return layout

    def make_layout(self, seq_len: int, causal: bool = True) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks live (reference ``DenseSparsityConfig`` — the debugging /
    parity baseline)."""

    def make_layout(self, seq_len: int, causal: bool = True) -> np.ndarray:
        layout = self._empty(seq_len)
        layout[:] = 1
        return self._finish(layout, causal)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Banded local attention (reference
    ``LocalSlidingWindowSparsityConfig``)."""

    def __init__(self, num_heads: int, block: int = 128,
                 num_sliding_window_blocks: int = 3,
                 different_layout_per_head: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks

    def make_layout(self, seq_len: int, causal: bool = True) -> np.ndarray:
        layout = self._empty(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks
        for i in range(nb):
            lo = max(0, i - w // 2) if not causal else max(0, i - w + 1)
            hi = min(nb, i + w // 2 + 1) if not causal else i + 1
            layout[:, i, lo:hi] = 1
        return self._finish(layout, causal)


class FixedSparsityConfig(SparsityConfig):
    """Local windows + periodic global columns (reference
    ``FixedSparsityConfig``, the Sparse-Transformer 'fixed' pattern): rows
    attend their own local window of ``num_local_blocks``, plus the last
    ``num_global_blocks`` block-columns of every window (the 'summary'
    columns). ``num_different_global_patterns`` rotates which columns act as
    global across head groups (requires per-head layouts)."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("num_different_global_patterns > 1 requires "
                             "different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // max(
                num_global_blocks, 1):
            raise ValueError("more global patterns than fit in a window")
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int, causal: bool = True) -> np.ndarray:
        layout = self._empty(seq_len)
        nb = layout.shape[1]
        nl, ng = self.num_local_blocks, self.num_global_blocks
        for h in range(layout.shape[0]):
            pat = (h * self.num_different_global_patterns //
                   max(layout.shape[0], 1)) if \
                self.num_different_global_patterns > 1 else 0
            for i in range(nb):
                w0 = (i // nl) * nl
                layout[h, i, w0:min(w0 + nl, nb)] = 1  # local window
            for w0 in range(0, nb, nl):
                # global columns: the pattern-selected ng columns at this
                # window's tail (pattern p shifts them back by p·ng)
                c_hi = min(w0 + nl, nb) - pat * ng
                c_lo = max(c_hi - ng, 0)
                layout[h, :, c_lo:c_hi] = 1
                if self.horizontal_global_attention:
                    layout[h, c_lo:c_hi, :] = 1
        return self._finish(layout, causal)


class BigBirdSparsityConfig(SparsityConfig):
    """Sliding window + global first/last blocks + random blocks (reference
    ``BigBirdSparsityConfig``)."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.seed = seed

    def make_layout(self, seq_len: int, causal: bool = True) -> np.ndarray:
        layout = self._empty(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks
        g = min(self.num_global_blocks, nb)
        rng = np.random.RandomState(self.seed)
        for h in range(layout.shape[0]):
            for i in range(nb):
                lo, hi = max(0, i - w // 2), min(nb, i + w // 2 + 1)
                layout[h, i, lo:hi] = 1                   # sliding window
                cand = np.arange(0, i + 1 if causal else nb)
                if len(cand):
                    pick = rng.choice(cand, size=min(self.num_random_blocks,
                                                     len(cand)),
                                      replace=False)
                    layout[h, i, pick] = 1                # random blocks
            layout[h, :, :g] = 1                          # global columns
            layout[h, :g, :] = 1                          # global rows
            if not causal:
                layout[h, :, nb - g:] = 1
                layout[h, nb - g:, :] = 1
        return self._finish(layout, causal)


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + designated global block indices (reference
    ``BSLongformerSparsityConfig``)."""

    def __init__(self, num_heads: int, block: int = 128,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        if global_block_end_indices is not None and \
                len(global_block_end_indices) != len(self.global_block_indices):
            raise ValueError(
                f"global_block_end_indices ({len(global_block_end_indices)}) "
                f"must match global_block_indices "
                f"({len(self.global_block_indices)})")
        self.global_block_end_indices = global_block_end_indices

    def make_layout(self, seq_len: int, causal: bool = True) -> np.ndarray:
        layout = self._empty(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks
        for i in range(nb):
            lo, hi = max(0, i - w // 2), min(nb, i + w // 2 + 1)
            layout[:, i, lo:hi] = 1
        ends = self.global_block_end_indices
        for n, start in enumerate(self.global_block_indices):
            stop = ends[n] if ends else start + 1
            layout[:, :, start:stop] = 1    # everyone sees global blocks
            layout[:, start:stop, :] = 1    # global blocks see everyone
        return self._finish(layout, causal)


def sparse_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     config: SparsityConfig, causal: bool = True,
                     interpret: Optional[bool] = None) -> jnp.ndarray:
    """Block-sparse attention over ``q/k/v [B, S, H, D]`` (the
    ``SparseSelfAttention.forward`` analog): builds the config's layout for
    the padded block grid and runs the flash kernel with dead blocks
    skipped."""
    from .flash_attention import _round_up, flash_attention

    b, s, h, d = q.shape
    if h != config.num_heads:
        raise ValueError(f"config.num_heads={config.num_heads} != {h}")
    blk = config.block
    if blk > _round_up(s, 128):
        # the kernel clamps its blocks to the 128-padded sequence; a layout
        # block coarser than that cannot map onto the launch grid
        raise ValueError(f"config.block={blk} exceeds the padded sequence "
                         f"({_round_up(s, 128)}) — use a smaller block")
    s_pad = _round_up(s, blk)
    layout = config.make_layout(s_pad, causal=causal)

    return flash_attention(q, k, v, causal=causal,
                           block_layout=jnp.asarray(layout),
                           block_q=blk, block_k=blk, interpret=interpret)
