"""Paged (blocked-KV) decode attention — Pallas TPU kernel.

The performance core of the v2 ragged engine: the reference's
``blocked_flash`` CUDA kernel family (``inference/v2/kernels/ragged_ops/
blocked_flash``, atom-based flash attention over paged KV). One query token
per sequence slot attends over its sequence's KV blocks, resolved through a
block table.

Kernel shape (TPU-first, not a CUDA translation):

* grid = one program per sequence slot; the block table row and sequence
  length ride in as SCALAR-PREFETCH args so KV block DMAs can be issued
  immediately (``PrefetchScalarGridSpec`` — the Pallas idiom for indirect
  addressing).
* K/V stay in HBM; each loop iteration DMAs ONE KV block into VMEM scratch
  and folds it into an online-softmax accumulator (flash recurrence), so VMEM
  holds O(block_size · D) regardless of context length, and compute overlaps
  the next block's fetch via the DMA queue.
* GQA: queries reshape to [KVH, G, D] and each kv head batch-matmuls its
  group — grouped heads share the streamed KV block, the reason GQA decode is
  bandwidth-cheap on TPU.

An exact jnp reference (:func:`paged_decode_attention_reference`) serves
off-TPU fallback and the kernel-vs-reference parity tests (the pattern the
reference repo uses for every CUDA kernel, SURVEY.md §4).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


# --------------------------------------------------------------------- kernel
def paged_decode_attention_pallas(q, k_cache, v_cache, block_tables, seq_lens,
                                  *, block_size: int,
                                  alibi=None, window=None,
                                  interpret: bool = False):
    """q: [S, H, D]; k/v_cache: [num_slots, KVH, D]; block_tables: [S, Bps];
    seq_lens: [S] valid KV tokens per slot. ``alibi``: per-head slopes [H];
    ``window``: sliding-window bound. Returns [S, H, D].

    Decode IS the single-row case of the generalized ragged kernel below
    (the paper's prefill/decode unification): each slot becomes a BQ=1 atom
    whose query position is its newest cached token — one kernel family to
    maintain, one DMA/online-softmax pipeline to tune."""
    seq_lens = jnp.asarray(seq_lens, jnp.int32)
    pos0 = jnp.maximum(seq_lens - 1, 0)
    qlen = jnp.where(seq_lens > 0, 1, 0).astype(jnp.int32)
    out = ragged_prefill_attention_pallas(
        q[:, None], k_cache, v_cache, block_tables, pos0, qlen,
        block_size=block_size, alibi=alibi, window=window,
        interpret=interpret)
    return out[:, 0]


# ------------------------------------------------------------------ reference
def paged_decode_attention_reference(q, k_cache, v_cache, block_tables,
                                     seq_lens, *, block_size: int,
                                     alibi=None, window=None):
    """Exact jnp oracle — decode as the BQ=1 case of the ragged reference
    (one oracle to maintain, mirroring the Pallas unification)."""
    seq_lens = jnp.asarray(seq_lens, jnp.int32)
    out = ragged_prefill_attention_reference(
        q[:, None], k_cache, v_cache, block_tables,
        jnp.maximum(seq_lens - 1, 0), (seq_lens > 0).astype(jnp.int32),
        block_size=block_size, alibi=alibi, window=window)
    return out[:, 0]


def paged_decode_attention(q, k_cache, v_cache, block_tables, seq_lens, *,
                           block_size: int, impl: str = "auto",
                           alibi=None, window=None):
    """Dispatch (the op-binding seam, like ``models/layers.attention``)."""
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        return paged_decode_attention_pallas(
            q, k_cache, v_cache, block_tables, seq_lens,
            block_size=block_size, alibi=alibi, window=window)
    if impl == "pallas_interpret":
        return paged_decode_attention_pallas(
            q, k_cache, v_cache, block_tables, seq_lens,
            block_size=block_size, alibi=alibi, window=window,
            interpret=True)
    return paged_decode_attention_reference(
        q, k_cache, v_cache, block_tables, seq_lens, block_size=block_size,
        alibi=alibi, window=window)


# ===================================================================== prefill
def _prefill_kernel(block_tables_ref, pos0_ref, qlen_ref,  # scalar prefetch
                    q_ref, k_hbm, v_hbm, ab_ref,           # tensors
                    out_ref,                               # output
                    k_vmem, v_vmem, sem,                   # scratch
                    *, block_size: int, max_blocks: int, group: int,
                    use_alibi: bool, window):
    """One program per ATOM: a ≤block_q-token slice of ONE sequence's packed
    prefill chunk. The atom's q tile attends over the sequence's paged KV
    (resolved through its block-table row) with per-row causality — the
    'ragged paged attention' unification of prefill and decode (paper
    arXiv:2604.15464; reference atom_builder + blocked_flash,
    ``inference/v2/kernels/ragged_ops/``). KV blocks stream through the same
    double-buffered DMA pipeline as the decode kernel, so per-sequence KV is
    NEVER materialized in HBM (the O(S·max_ctx) gather this replaces)."""
    a = pl.program_id(0)
    pos0 = pos0_ref[a]
    qlen = qlen_ref[a]
    # kv tokens this atom may see, clamped to the block table's capacity so
    # the prefetch below can never index past the table or start a DMA that
    # is never awaited
    kv_hi = jnp.minimum(pos0 + qlen, max_blocks * block_size)
    # sliding window: blocks entirely below row 0's window are masked for
    # EVERY row — skip their DMA and matmuls instead of NEG_INF-ing them
    if window is not None:
        lo_blk = jnp.maximum(pos0 + 1 - window, 0) // block_size
    else:
        lo_blk = jnp.int32(0)
    q = q_ref[0].astype(jnp.float32)          # [BQ, H, D]
    bq, h, d = q.shape
    kvh = k_vmem.shape[2]
    g = group
    # [KVH, BQ·G, D]: kv head-major so each kv head batch-matmuls its group
    q_g = jnp.transpose(q.reshape(bq, kvh, g, d), (1, 0, 2, 3)) \
        .reshape(kvh, bq * g, d)
    # q row of each [BQ·G] lane (its position is pos0 + row)
    row = jax.lax.broadcasted_iota(jnp.int32, (kvh, bq * g, block_size),
                                   1) // g

    def copies(j, slot):
        blk = block_tables_ref[a, j]
        cp_k = pltpu.make_async_copy(
            k_hbm.at[pl.ds(blk * block_size, block_size)], k_vmem.at[slot],
            sem.at[slot, 0])
        cp_v = pltpu.make_async_copy(
            v_hbm.at[pl.ds(blk * block_size, block_size)], v_vmem.at[slot],
            sem.at[slot, 1])
        return cp_k, cp_v

    # guard on lo_blk (not just kv_hi > 0): with a sliding window and pos0
    # beyond the table's capacity, lo_blk can reach max_blocks — the loop
    # below would run zero iterations, so an unguarded warm-up would index
    # the table out of bounds and start a DMA that is never awaited
    @pl.when(lo_blk * block_size < kv_hi)
    def _():
        cp_k, cp_v = copies(lo_blk, jax.lax.rem(lo_blk, 2))
        cp_k.start()
        cp_v.start()

    def body(j, carry):
        m, l, acc = carry
        active = j * block_size < kv_hi
        cur = jax.lax.rem(j, 2)

        @pl.when(jnp.logical_and((j + 1) * block_size < kv_hi,
                                 j + 1 < max_blocks))
        def _():
            cp_k, cp_v = copies(j + 1, jax.lax.rem(j + 1, 2))
            cp_k.start()
            cp_v.start()

        @pl.when(active)
        def _():
            cp_k, cp_v = copies(j, cur)
            cp_k.wait()
            cp_v.wait()

        k = k_vmem[cur].astype(jnp.float32)    # [bs, KVH, D]
        v = v_vmem[cur].astype(jnp.float32)
        k_t = jnp.transpose(k, (1, 0, 2))      # [KVH, bs, D]
        v_t = jnp.transpose(v, (1, 0, 2))
        scores = jax.lax.dot_general(           # [KVH, BQ·G, bs]
            q_g, k_t, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) / np.sqrt(d)
        pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (kvh, bq * g, block_size), 2)
        if use_alibi:
            scores = scores + ab_ref[...].astype(jnp.float32) * (
                pos - (pos0 + row)).astype(jnp.float32)
        valid = jnp.logical_and(pos <= pos0 + row,   # per-row causality
                                jnp.logical_and(row < qlen, active))
        if window is not None:
            valid = jnp.logical_and(valid, (pos0 + row) - pos < window)
        scores = jnp.where(valid, scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.where(valid, jnp.exp(scores - m_new), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_t, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        acc_new = acc * alpha + pv
        return (jnp.where(active, m_new, m), jnp.where(active, l_new, l),
                jnp.where(active, acc_new, acc))

    m0 = jnp.full((kvh, bq * g, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((kvh, bq * g, 1), jnp.float32)
    acc0 = jnp.zeros((kvh, bq * g, d), jnp.float32)
    # DYNAMIC trip count: dead atoms (kv_hi = 0) run zero iterations — with
    # A_max sized for the worst case, most grid programs of a typical batch
    # are dead and must not burn max_blocks MXU loops each
    n_blk = (kv_hi + block_size - 1) // block_size
    m, l, acc = jax.lax.fori_loop(lo_blk, n_blk, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)
    out = jnp.transpose(out.reshape(kvh, bq, g, d), (1, 0, 2, 3))
    out_ref[0] = out.reshape(bq, h, d).astype(out_ref.dtype)


def ragged_prefill_attention_pallas(q_atoms, k_cache, v_cache, atom_tables,
                                    atom_pos0, atom_qlen, *,
                                    block_size: int, alibi=None, window=None,
                                    interpret: bool = False):
    """q_atoms: [A, BQ, H, D] (one sequence per atom row block);
    k/v_cache: [num_slots, KVH, D]; atom_tables: [A, Bps] (the owning
    sequence's block-table row per atom); atom_pos0/atom_qlen: [A].
    ``alibi``: per-head slopes [H]; ``window``: sliding-window bound.
    Returns [A, BQ, H, D]."""
    a, bq, h, d = q_atoms.shape
    kvh = k_cache.shape[1]
    g = h // kvh
    max_blocks = atom_tables.shape[1]
    if alibi is not None:
        # per-lane slope layout matches the kernel's [KVH, BQ·G] score rows:
        # lane (r·G + gi) of kv head kh carries q head kh·G + gi
        ab = jnp.tile(jnp.asarray(alibi, jnp.float32).reshape(kvh, 1, g),
                      (1, bq, 1)).reshape(kvh, bq * g, 1)
    else:
        ab = jnp.zeros((kvh, bq * g, 1), jnp.float32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(a,),
        in_specs=[
            pl.BlockSpec((1, bq, h, d), lambda i, *_: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),   # K stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # V stays in HBM
            pl.BlockSpec((kvh, bq * g, 1), lambda i, *_: (0, 0, 0),
                         memory_space=pltpu.VMEM),  # slopes per lane
        ],
        out_specs=pl.BlockSpec((1, bq, h, d), lambda i, *_: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, block_size, kvh, d), k_cache.dtype),
            pltpu.VMEM((2, block_size, kvh, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(_prefill_kernel, block_size=block_size,
                               max_blocks=max_blocks, group=g,
                               use_alibi=alibi is not None,
                               window=None if window is None else int(window))
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((a, bq, h, d), q_atoms.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(jnp.asarray(atom_tables, jnp.int32), jnp.asarray(atom_pos0, jnp.int32),
      jnp.asarray(atom_qlen, jnp.int32), q_atoms, k_cache, v_cache, ab)


def ragged_prefill_attention_reference(q_atoms, k_cache, v_cache, atom_tables,
                                       atom_pos0, atom_qlen, *,
                                       block_size: int, alibi=None,
                                       window=None):
    """Exact jnp oracle for the prefill kernel (parity tests + off-TPU)."""
    a, bq, h, d = q_atoms.shape
    kvh = k_cache.shape[1]
    bps = atom_tables.shape[1]
    max_ctx = bps * block_size
    j = jnp.arange(max_ctx)
    slot = atom_tables[:, j // block_size] * block_size + j % block_size
    k_seq = k_cache[slot].astype(jnp.float32)   # [A, C, KVH, D]
    v_seq = v_cache[slot].astype(jnp.float32)
    if kvh != h:
        rep = h // kvh
        k_seq = jnp.repeat(k_seq, rep, axis=2)
        v_seq = jnp.repeat(v_seq, rep, axis=2)
    logits = jnp.einsum("aqhd,achd->ahqc", q_atoms.astype(jnp.float32),
                        k_seq) / np.sqrt(d)
    r = jnp.arange(bq)[None, None, :, None]
    q_pos = atom_pos0[:, None, None, None] + r
    if alibi is not None:
        logits = logits + jnp.asarray(alibi, jnp.float32)[None, :, None,
                                                          None] * (
            j[None, None, None, :] - q_pos).astype(jnp.float32)
    mask = jnp.logical_and(
        j[None, None, None, :] <= q_pos,
        r < atom_qlen[:, None, None, None])
    if window is not None:
        mask = jnp.logical_and(mask, q_pos - j[None, None, None, :] < window)
    logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    p = jnp.where(mask.any(-1, keepdims=True), p, 0.0)  # dead rows → 0
    out = jnp.einsum("ahqc,achd->aqhd", p, v_seq)
    return out.astype(q_atoms.dtype)


def ragged_prefill_attention(q_atoms, k_cache, v_cache, atom_tables,
                             atom_pos0, atom_qlen, *, block_size: int,
                             impl: str = "auto", alibi=None, window=None):
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "pallas":
        return ragged_prefill_attention_pallas(
            q_atoms, k_cache, v_cache, atom_tables, atom_pos0, atom_qlen,
            block_size=block_size, alibi=alibi, window=window)
    if impl == "pallas_interpret":
        return ragged_prefill_attention_pallas(
            q_atoms, k_cache, v_cache, atom_tables, atom_pos0, atom_qlen,
            block_size=block_size, alibi=alibi, window=window,
            interpret=True)
    return ragged_prefill_attention_reference(
        q_atoms, k_cache, v_cache, atom_tables, atom_pos0, atom_qlen,
        block_size=block_size, alibi=alibi, window=window)
