"""TPU kernel library (Pallas) + native host ops — the analog of the
reference's ``csrc/`` + ``deepspeed/ops`` native-op layer (SURVEY.md §2.5).

Device compute ops (``flash_attention``) dispatch from the model/engine level
and fall back to XLA-fused jnp references off-TPU. Host systems ops (async IO)
are C++ behind a C ABI, JIT-built and loaded through :mod:`.op_builder` — the
reference's ``OpBuilder.load()`` pattern without torch/pybind11.
"""
from .op_builder import ALL_OPS, AsyncIOBuilder, OpBuilder, get_op_builder  # noqa: F401
from .evoformer_attn import DS4Sci_EvoformerAttention  # noqa: F401
