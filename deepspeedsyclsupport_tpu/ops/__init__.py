"""TPU kernel library (Pallas) — the analog of the reference's ``csrc/`` +
``deepspeed/ops`` native-op layer (SURVEY.md §2.5). Ops dispatch from the model/
engine level and fall back to XLA-fused jnp references off-TPU."""
