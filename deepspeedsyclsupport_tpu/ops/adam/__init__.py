"""Import-path compat: ``deepspeed.ops.adam`` (reference FusedAdam /
DeepSpeedCPUAdam classes over CUDA/AVX kernels). Here both resolve to the
XLA-fused optax chain the engine builds — construct and pass as the
``optimizer`` argument to ``initialize`` or use standalone as an optax
GradientTransformation factory."""
from typing import Iterable, Optional, Tuple


def _build(t: str, lr, betas, eps, weight_decay, adam_w_mode=True):
    from ...runtime.optimizers import build_optimizer

    params = {"lr": lr, "betas": list(betas), "eps": eps,
              "weight_decay": weight_decay, "adam_w_mode": adam_w_mode}
    return build_optimizer(t, params)


def FusedAdam(params: Optional[Iterable] = None, lr: float = 1e-3,
              betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
              weight_decay: float = 0.0, adam_w_mode: bool = True,
              **_ignored):
    """Reference ``FusedAdam`` (multi-tensor CUDA Adam) → the fused optax
    transform (``params`` is unused: JAX optimizers bind at ``init``)."""
    return _build("adam", lr, betas, eps, weight_decay, adam_w_mode)


def DeepSpeedCPUAdam(model_params: Optional[Iterable] = None,
                     lr: float = 1e-3,
                     betas: Tuple[float, float] = (0.9, 0.999),
                     eps: float = 1e-8, weight_decay: float = 0.0,
                     adamw_mode: bool = True, **_ignored):
    """Reference ``DeepSpeedCPUAdam`` (AVX host Adam for ZeRO-Offload) —
    same math; host placement comes from the engine's offload config, not
    the optimizer class."""
    return _build("cpu_adam", lr, betas, eps, weight_decay, adamw_mode)
