"""EvoformerAttention — DS4Science fused MSA attention, TPU-native.

API-compatible analog of the reference's ``DS4Sci_EvoformerAttention``
(``deepspeed/ops/deepspeed4science/evoformer_attn.py``, backed by ~14.9k LoC
of CUTLASS fMHA in ``csrc/deepspeed4science/evoformer_attn/``): attention
over AlphaFold-style MSA tensors ``[B, N, S, H, D]`` with up to two additive
logit biases —

* ``bias1 [B, N, 1, 1, S]``: per-key residue-mask bias (0 / −inf rows;
  non-differentiable, as in the reference kernels' mask role),
* ``bias2 [B, 1, H, S, S]``: the pair-representation bias, shared across the
  N MSA rows and differentiable (its gradient sums over N).

Instead of a dedicated CUTLASS kernel family, the (B, N) leading dims
flatten into the flash kernel's batch axis and the biases ride the kernel's
broadcast-aware bias inputs (``ops/flash_attention.py``): ``bias2`` streams
tile-by-tile with its batch index mapped ``b → b // N`` (never materialized
per-row), and ``bias1`` collapses to the per-key row bias. Gradients flow
through the kernel's fused backward (dbias2 reduced over the broadcast N).
"""
from typing import List, Optional

import jax.numpy as jnp

from .flash_attention import flash_attention

__all__ = ["DS4Sci_EvoformerAttention", "evoformer_attention"]


def evoformer_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        biases: Optional[List[Optional[jnp.ndarray]]] = None,
                        block_q: int = 128, block_k: int = 128,
                        interpret: Optional[bool] = None) -> jnp.ndarray:
    """q/k/v: ``[B, N, S, H, D]``; ``biases``: up to
    ``[mask_bias [B,N,1,1,Skv], pair_bias [B,1,H,Sq,Skv]]`` (either may be
    None). Returns ``[B, N, Sq, H, D]``, non-causal.
    """
    if q.ndim != 5:
        raise ValueError(f"expected [B, N, S, H, D], got {q.shape}")
    b, n, sq, h, d = q.shape
    skv = k.shape[2]
    mask_bias = pair_bias = None
    for bias in (biases or []):
        if bias is None:
            continue
        if bias.ndim != 5:
            raise ValueError(f"bias rank must be 5, got {bias.shape}")
        if bias.shape[2] == 1 and bias.shape[3] == 1:
            mask_bias = bias      # [B, N, 1, 1, Skv]
        elif bias.shape[1] == 1:
            pair_bias = bias      # [B, 1, H, Sq, Skv]
        else:
            raise ValueError(f"unrecognized evoformer bias shape "
                             f"{bias.shape} (want [B,N,1,1,S] mask or "
                             f"[B,1,H,S,S] pair)")

    qf = q.reshape(b * n, sq, h, d)
    kf = k.reshape(b * n, skv, h, d)
    vf = v.reshape(b * n, skv, h, d)
    k_bias = (mask_bias.reshape(b * n, skv)
              if mask_bias is not None else None)
    bias = pair_bias[:, 0] if pair_bias is not None else None  # [B,H,Sq,Skv]
    out = flash_attention(qf, kf, vf, causal=False, bias=bias,
                          k_bias=k_bias, block_q=block_q, block_k=block_k,
                          interpret=interpret)
    return out.reshape(b, n, sq, h, d)


# reference-exact alias (deepspeed/ops/deepspeed4science/evoformer_attn.py)
DS4Sci_EvoformerAttention = evoformer_attention
