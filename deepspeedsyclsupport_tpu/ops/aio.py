"""Python surface of the async-IO op.

Analog of the reference's ``deepspeed.ops.aio`` / ``AsyncIOBuilder().load()``
handle object (``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp``): submit
pread/pwrite against numpy buffers, overlap with compute, wait/poll for
completion. Feeds ``runtime/swap_tensor.py`` (NVMe offload).
"""
import ctypes
from typing import Dict

import numpy as np

from .op_builder import AsyncIOBuilder


class AsyncIOHandle:
    """Thread-pooled async file IO (reference ``aio_handle``)."""

    def __init__(self, n_threads: int = 4):
        lib = AsyncIOBuilder().load()
        lib.dstpu_aio_new.restype = ctypes.c_void_p
        lib.dstpu_aio_new.argtypes = [ctypes.c_int]
        lib.dstpu_aio_free.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_pread.restype = ctypes.c_int64
        lib.dstpu_aio_pread.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_int64]
        lib.dstpu_aio_pwrite.restype = ctypes.c_int64
        lib.dstpu_aio_pwrite.argtypes = lib.dstpu_aio_pread.argtypes + [
            ctypes.c_int, ctypes.c_int]
        lib.dstpu_aio_wait.restype = ctypes.c_int
        lib.dstpu_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.dstpu_aio_poll.restype = ctypes.c_int
        lib.dstpu_aio_poll.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        self._lib = lib
        self._h = lib.dstpu_aio_new(int(n_threads))
        # keep submitted buffers alive until reaped (the pinned-tensor-manager
        # concern of the reference, reduced to a refcount)
        self._inflight: Dict[int, np.ndarray] = {}

    def _check_open(self):
        if self._h is None:
            raise RuntimeError("AsyncIOHandle used after close()")

    def pwrite(self, path: str, arr: np.ndarray, offset: int = 0,
               fsync: bool = False, truncate: bool = False) -> int:
        """``fsync=True`` for durability-critical writes (checkpoints); swap
        scratch traffic keeps the default and skips the device flush.

        ``truncate=True`` is the whole-file-rewrite flag. It is never inferred:
        an offset-0 chunk of a partitioned multi-chunk write must not zero
        sibling chunks, so chunked writers get safe behavior by default and
        whole-file rewriters opt in explicitly.
        """
        self._check_open()
        arr = np.ascontiguousarray(arr)
        req = self._lib.dstpu_aio_pwrite(
            self._h, path.encode(), arr.ctypes.data_as(ctypes.c_void_p),
            arr.nbytes, offset, 1 if fsync else 0, 1 if truncate else 0)
        self._inflight[req] = arr
        return req

    def pread(self, path: str, arr: np.ndarray, offset: int = 0) -> int:
        self._check_open()
        assert arr.flags["C_CONTIGUOUS"] and arr.flags["WRITEABLE"]
        req = self._lib.dstpu_aio_pread(
            self._h, path.encode(), arr.ctypes.data_as(ctypes.c_void_p),
            arr.nbytes, offset)
        self._inflight[req] = arr
        return req

    def wait(self, req: int) -> None:
        self._check_open()
        rc = self._lib.dstpu_aio_wait(self._h, req)
        self._inflight.pop(req, None)
        if rc != 1:
            raise OSError(-rc, f"async io request {req} failed")

    def poll(self, req: int) -> bool:
        """True when complete (does not reap; call wait() to finalize)."""
        self._check_open()
        rc = self._lib.dstpu_aio_poll(self._h, req)
        if rc < 0:
            self.wait(req)  # reap the failed request, then raise via wait
            raise OSError(-rc, f"async io request {req} failed")  # fallback
        return rc == 1

    def close(self):
        if self._h:
            self._lib.dstpu_aio_free(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
