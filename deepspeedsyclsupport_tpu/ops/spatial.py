"""Spatial (diffusers) ops — fused bias-add family.

Analog of the reference's ``csrc/spatial/csrc/opt_bias_add.cu`` (298 LoC
CUDA) behind ``op_builder/spatial_inference.py``, used by its diffusers
UNet/VAE integration (``deepspeed/ops/transformer/inference/diffusers_*``).
On TPU these are pure jnp compositions — XLA fuses the bias/residual adds
into the producing matmul/conv epilogue, which is the entire point of the
CUDA kernels — so the value here is the stable op surface, kept so
diffusers-style model code ports 1:1.
"""
from typing import Optional

import jax.numpy as jnp

__all__ = ["bias_add", "bias_add_add", "nhwc_bias_add"]


def bias_add(activation: jnp.ndarray, bias: jnp.ndarray) -> jnp.ndarray:
    """``activation [..., C] + bias [C]`` (reference ``opt_bias_add``)."""
    return activation + bias.astype(activation.dtype)


def bias_add_add(activation: jnp.ndarray, bias: jnp.ndarray,
                 other: jnp.ndarray) -> jnp.ndarray:
    """Fused bias + residual add (reference ``opt_bias_add_add``)."""
    return activation + bias.astype(activation.dtype) + other


def nhwc_bias_add(activation: jnp.ndarray, bias: jnp.ndarray,
                  other: Optional[jnp.ndarray] = None,
                  other_bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """The reference's general entry (``nhwc_bias_add`` binding): NHWC
    activation + per-channel bias, optionally adding a second activation
    (+ its own bias) — the UNet residual-merge pattern."""
    out = activation + bias.astype(activation.dtype)
    if other is not None:
        out = out + other
        if other_bias is not None:
            out = out + other_bias.astype(activation.dtype)
    return out
