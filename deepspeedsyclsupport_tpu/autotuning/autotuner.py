"""Autotuner — model-pruned, measured search over engine configs.

Analog of ``deepspeed/autotuning/`` (2717 LoC): the reference forks whole
training jobs per experiment, scrapes metric files, and model-prunes the
space (``autotuner.py`` ``tune_space`` / ``model_based_tuning`` /
``max_train_micro_batch_size``). Under JAX an "experiment" is cheap — build
an Engine in-process, jit once, time a few steps — so the same search
collapses to a loop over the same dimensions the reference explores:

* space: micro-batch size × ZeRO stage × activation-checkpointing (remat)
  × optimizer offload (× user extras), with per-dimension overrides.
* model-based pruning: candidates whose PREDICTED device memory
  (``runtime/zero.predict_memory_per_device`` — the numeric form of the
  stage partition math) exceeds the HBM budget are skipped without ever
  compiling, mirroring the reference's memory-model experiment pruning.
* metric: measured samples/sec over ``steps`` after warmup — the
  ``throughput`` metric the reference optimizes.
* OOM-safe: a candidate that still fails in practice (XLA OOM / invalid
  combo) scores -inf and the search continues, mirroring the reference's
  failed-experiment handling.
"""
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils.logging import log_dist, logger


@dataclass
class TuneResult:
    best_config: Dict[str, Any]
    best_throughput: float  # samples/sec
    trials: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def pruned(self) -> List[Dict[str, Any]]:
        return [t for t in self.trials if t.get("pruned")]


DEFAULT_SPACE = {
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8, 16],
    "zero_optimization.stage": [0, 1, 2, 3],
    "activation_checkpointing.enabled": [False, True],
    "zero_optimization.offload_optimizer.device": ["none", "cpu"],
}


def _set_nested(cfg: Dict, dotted: str, value):
    parts = dotted.split(".")
    d = cfg
    for p in parts[:-1]:
        d = d.setdefault(p, {})
    d[parts[-1]] = value


class Autotuner:
    def __init__(self, model, base_config: Dict[str, Any],
                 make_batch: Callable[[int], Any],
                 space: Optional[Dict[str, Sequence]] = None,
                 steps: int = 3, warmup: int = 1,
                 hbm_bytes: Optional[float] = None,
                 seq_len: Optional[int] = None):
        """``make_batch(global_batch_size) -> batch`` supplies data per
        trial. ``hbm_bytes`` enables model-based pruning against a device
        memory budget (None: probe the accelerator, 0/failed probe: no
        pruning). ``seq_len`` feeds the activation-memory model (defaults
        to the model config's ``max_seq_len`` when available)."""
        self.model = model
        self.base_config = base_config
        self.make_batch = make_batch
        self.space = space or DEFAULT_SPACE
        self.steps = steps
        self.warmup = warmup
        if hbm_bytes is None:
            hbm_bytes = self._probe_hbm()
        self.hbm_bytes = hbm_bytes or 0
        mcfg = getattr(model, "config", None)
        self.seq_len = seq_len or getattr(mcfg, "max_seq_len", 0)
        self._n_params = self._count_params()

    # ------------------------------------------------------------ memory model
    def _probe_hbm(self) -> float:
        try:
            import jax

            stats = jax.devices()[0].memory_stats() or {}
            return float(stats.get("bytes_limit", 0))
        except Exception:
            return 0

    def _count_params(self) -> int:
        import jax
        import numpy as np

        if not hasattr(self.model, "init_params"):
            return 0
        shapes = jax.eval_shape(self.model.init_params)
        return int(sum(np.prod(l.shape)
                       for l in jax.tree_util.tree_leaves(shapes)))

    def _effective(self, label: Dict[str, Any], dotted: str, default):
        """Trial value for a dimension: the label wins, else whatever the
        base config pins, else the default — so dimensions FIXED in
        base_config are modeled as configured, not as their defaults."""
        if dotted in label:
            return label[dotted]
        d: Any = self.base_config
        for p in dotted.split("."):
            if not isinstance(d, dict) or p not in d:
                return default
            d = d[p]
        return d

    def _predict_bytes(self, label: Dict[str, Any]) -> float:
        """Device-memory prediction for one candidate (0 = unknown)."""
        from ..runtime.zero import predict_memory_per_device

        if not self._n_params:
            return 0
        import jax

        mcfg = getattr(self.model, "config", None)
        hidden = getattr(mcfg, "hidden_size", 0)
        layers = getattr(mcfg, "num_layers", 1)
        mbs = int(self._effective(label, "train_micro_batch_size_per_gpu",
                                  1))
        stage = int(self._effective(label, "zero_optimization.stage", 0))
        remat = bool(self._effective(
            label, "activation_checkpointing.enabled", False))
        offload = self._effective(
            label, "zero_optimization.offload_optimizer.device",
            "none") == "cpu"
        # ~16 residual-stream-sized tensors live per layer without remat
        # (qkv, scores-free flash, mlp intermediates, residuals)
        act = (mbs * self.seq_len * hidden * 4 * 16 * layers
               if hidden and self.seq_len else 0.0)
        fsdp = jax.device_count() if stage >= 1 else 1
        return predict_memory_per_device(
            self._n_params, fsdp, stage, offload=offload,
            activation_bytes=act, remat=remat, num_layers=layers)

    # ------------------------------------------------------------------ search
    def tune(self) -> TuneResult:
        keys = list(self.space)
        trials = []
        best = (None, float("-inf"))
        for combo in itertools.product(*(self.space[k] for k in keys)):
            cfg = _deepcopy_config(self.base_config)
            label = dict(zip(keys, combo))
            for k, v in zip(keys, combo):
                # every dimension is written explicitly — "device": "none"
                # must CLEAR an offload section the base config carries,
                # and writing the leaf key preserves sibling settings
                _set_nested(cfg, k, v)
            pred = self._predict_bytes(label)
            if self.hbm_bytes and pred > self.hbm_bytes:
                trials.append({**label, "throughput": float("-inf"),
                               "pruned": True,
                               "predicted_bytes": pred})
                logger.info("autotune: pruned %s (predicted %.2f GB > "
                            "budget %.2f GB)", label, pred / 1e9,
                            self.hbm_bytes / 1e9)
                continue
            tput = self._measure(cfg, label)
            trials.append({**label, "throughput": tput,
                           "predicted_bytes": pred})
            if tput > best[1]:
                best = (cfg, tput)
        if best[0] is None:
            raise RuntimeError("no autotuning candidate succeeded")
        result = TuneResult(best[0], best[1], trials)
        log_dist(f"autotune: best {best[1]:.1f} samples/s with "
                 f"{ {k: _get_nested(best[0], k) for k in keys} } "
                 f"({len(result.pruned)} candidates pruned by the memory "
                 f"model, {len(trials)} trials)")
        return result

    # ------------------------------------------------------------------ trial
    def _measure(self, cfg: Dict[str, Any], label) -> float:
        import jax

        from ..comm.topology import reset_world_topology
        from ..runtime.engine import initialize

        try:
            reset_world_topology()
            engine, *_ = initialize(model=self.model, config=cfg)
            batch = self.make_batch(engine.train_batch_size())
            for _ in range(self.warmup):
                engine.train_batch(batch)
            jax.block_until_ready(engine.params)
            t0 = time.perf_counter()
            for _ in range(self.steps):
                m = engine.train_batch(batch)
            jax.block_until_ready(m["loss"])
            dt = time.perf_counter() - t0
            tput = self.steps * engine.train_batch_size() / dt
            log_dist(f"autotune trial {label}: {tput:.1f} samples/s")
            return tput
        except Exception as e:  # OOM / invalid combo → skip, keep searching
            logger.warning("autotune trial %s failed: %s", label, e)
            return float("-inf")


def _deepcopy_config(cfg):
    import copy

    return copy.deepcopy(cfg)


def _get_nested(cfg: Dict, dotted: str):
    d = cfg
    for p in dotted.split("."):
        d = d[p]
    return d

