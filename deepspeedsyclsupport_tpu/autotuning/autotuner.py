"""Autotuner — measured search over engine configs.

Analog of ``deepspeed/autotuning/`` (2717 LoC): the reference forks whole
training jobs per experiment, scrapes metric files, and model-prunes the space
(``autotuner.py`` ``tune_space`` / ``model_based_tuning``). Under JAX an
"experiment" is cheap — build an Engine in-process, jit once, time a few steps —
so the same search collapses to a loop:

* space: micro-batch size × ZeRO stage (× user extras), fastest-first ordering.
* metric: measured samples/sec (or tokens/sec) over ``steps`` after warmup —
  the same `throughput` metric the reference optimizes.
* OOM-safe: a failing candidate (XLA OOM / bad config) scores -inf and the
  search continues, mirroring the reference's failed-experiment handling.
"""
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils.logging import log_dist, logger


@dataclass
class TuneResult:
    best_config: Dict[str, Any]
    best_throughput: float  # samples/sec
    trials: List[Dict[str, Any]] = field(default_factory=list)


DEFAULT_SPACE = {
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8, 16],
    "zero_optimization.stage": [0, 1, 2, 3],
}


def _set_nested(cfg: Dict, dotted: str, value):
    parts = dotted.split(".")
    d = cfg
    for p in parts[:-1]:
        d = d.setdefault(p, {})
    d[parts[-1]] = value


class Autotuner:
    def __init__(self, model, base_config: Dict[str, Any],
                 make_batch: Callable[[int], Any],
                 space: Optional[Dict[str, Sequence]] = None,
                 steps: int = 3, warmup: int = 1):
        """``make_batch(global_batch_size) -> batch`` supplies data per trial."""
        self.model = model
        self.base_config = base_config
        self.make_batch = make_batch
        self.space = space or DEFAULT_SPACE
        self.steps = steps
        self.warmup = warmup

    def tune(self) -> TuneResult:
        keys = list(self.space)
        trials = []
        best = (None, float("-inf"))
        for combo in itertools.product(*(self.space[k] for k in keys)):
            cfg = _deepcopy_config(self.base_config)
            for k, v in zip(keys, combo):
                _set_nested(cfg, k, v)
            label = dict(zip(keys, combo))
            tput = self._measure(cfg, label)
            trials.append({**label, "throughput": tput})
            if tput > best[1]:
                best = (cfg, tput)
        if best[0] is None:
            raise RuntimeError("no autotuning candidate succeeded")
        result = TuneResult(best[0], best[1], trials)
        log_dist(f"autotune: best {best[1]:.1f} samples/s with "
                 f"{ {k: _get_nested(best[0], k) for k in keys} }")
        return result

    # ------------------------------------------------------------------ trial
    def _measure(self, cfg: Dict[str, Any], label) -> float:
        import jax

        from ..comm.topology import reset_world_topology
        from ..runtime.engine import initialize

        try:
            reset_world_topology()
            engine, *_ = initialize(model=self.model, config=cfg)
            batch = self.make_batch(engine.train_batch_size())
            for _ in range(self.warmup):
                engine.train_batch(batch)
            jax.block_until_ready(engine.params)
            t0 = time.perf_counter()
            for _ in range(self.steps):
                m = engine.train_batch(batch)
            jax.block_until_ready(m["loss"])
            dt = time.perf_counter() - t0
            tput = self.steps * engine.train_batch_size() / dt
            log_dist(f"autotune trial {label}: {tput:.1f} samples/s")
            return tput
        except Exception as e:  # OOM / invalid combo → skip, keep searching
            logger.warning("autotune trial %s failed: %s", label, e)
            return float("-inf")


def _deepcopy_config(cfg):
    import copy

    return copy.deepcopy(cfg)


def _get_nested(cfg: Dict, dotted: str):
    d = cfg
    for p in dotted.split("."):
        d = d[p]
    return d
