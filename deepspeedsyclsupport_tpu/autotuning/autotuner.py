"""Autotuner — model-pruned, measured search over engine configs.

Analog of ``deepspeed/autotuning/`` (2717 LoC): the reference forks whole
training jobs per experiment, scrapes metric files, and model-prunes the
space (``autotuner.py`` ``tune_space`` / ``model_based_tuning`` /
``max_train_micro_batch_size``). Under JAX an "experiment" is cheap — build
an Engine in-process, jit once, time a few steps — so the same search
collapses to a loop over the same dimensions the reference explores:

* space: micro-batch size × ZeRO stage × activation-checkpointing (remat)
  × optimizer offload (× user extras), with per-dimension overrides.
* model-based pruning: candidates whose PREDICTED device memory
  (``runtime/zero.predict_memory_per_device`` — the numeric form of the
  stage partition math) exceeds the HBM budget are skipped without ever
  compiling, mirroring the reference's memory-model experiment pruning.
* metric: measured samples/sec over ``steps`` after warmup — the
  ``throughput`` metric the reference optimizes.
* OOM-safe: a candidate that still fails in practice (XLA OOM / invalid
  combo) scores -inf and the search continues, mirroring the reference's
  failed-experiment handling.
"""
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils.logging import log_dist, logger


@dataclass
class TuneResult:
    best_config: Dict[str, Any]
    best_throughput: float  # samples/sec (train) or tokens/sec (serve)
    trials: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def pruned(self) -> List[Dict[str, Any]]:
        return [t for t in self.trials if t.get("pruned")]

    def write_report(self, path: str) -> str:
        """Reference-style report artifact (the summary/exps files the
        reference autotuner leaves behind, ``autotuning/autotuner.py:1``):
        a JSON record plus a human-readable ranking table."""
        import json
        import os

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        record = {
            "best_throughput": self.best_throughput,
            "best_config": self.best_config,
            "num_trials": len(self.trials),
            "num_pruned": len(self.pruned),
            "trials": self.trials,
        }
        with open(path, "w") as f:
            json.dump(record, f, indent=2, default=str)
        ranked = sorted((t for t in self.trials
                         if not t.get("pruned") and not t.get("skipped")),
                        key=lambda t: -t["throughput"])
        drop_keys = ("throughput", "predicted_bytes", "pruned", "skipped",
                     "error")
        lines = [f"{'rank':<6}{'throughput':>14}  config",
                 "-" * 72]
        for i, t in enumerate(ranked):
            label = {k: v for k, v in t.items() if k not in drop_keys}
            lines.append(f"{i:<6}{t['throughput']:>14.1f}  {label}")
        for t in self.trials:
            if t.get("pruned") or t.get("skipped"):
                label = {k: v for k, v in t.items() if k not in drop_keys}
                tag = "pruned" if t.get("pruned") else "skipped"
                lines.append(f"{'—':<6}{tag:>14}  {label}")
        txt = path.rsplit(".", 1)[0] + "_summary.txt"
        with open(txt, "w") as f:
            f.write("\n".join(lines) + "\n")
        return path


DEFAULT_SPACE = {
    "train_micro_batch_size_per_gpu": [1, 2, 4, 8, 16],
    "zero_optimization.stage": [0, 1, 2, 3],
    "activation_checkpointing.enabled": [False, True],
    "zero_optimization.offload_optimizer.device": ["none", "cpu"],
}

# serve rung: the SplitFuse scheduler's two first-order knobs
DEFAULT_SERVE_SPACE = {
    "max_tokens_per_batch": [64, 128, 256, 512],
    "block_size": [16, 32, 64],
}


def _set_nested(cfg: Dict, dotted: str, value):
    parts = dotted.split(".")
    d = cfg
    for p in parts[:-1]:
        d = d.setdefault(p, {})
    d[parts[-1]] = value


class Autotuner:
    def __init__(self, model, base_config: Dict[str, Any],
                 make_batch: Optional[Callable[[int], Any]] = None,
                 space: Optional[Dict[str, Sequence]] = None,
                 steps: int = 3, warmup: int = 1,
                 hbm_bytes: Optional[float] = None,
                 seq_len: Optional[int] = None,
                 mode: str = "in_process",
                 kind: str = "train",
                 model_name: Optional[str] = None,
                 model_kw: Optional[Dict[str, Any]] = None,
                 trial_timeout: float = 600.0,
                 trial_env: Optional[Dict[str, str]] = None):
        """``make_batch(global_batch_size) -> batch`` supplies data per
        in-process trial. ``hbm_bytes`` enables model-based pruning against
        a device memory budget (None: probe the accelerator, 0/failed
        probe: no pruning). ``seq_len`` feeds the activation-memory model
        (defaults to the model config's ``max_seq_len`` when available).

        ``mode='subprocess'`` runs every measured trial in its own child
        interpreter (the reference's experiment-per-job isolation,
        ``autotuning/scheduler.py``): an OOM or wedged compile kills the
        child, scores -inf, and the search continues. Requires the model to
        be nameable in the zoo (``model_name`` + ``model_kw``).
        ``kind='serve'`` tunes the v2 serving engine (token budget / block
        size space) by measured decode tokens/sec instead of the train
        step."""
        if mode not in ("in_process", "subprocess"):
            raise ValueError(f"unknown mode {mode!r}")
        if kind not in ("train", "serve"):
            raise ValueError(f"unknown kind {kind!r}")
        if mode == "subprocess" and model_name is None:
            raise ValueError("subprocess mode needs model_name= (a models/ "
                             "zoo name the child can rebuild)")
        if kind == "serve" and mode != "subprocess":
            raise ValueError("serve tuning runs trials in subprocesses "
                             "(each trial owns the device)")
        self.model = model
        self.base_config = base_config
        self.make_batch = make_batch
        self.space = space or (DEFAULT_SPACE if kind == "train"
                               else DEFAULT_SERVE_SPACE)
        self.steps = steps
        self.warmup = warmup
        self.mode = mode
        self.kind = kind
        self.model_name = model_name
        self.model_kw = model_kw or {}
        self.trial_timeout = trial_timeout
        self.trial_env = trial_env or {}
        if hbm_bytes is None:
            hbm_bytes = self._probe_hbm()
        self.hbm_bytes = hbm_bytes or 0
        mcfg = getattr(model, "config", None)
        self.seq_len = seq_len or getattr(mcfg, "max_seq_len", 0)
        self._n_params = self._count_params()

    # ------------------------------------------------------------ memory model
    def _probe_hbm(self) -> float:
        try:
            import jax

            stats = jax.devices()[0].memory_stats() or {}
            return float(stats.get("bytes_limit", 0))
        except Exception:
            return 0

    def _count_params(self) -> int:
        import jax
        import numpy as np

        if not hasattr(self.model, "init_params"):
            return 0
        shapes = jax.eval_shape(self.model.init_params)
        return int(sum(np.prod(l.shape)
                       for l in jax.tree_util.tree_leaves(shapes)))

    def _effective(self, label: Dict[str, Any], dotted: str, default):
        """Trial value for a dimension: the label wins, else whatever the
        base config pins, else the default — so dimensions FIXED in
        base_config are modeled as configured, not as their defaults."""
        if dotted in label:
            return label[dotted]
        d: Any = self.base_config
        for p in dotted.split("."):
            if not isinstance(d, dict) or p not in d:
                return default
            d = d[p]
        return d

    def _fsdp_factor(self, cfg: Dict[str, Any], stage: int, label) -> int:
        """Shard factor the trial's topology will actually use. Deriving it
        from the trial's ParallelismConfig (not ``device_count()``) matters
        when the base config dedicates devices to tp/pp/ep/sp: assuming the
        whole world shards the optimizer over-divides per-device memory and
        prunes candidates that would fit."""
        import jax

        if stage < 1:
            return 1
        n_dev = jax.device_count()
        try:
            from ..runtime.config import ParallelismConfig

            mics = int(self._effective(
                label, "zero_optimization.mics_shard_size", -1) or -1)
            p = ParallelismConfig.from_config_dict(cfg, stage, mics)
            fixed = max(1, p.tp * p.pp * p.ep * p.sp)
            if p.fsdp > 0:
                return p.fsdp
            dp = p.dp if p.dp > 0 else 1
            return max(1, n_dev // (fixed * dp))
        except Exception:
            return n_dev

    def _predict_bytes(self, label: Dict[str, Any],
                       cfg: Optional[Dict[str, Any]] = None) -> float:
        """Device-memory prediction for one candidate (0 = unknown)."""
        from ..runtime.zero import predict_memory_per_device

        if not self._n_params:
            return 0

        mcfg = getattr(self.model, "config", None)
        hidden = getattr(mcfg, "hidden_size", 0)
        layers = getattr(mcfg, "num_layers", 1)
        mbs = int(self._effective(label, "train_micro_batch_size_per_gpu",
                                  1))
        stage = int(self._effective(label, "zero_optimization.stage", 0))
        remat = bool(self._effective(
            label, "activation_checkpointing.enabled", False))
        offload = self._effective(
            label, "zero_optimization.offload_optimizer.device",
            "none") == "cpu"
        # ~16 residual-stream-sized tensors live per layer without remat
        # (qkv, scores-free flash, mlp intermediates, residuals)
        act = (mbs * self.seq_len * hidden * 4 * 16 * layers
               if hidden and self.seq_len else 0.0)
        fsdp = self._fsdp_factor(cfg if cfg is not None else self.base_config,
                                 stage, label)
        return predict_memory_per_device(
            self._n_params, fsdp, stage, offload=offload,
            activation_bytes=act, remat=remat, num_layers=layers)

    # ------------------------------------------------------------------ search
    def tune(self, strategy: str = "grid",
             num_trials: Optional[int] = None,
             seed: int = 0) -> TuneResult:
        """Search the space (reference tuner strategies,
        ``autotuning/tuner/``):

        * ``grid`` — measure every in-budget candidate (GridSearchTuner).
        * ``random`` — measure ``num_trials`` uniformly sampled candidates
          (RandomTuner).
        * ``model_based`` — rank in-budget candidates by the memory model
          (largest predicted footprint that still fits first — the
          max-micro-batch-first philosophy of the reference's cost-model
          tuner) and measure only the top ``num_trials``.
        """
        if strategy not in ("grid", "random", "model_based"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy != "grid" and not num_trials:
            raise ValueError(f"{strategy} strategy needs num_trials=")
        keys = list(self.space)
        trials = []
        # enumerate + model-prune first (cheap, no compilation)
        candidates = []
        for combo in itertools.product(*(self.space[k] for k in keys)):
            cfg = _deepcopy_config(self.base_config)
            label = dict(zip(keys, combo))
            for k, v in zip(keys, combo):
                # every dimension is written explicitly — "device": "none"
                # must CLEAR an offload section the base config carries,
                # and writing the leaf key preserves sibling settings
                _set_nested(cfg, k, v)
            pred = (self._predict_bytes(label, cfg)
                    if self.kind == "train" else 0)
            if self.hbm_bytes and pred > self.hbm_bytes:
                trials.append({**label, "throughput": float("-inf"),
                               "pruned": True,
                               "predicted_bytes": pred})
                logger.info("autotune: pruned %s (predicted %.2f GB > "
                            "budget %.2f GB)", label, pred / 1e9,
                            self.hbm_bytes / 1e9)
                continue
            candidates.append((label, cfg, pred))

        if strategy == "random" and num_trials < len(candidates):
            import random as _random

            rng = _random.Random(seed)
            candidates = rng.sample(candidates, num_trials)
        elif strategy == "model_based" and num_trials < len(candidates):
            if not any(pred for _l, _c, pred in candidates):
                # no memory model available (serve kind / no init_params):
                # a silent arbitrary pick would masquerade as model-ranked
                raise ValueError(
                    "model_based strategy has no memory-model predictions "
                    "to rank by here (kind='serve' or un-countable model); "
                    "use strategy='random' or 'grid'")
            ranked = sorted(candidates, key=lambda c: -c[2])
            candidates, skipped = ranked[:num_trials], ranked[num_trials:]
            for label, _cfg, pred in skipped:
                trials.append({**label, "throughput": float("-inf"),
                               "skipped": True, "predicted_bytes": pred})

        best = (None, float("-inf"))
        for label, cfg, pred in candidates:
            tput = (self._measure(cfg, label) if self.mode == "in_process"
                    else self._measure_subprocess(cfg, label))
            trials.append({**label, "throughput": tput,
                           "predicted_bytes": pred})
            if tput > best[1]:
                best = (cfg, tput)
        if best[0] is None:
            raise RuntimeError("no autotuning candidate succeeded")
        result = TuneResult(best[0], best[1], trials)
        n_measured = len(candidates)
        n_skipped = sum(1 for t in trials if t.get("skipped"))
        log_dist(f"autotune[{strategy}]: best {best[1]:.1f} with "
                 f"{ {k: _get_nested(best[0], k) for k in keys} } "
                 f"({n_measured} measured, {len(result.pruned)} pruned by "
                 f"the memory model, {n_skipped} skipped)")
        return result

    # ------------------------------------------------------------------ trial
    def _measure(self, cfg: Dict[str, Any], label) -> float:
        import jax

        from ..comm.topology import reset_world_topology
        from ..runtime.engine import initialize

        try:
            reset_world_topology()
            engine, *_ = initialize(model=self.model, config=cfg)
            batch = self.make_batch(engine.train_batch_size())
            for _ in range(self.warmup):
                engine.train_batch(batch)
            jax.block_until_ready(engine.params)
            t0 = time.perf_counter()
            for _ in range(self.steps):
                m = engine.train_batch(batch)
            jax.block_until_ready(m["loss"])
            dt = time.perf_counter() - t0
            tput = self.steps * engine.train_batch_size() / dt
            log_dist(f"autotune trial {label}: {tput:.1f} samples/s")
            return tput
        except Exception as e:  # OOM / invalid combo → skip, keep searching
            logger.warning("autotune trial %s failed: %s", label, e)
            return float("-inf")

    def _measure_subprocess(self, cfg: Dict[str, Any], label) -> float:
        """One measured trial in its own interpreter (reference: each
        experiment is its own job). Child crash/timeout/OOM → -inf."""
        import json
        import os
        import subprocess
        import sys
        import tempfile

        payload = {
            "kind": self.kind,
            "model": self.model_name,
            "model_kw": self.model_kw,
            "config": cfg,
            "steps": self.steps,
            "warmup": self.warmup,
            "seq_len": self.seq_len or None,
        }
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump(payload, f)
            path = f.name
        env = {**os.environ, **self.trial_env}
        try:
            proc = subprocess.run(
                [sys.executable, "-m",
                 "deepspeedsyclsupport_tpu.autotuning.trial_runner", path],
                capture_output=True, text=True, timeout=self.trial_timeout,
                env=env)
        except subprocess.TimeoutExpired:
            logger.warning("autotune trial %s timed out after %.0fs", label,
                           self.trial_timeout)
            return float("-inf")
        finally:
            os.unlink(path)
        for line in reversed((proc.stdout or "").splitlines()):
            if line.startswith("DSTPU_TRIAL "):
                result = json.loads(line[len("DSTPU_TRIAL "):])
                if result.get("ok"):
                    log_dist(f"autotune trial {label}: "
                             f"{result['throughput']:.1f} {result['unit']}")
                    return float(result["throughput"])
                logger.warning("autotune trial %s failed in child: %s",
                               label, result.get("error"))
                return float("-inf")
        logger.warning("autotune trial %s: child emitted no result "
                       "(rc=%d): %s", label, proc.returncode,
                       (proc.stderr or "")[-500:])
        return float("-inf")


def _deepcopy_config(cfg):
    import copy

    return copy.deepcopy(cfg)


def _get_nested(cfg: Dict, dotted: str):
    d = cfg
    for p in dotted.split("."):
        d = d[p]
    return d

