"""Subprocess trial executor for the autotuner.

Analog of the reference's experiment runner (``autotuning/scheduler.py``
launching each config as its own training job and scraping metric files):
one trial = one child interpreter, so an XLA OOM, a wedged compile, or a
crashing config kills the CHILD and scores -inf instead of taking down the
search. Payload in (JSON file path argv[1]), one JSON result line out.

Train trials measure engine.train_batch samples/sec on the framework model
zoo; serve trials measure v2-engine decode tokens/sec under the SplitFuse
scheduler — the two rungs the driver benches.
"""
import json
import sys
import time


def _train_trial(payload):
    import jax
    import numpy as np

    import deepspeedsyclsupport_tpu as dstpu
    from deepspeedsyclsupport_tpu.models import build_model

    model = build_model(payload["model"], **payload.get("model_kw", {}))
    engine, _, _, _ = dstpu.initialize(model=model, config=payload["config"])
    gbs = engine.train_batch_size()
    seq = int(payload.get("seq_len") or
              min(model.config.max_seq_len, 128))
    ids = jax.random.randint(jax.random.PRNGKey(0), (gbs, seq), 0,
                             model.config.vocab_size)
    batch = {"input_ids": ids}
    # at least one warmup step: it also compiles the program outside the
    # timed window
    for _ in range(max(1, int(payload.get("warmup", 1)))):
        m = engine.train_batch(batch)
    jax.block_until_ready(m["loss"])
    steps = int(payload.get("steps", 3))
    t0 = time.perf_counter()
    for _ in range(steps):
        m = engine.train_batch(batch)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0
    return {"throughput": steps * gbs / dt, "unit": "samples/s",
            "loss": float(np.asarray(jax.device_get(m["loss"])))}


def _serve_trial(payload):
    import jax
    import numpy as np

    from deepspeedsyclsupport_tpu.inference.v2 import InferenceEngineV2
    from deepspeedsyclsupport_tpu.models import build_model

    model = build_model(payload["model"], **payload.get("model_kw", {}))
    params = model.init_params(jax.random.PRNGKey(0))
    eng = InferenceEngineV2(model, params, config=payload["config"])
    rng = np.random.RandomState(0)
    n_seqs = int(payload.get("clients", 4))
    prompt_len = int(payload.get("prompt_len", 32))
    gen_len = int(payload.get("gen_len", 8))
    prompts = {u: rng.randint(1, model.config.vocab_size,
                              size=prompt_len).tolist()
               for u in range(n_seqs)}
    # warmup pass compiles prefill+decode in both KV states
    eng.warmup()
    out = eng.put(list(prompts), list(prompts.values()))
    # prefill is async-dispatched and logits are device-resident: force it
    # OUTSIDE the timed decode window or prefill cost pollutes the metric
    last = {u: int(np.argmax(np.asarray(out[u]))) for u in out}
    t0 = time.perf_counter()
    decoded = 0
    for _ in range(gen_len):
        res = eng.put(list(last), [[t] for t in last.values()])
        for u in list(last):
            if u in res:
                last[u] = int(np.argmax(res[u]))
                decoded += 1
    dt = time.perf_counter() - t0
    return {"throughput": decoded / dt, "unit": "tokens/s"}


def main() -> int:
    import os

    with open(sys.argv[1]) as f:
        payload = json.load(f)
    try:
        import jax

        # a site-level TPU plugin may force-pin jax_platforms at interpreter
        # start, IGNORING the env var the parent set — re-pin explicitly or
        # a CPU-intended trial hangs on a dead TPU tunnel
        plat = os.environ.get("JAX_PLATFORMS")
        if plat:
            jax.config.update("jax_platforms", plat)
        # persistent compile cache: sibling trials re-lower mostly identical
        # programs; sharing the cache makes a sweep compile-bound only once
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("DSTPU_TEST_CACHE",
                                         "/tmp/dstpu_jax_test_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass
    try:
        result = (_serve_trial(payload) if payload.get("kind") == "serve"
                  else _train_trial(payload))
        result["ok"] = True
    except Exception as e:  # scored -inf by the parent
        result = {"ok": False, "error": f"{type(e).__name__}: {e}"[:500]}
    print("DSTPU_TRIAL " + json.dumps(result), flush=True)
    return 0 if result.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
