from .autotuner import Autotuner, TuneResult  # noqa: F401
