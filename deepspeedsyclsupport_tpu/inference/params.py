"""Shared inference weight placement.

Both engines (v1 ``engine.py``, v2 ``engine_v2.py``) place weights the same way:
stage-0 (replicate-unless-ruled) shardings composed with the model's declarative
TP rules — the whole of the reference's auto-TP weight surgery
(``module_inject/auto_tp.py``) — then cast floating leaves to the serving dtype.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..comm.topology import MeshTopology
from ..runtime import zero as zero_lib


def place_inference_params(params: Any, topology: MeshTopology, rules, dtype):
    """Returns (placed_params, shardings)."""
    shardings = zero_lib.tree_param_shardings(
        params, topology, stage=0, extra_rules=rules)

    def place(x, s):
        x = jnp.asarray(x)
        if jnp.issubdtype(x.dtype, jnp.floating):
            x = x.astype(dtype)
        return jax.device_put(x, s)

    return jax.tree_util.tree_map(place, params, shardings), shardings
