"""Inference configuration.

Analog of ``DeepSpeedInferenceConfig`` (``deepspeed/inference/config.py``, 304 LoC):
the same key families — dtype, tensor_parallel, generation limits — minus the knobs
that only exist to steer CUDA kernel injection (``replace_with_kernel_inject``,
``enable_cuda_graph``…), which are accepted and ignored so reference-style config
dicts keep working (XLA jit-compiles and fuses unconditionally; there is nothing to
inject or capture).
"""
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax.numpy as jnp

_IGNORED_KEYS = {
    # CUDA-specific knobs with no TPU meaning; jit/XLA subsumes them.
    "replace_with_kernel_inject", "enable_cuda_graph", "use_triton",
    "triton_autotune", "cuda_graph_max_batch_size", "injection_policy",
    "injection_policy_tuple", "replace_method", "moe_experts", "save_mp_checkpoint_path",
}

_DTYPES = {
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16, "torch.bfloat16": jnp.bfloat16,
    "fp16": jnp.float16, "half": jnp.float16, "torch.half": jnp.float16,
    "float16": jnp.float16, "torch.float16": jnp.float16,
    "fp32": jnp.float32, "float": jnp.float32, "float32": jnp.float32,
    "torch.float32": jnp.float32,
    "int8": jnp.int8,
}


@dataclass
class TensorParallelConfig:
    """Reference ``DeepSpeedTPConfig`` (``inference/config.py``)."""
    tp_size: int = 1
    enabled: bool = True


@dataclass
class ZeroInferenceQuantConfig:
    """ZeRO-Inference weight quantization (reference
    ``deepspeed/inference/quantization/`` + the v1 config ``quant`` section):
    big weights live in HBM as int8 + blockwise scales and dequantize per
    layer inside the scan."""
    enabled: bool = False
    group_size: int = 64    # elements per scale block
    min_size: int = 4096    # leaves smaller than this stay full precision
    bits: int = 8           # 8 (int8) or 4 (packed int4, quantize_intX analog)

    @classmethod
    def from_value(cls, v) -> "ZeroInferenceQuantConfig":
        if isinstance(v, ZeroInferenceQuantConfig):
            return v
        if isinstance(v, bool):
            return cls(enabled=v)
        d = dict(v or {})
        bits = int(d.get("bits", 8))
        if bits not in (4, 8):
            raise ValueError(f"quant.bits must be 4 or 8, got {bits}")
        return cls(enabled=bool(d.get("enabled", False)),
                   group_size=int(d.get("group_size", 64)),
                   min_size=int(d.get("min_size", 4096)),
                   bits=bits)


@dataclass
class DSTpuInferenceConfig:
    dtype: Any = jnp.bfloat16
    tensor_parallel: TensorParallelConfig = field(
        default_factory=TensorParallelConfig)
    max_out_tokens: int = 1024          # reference: max_out_tokens (clamps generate)
    min_out_tokens: int = 1             # reference: min_out_tokens; a scheduler
    # admission hint — enforced by the v2 ragged engine's can_schedule, not v1
    max_seq_len: int = 2048             # prompt + generation KV budget
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    seed: int = 0
    quant: ZeroInferenceQuantConfig = field(
        default_factory=ZeroInferenceQuantConfig)
    # ZeRO-Inference's other half (reference README 20x claim: weight quant
    # + KV offload): keep the decode KV cache in host memory, streaming
    # per-layer slices through HBM — contexts larger than HBM allows
    kv_offload: bool = False

    @classmethod
    def from_config(cls, config: Optional[Dict[str, Any]] = None, **kw
                    ) -> "DSTpuInferenceConfig":
        cfg = dict(config or {})
        cfg.update(kw)
        for k in list(cfg):
            if k in _IGNORED_KEYS:
                cfg.pop(k)
        tp = cfg.pop("tensor_parallel", None) or {}
        if isinstance(tp, TensorParallelConfig):
            tp_cfg = tp
        else:
            if isinstance(tp, int):
                tp = {"tp_size": tp}
            tp_cfg = TensorParallelConfig(**tp)
        if "mp_size" in cfg:  # reference legacy alias
            tp_cfg.tp_size = cfg.pop("mp_size")
        quant = ZeroInferenceQuantConfig.from_value(cfg.pop("quant", None))
        cfg["quant"] = quant
        dtype = cfg.pop("dtype", jnp.bfloat16)
        if isinstance(dtype, str):
            try:
                dtype = _DTYPES[dtype.lower()]
            except KeyError:
                raise ValueError(
                    f"unknown inference dtype {dtype!r}; one of "
                    f"{sorted(_DTYPES)}") from None
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown inference config keys: {sorted(unknown)}")
        return cls(dtype=dtype, tensor_parallel=tp_cfg, **cfg)
