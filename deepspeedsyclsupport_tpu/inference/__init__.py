"""Inference engines.

TPU-native analogs of the reference inference stack (SURVEY.md §2.6):

* :mod:`.engine` — v1-style engine (``deepspeed/inference/engine.py:39``):
  TP-sharded model + jitted prefill/decode generate loop with a static KV cache.
* :mod:`.v2` — FastGen analog (``deepspeed/inference/v2/``): paged KV cache,
  ragged continuous batching, Dynamic-SplitFuse scheduling.
"""
from .config import DSTpuInferenceConfig  # noqa: F401
from .engine import InferenceEngine, init_inference  # noqa: F401
