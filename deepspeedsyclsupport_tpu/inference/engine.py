"""Inference engine v1 — TP-sharded model with a jitted generate loop.

Analog of ``InferenceEngine`` / ``deepspeed.init_inference``
(``deepspeed/inference/engine.py:39``, ``deepspeed/__init__.py:269``). The
reference's jobs and their TPU-native forms:

==============================================  =================================
reference (CUDA/torch)                          here (JAX/XLA)
==============================================  =================================
kernel injection (``replace_transformer_layer``  nothing to inject: the framework
``module_inject/replace_module.py:182``)         owns the model (``models/``) and
                                                 XLA fuses what the CUDA kernels
                                                 hand-fused
auto-TP weight surgery (``auto_tp.py``,          TP is declarative: the model's
``LinearAllreduce`` per-layer allreduce)         ``sharding_rules`` + GSPMD insert
                                                 the identical collectives
CUDA-graph capture (``engine.py`` graph path)    ``jax.jit`` — the whole decode
                                                 step is one compiled program
KV cache inside kernel workspace                 explicit ``KVCache`` pytree,
(``inference_context.h``)                        sharded over the mesh
HF ``generate`` driving per-token forwards       ``lax.scan`` decode loop compiled
                                                 once (host never in the loop)
==============================================  =================================

Ragged batches are right-padded; correctness under padding comes from explicit
slot-validity masks (see :meth:`InferenceEngine._generate_fn`), the same masking
contract the v2 ragged engine gets from its atom builder.
"""
import os
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import DSTpuInferenceConfig
from .params import place_inference_params
from .sampling import SamplingParams, sample_token
from ..comm.topology import MeshTopology, build_topology
from ..utils.logging import log_dist


def init_inference(model: Any = None,
                   params: Any = None,
                   config: Optional[Dict] = None,
                   **kwargs) -> "InferenceEngine":
    """Reference ``deepspeed.init_inference`` (``deepspeed/__init__.py:269``).

    ``model``: a ``models.CausalLM`` (or any object with ``_forward``-style
    ``apply/decode_step/init_kv_cache/sharding_rules``). ``params``: its pytree
    (host or device). kwargs merge into config (reference allows both styles).
    """
    cfg = DSTpuInferenceConfig.from_config(config, **kwargs)
    if isinstance(model, str):
        # HF checkpoint directory (reference: init_inference over an HF model
        # + checkpoint dict; here the policy/name-map layer loads it directly).
        # Streaming discipline: build the model skeleton from config.json,
        # derive the serving shardings from shapes alone, then stream each
        # leaf straight to its target sharding at the serving dtype — the
        # full model never materializes on one device or at fp32.
        if not os.path.isdir(model):
            raise FileNotFoundError(
                f"init_inference(model=...) got a string that is not a local "
                f"checkpoint directory: {model!r} (hub names are not "
                f"downloaded here — pass a downloaded snapshot path)")
        import json as _json

        from ..checkpoint.hf import config_from_hf, load_hf_checkpoint
        from ..models.transformer import CausalLM
        from ..runtime import zero as zero_lib

        with open(os.path.join(model, "config.json")) as f:
            skeleton = CausalLM(config_from_hf(
                _json.load(f), dtype=jnp.dtype(cfg.dtype).name))
        tp = (cfg.tensor_parallel.tp_size
              if cfg.tensor_parallel.enabled else 1)
        topology = build_topology(dp=-1, tp=tp)
        shapes = jax.eval_shape(skeleton.init_params)
        shardings = zero_lib.tree_param_shardings(
            shapes, topology, stage=0, extra_rules=skeleton.sharding_rules)
        model, params = load_hf_checkpoint(model, model=skeleton,
                                           dtype=cfg.dtype,
                                           shardings=shardings)
        return InferenceEngine(model, params, cfg, topology=topology)
    if model is None:
        raise ValueError("init_inference needs a model")
    if params is None:
        if not hasattr(model, "init_params"):
            raise ValueError("provide params, or a model with init_params()")
        params = model.init_params()
    return InferenceEngine(model, params, cfg)


class InferenceEngine:
    def __init__(self, model: Any, params: Any, config: DSTpuInferenceConfig,
                 topology: Optional[MeshTopology] = None):
        self.module = model
        self.config = config
        tp = config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1
        # serving mesh: TP innermost, leftover devices become batch ("data") ranks
        self.topology = topology or build_topology(dp=-1, tp=tp)

        # --------------------------------------------------- weight placement
        # stage-0 placement + the model's TP rules = auto-TP without surgery
        # (reference: AutoTP row/col sharding, module_inject/auto_tp.py:483)
        rules = getattr(model, "sharding_rules", None)
        dtype = config.dtype
        self.params, self.param_shardings = place_inference_params(
            params, self.topology, rules, dtype)
        if config.quant.enabled:
            self._quantize_weights(config.quant)
        log_dist(f"inference engine: tp={tp}, dtype={jnp.dtype(dtype).name}, "
                 f"quant={config.quant.enabled}, "
                 f"mesh={self.topology.axis_sizes}")

        self._forward_fn = None
        self._generate_fns: Dict[Tuple, Callable] = {}
        self._rng = jax.random.PRNGKey(config.seed)
        if config.kv_offload:
            log_dist("ZeRO-Inference KV offload: decode cache pinned to "
                     "host memory (per-layer slices stream through HBM)")

    def _kv_to_host(self, cache):
        """Annotate the decode cache as host-resident (ZeRO-Inference KV
        offload — reference pairs weight quant with a CPU-side KV cache for
        its 20x claim). Inside jit this is a memory-space annotation: XLA's
        host-offloader streams each layer's k/v slice through HBM as the
        layer scan consumes it, and the single-token write lands back in
        host memory. The [*, *, *, kv_heads, *] spec keeps TP sharding."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        host = NamedSharding(self.topology.mesh,
                             P(None, None, None, "model", None),
                             memory_kind="pinned_host")
        return type(cache)(jax.device_put(cache.k, host),
                           jax.device_put(cache.v, host),
                           cache.write_pos)

    def _quantize_weights(self, qcfg):
        """ZeRO-Inference: per-layer weights → int8 + blockwise scales
        (reference ``deepspeed/inference/quantization/``). Applied after
        placement so scales stay fp32; dequantization happens inside the
        model's layer scan (one layer fp at a time). TP is unsupported here
        — the point of ZeRO-Inference is serving big models on FEW chips."""
        if self.topology.axis_sizes["model"] > 1:
            raise ValueError("weight quantization (ZeRO-Inference) does not "
                             "compose with tensor_parallel yet")
        from ..compression.quantize import quantize_tree

        if not (isinstance(self.params, dict) and "layers" in self.params):
            raise ValueError(
                "weight quantization needs the framework model layout "
                "(params['layers'] consumed by models.CausalLM, which "
                "dequantizes inside its layer scan) — arbitrary models "
                "would trace ops against QuantTensor leaves and fail")
        stacked = bool(getattr(getattr(self.module, "config", None),
                               "scan_layers", False))
        nbytes = lambda t: sum(x.nbytes
                               for x in jax.tree_util.tree_leaves(t))
        before = nbytes(self.params["layers"])
        self.params = dict(self.params)
        # NOTE: no donation — placement may alias caller-held arrays
        # (device_put of an already-placed array is a no-op), so the fp
        # buffers are not ours to free. Transient peak during conversion is
        # fp + int8; for models near the HBM limit quantize before placing.
        self.params["layers"] = jax.jit(
            lambda t: quantize_tree(t, qcfg.group_size, qcfg.min_size,
                                    stacked=stacked,
                                    bits=qcfg.bits))(self.params["layers"])
        after = nbytes(self.params["layers"])
        # shardings must mirror the (changed) params tree; tp==1 here, so
        # everything is replicated
        repl = self.topology.replicated()
        self.param_shardings = jax.tree_util.tree_map(lambda _: repl,
                                                      self.params)
        log_dist(f"zero-inference: layer weights {before / 2**20:.1f} MB "
                 f"→ {after / 2**20:.1f} MB int8")

    # ------------------------------------------------------------------ forward
    def forward(self, input_ids: jnp.ndarray, *args) -> jnp.ndarray:
        """Full-sequence logits (reference ``InferenceEngine.forward``).
        Extra positional args pass through to ``module.apply`` — encoder
        models (BERT-family) take attention_mask / token_type_ids here."""
        if self._forward_fn is None:
            self._forward_fn = jax.jit(self.module.apply)
        return self._forward_fn(self.params, input_ids, *args)

    __call__ = forward

    # ----------------------------------------------------------------- generate
    def generate(self,
                 input_ids: jnp.ndarray,
                 prompt_lens: Optional[jnp.ndarray] = None,
                 max_new_tokens: int = 32,
                 do_sample: bool = False,
                 temperature: float = 1.0,
                 top_k: int = 0,
                 top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 rng: Optional[jax.Array] = None) -> jnp.ndarray:
        """Autoregressive generation (the role HF ``generate`` plays over the
        reference engine; here one jitted prefill + ``lax.scan`` decode).

        ``input_ids``: [B, S] right-padded prompts; ``prompt_lens``: [B] true
        lengths (defaults to S for all). Returns [B, max_new_tokens] generated
        ids, post-EOS positions filled with ``pad_token_id``.
        """
        b, s = input_ids.shape
        if prompt_lens is None:
            prompt_lens = jnp.full((b,), s, jnp.int32)
        eos = eos_token_id if eos_token_id is not None else self.config.eos_token_id
        # generation limits (reference: max_out_tokens / max input+output budget,
        # inference/config.py + inference_context workspace sizing)
        max_new_tokens = min(int(max_new_tokens), self.config.max_out_tokens)
        if s + max_new_tokens > self.config.max_seq_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_seq_len ({self.config.max_seq_len}); raise max_seq_len "
                f"in the inference config")
        sp = SamplingParams(do_sample, float(temperature), int(top_k),
                            float(top_p))
        if self.config.kv_offload:
            # the model-side KV memory annotations (layers.attention_block)
            # read the WORLD topology at trace time — pin it to THIS
            # engine's mesh so an interleaved training engine / explicit
            # topology= argument can't leave the two meshes diverged inside
            # one jitted decode program
            from ..comm.topology import set_world_topology

            set_world_topology(self.topology)
        key = (s, int(max_new_tokens), sp, -1 if eos is None else int(eos))
        if key not in self._generate_fns:
            self._generate_fns[key] = jax.jit(partial(
                self._generate_fn, max_new_tokens=int(max_new_tokens), sp=sp,
                eos_id=-1 if eos is None else int(eos)))
        if rng is None:
            self._rng, rng = jax.random.split(self._rng)
        return self._generate_fns[key](
            self.params, jnp.asarray(input_ids), jnp.asarray(prompt_lens,
                                                             jnp.int32), rng)

    def _generate_fn(self, params, input_ids, prompt_lens, rng, *,
                     max_new_tokens: int, sp: SamplingParams, eos_id: int):
        """Prefill + decode under one jit.

        KV layout: slots [0, S) hold the (right-padded) prompt — pad slots are
        garbage, masked out; slots [S, S+t] hold generated tokens, shared across
        the batch. Slot-validity mask per sequence i at decode step t:
        ``slot < prompt_lens[i]  or  S <= slot <= S+t``. RoPE positions stay
        *logical* (``prompt_lens[i] + t``), so padding never shifts phases.
        """
        model = self.module
        b, s = input_ids.shape
        max_len = s + max_new_tokens
        pad_id = self.config.pad_token_id

        cache = model.init_kv_cache(b, max_len, dtype=self.config.dtype)
        if self.config.kv_offload:
            cache = self._kv_to_host(cache)
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        logits, cache = model.decode_step(params, cache, input_ids,
                                          positions=positions)
        if self.config.kv_offload:
            cache = self._kv_to_host(cache)
        last = jnp.take_along_axis(
            logits, (prompt_lens - 1)[:, None, None], axis=1)[:, 0]  # [B, V]
        rng, sub = jax.random.split(rng)
        tok0 = sample_token(last, sub, sp)
        done0 = (tok0 == eos_id) if eos_id >= 0 else jnp.zeros((b,), bool)
        slots = jnp.arange(max_len)

        def step(carry, _):
            cache, tok, done, key = carry
            t = cache.write_pos - s  # decode step index (0-based)
            pos = (prompt_lens + t)[:, None]
            kv_mask = (slots[None, :] < prompt_lens[:, None]) | \
                      ((slots >= s) & (slots <= s + t))[None, :]
            # true logical position of each cache slot (prompt slots sit at
            # slot==position; decode slot s+j holds position prompt_len+j) —
            # keeps causality/ALiBi/sliding-window in position space even
            # though ragged padding makes slot != position
            kv_pos = jnp.where(slots[None, :] < s, slots[None, :],
                               prompt_lens[:, None] + (slots[None, :] - s))
            logits, cache = model.decode_step(params, cache, tok[:, None],
                                              positions=pos, kv_mask=kv_mask,
                                              kv_positions=kv_pos)
            if self.config.kv_offload:
                # the carry must stay host-resident between decode steps —
                # without this the first update migrates the whole cache
                # back into HBM
                cache = self._kv_to_host(cache)
            key, sub = jax.random.split(key)
            nxt = sample_token(logits[:, 0], sub, sp)
            if eos_id >= 0:
                nxt = jnp.where(done, pad_id, nxt)
                done = done | (nxt == eos_id)
            return (cache, nxt, done, key), tok

        (_, _, _, _), toks = jax.lax.scan(
            step, (cache, tok0, done0, rng), None, length=max_new_tokens)
        return jnp.swapaxes(toks, 0, 1)  # [B, max_new_tokens]
