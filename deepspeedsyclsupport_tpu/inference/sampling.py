"""Token sampling — greedy, temperature, top-k, top-p (nucleus).

The reference scatters sampling across HF ``generate`` (it never owns the sampler;
``inference/engine.py`` wraps the HF module). The TPU engine owns its jitted decode
loop, so the sampler lives here as pure jnp — one function usable under ``lax.scan``.
"""
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    do_sample: bool = False
    temperature: float = 1.0  # may be a traced scalar under jit
    top_k: int = 0          # 0 = disabled (structural: lax.top_k needs it static)
    top_p: float = 1.0      # 1.0 = disabled; may be a traced scalar under jit

    @property
    def structure(self) -> tuple:
        """The hashable compile-relevant part: ``do_sample``/``top_k`` pick
        branches and shapes; temperature and top_p are data (traceable), so
        one compiled program serves every temperature/top_p — only whether
        top_p filtering runs at all is structural."""
        if not self.do_sample:  # greedy never reads top_k/top_p: one
            return False, 0, False  # structure regardless of incidental knobs
        try:  # any concrete numeric >= 1.0 (int, np scalar, float) disables
            use_top_p = float(self.top_p) < 1.0
        except TypeError:  # traced scalar: filtering must be in the program
            use_top_p = True
        return True, int(self.top_k), use_top_p


def sample_token_dyn(logits: jnp.ndarray, rng: Optional[jax.Array],
                     temperature, top_p, structure) -> jnp.ndarray:
    """:func:`sample_token` with the static/traced split pre-applied:
    ``structure`` is :attr:`SamplingParams.structure` (hashable, jit-static);
    temperature/top_p are runtime operands — sweeping them reuses one
    compiled program."""
    do_sample, top_k, use_top_p = structure
    return sample_token(logits, rng, SamplingParams(
        do_sample, temperature, top_k, top_p if use_top_p else 1.0))


def sample_token(logits: jnp.ndarray, rng: Optional[jax.Array],
                 params: SamplingParams) -> jnp.ndarray:
    """logits [B, V] → token ids [B] (int32).

    ``do_sample`` and ``top_k`` must be concrete (they select program
    structure); ``temperature`` and ``top_p`` may be traced scalars.
    """
    if not params.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(params.temperature, 1e-6)
    if params.top_k and params.top_k > 0:
        kth = jax.lax.top_k(logits, params.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if params.structure[2]:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p (always >= 1 tok)
        keep = cum - probs < params.top_p
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
