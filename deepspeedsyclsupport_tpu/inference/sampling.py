"""Token sampling — greedy, temperature, top-k, top-p (nucleus).

The reference scatters sampling across HF ``generate`` (it never owns the sampler;
``inference/engine.py`` wraps the HF module). The TPU engine owns its jitted decode
loop, so the sampler lives here as pure jnp — one function usable under ``lax.scan``.
"""
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0          # 0 = disabled
    top_p: float = 1.0      # 1.0 = disabled


def sample_token(logits: jnp.ndarray, rng: Optional[jax.Array],
                 params: SamplingParams) -> jnp.ndarray:
    """logits [B, V] → token ids [B] (int32)."""
    if not params.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(params.temperature, 1e-6)
    if params.top_k and params.top_k > 0:
        kth = jax.lax.top_k(logits, params.top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if params.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative mass >= top_p (always >= 1 tok)
        keep = cum - probs < params.top_p
        cutoff = jnp.min(jnp.where(keep, sorted_logits, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
