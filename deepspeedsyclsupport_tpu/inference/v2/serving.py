"""SLA-aware serving policy layer above :class:`InferenceEngineV2`.

The scheduling policy the "Ragged Paged Attention" stack assumes sits above
the paged KV cache (PAPERS.md): the engine below this module is a batch
executor — it will happily admit everyone and let everyone miss deadline
(the r05 SLA bench: 100% miss at 10 clients). This layer makes overload
degrade *gracefully* instead:

* **admission control** — every request carries a deadline budget (TTFT
  bound + decode token-rate SLA, stamped onto its
  :class:`~.ragged.SequenceDescriptor`); an EWMA :class:`CapacityModel` of
  measured prefill tok/s and decode step time projects each arrival's
  completion, and the gate admits, queues, or *sheds* it so that admitting
  never blows the SLA of already-admitted streams;
* **deadline-driven batch composition** — admitted work is ordered by
  slack (:func:`~.scheduler.slack_of`) with starvation aging and a
  per-tenant prefill budget per round (:class:`~.scheduler.SlackPolicy`);
* **overload-graceful eviction** — when the paged KV pool exhausts, the
  lowest-slack stream is preempted (`engine.preempt`: blocks freed,
  request rejected with partial output or requeued) rather than stalling
  the whole batch;
* **dispatch amortization** — whenever every live stream is decoding and
  nothing admissible waits, up to K decode steps fuse into ONE device
  dispatch (``engine._decode_multi_dispatch``), with K capped by the
  slack of the most urgent queued request so fusion never starves TTFT.

Everything here is host-side policy over monotonic time
(``time.perf_counter``); the ``clock`` hook exists so tests drive the
policy with a synthetic clock and capacity model. See ``docs/serving.md``
for the overload-behavior contract and config keys.
"""
import math
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import ServingPolicyConfig
from .kv_cache import kv_pool_stats
from .scheduler import SlackPolicy, slack_of
from ..sampling import SamplingParams
from ...comm.watchdog import SERVE_HANG_EXIT_CODE, CollectiveWatchdog
from ...utils.fault_injection import get_fault_injector
from ...utils.logging import logger

#: ``Serve/*`` metric names this module emits (registered in
#: ``monitor.telemetry.EVENT_NAMES`` so ``DSTPU_STRICT_EVENTS=1`` passes).
SERVE_COUNTERS = ("Serve/admitted", "Serve/queued", "Serve/shed",
                  "Serve/evicted", "Serve/completed")
#: sliding-window SLO burn gauges (request-time attribution,
#: docs/observability.md): TTFT-SLA miss fraction, shed fraction, and
#: max(miss, shed)/error-budget burn rate over ``policy.slo_window_s``
SERVE_SLO_GAUGES = ("Serve/slo.ttft_miss_frac", "Serve/slo.shed_frac",
                    "Serve/slo.burn_rate")
SERVE_GAUGES = ("Serve/queue_depth", "Serve/kv_occupancy",
                "Serve/live_seqs") + SERVE_SLO_GAUGES
#: ``Serve/queue_wait_s`` is the satellite admission→prefill-dispatch wait;
#: the ``Serve/stage.*_s`` pair are the per-request prefill/decode phase
#: self-times observed at close — all surface p50/p95/p99 via
#: :meth:`ServingSession.summary_events` (quantile members are registry-
#: enumerated in ``monitor/telemetry.py``)
SERVE_HISTOGRAMS = ("Serve/ttft_s", "Serve/itl_s", "Serve/queue_wait_s",
                    "Serve/stage.prefill_s", "Serve/stage.decode_s",
                    "Serve/recovery.time_to_recover_s")
#: crash-replay recovery family (``inference/v2/supervisor.py`` — journal
#: replay counters + the stuck-decode watchdog's abort count). Full
#: literals on purpose: the static event-name lint resolves each against
#: the registry.
_RECOVERY_COUNTERS = {"replays": "Serve/recovery.replays",
                      "replay_sheds": "Serve/recovery.replay_sheds"}
SERVE_RECOVERY = (_RECOVERY_COUNTERS["replays"],
                  _RECOVERY_COUNTERS["replay_sheds"],
                  "Serve/recovery.serve_hang_aborts")
#: cross-request prefix cache (``inference/v2/prefix_cache.py`` —
#: docs/serving.md "prefix reuse"). Full literals on purpose: the static
#: event-name lint resolves each against the registry.
_PREFIX_COUNTERS = {"hits": "Serve/prefix.hits",
                    "misses": "Serve/prefix.misses",
                    "tokens_saved": "Serve/prefix.tokens_saved",
                    "blocks_shared": "Serve/prefix.blocks_shared",
                    "cow_copies": "Serve/prefix.cow_copies"}
SERVE_PREFIX = (_PREFIX_COUNTERS["hits"], _PREFIX_COUNTERS["misses"],
                _PREFIX_COUNTERS["tokens_saved"],
                _PREFIX_COUNTERS["blocks_shared"],
                _PREFIX_COUNTERS["cow_copies"],
                "Serve/prefix.hit_ratio", "Serve/prefix.pinned_blocks")
SERVE_EVENT_NAMES = (SERVE_COUNTERS + SERVE_GAUGES + SERVE_HISTOGRAMS
                     + SERVE_RECOVERY + SERVE_PREFIX)


class Ewma:
    """Exponentially-weighted moving average seeded with a prior; the first
    measured sample replaces the prior outright (a prior is a guess, not
    data — blending it in would drag measurements toward it for ~1/alpha
    samples)."""

    __slots__ = ("value", "alpha", "samples")

    def __init__(self, prior: float, alpha: float = 0.25):
        self.value = float(prior)
        self.alpha = float(alpha)
        self.samples = 0

    def update(self, x: float) -> float:
        x = float(x)
        self.value = x if self.samples == 0 else \
            (1.0 - self.alpha) * self.value + self.alpha * x
        self.samples += 1
        return self.value


class CapacityModel:
    """Measured service capacity: prefill tokens/s and decode seconds/step.

    The engine's forwards are shape-padded (every decode dispatch computes
    ``max_sequences`` slots), so decode step time is close to
    occupancy-independent — one EWMA per quantity captures it; the
    admission gate multiplies by ``sla_headroom`` instead of modelling the
    residual occupancy sensitivity.
    """

    def __init__(self, prefill_tok_s: float = 1000.0,
                 decode_step_s: float = 0.05, alpha: float = 0.25):
        self._prefill = Ewma(prefill_tok_s, alpha)
        self._step = Ewma(decode_step_s, alpha)
        # best-case (least-loaded) rates ever measured: what an IDLE engine
        # delivers. The EWMA deliberately folds queueing delay in (that is
        # the backpressure signal), which makes it an over-estimate of
        # service time on an empty engine — and once everything is shed no
        # new samples arrive, so gating an idle engine on the loaded EWMA
        # is an absorbing shed-everything state.
        self._prefill_best = 0.0
        self._step_best = math.inf

    # ------------------------------------------------------------- recording
    def record_prefill(self, tokens: int, seconds: float) -> None:
        if tokens > 0 and seconds > 0:
            sample = tokens / seconds
            rate = self._prefill.update(sample)
            # best only rises when the smoothed rate supports the sample:
            # one spuriously fast outlier must not pin the idle-engine
            # projection optimistic forever
            self._prefill_best = max(self._prefill_best, min(rate, sample))

    def record_decode(self, steps: int, seconds: float) -> None:
        if steps > 0 and seconds > 0:
            sample = seconds / steps
            step = self._step.update(sample)
            # symmetric outlier guard (see record_prefill)
            self._step_best = min(self._step_best, max(step, sample))

    # ------------------------------------------------------------- estimates
    @property
    def prefill_tok_s(self) -> float:
        return max(self._prefill.value, 1e-9)

    @property
    def prefill_tok_s_best(self) -> float:
        """Best-case prefill rate: for projecting service on an idle
        engine (falls back to the EWMA/prior before any measurement)."""
        return max(self._prefill_best, self.prefill_tok_s)

    @property
    def decode_step_s(self) -> float:
        return max(self._step.value, 1e-9)

    @property
    def decode_step_s_best(self) -> float:
        return max(min(self._step_best, self.decode_step_s), 1e-9)

    @property
    def decode_tok_s(self) -> float:
        """Per-stream decode rate (1 token per live stream per step)."""
        return 1.0 / self.decode_step_s

    @property
    def decode_tok_s_best(self) -> float:
        return 1.0 / self.decode_step_s_best

    def prefill_eta_s(self, tokens: int, best: bool = False) -> float:
        return tokens / (self.prefill_tok_s_best if best
                         else self.prefill_tok_s)


@dataclass
class ServeEvent:
    """One observable serving outcome, stamped on the session clock.

    kinds: ``token`` (``tokens`` delivered at ``t``; a fused dispatch
    delivers several at once), ``finish`` (reason: done|eos|context|
    evicted), ``shed`` (admission rejected the request; reason names why),
    ``evict`` (KV-pressure preemption; reason: reject|requeue).
    """

    kind: str
    uid: int
    t: float
    tokens: List[int] = field(default_factory=list)
    reason: str = ""


@dataclass
class _Request:
    uid: int
    tokens: List[int]
    max_new_tokens: int
    tenant: str
    arrival_s: float
    deadline_s: Optional[float]
    rate_sla: float
    budget: int = 0                 # remaining new-token budget
    out: List[int] = field(default_factory=list)  # emitted tokens (requeue)
    enqueue_s: float = 0.0          # when the prompt entered the engine
    queued_s: float = 0.0           # when it (last) entered the queue
    cached_prefix_len: int = 0      # prefix-cache hit at (last) activation
    preempted: bool = False         # next activation is a requeue, not fresh
    #: ``tokens`` stays the ORIGINAL prompt forever; a requeued stream's
    #: context is rebuilt as tokens + out at activation (mutating tokens
    #: would duplicate the partial output on a second eviction)

    @property
    def n_prefill(self) -> int:
        """Tokens a (re)admission must prefill: prompt + emitted prefix."""
        return len(self.tokens) + len(self.out)
    first_token_s: Optional[float] = None
    last_emit_s: Optional[float] = None


class ServingSession:
    """Drives one engine under the SLA policy; the serving loop an MII-style
    frontend (or ``bench.py``'s closed-loop clients) sits on.

    ``submit()`` is the admission gate; ``step()`` runs one scheduling
    round — queue maintenance, slack-ordered batch composition, fused or
    per-token dispatch, KV-pressure eviction — and returns the round's
    :class:`ServeEvent` stream. The caller owns pacing (when to call
    ``step``) and delivery; the session owns policy.
    """

    def __init__(self, engine, policy: Optional[ServingPolicyConfig] = None,
                 *, clock: Callable[[], float] = time.perf_counter,
                 capacity: Optional[CapacityModel] = None,
                 sampling: Optional[SamplingParams] = None,
                 eos_token_id: Optional[int] = None,
                 rng: Optional[jax.Array] = None,
                 journal: Any = None, watchdog: Any = None):
        self.eng = engine
        self.policy = policy or ServingPolicyConfig()
        self.clock = clock
        self.capacity = capacity or CapacityModel(
            self.policy.prefill_tok_s_prior, self.policy.decode_step_s_prior,
            self.policy.ewma_alpha)
        self.sampling = sampling or SamplingParams()
        self.eos_token_id = eos_token_id
        self.queue: List[_Request] = []
        self.running: Dict[int, _Request] = {}
        self.counters: Dict[str, int] = {
            "admitted": 0, "queued": 0, "shed": 0, "evicted": 0,
            "completed": 0}
        #: crash-replay recovery accounting (``Serve/recovery.*`` family)
        self.recovery_counters: Dict[str, int] = {"replays": 0,
                                                  "replay_sheds": 0}
        self._pending_tok: Dict[int, int] = {}  # sampled, not yet submitted
        self._last_decode_s: Optional[float] = None
        self._round = 0            # scheduling rounds (watchdog step label)
        self._tokens_emitted = 0   # serve_crash fault trigger input
        self._stall_rounds = 0     # consecutive no-progress rounds
        # request-time attribution (monitor/reqtrace.py; docs/
        # observability.md): lifecycle-edge records mirrored into a bounded
        # in-memory ring so bench load points join per-request waterfalls
        # with zero disk IO in the measured path (the journal — when
        # configured — carries the same records durably). The fixed wall
        # offset maps this session's monotonic clock onto the journal's
        # wall stamps: every record rides ONE clock base, so the offline
        # join can order router and replica streams together.
        self._tracing = bool(self.policy.trace_stages)
        self.trace_log: deque = deque(maxlen=65536)
        self._wall0 = time.time() - self.clock()  # dslint: allow(wall-clock-in-step-path)
        # SLO burn accounting (Serve/slo.* gauges): sliding windows of
        # (t, first-token-met-SLA) and (t, outcome-was-shed) samples
        self._slo_ttft: deque = deque()
        self._slo_gate: deque = deque()
        self._rng = rng if rng is not None else \
            jax.random.PRNGKey(engine.config.seed + 1)
        # cross-request prefix reuse (docs/serving.md "prefix reuse"): the
        # policy owns the knobs, the engine owns the cache — installing is
        # idempotent, so a recovered session reuses the warm index
        pc_cfg = self.policy.prefix_cache
        if pc_cfg and pc_cfg.get("enabled", True):
            engine.install_prefix_cache(
                scope=pc_cfg.get("scope", "tenant"),
                min_block_hits=int(pc_cfg.get("min_block_hits", 1)),
                max_pinned_blocks=pc_cfg.get("max_pinned_blocks"))
        # registry counters are monotone increments; the cache keeps plain
        # totals — this snapshot turns totals into deltas at flush time
        self._prefix_reported: Dict[str, int] = {}
        if self.policy.telemetry:
            from ...monitor.telemetry import metrics_registry as _mr

            self._metrics = _mr
        else:
            self._metrics = None
        # request journal: in-flight state survives the process (see
        # docs/serving.md "failure contract"); caller-provided instance
        # wins over the config path
        if journal is None and self.policy.journal_path:
            from .supervisor import RequestJournal

            journal = RequestJournal(self.policy.journal_path)
        self.journal = journal
        # stuck-decode watchdog: the collective watchdog's machinery with
        # the serving contract's names — rc 219, serve_hang_aborts, and
        # serve/arm-serve/hang deadline records into the journal stream
        if watchdog is None and self.policy.watchdog_enabled:
            watchdog = CollectiveWatchdog(
                deadline_s=self.policy.watchdog_deadline_s,
                warmup_deadline_s=self.policy.watchdog_warmup_deadline_s,
                poll_s=self.policy.watchdog_poll_s,
                telemetry=self.journal,
                exit_code=SERVE_HANG_EXIT_CODE,
                abort_counter="serve_hang_aborts",
                arm_name="serve/arm", hang_name="serve/hang",
                what="serving decode").start()
        self.watchdog = watchdog

    def close(self) -> None:
        """Stop the watchdog poller and close the journal stream.
        Idempotent; live/queued requests stay journaled as in-flight (the
        truthful state for a replica being stopped mid-serve)."""
        if self.watchdog is not None:
            try:
                self.watchdog.stop()
            except Exception:  # teardown must never raise out of serving
                pass
        if self.journal is not None:
            self.journal.close()

    # ----------------------------------------------- request-time attribution
    def _trace(self, name: str, t: float, data: Dict[str, Any]) -> None:
        """Mirror one lifecycle record (journal-record shape) into the
        in-memory ring, stamped on the session-clock→wall mapping."""
        if self._tracing:
            self.trace_log.append(
                {"name": name, "t": t + self._wall0, "data": data})

    def _stage(self, uid: int, stage: str, t: float,
               dur: Optional[float] = None, **data: Any) -> None:
        """``serve/stage`` lifecycle edge: in-memory ring always (when
        tracing), journal stream when one is configured — same record, one
        clock base, no second transport."""
        if not self._tracing:
            return
        payload = {"uid": int(uid), "stage": stage,
                   **({"dur": float(dur)} if dur is not None else {}),
                   **data}
        self.trace_log.append(
            {"name": "serve/stage", "t": t + self._wall0, "data": payload})
        if self.journal is not None:
            self.journal.stage(uid, stage, dur=dur, **data)

    def note_stage(self, uid: int, stage: str,
                   dur: Optional[float] = None, **data: Any) -> None:
        """Public stamping hook for the owning loop (``serve_worker``
        stamps ``spool_wait`` through this; a future RPC front-end stamps
        its ingress edge the same way)."""
        self._stage(uid, stage, self.clock(), dur=dur, **data)

    def drain_trace(self) -> List[Dict[str, Any]]:
        """Hand over and clear the in-memory lifecycle records — the bench
        rungs drain per load point so each point's waterfall joins only its
        own requests."""
        out = list(self.trace_log)
        self.trace_log.clear()
        return out

    def export_metrics(self, path: str) -> Optional[str]:
        """Prometheus textfile snapshot of the session's registry (atomic
        rename — the training exporter's contract). No-op without
        telemetry."""
        if self._metrics is None:
            return None
        from ...monitor.telemetry import export_metrics_textfile

        return export_metrics_textfile(path, self._metrics.snapshot())

    def _slo_snapshot(self, now: float) -> Tuple[float, float, float]:
        """(ttft_miss_frac, shed_frac, burn_rate) over the trailing
        ``policy.slo_window_s`` window. Burn is the worse of the two miss
        fractions priced against the error budget: burn > 1 means the SLO
        budget is being spent faster than it accrues."""
        horizon = now - self.policy.slo_window_s
        for dq in (self._slo_ttft, self._slo_gate):
            while dq and dq[0][0] < horizon:
                dq.popleft()
        miss = (1.0 - sum(1 for _, ok in self._slo_ttft if ok)
                / len(self._slo_ttft)) if self._slo_ttft else 0.0
        shed = (sum(1 for _, s in self._slo_gate if s)
                / len(self._slo_gate)) if self._slo_gate else 0.0
        burn = max(miss, shed) / max(self.policy.slo_budget, 1e-9)
        return miss, shed, burn

    # ------------------------------------------------------------- admission
    def submit(self, uid: int, tokens: Sequence[int], max_new_tokens: int,
               *, tenant: str = "default", now: Optional[float] = None,
               ttft_sla_s: Optional[float] = None,
               rate_sla: Optional[float] = None) -> str:
        """Admission gate. Returns ``"admitted"`` (prompt enqueued for the
        next round), ``"queued"`` (held; re-evaluated every round), or
        ``"shed"`` (rejected now — the graceful-overload answer: the client
        learns in O(1) instead of timing out)."""
        if not tokens:
            raise ValueError("cannot serve an empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if uid in self.running or uid in self.eng.seqs \
                or any(r.uid == uid for r in self.queue):
            raise ValueError(f"uid {uid} is already being served")
        now = self.clock() if now is None else now
        ttft = ttft_sla_s if ttft_sla_s is not None else self.policy.ttft_sla_s
        req = _Request(
            uid=uid, tokens=list(tokens), max_new_tokens=int(max_new_tokens),
            tenant=tenant, arrival_s=now,
            deadline_s=(now + ttft) if ttft is not None else None,
            rate_sla=(rate_sla if rate_sla is not None
                      else self.policy.token_rate_sla),
            budget=int(max_new_tokens), queued_s=now)
        decision = self._gate(req, now, ahead_tokens=self._queued_tokens())
        if decision == "admit" and self.queue:
            # no leapfrogging: a new arrival must not take a freed slot
            # ahead of older queued requests — it joins the queue, which
            # _maintain_queue re-gates in deadline order every round (an
            # urgent arrival still legitimately outranks laxer ones there)
            decision = "queue"
        # gate-verdict edge: the ONLY trace a shed-at-submit request leaves
        # (terminal sheds are never journaled as admits), so the waterfall
        # still counts and names them
        self._stage(uid, "gate", now, verdict=decision,
                    n_prompt=len(req.tokens))
        self._slo_gate.append((now, decision == "shed"))
        if decision == "shed":
            # terminal at submit: the caller learns synchronously, nothing
            # is in flight — so nothing to journal
            self._count("shed")
            return "shed"
        if self.journal is not None:
            # journaled BEFORE any token can be produced: from here the
            # request is in flight and must survive the process
            self.journal.admit(uid, req.tokens, req.max_new_tokens,
                               tenant=req.tenant, rate_sla=req.rate_sla,
                               ttft_sla_s=ttft)
        self._trace("serve/admit", now, {
            "uid": int(uid), "n_tokens": len(req.tokens),
            "max_new_tokens": req.max_new_tokens, "tenant": req.tenant,
            "rate_sla": req.rate_sla,
            **({"ttft_sla_s": float(ttft)} if ttft is not None else {})})
        if decision == "admit":
            self._activate(req, now)
            return "admitted"
        self.queue.append(req)
        self._count("queued")
        return "queued"

    def replay(self, uid: int, tokens: Sequence[int], max_new_tokens: int,
               *, emitted_tokens: Sequence[int] = (),
               tenant: str = "default", rate_sla: Optional[float] = None,
               now: Optional[float] = None) -> str:
        """Re-admit a journaled in-flight request from its emitted-token
        watermark after an engine death (``supervisor.recover_requests``).

        The TTFT deadline is burned (the first token — if any — was
        delivered in a previous incarnation), so the gate re-projects on
        the **rate SLA only**, exactly like PR 4's requeue path; the
        context is rebuilt as prompt + emitted prefix at activation, so
        the stream continues from the watermark with zero duplicate
        tokens. Returns ``"replayed"`` (re-admitted or queued),
        ``"shed"`` (provably unmeetable — terminal, counted under
        ``Serve/recovery.replay_sheds``), or ``"completed"`` (the crash
        landed between the final emit and the close record — the output
        was already fully delivered)."""
        if not tokens:
            raise ValueError("cannot replay an empty prompt")
        if uid in self.running or uid in self.eng.seqs \
                or any(r.uid == uid for r in self.queue):
            raise ValueError(f"uid {uid} is already being served")
        now = self.clock() if now is None else now
        out = [int(t) for t in emitted_tokens]
        rate = (rate_sla if rate_sla is not None
                else self.policy.token_rate_sla)
        if len(out) >= max_new_tokens:
            # fully delivered before the crash; only the close record was
            # lost — re-journal the final state (admit carrying the full
            # prefix, so THIS incarnation's journal is self-contained)
            # plus the missing close, and the next recovery skips the uid
            self._count("completed")
            if self.journal is not None:
                self.journal.admit(uid, tokens, max_new_tokens,
                                   tenant=tenant, rate_sla=rate, out=out,
                                   replayed=True)
                self.journal.close_request(uid, "done")
            self._trace("serve/admit", now, {
                "uid": int(uid), "n_tokens": len(tokens),
                "max_new_tokens": int(max_new_tokens), "tenant": tenant,
                "replayed": True, "watermark": len(out)})
            self._trace("serve/close", now,
                        {"uid": int(uid), "reason": "done"})
            return "completed"
        req = _Request(
            uid=uid, tokens=[int(t) for t in tokens],
            max_new_tokens=int(max_new_tokens), tenant=tenant,
            arrival_s=now, deadline_s=None, rate_sla=rate,
            budget=int(max_new_tokens) - len(out), out=out, queued_s=now)
        if out:
            # decode phase: slack scoring and the admission gate must see
            # the first token as delivered (see _activate's same rule for
            # requeued streams)
            req.first_token_s = now
        # replay gate: rate SLA only, and against the BEST-CASE (idle-
        # engine) measured rate — the replay set was running together
        # before the crash, so it is proven placeable; _gate's loaded-EWMA
        # heuristic would shed every replay after the first one re-fills
        # the engine. "Provably unmeetable" here means even an idle engine
        # cannot deliver the rate.
        # a replayed context is prime prefix-cache material: the donor
        # incarnation's committed blocks (or a sibling stream's) make the
        # re-prefill a block-table copy up to the first uncached token
        decision = "admit" if uid in self.eng.check_schedule(
            [uid], [req.n_prefill],
            cached_prefix={uid: self._peek_prefix(req)}).admitted \
            else "queue"
        if self.policy.admission != "none" and req.rate_sla > 0 \
                and self.capacity.decode_tok_s_best \
                < self.policy.rate_feasibility_margin * req.rate_sla:
            decision = "shed"
        if decision == "shed":
            self._count("shed")
            self._count_recovery("replay_sheds")
            if self.journal is not None:
                self.journal.close_request(uid, "replay_shed")
            self._trace("serve/close", now,
                        {"uid": int(uid), "reason": "replay_shed"})
            return "shed"
        if self.journal is not None:
            self.journal.admit(uid, req.tokens, req.max_new_tokens,
                               tenant=tenant, rate_sla=rate, out=out,
                               replayed=True)
        self._trace("serve/admit", now, {
            "uid": int(uid), "n_tokens": len(req.tokens),
            "max_new_tokens": req.max_new_tokens, "tenant": tenant,
            "rate_sla": rate, "replayed": True, "watermark": len(out)})
        # replay-segment edge: the survivor side of a failover-spanning
        # trace (generation/incarnation carried by the journal filename)
        self._stage(uid, "replay", now, watermark=len(out))
        self._count_recovery("replays")
        if decision == "admit" and not self.queue:
            self._activate(req, now)
        else:
            self.queue.append(req)
            self._count("queued")
        return "replayed"

    def _peek_prefix(self, req: _Request) -> int:
        """Cached-prefix length for ``req``'s full context, side-effect
        free (no counters, no recency) — the gate prices prefill at the
        NOVEL tokens only; the request may still be shed."""
        pc = self.eng.prefix_cache
        if pc is None:
            return 0
        return pc.peek(req.tokens + req.out, req.tenant)

    def _gate(self, req: _Request, now: float, ahead_tokens: int = 0) -> str:
        """admit | queue | shed for one request against the capacity model
        and the engine's structural limits. Prefill cost — both the KV
        block demand and the TTFT projection — is priced at
        ``n_prefill − cached_prefix_len``: a cached prefix is a
        block-table copy, not a forward."""
        cached = self._peek_prefix(req)
        res = self.eng.check_schedule([req.uid], [req.n_prefill],
                                      cached_prefix={req.uid: cached})
        structural_ok = req.uid in res.admitted
        if self.policy.admission == "none":
            return "admit" if structural_ok else "queue"
        # an IDLE engine projects at the best-case (least-loaded) measured
        # rates: the EWMA folds queueing delay in (the backpressure signal
        # while streams run), so after a shed-heavy phase empties the
        # engine it over-states service time — and with nothing admitted
        # no new samples would ever correct it (shed-everything lock-in)
        idle = not self.running
        # rate feasibility: a per-stream decode rate the hardware CLEARLY
        # cannot deliver is never meetable — admitting would only push the
        # already admitted streams' ITL over their SLA too. Margin < 1, not
        # headroom > 1: the EWMA breathes under load, and shedding the
        # whole fleet over a few-percent reading is the opposite of
        # graceful (TTFT projection is the overload valve)
        decode_rate = (self.capacity.decode_tok_s_best if idle
                       else self.capacity.decode_tok_s)
        if req.rate_sla > 0 and decode_rate \
                < self.policy.rate_feasibility_margin * req.rate_sla:
            return "shed"
        # TTFT projection only gates requests that have not started: a
        # requeued (evicted mid-decode) stream already delivered its first
        # token — its TTFT deadline is long past and meaningless; what it
        # must still sustain is the rate SLA, checked above
        if req.deadline_s is not None and req.first_token_s is None:
            slot_wait = 0.0 if structural_ok else self._slot_wait_s()
            eta = self.policy.sla_headroom * self.capacity.prefill_eta_s(
                self._prefill_backlog_tokens() + ahead_tokens
                + req.n_prefill - cached, best=idle)
            if now + slot_wait + eta > req.deadline_s:
                return "shed"
        if not structural_ok:
            return "queue" if self.policy.shed_policy == "queue" else "shed"
        return "admit"

    def _activate(self, req: _Request, now: float) -> None:
        """Hand the admitted request to the engine: descriptor created with
        its SLA budget BEFORE the first scheduler pass, prompt enqueued —
        the actual forwards run inside :meth:`step`."""
        d = self.eng.ensure_seq(
            req.uid, arrival_s=req.arrival_s, deadline_s=req.deadline_s,
            rate_sla=req.rate_sla, tenant=req.tenant,
            target_new_tokens=req.max_new_tokens, emitted=len(req.out),
            # a requeued stream keeps its first-token stamp: without it
            # slack_of scores the re-prefill against the long-expired TTFT
            # deadline (hugely negative slack) and the slack eviction
            # policies re-victimize the very stream we chose to resume
            first_token_s=req.first_token_s)
        # probe the prefix cache with the FULL context (prompt + emitted
        # prefix): an admission, a requeue after eviction and a crash
        # replay all re-enter here, so all three skip straight to the
        # first uncached token when the blocks are still indexed
        ctx = [int(t) for t in req.tokens] + [int(t) for t in req.out]
        cached = self.eng.map_cached_prefix(req.uid, ctx)
        d.pending.extend(ctx[cached:])
        d.last_logits = None
        req.enqueue_s = now
        req.cached_prefix_len = cached
        # queue-wait edge (admission→prefill dispatch; the prompt fuses
        # into the very next forward). A preemption-requeue re-enters here
        # as requeue_wait so the waterfall separates first-admission queue
        # time from re-admission backoff; both waits land in the satellite
        # Serve/queue_wait_s histogram.
        wait = max(0.0, now - req.queued_s)
        self._observe("Serve/queue_wait_s", wait)
        self._stage(req.uid,
                    "requeue_wait" if req.preempted else "queue_wait",
                    now, dur=wait, cached_prefix_len=cached,
                    novel_tokens=len(ctx) - cached)
        req.preempted = False
        self.running[req.uid] = req
        self._count("admitted")

    # --------------------------------------------------------- projections
    def _prefill_backlog_tokens(self) -> int:
        return sum(len(d.pending) for d in self.eng.seqs.values())

    def _queued_tokens(self) -> int:
        return sum(r.n_prefill for r in self.queue)

    def _slot_wait_s(self) -> float:
        """Earliest a slot/KV frees: the closest-to-done running stream's
        remaining tokens at the measured step time (∞ when nothing runs —
        structurally stuck)."""
        if not self.running:
            return math.inf
        rem = min(r.budget for r in self.running.values())
        return rem * self.capacity.decode_step_s

    def _slack_policy(self, now: float) -> SlackPolicy:
        return SlackPolicy(
            now=now, prefill_tok_s=self.capacity.prefill_tok_s,
            decode_tok_s=self.capacity.decode_tok_s,
            aging_weight=self.policy.aging_weight,
            tenant_budget=self.policy.tenant_token_budget)

    # -------------------------------------------------------------- stepping
    def step(self, now: Optional[float] = None) -> List[ServeEvent]:
        """One scheduling round; returns the round's event stream (possibly
        empty — e.g. nothing live and nothing admissible).

        The round's device dispatches run inside an armed stuck-decode
        watchdog window (``policy.watchdog_enabled``): a dispatch that
        never returns becomes a faulthandler dump + journal flush +
        ``os._exit(219)`` — the serving twin of the rc-218 collective-hang
        contract — instead of a silent forever-hang the supervisor can
        only guess at."""
        now = self.clock() if now is None else now
        self._round += 1
        injector = get_fault_injector()
        rc = injector.should_serve_crash(self._round, self._tokens_emitted)
        if rc is not None:
            # a hard crash by definition: no journal close, no flush — the
            # per-record journal durability is what recovery rides
            logger.error("fault injection: serving process crashing "
                         "mid-decode (round %d, %d tokens emitted, rc=%d)",
                         self._round, self._tokens_emitted, rc)
            os._exit(rc)
        events: List[ServeEvent] = []
        self._maintain_queue(now, events)
        self.eng.slack_policy = self._slack_policy(now)
        # arm only when the round has work: an idle poll (the natural
        # serving-loop pattern while awaiting the first request) must not
        # consume the one-shot warmup allowance — the first REAL round
        # compiles prefill + sampler + fused rungs and needs it
        wd = self.watchdog if (self.running or self.queue) else None
        if wd is not None:
            wd.arm(self._round)
        dispatches0 = self.eng.host_dispatches
        try:
            # decode_wedge lands HERE — after arming, inside the watched
            # window — so the injected stall is exactly the hang the
            # watchdog exists to convert into rc 219
            injector.maybe_wedge_decode(self._round)
            fused = self._can_fuse() and self._fused_round(now, events)
            if not fused:
                self._per_token_round(now, events)
        finally:
            # disarm in a finally: an exception mid-round must not leave
            # the deadline live to rc-219 the process during ordinary
            # error handling (the PR 6 watchdog lesson)
            if wd is not None:
                wd.disarm(self._round)
            self.eng.slack_policy = None
        self._note_progress(events, dispatches0, now)
        self._flush_gauges(now)
        return events

    def _note_progress(self, events: List[ServeEvent], dispatches0: int,
                       now: float) -> None:
        """Structured backpressure valve: a round with live streams that
        neither emitted an event nor dispatched anything is a wedged batch
        (KV pool exhausted with the remaining holders un-evictable, an
        injected ``kv_alloc_fail`` streak, allocator drift). After
        ``stall_patience_rounds`` such rounds the lowest-slack stream is
        preempted — requeued or rejected-with-partial-output per
        ``preempt_policy`` — so the batch un-wedges through the session's
        own event stream instead of an exception (or a caller's stall
        guard) killing the serving loop."""
        if events or self.eng.host_dispatches != dispatches0 \
                or not self.running:
            self._stall_rounds = 0
            return
        self._stall_rounds += 1
        if self._stall_rounds < self.policy.stall_patience_rounds:
            return
        self._stall_rounds = 0
        victim = self._eviction_victim(now)
        if victim is None:
            # no block-holding stream: fall back to lowest slack outright
            # (its re-prefill is the cheapest to redo)
            victim = min(self.running, key=lambda u: (
                slack_of(self.eng.seqs[u], now, self.capacity.prefill_tok_s,
                         self.capacity.decode_tok_s)
                if u in self.eng.seqs else 0.0))
        logger.warning("serving session: %d no-progress rounds with %d "
                       "live stream(s) — preempting uid %d to un-wedge "
                       "the batch", self.policy.stall_patience_rounds,
                       len(self.running), victim)
        self._evict(victim, now, events)

    def _maintain_queue(self, now: float, events: List[ServeEvent]) -> None:
        """Shed queued requests that aged out or became unmeetable; admit
        (in slack order) the ones the gate now accepts."""
        if not self.queue:
            return
        self.queue.sort(key=lambda r: (r.deadline_s is None,
                                       r.deadline_s or 0.0, r.arrival_s))
        kept: List[_Request] = []
        ahead = 0
        for req in self.queue:
            if now - req.queued_s > self.policy.max_queue_s:
                self._drop_queued(req, now, events, "queue timeout")
                continue
            decision = self._gate(req, now, ahead_tokens=ahead)
            if decision == "admit":
                self._activate(req, now)
            elif decision == "shed" and self.policy.admission != "none":
                self._drop_queued(req, now, events, "deadline unmeetable")
            else:
                kept.append(req)
                ahead += req.n_prefill
        self.queue = kept

    def _drop_queued(self, req: _Request, now: float,
                     events: List[ServeEvent], reason: str) -> None:
        """Terminal shed of a queued request. A requeued stream that
        already delivered tokens gets a ``finish`` (reason ``evicted``,
        partial output) instead of a bare ``shed`` — callers tracking
        completion must see closure for a request they received tokens
        from (one terminal event either way, never both)."""
        self._count("shed")
        self._slo_gate.append((now, True))
        close_reason = ("evicted" if req.first_token_s is not None
                        else f"shed:{reason}")
        if self.journal is not None:
            self.journal.close_request(req.uid, close_reason)
        self._trace("serve/close", now,
                    {"uid": int(req.uid), "reason": close_reason})
        if req.first_token_s is not None:
            events.append(ServeEvent("finish", req.uid, now,
                                     reason="evicted"))
        else:
            events.append(ServeEvent("shed", req.uid, now, reason=reason))

    # --------------------------------------------------------- fused decode
    def _can_fuse(self) -> bool:
        """Steady state: every live stream is decoding with fresh logits and
        nothing admissible is waiting (queue heads were just re-gated by
        :meth:`_maintain_queue`) — the fused K-step program applies even
        below full occupancy."""
        if self.eng.config.decode_steps_per_dispatch <= 1 or not self.running:
            return False
        if self._pending_tok:
            return False  # a sampled-but-unsubmitted token must ship first
        for uid, req in self.running.items():
            d = self.eng.seqs.get(uid)
            if d is None or d.pending or d.last_logits is None:
                return False
            if req.first_token_s is None:
                # a just-drained prefill must deliver its first token NOW
                # (one per-token round), not after a whole K-step device
                # loop — fusing here would bake K*step_time into TTFT
                return False
        return True

    def _k_cap(self, now: float) -> Optional[int]:
        """Bound the fused dispatch so a queued request with little TTFT
        slack is not starved behind a long device loop: K ≤ that slack in
        decode steps (the ladder in the engine rounds it down)."""
        cap: Optional[int] = None
        for req in self.queue:
            if req.deadline_s is None:
                continue
            slack = (req.deadline_s - now
                     - self.capacity.prefill_eta_s(req.n_prefill))
            k = int(slack / self.capacity.decode_step_s)
            cap = k if cap is None else min(cap, k)
        return None if cap is None else max(2, cap)

    def _fused_round(self, now: float, events: List[ServeEvent]) -> bool:
        budgets = {u: self.running[u].budget for u in self.running}
        self._rng, sub = jax.random.split(self._rng)
        emitted = self.eng._decode_multi_dispatch(
            budgets, self.sampling, self.eos_token_id, sub,
            k_cap=self._k_cap(now))
        if emitted is None:
            return False  # KV pool can't pre-fund ≥2 steps → per-token path
        t1 = self.clock()
        steps = max((len(v) for v in emitted.values()), default=0)
        self.capacity.record_decode(steps, t1 - now)
        self._last_decode_s = t1
        # one record per scheduling round (uid −1 = session scope; the
        # scheduled uids ride in data) — per-uid stamps here would double
        # the journal volume for no join benefit
        self._stage(-1, "decode_round", t1, dur=t1 - now, mode="fused",
                    k=steps, uids=sorted(emitted))
        for uid, toks in emitted.items():
            req = self.running[uid]
            req.budget -= len(toks)
            if toks:
                events.append(ServeEvent("token", uid, t1, tokens=list(toks)))
                self._note_emission(req, toks, t1)
            if uid not in budgets:  # retired on device; engine flushed it
                reason = ("eos" if (toks and self.eos_token_id is not None
                                    and toks[-1] == self.eos_token_id)
                          else ("done" if req.budget <= 0 else "context"))
                self._finish(uid, t1, events, reason, flush=False)
            else:
                req.budget = budgets[uid]  # authoritative (device counted)
        return True

    # ------------------------------------------------------ per-token round
    def _per_token_round(self, now: float, events: List[ServeEvent]) -> None:
        eng = self.eng
        sp = self.sampling
        # 1. one batched device sample over every drained stream
        drained: List[Tuple[int, jax.Array]] = []
        for uid in list(self.running):
            if uid in self._pending_tok:
                continue
            lg = eng.query(uid)
            if lg is not None:
                drained.append((uid, lg))
        if drained:
            self._rng, sub = jax.random.split(self._rng)
            toks = np.asarray(eng._sample_fn(
                jnp.stack([lg for _, lg in drained]), sub,
                jnp.float32(sp.temperature), jnp.float32(sp.top_p),
                sp.structure))
            eng.host_dispatches += 1  # the sampler is a dispatch too
            t1 = self.clock()
            if self._last_decode_s is not None:
                self.capacity.record_decode(1, t1 - self._last_decode_s)
            self._last_decode_s = t1
            self._stage(-1, "decode_round", t1, dur=t1 - now,
                        mode="per_token",
                        uids=sorted(u for u, _lg in drained))
            for (uid, _lg), tok in zip(drained, toks):
                tok = int(tok)
                req = self.running[uid]
                events.append(ServeEvent("token", uid, t1, tokens=[tok]))
                self._note_emission(req, [tok], t1)
                req.budget -= 1
                d = eng.seqs[uid]
                d.emitted += 1
                done = (req.budget <= 0
                        or (self.eos_token_id is not None
                            and tok == self.eos_token_id)
                        or d.n_cached >= eng.config.max_context)
                if done:
                    reason = ("eos" if (self.eos_token_id is not None
                                        and tok == self.eos_token_id)
                              else ("done" if req.budget <= 0 else "context"))
                    self._finish(uid, t1, events, reason)
                else:
                    self._pending_tok[uid] = tok
        else:
            self._last_decode_s = None  # no decode this round: break the
            #                             ITL chain across prefill-only gaps
        # 2. KV pressure: preempt the lowest-slack stream until the decode
        # tokens fit (never stall the whole batch on an exhausted pool)
        put_uids = list(self._pending_tok)
        while put_uids:
            res = eng.check_schedule(put_uids, [1] * len(put_uids))
            if not any(res.reasons.get(u, "").startswith("kv")
                       for u in res.rejected):
                break
            victim = self._eviction_victim(now)
            if victim is None:
                break
            self._evict(victim, now, events)
            put_uids = [u for u in put_uids if u != victim]
        # 3. submit: decode tokens + (slack-ordered, tenant-capped) prompt
        # chunks fuse into the same forward inside put()
        if put_uids or any(d.pending for d in eng.seqs.values()):
            t0 = self.clock()
            pend0 = ({u: len(d.pending) for u, d in eng.seqs.items()
                      if d.pending} if self._tracing else {})
            res = eng.put(put_uids, [[self._pending_tok[u]] for u in put_uids],
                          drain=False)
            for uid in res.admission.admitted:
                self._pending_tok.pop(uid, None)
            t1 = self.clock()
            # prefill-chunk edges: which uids advanced their prompt this
            # forward and by how many tokens (dur is the whole mixed
            # forward's wall — chunks share the dispatch, annotation only)
            for u, n0 in pend0.items():
                d = eng.seqs.get(u)
                n1 = len(d.pending) if d is not None else 0
                if n1 < n0:
                    self._stage(u, "prefill_chunk", t1, dur=t1 - t0,
                                tokens=n0 - n1)
            # first-token landings this pass: prefill capacity samples.
            # DELIBERATELY enqueue-to-first-token per request, not raw
            # forward throughput: the sample folds in the scheduling delay
            # a prompt experiences at the CURRENT concurrency, so the rate
            # sinks as load rises and the admission gate tightens — the
            # closed-loop backpressure that keeps admitted streams inside
            # their SLA under overload. A per-forward throughput sample
            # (budget tokens / forward time) reads ~constant regardless of
            # how many streams share the budget; gating on it admits far
            # past capacity and every admitted stream goes borderline-miss
            # (measured: 25-client shed 80%→28%, goodput 76→9 tok/s).
            # (a uid drained this round has first_token_s set by
            # _note_emission, so only freshly-landed prefills sample here)
            for uid, req in self.running.items():
                if req.first_token_s is None and eng.query(uid) is not None:
                    self.capacity.record_prefill(len(req.tokens),
                                                 t1 - req.enqueue_s)

    def _exclusive_blocks(self, uid: int) -> int:
        """Blocks only ``uid`` holds (refcount 1): preempting it frees
        exactly these — shared blocks stay alive under their other holders
        (sibling streams or the prefix index), so they buy no relief."""
        alloc = self.eng.allocator
        blocks = self.eng.seqs[uid].blocks
        if not hasattr(alloc, "refcount"):
            return len(blocks)
        return sum(1 for b in blocks if alloc.refcount(b) == 1)

    def _eviction_victim(self, now: float) -> Optional[int]:
        """Lowest slack first — the stream most likely to miss its SLA
        anyway; ties (e.g. every stream slack-less) break toward the most
        EXCLUSIVE (unshared) blocks, which buy the most actual relief —
        a stream riding a hot shared prefix frees almost nothing — then
        toward the longest context."""
        live = [u for u in self.running if u in self.eng.seqs
                and self.eng.seqs[u].blocks]
        if not live:
            return None
        return min(live, key=lambda u: (
            slack_of(self.eng.seqs[u], now, self.capacity.prefill_tok_s,
                     self.capacity.decode_tok_s),
            -self._exclusive_blocks(u),
            -self.eng.seqs[u].n_cached))

    def _evict(self, uid: int, now: float, events: List[ServeEvent]) -> None:
        req = self.running.pop(uid)
        self._pending_tok.pop(uid, None)
        self.eng.preempt(uid)
        self._count("evicted")
        requeue = self.policy.preempt_policy == "requeue"
        self._stage(uid, "preempt", now,
                    policy="requeue" if requeue else "reject")
        events.append(ServeEvent("evict", uid, now,
                                 reason="requeue" if requeue else "reject"))
        if requeue:
            # the emitted prefix is part of the context now — a fresh
            # prefill (tokens + out, rebuilt at activation) must restore
            # its KV before decode can continue. Still in flight: no
            # journal close (a crash here replays it from the watermark)
            req.queued_s = now
            req.preempted = True
            self.queue.append(req)
            self._count("queued")
        else:
            if self.journal is not None:
                self.journal.close_request(uid, "evicted")
            self._observe_stage_times(req)
            self._trace("serve/close", now,
                        {"uid": int(uid), "reason": "evicted"})
            events.append(ServeEvent("finish", uid, now, reason="evicted"))

    # ------------------------------------------------------------- plumbing
    def _note_emission(self, req: _Request, toks: Sequence[int],
                       t: float) -> None:
        req.out.extend(int(t_) for t_ in toks)
        self._tokens_emitted += len(toks)
        if self.journal is not None:
            # journal-before-release: the watermark is on disk before the
            # caller sees the tokens (step() returns the events after this),
            # which is what makes crash replay exactly-once
            self.journal.emit(req.uid, toks, len(req.out))
        self._trace("serve/emit", t, {"uid": int(req.uid), "n": len(toks)})
        if req.first_token_s is None:
            req.first_token_s = t
            d = self.eng.seqs.get(req.uid)
            if d is not None:
                d.first_token_s = t
            self._observe("Serve/ttft_s", t - req.arrival_s)
            # prefill edge closes at the first token; cached_prefix_len
            # makes the prefix-cache saving visible per request
            self._stage(req.uid, "prefill", t,
                        dur=max(0.0, t - req.enqueue_s),
                        cached_prefix_len=req.cached_prefix_len)
            if req.deadline_s is not None:
                self._slo_ttft.append((t, t <= req.deadline_s))
        elif req.last_emit_s is not None and toks:
            itl = (t - req.last_emit_s) / len(toks)
            for _ in toks:
                self._observe("Serve/itl_s", itl)
        req.last_emit_s = t

    def _finish(self, uid: int, now: float, events: List[ServeEvent],
                reason: str, flush: bool = True) -> None:
        req = self.running.pop(uid, None)
        self._pending_tok.pop(uid, None)
        if flush:
            self.eng.flush([uid])
        self._count("completed")
        if self.journal is not None:
            self.journal.close_request(uid, reason)
        if req is not None:
            self._observe_stage_times(req)
        self._trace("serve/close", now, {"uid": int(uid), "reason": reason})
        events.append(ServeEvent("finish", uid, now, reason=reason))

    def _observe_stage_times(self, req: _Request) -> None:
        """Terminal per-request phase self-times into the Serve/stage.*_s
        histograms (the streaming twin of the offline join's stage sums;
        guarded against requeue reorderings where first_token predates the
        last activation)."""
        if req.first_token_s is not None \
                and req.first_token_s >= req.enqueue_s:
            self._observe("Serve/stage.prefill_s",
                          req.first_token_s - req.enqueue_s)
        if req.first_token_s is not None and req.last_emit_s is not None \
                and req.last_emit_s > req.first_token_s:
            self._observe("Serve/stage.decode_s",
                          req.last_emit_s - req.first_token_s)

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n
        if self._metrics is not None:
            self._metrics.counter(f"Serve/{name}").incr(n)

    def _count_recovery(self, name: str, n: int = 1) -> None:
        self.recovery_counters[name] = \
            self.recovery_counters.get(name, 0) + n
        if self._metrics is not None:
            self._metrics.counter(_RECOVERY_COUNTERS[name]).incr(n)

    def _observe(self, name: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.histogram(name).observe(value)

    def _kv_occupancy(self) -> float:
        return kv_pool_stats(self.eng.kv, self.eng.allocator)["occupancy"]

    def _flush_gauges(self, now: Optional[float] = None) -> None:
        if self._metrics is None:
            return
        self._metrics.gauge("Serve/queue_depth").set(len(self.queue))
        self._metrics.gauge("Serve/kv_occupancy").set(self._kv_occupancy())
        self._metrics.gauge("Serve/live_seqs").set(len(self.running))
        if now is not None:
            miss, shed, burn = self._slo_snapshot(now)
            self._metrics.gauge("Serve/slo.ttft_miss_frac").set(miss)
            self._metrics.gauge("Serve/slo.shed_frac").set(shed)
            self._metrics.gauge("Serve/slo.burn_rate").set(burn)
        pc = self.eng.prefix_cache
        if pc is not None:
            # the cache keeps lifetime totals; registry counters take the
            # delta since the last flush (monotone either way)
            for key, metric in _PREFIX_COUNTERS.items():
                delta = pc.counters[key] - self._prefix_reported.get(key, 0)
                if delta:
                    self._metrics.counter(metric).incr(delta)
                    self._prefix_reported[key] = pc.counters[key]
            self._metrics.gauge("Serve/prefix.hit_ratio").set(pc.hit_ratio)
            self._metrics.gauge("Serve/prefix.pinned_blocks").set(
                pc.pinned_blocks)

    # ------------------------------------------------------------ reporting
    @property
    def idle(self) -> bool:
        return not self.running and not self.queue

    def prefix_stats(self) -> Optional[Dict[str, float]]:
        """Prefix-cache counters + hit ratio (None when no cache is
        installed) — what the fleet router joins with its placement-side
        ``Fleet/affinity_hits`` for REALIZED reuse."""
        pc = self.eng.prefix_cache
        return None if pc is None else pc.stats()

    def stats(self) -> Dict[str, float]:
        """Counters + instantaneous state, for bench lines and operators."""
        out = {**self.counters,
               **{f"recovery_{n}": v
                  for n, v in self.recovery_counters.items()},
               "queue_depth": len(self.queue),
               "live_seqs": len(self.running),
               "kv_occupancy": round(self._kv_occupancy(), 4),
               "prefill_tok_s_est": round(self.capacity.prefill_tok_s, 1),
               "decode_step_s_est": round(self.capacity.decode_step_s, 5)}
        ps = self.prefix_stats()
        if ps is not None:
            out.update({f"prefix_{k}": v for k, v in ps.items()})
        return out

    def summary_events(self, step: Optional[int] = None) -> List[Tuple]:
        """Scalar ``Serve/*`` events for a MonitorMaster print boundary —
        validated against the telemetry registry (strict mode safe).
        TTFT/ITL histograms surface their estimated p50/p95/p99 (bucket-
        interpolated, ``Histogram.quantile``) alongside the raw bucket
        counts the registry already holds — the scalar a dashboard or the
        pod report's skew table actually wants."""
        from ...monitor.telemetry import check_events

        from ...monitor.telemetry import resilience_counters

        ev = [(f"Serve/{n}", float(v), step)
              for n, v in self.counters.items()]
        ev += [(_RECOVERY_COUNTERS[n], float(v), step)
               for n, v in self.recovery_counters.items()]
        ev += [("Serve/recovery.serve_hang_aborts",
                float(resilience_counters.get("serve_hang_aborts")), step),
               ("Serve/queue_depth", float(len(self.queue)), step),
               ("Serve/live_seqs", float(len(self.running)), step),
               ("Serve/kv_occupancy", self._kv_occupancy(), step)]
        # getattr chain: skeleton sessions (offline renderers, report
        # tests) carry no engine at all
        pc = getattr(getattr(self, "eng", None), "prefix_cache", None)
        if pc is not None:
            ev += [(_PREFIX_COUNTERS[n], float(pc.counters[n]), step)
                   for n in _PREFIX_COUNTERS]
            ev += [("Serve/prefix.hit_ratio", float(pc.hit_ratio), step),
                   ("Serve/prefix.pinned_blocks",
                    float(pc.pinned_blocks), step)]
        if getattr(self, "_slo_ttft", None) is not None \
                and getattr(self, "clock", None) is not None:
            miss, shed, burn = self._slo_snapshot(self.clock())
            ev += [("Serve/slo.ttft_miss_frac", miss, step),
                   ("Serve/slo.shed_frac", shed, step),
                   ("Serve/slo.burn_rate", burn, step)]
        if self._metrics is not None:
            for name in SERVE_HISTOGRAMS:
                hist = self._metrics.histogram(name)
                if not hist.count:
                    continue
                for q, value in hist.quantiles().items():
                    if value is not None:
                        ev.append((f"{name}/{q}", float(value), step))
        return check_events(ev)
