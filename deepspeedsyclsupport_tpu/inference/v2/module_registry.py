"""Pluggable layer-implementation registry + selection heuristics.

Analog of the reference's v2 module system (``inference/v2/modules/
module_registry.py`` ConfigBundle/registry and ``modules/heuristics.py``
``instantiate_attn``-style pickers): each module KIND (prefill attention,
decode attention) has named implementations registered with an availability
predicate and a preference priority; configs name an impl — or ``auto``,
which resolves to the highest-priority implementation available in the
current context. Third-party code can register additional implementations
and select them by name from the same config key, which is what makes the
surface a registry rather than a closed enum.
"""
from collections import defaultdict
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

__all__ = ["register_impl", "get_impl", "list_impls", "select_impl",
           "ImplSpec"]


@dataclass(frozen=True)
class ImplSpec:
    kind: str
    name: str
    fn: Callable
    # availability in a given context dict (backend, shipped metadata, ...)
    available: Callable[[Dict[str, Any]], bool]
    priority: int  # higher wins under "auto"
    # eligibility for AUTO selection only — an impl can be explicitly
    # selectable (debug/interpret variants) yet never auto-picked
    auto_eligible: Callable[[Dict[str, Any]], bool] = lambda ctx: True
    # impl-declared facts the caller may consult (e.g. needs_atoms: the
    # engine ships atom metadata only to impls that consume it)
    metadata: Optional[Dict[str, Any]] = None


_REGISTRY: Dict[str, Dict[str, ImplSpec]] = defaultdict(dict)


def register_impl(kind: str, name: str, *, priority: int = 0,
                  available: Optional[Callable[[Dict[str, Any]], bool]] = None,
                  auto_eligible: Optional[Callable[[Dict[str, Any]], bool]]
                  = None,
                  metadata: Optional[Dict[str, Any]] = None
                  ) -> Callable[[Callable], Callable]:
    """Decorator: register ``fn`` as implementation ``name`` of ``kind``.
    Re-registering a name replaces it (user overrides win)."""

    def deco(fn: Callable) -> Callable:
        avail = available or (lambda ctx: True)
        _REGISTRY[kind][name] = ImplSpec(
            kind=kind, name=name, fn=fn, available=avail, priority=priority,
            auto_eligible=auto_eligible or avail, metadata=metadata or {})
        return fn

    return deco


def get_impl(kind: str, name: str) -> ImplSpec:
    impls = _REGISTRY.get(kind, {})
    if name not in impls:
        raise KeyError(f"no {kind!r} implementation named {name!r}; "
                       f"registered: {sorted(impls) or 'none'}")
    return impls[name]


def list_impls(kind: str) -> List[str]:
    return sorted(_REGISTRY.get(kind, {}))


def select_impl(kind: str, requested: str,
                context: Optional[Dict[str, Any]] = None) -> ImplSpec:
    """Resolve a config value to an implementation (the heuristics seam,
    reference ``modules/heuristics.py``): explicit names are validated
    against availability; ``auto`` picks the highest-priority available
    impl."""
    context = context or {}
    if requested != "auto":
        spec = get_impl(kind, requested)
        if not spec.available(context):
            raise ValueError(
                f"{kind} implementation {requested!r} is not available in "
                f"this context ({context}); available: "
                f"{[s.name for s in _available(kind, context)]}")
        return spec
    candidates = [s for s in _available(kind, context)
                  if s.auto_eligible(context)]
    if not candidates:
        raise RuntimeError(f"no {kind!r} implementation available "
                           f"(context {context})")
    return candidates[0]


def _available(kind: str, context: Dict[str, Any]) -> List[ImplSpec]:
    impls = [s for s in _REGISTRY.get(kind, {}).values()
             if s.available(context)]
    return sorted(impls, key=lambda s: -s.priority)
