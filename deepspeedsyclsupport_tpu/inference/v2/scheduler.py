"""Dynamic SplitFuse token-budget scheduler, with deadline-driven ordering.

The reference's scheduling contract lives half in ``InferenceEngineV2.put/
can_schedule`` (``inference/v2/engine_v2.py:107,179``) and half in MII's
ragged batch scheduler; the policy (from the FastGen blog,
``blogs/deepspeed-fastgen/README.md``) is Dynamic SplitFuse:

* decode tokens (1 per running sequence) are never starved — they ship in every
  forward;
* long prompts are SPLIT into chunks of at most the remaining token budget;
* short prompts are FUSED together to fill the budget exactly, so every forward
  runs at a near-constant, throughput-optimal token count.

On top of that sits the SLA layer (``docs/serving.md``): when the caller
passes a :class:`SlackPolicy`, chunks are ordered by *slack* —
time-to-deadline minus the remaining-service estimate — instead of arrival
order, with a starvation-proof aging term and a per-tenant prefill token
budget per scheduling round. Without a policy the pre-SLA behavior is
byte-identical (least-recently-served prompt order).
"""
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple, Union

from .ragged import BlockedAllocator, SequenceDescriptor

#: Slack values are clamped to ±SLACK_CAP seconds so no-SLA sequences
#: (slack = +inf) stay *orderable*: the aging term can eventually lift a
#: starved best-effort prompt above an SLA prompt with comfortable slack —
#: without the cap, inf - anything stays inf and best-effort work starves
#: forever under sustained SLA load.
SLACK_CAP = 60.0


@dataclass
class SlackPolicy:
    """Deadline-driven ordering inputs for one scheduling round.

    ``now`` and the descriptor timestamps share one monotonic clock base;
    ``prefill_tok_s`` / ``decode_tok_s`` are the capacity estimates
    (``serving.CapacityModel``) that turn remaining work into remaining
    seconds. ``tenant_budget`` caps the PREFILL tokens any one tenant may
    take per round (decode tokens — one per live stream, the SLA-critical
    part — are exempt); an int applies to every tenant, a dict keys
    per-tenant overrides with ``"*"`` as the default.
    """

    now: float = 0.0
    prefill_tok_s: float = float("inf")
    decode_tok_s: float = float("inf")
    aging_weight: float = 2.0       # seconds of slack credit per second waited
    tenant_budget: Optional[Union[int, Dict[str, int]]] = None

    def budget_for(self, tenant: str) -> float:
        if self.tenant_budget is None:
            return float("inf")
        if isinstance(self.tenant_budget, dict):
            b = self.tenant_budget.get(tenant,
                                       self.tenant_budget.get("*"))
            return float("inf") if b is None else float(b)
        return float(self.tenant_budget)


def slack_of(d: SequenceDescriptor, now: float,
             prefill_tok_s: float = float("inf"),
             decode_tok_s: float = float("inf")) -> float:
    """Seconds to spare before ``d`` misses its SLA, minus the service it
    still needs — negative means the deadline is already unmeetable at the
    estimated capacity.

    Prefill phase (no first token yet): slack against the TTFT deadline,
    remaining service = pending prompt tokens at the prefill rate. Decode
    phase: slack against the implied completion deadline
    ``first_token + target_new_tokens / rate_sla``, remaining service =
    remaining tokens at the decode rate. No SLA → ``+inf`` (clamped by the
    caller for ordering).
    """
    if d.first_token_s is None:
        if d.deadline_s is None:
            return math.inf
        rem = len(d.pending) / prefill_tok_s if prefill_tok_s > 0 else 0.0
        return (d.deadline_s - now) - rem
    if d.rate_sla <= 0 or d.target_new_tokens <= 0:
        return math.inf
    finish_deadline = d.first_token_s + d.target_new_tokens / d.rate_sla
    remaining = max(0, d.target_new_tokens - d.emitted)
    rem_s = remaining / decode_tok_s if decode_tok_s > 0 else 0.0
    return (finish_deadline - now) - rem_s


def _priority(d: SequenceDescriptor, policy: SlackPolicy) -> float:
    """Lower = scheduled earlier. Clamped slack minus the aging credit: a
    chunk that keeps losing admission races accrues ``aging_weight`` seconds
    of priority per second since it was last served (arrival if never), so
    even a no-deadline prompt eventually outranks comfortable-slack work —
    the starvation proof."""
    slack = slack_of(d, policy.now, policy.prefill_tok_s,
                     policy.decode_tok_s)
    slack = max(-SLACK_CAP, min(SLACK_CAP, slack))
    since = d.last_service_s if d.last_service_s >= 0 else d.arrival_s
    waited = max(0.0, policy.now - since)
    return slack - policy.aging_weight * waited


def schedule_chunks(seqs: Sequence[SequenceDescriptor],
                    allocator: BlockedAllocator,
                    *, max_tokens: int, max_sequences: int, block_size: int,
                    max_context: int,
                    max_prefill_fraction: float = 1.0,
                    policy: Optional[SlackPolicy] = None
                    ) -> List[Tuple[SequenceDescriptor, int]]:
    """Pick ``(sequence, n_tokens)`` chunks for one forward.

    Decode-phase sequences (pending == 1, already cached context) are admitted
    first; prompt-phase sequences then split/fuse into the remaining budget.
    Block allocation happens here so a chunk is only admitted if its KV fits
    (the ``can_schedule`` KV-pressure check, ``engine_v2.py:179``).

    ``max_prefill_fraction`` bounds the share of the TOKEN BUDGET prompt
    chunks may take in a forward that also carries decode tokens — the
    inter-token-latency lever for the reference's SLA-bound serving
    (``blogs/deepspeed-fastgen/README.md:163``: decode ITL must not spike
    when a long prompt arrives). Pure-prefill forwards (no decodes live)
    ignore it.

    Ordering: with ``policy`` (the SLA layer), both decode slots and prompt
    chunks go lowest-:func:`_priority` first — slack order with starvation
    aging — and each tenant's prompt chunks are capped at
    ``policy.budget_for(tenant)`` tokens this round. Without a policy,
    prompt order is least-recently-scheduled first, so a prompt that kept
    losing admission races cannot starve behind later arrivals.
    """
    chunks: List[Tuple[SequenceDescriptor, int]] = []
    budget = max_tokens

    decode = [d for d in seqs if d.needs_tokens == 1 and d.n_cached > 0]
    prefill = [d for d in seqs if d.needs_tokens > 0 and d not in decode]
    if policy is not None:
        # slack order: most-urgent first; ties keep list order (stable sort)
        decode.sort(key=lambda d: _priority(d, policy))
        prefill.sort(key=lambda d: _priority(d, policy))
    else:
        # fairness: least-recently-SERVED prompts first so an in-progress
        # (chunked) prompt that keeps losing admission races cannot starve;
        # never-scheduled arrivals rank NEWEST (behind every in-progress
        # prompt — they hold no KV yet), ties keep arrival order (stable
        # sort)
        prefill.sort(key=lambda d: (d.last_scheduled < 0, d.last_scheduled))

    for d in decode:
        if budget < 1 or len(chunks) >= max_sequences:
            break
        if not _admit(d, 1, allocator, block_size, max_context):
            continue
        chunks.append((d, 1))
        budget -= 1

    if chunks and max_prefill_fraction < 1.0:
        # never floor to zero: a tiny fraction must still admit >= 1 prompt
        # token per forward or waiting prompts starve while decodes run
        budget = min(budget, max(1, int(max_tokens * max_prefill_fraction)))
    tenant_spent: Dict[str, int] = {}
    for d in prefill:
        if budget < 1 or len(chunks) >= max_sequences:
            break
        n = min(d.needs_tokens, budget)
        if policy is not None:
            left = policy.budget_for(d.tenant) - tenant_spent.get(d.tenant, 0)
            if left < 1:
                continue  # tenant's round budget spent; aging lifts it later
            n = int(min(n, left))
        if d.n_cached + n > max_context:
            n = max_context - d.n_cached
            if n < 1:
                continue  # out of context budget; caller decides eviction
        if not _admit(d, n, allocator, block_size, max_context):
            continue
        chunks.append((d, n))
        budget -= n
        if policy is not None:
            tenant_spent[d.tenant] = tenant_spent.get(d.tenant, 0) + n
    return chunks


def _admit(d: SequenceDescriptor, n: int, allocator: BlockedAllocator,
           block_size: int, max_context: int) -> bool:
    want = d.blocks_needed(n, block_size)
    if want:
        # try_allocate: pool exhaustion (or an injected kv_alloc_fail)
        # skips the chunk this round — structured backpressure, never an
        # exception out of put()'s scheduling pass
        got = allocator.try_allocate(want)
        if got is None:
            return False
        d.blocks.extend(got)
    return True
