"""Dynamic SplitFuse token-budget scheduler.

The reference's scheduling contract lives half in ``InferenceEngineV2.put/
can_schedule`` (``inference/v2/engine_v2.py:107,179``) and half in MII's
ragged batch scheduler; the policy (from the FastGen blog,
``blogs/deepspeed-fastgen/README.md``) is Dynamic SplitFuse:

* decode tokens (1 per running sequence) are never starved — they ship in every
  forward;
* long prompts are SPLIT into chunks of at most the remaining token budget;
* short prompts are FUSED together to fill the budget exactly, so every forward
  runs at a near-constant, throughput-optimal token count.
"""
from typing import List, Sequence, Tuple

from .ragged import BlockedAllocator, SequenceDescriptor


def schedule_chunks(seqs: Sequence[SequenceDescriptor],
                    allocator: BlockedAllocator,
                    *, max_tokens: int, max_sequences: int, block_size: int,
                    max_context: int,
                    max_prefill_fraction: float = 1.0
                    ) -> List[Tuple[SequenceDescriptor, int]]:
    """Pick ``(sequence, n_tokens)`` chunks for one forward.

    Decode-phase sequences (pending == 1, already cached context) are admitted
    first; prompt-phase sequences then split/fuse into the remaining budget.
    Block allocation happens here so a chunk is only admitted if its KV fits
    (the ``can_schedule`` KV-pressure check, ``engine_v2.py:179``).

    ``max_prefill_fraction`` bounds the share of the TOKEN BUDGET prompt
    chunks may take in a forward that also carries decode tokens — the
    inter-token-latency lever for the reference's SLA-bound serving
    (``blogs/deepspeed-fastgen/README.md:163``: decode ITL must not spike
    when a long prompt arrives). Pure-prefill forwards (no decodes live)
    ignore it. Prompt order is least-recently-scheduled first, so a prompt
    that kept losing admission races cannot starve behind later arrivals.
    """
    chunks: List[Tuple[SequenceDescriptor, int]] = []
    budget = max_tokens

    decode = [d for d in seqs if d.needs_tokens == 1 and d.n_cached > 0]
    prefill = [d for d in seqs if d.needs_tokens > 0 and d not in decode]
    # fairness: least-recently-SERVED prompts first so an in-progress
    # (chunked) prompt that keeps losing admission races cannot starve;
    # never-scheduled arrivals rank NEWEST (behind every in-progress
    # prompt — they hold no KV yet), ties keep arrival order (stable sort)
    prefill.sort(key=lambda d: (d.last_scheduled < 0, d.last_scheduled))

    for d in decode:
        if budget < 1 or len(chunks) >= max_sequences:
            break
        if not _admit(d, 1, allocator, block_size, max_context):
            continue
        chunks.append((d, 1))
        budget -= 1

    if chunks and max_prefill_fraction < 1.0:
        # never floor to zero: a tiny fraction must still admit >= 1 prompt
        # token per forward or waiting prompts starve while decodes run
        budget = min(budget, max(1, int(max_tokens * max_prefill_fraction)))
    for d in prefill:
        if budget < 1 or len(chunks) >= max_sequences:
            break
        n = min(d.needs_tokens, budget)
        if d.n_cached + n > max_context:
            n = max_context - d.n_cached
            if n < 1:
                continue  # out of context budget; caller decides eviction
        if not _admit(d, n, allocator, block_size, max_context):
            continue
        chunks.append((d, n))
        budget -= n
    return chunks


def _admit(d: SequenceDescriptor, n: int, allocator: BlockedAllocator,
           block_size: int, max_context: int) -> bool:
    want = d.blocks_needed(n, block_size)
    if want > allocator.free_blocks:
        return False
    if want:
        d.blocks.extend(allocator.allocate(want))
    return True
