"""Ragged forward over the paged KV cache.

The compute core of the v2 engine — the role of the reference's CUDA ragged
kernel set (``inference/v2/kernels/ragged_ops/``):

* ``linear_blocked_kv_rotary`` — fused QKV + RoPE + paged-KV append → here the
  qkv einsums + :func:`apply_rope` + one scatter into the flat slot axis.
* ``blocked_flash`` (attention over ragged atoms) → :func:`_paged_attention`,
  an exact XLA implementation gathering each slot's block-table-resolved KV.
  (A Pallas blocked-flash variant is the planned fast path; this is the
  correctness reference the kernel will be tested against, the same
  kernel-vs-reference pattern the CUDA tests use, SURVEY.md §4.)
* ``logits_gather`` — only each sequence's last scheduled token reaches the
  unembedding matmul (``engine_v2.py`` forward tail).

Operates on ONE flat token stream [T] with per-token (seq-slot, position)
routing — batch composition never changes the compiled program.

Reuses the training model's parameters and sublayer math (``models/layers.py``)
— the weight-sharing the reference needs separate inference containers for.
The full architecture-config surface (layernorm/rmsnorm, rope/learned/alibi
positions, partial rotary, gated/standard MLP, parallel residual blocks,
biases, sliding window) serves here exactly as in training — the analog of the
reference's v2 model zoo (``inference/v2/model_implementations/{llama_v2,
mistral,mixtral,opt,falcon,phi}.py``) as config axes instead of classes.
"""
from functools import partial
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .kv_cache import BlockedKV
from .module_registry import register_impl, select_impl
from ...models.layers import alibi_slopes, apply_rope, mlp_block, norm

NEG_INF = jnp.finfo(jnp.float32).min


class PrefillAttnContext(NamedTuple):
    """Everything a prefill-attention implementation may consume — the
    uniform contract registered impls are called with (the reference's
    ConfigBundle role, ``modules/module_registry.py``)."""
    k_cache: Any
    v_cache: Any
    token_seq: Any
    token_pos: Any
    block_tables: Any
    block_size: int
    alibi: Any
    window: Optional[int]
    atom_qidx: Any = None
    atom_pos0: Any = None
    atom_qlen: Any = None
    atom_tables: Any = None
    atom_inv: Any = None


def _dequant(p, dtype):
    """ZeRO-Inference: materialize int8 QuantTensor leaves per layer."""
    from ...compression.quantize import dequantize_tree

    return dequantize_tree(p, dtype)


def _mlp(p, y, cfg):
    """Per-layer MLP over flat tokens [T, D]: dense (GLU or fc1/fc2), or exact
    top-k MoE via grouped GEMMs (the moe_scatter/cutlass-multi-GEMM/moe_gather
    analog, ``parallel/moe.moe_mlp_nodrop``)."""
    if cfg.any_moe:
        from ...parallel.moe import moe_mlp_nodrop

        return moe_mlp_nodrop(p["moe"], y, cfg)
    return mlp_block(p["mlp"], y[None], cfg)[0]


def _qkv(p, y, cfg, n):
    """Fused qkv projection over flat tokens [n, D] (+ optional biases)."""
    q = jnp.einsum("td,dq->tq", y, p["wq"])
    k = jnp.einsum("td,dk->tk", y, p["wk"])
    v = jnp.einsum("td,dk->tk", y, p["wv"])
    if cfg.qkv_bias:
        q = q + p["bq"].astype(q.dtype)
        k = k + p["bk"].astype(k.dtype)
        v = v + p["bv"].astype(v.dtype)
    return (q.reshape(n, cfg.num_heads, cfg.head_dim),
            k.reshape(n, cfg.num_kv_heads, cfg.head_dim),
            v.reshape(n, cfg.num_kv_heads, cfg.head_dim))


def _attn_out(p, attn, cfg, n):
    out = jnp.einsum("tq,qd->td", attn.reshape(n, cfg.q_dim), p["wo"])
    if cfg.attn_out_bias:
        out = out + p["bo"].astype(out.dtype)
    return out


def _lane_pad(x, d_pad: int, is_q: bool = False):
    """Zero-pad the trailing head dim to the cache pool's lane-padded width
    (see ``kv_cache.lane_padded_head_dim``). Zero lanes cannot change q·k
    dot products, but every attention impl derives its softmax scale from
    the (padded) trailing dim — so q is pre-scaled by sqrt(d_pad/d), making
    scores/softmax mathematically identical to the unpadded computation (up
    to one fp rounding on q). The attention output is sliced back."""
    d = x.shape[-1]
    if d == d_pad:
        return x
    if is_q:
        x = x * np.sqrt(d_pad / d).astype(x.dtype)
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, d_pad - d)])


def _positionize(cfg, q, k, positions):
    if cfg.pos_embed == "rope":
        q = apply_rope(q[None], positions[None], cfg.rope_theta,
                       cfg.rotary_dim)[0]
        k = apply_rope(k[None], positions[None], cfg.rope_theta,
                       cfg.rotary_dim)[0]
    return q, k


def _arch_bias(cfg):
    ab = (jnp.asarray(alibi_slopes(cfg.num_heads) * cfg.alibi_scale)
          if cfg.pos_embed == "alibi" else None)
    return ab, cfg.sliding_window


def _embed(params, tokens, positions, cfg):
    x = jnp.take(params["embed"]["embedding"], tokens, axis=0)
    if cfg.pos_embed == "learned":
        table = params["pos_embed"]["embedding"]
        pos = jnp.clip(positions + cfg.pos_embed_offset, 0,
                       table.shape[0] - 1)
        x = x + jnp.take(table, pos, axis=0).astype(x.dtype)
    x = x.astype(jnp.dtype(cfg.dtype))
    if cfg.embed_norm:
        x = norm(x, params["embed_norm"], cfg)
    return x


def _unembed(params, x, cfg):
    if cfg.tie_embeddings:
        return jnp.einsum("sd,vd->sv", x,
                          params["embed"]["embedding"].astype(x.dtype))
    logits = jnp.einsum("sd,dv->sv", x,
                        params["lm_head"]["kernel"].astype(x.dtype))
    if cfg.lm_head_bias:
        logits = logits + params["lm_head"]["bias"].astype(logits.dtype)
    return logits


def _block(cfg, p, x, attn_fn):
    """One transformer block over flat tokens, covering sequential and
    parallel (GPT-J/NeoX/Falcon/Phi) residual forms."""
    x_norm = norm(x, p["attn_norm"], cfg)
    attn = attn_fn(x_norm)
    h = _attn_out(p["attn"], attn, cfg, x.shape[0])
    if cfg.parallel_block:
        y = x_norm if cfg.shared_block_norm else norm(x, p["mlp_norm"], cfg)
        return (x + h + _mlp(p, y, cfg)).astype(x.dtype)
    x = (x + h).astype(x.dtype)
    return (x + _mlp(p, norm(x, p["mlp_norm"], cfg), cfg)).astype(x.dtype)


def _paged_attention(q, k_cache, v_cache, token_seq, token_pos, block_tables,
                     block_size: int, alibi=None, window=None):
    """q: [T, H, D]; caches: [num_slots, KVH, D] (flat slot axis);
    block_tables: [S, Bps]. Returns [T, H, D].

    Each token's query attends to its sequence's KV at positions <= its own.
    Per-sequence KV is materialized by resolving the block table to flat slot
    ids and gathering — O(S · max_ctx) memory, the XLA-correctness baseline the
    Pallas kernel will replace with true block-sparse streaming.
    """
    t, h, d = q.shape
    s, bps = block_tables.shape
    max_ctx = bps * block_size
    kvh = k_cache.shape[1]

    # seq-relative position j lives in flat slot table[j // bs] * bs + j % bs
    j = jnp.arange(max_ctx)
    slot_of_pos = block_tables[:, j // block_size] * block_size + (j % block_size)
    k_seq = k_cache[slot_of_pos]  # [S, max_ctx, KVH, D]
    v_seq = v_cache[slot_of_pos]

    seq_clip = jnp.minimum(token_seq, s - 1)  # padded tokens: any valid row
    k_tok = k_seq[seq_clip]  # [T, max_ctx, KVH, D]
    v_tok = v_seq[seq_clip]
    if kvh != h:
        rep = h // kvh
        k_tok = jnp.repeat(k_tok, rep, axis=2)
        v_tok = jnp.repeat(v_tok, rep, axis=2)

    scale = 1.0 / np.sqrt(d)
    logits = jnp.einsum("thd,tchd->thc", q.astype(jnp.float32),
                        k_tok.astype(jnp.float32)) * scale
    if alibi is not None:
        logits = logits + alibi.astype(jnp.float32)[None, :, None] * (
            j[None, None, :] - token_pos[:, None, None]).astype(jnp.float32)
    mask = (j[None, :] <= token_pos[:, None])[:, None, :]  # causal over own seq
    if window is not None:
        mask = jnp.logical_and(
            mask, (token_pos[:, None] - j[None, :] < window)[:, None, :])
    logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("thc,tchd->thd", probs, v_tok.astype(jnp.float32))
    return out.astype(q.dtype)


def _packed_flash_attention(q, k_cache, v_cache, token_seq, token_pos,
                            block_tables, block_size: int, alibi=None,
                            window=None):
    """Chunked-prefill attention through the Pallas flash kernel.

    The fix for the O(T·max_ctx) per-token KV gather of
    :func:`_paged_attention`: KV is gathered once per SEQUENCE
    ([S, max_ctx] resolved from the block table), flattened into one packed
    stream with per-slot segment ids + positions, and the flat token
    queries attend through ``flash_attention``'s ragged cross-attention
    mode — per-sequence boundaries from q/kv segment ids, causality in
    position space, logits streamed (never materialized). This is the
    TTFT-critical path (reference ``blocked_flash`` over ragged atoms).
    """
    from ...ops.flash_attention import flash_attention

    t, h, d = q.shape
    s, bps = block_tables.shape
    bs = block_size
    max_ctx = bps * bs

    j = jnp.arange(max_ctx)
    slot_of_pos = block_tables[:, j // bs] * bs + (j % bs)
    k_seq = k_cache[slot_of_pos]  # [S, max_ctx, KVH, D] — once per sequence
    v_seq = v_cache[slot_of_pos]
    kvh = k_cache.shape[1]
    k_flat = k_seq.reshape(1, s * max_ctx, kvh, d)
    v_flat = v_seq.reshape(1, s * max_ctx, kvh, d)
    kv_seg = jnp.repeat(jnp.arange(s, dtype=jnp.int32), max_ctx)[None]
    kv_pos = jnp.tile(jnp.arange(max_ctx, dtype=jnp.int32), s)[None]
    # pad tokens carry token_seq == S, matching no kv segment → fully masked
    out = flash_attention(q[None], k_flat, v_flat, causal=True,
                          segment_ids=token_seq[None].astype(jnp.int32),
                          kv_segment_ids=kv_seg,
                          q_positions=token_pos[None].astype(jnp.int32),
                          kv_positions=kv_pos, alibi=alibi, window=window)
    return out[0]


# ------------------------------------------ registered prefill-attn impls
# (the reference's modules/implementations/* + heuristics, as registry
# entries; users can register_impl their own and name it in the config)
def _has_atoms(ctx):
    return bool(ctx.get("has_atoms"))


@register_impl("prefill_attn", "kernel", priority=10, available=_has_atoms,
               auto_eligible=lambda c: _has_atoms(c)
               and c.get("backend") == "tpu",
               metadata={"needs_atoms": True})
def _prefill_kernel_impl(q, ctx: PrefillAttnContext, interpret=False):
    """Ragged paged-attention Pallas kernel (arXiv:2604.15464; reference
    blocked_flash + atom_builder): q gathers into fixed-size
    single-sequence atoms; KV blocks stream via block-table DMA — the
    [S, max_ctx] HBM gather of the xla impl never happens."""
    from ...ops.paged_attention import ragged_prefill_attention

    q_at = q[ctx.atom_qidx]                          # [A, BQ, H, D]
    out_at = ragged_prefill_attention(
        q_at, ctx.k_cache, ctx.v_cache, ctx.atom_tables, ctx.atom_pos0,
        ctx.atom_qlen, block_size=ctx.block_size, alibi=ctx.alibi,
        window=ctx.window,
        impl="pallas_interpret" if interpret else "pallas")
    flat = out_at.reshape(-1, *out_at.shape[2:])
    return flat[ctx.atom_inv]                        # back to packed rows


@register_impl("prefill_attn", "kernel_interpret", priority=-10,
               available=_has_atoms, auto_eligible=lambda c: False,
               metadata={"needs_atoms": True})
def _prefill_kernel_interpret_impl(q, ctx: PrefillAttnContext):
    return _prefill_kernel_impl(q, ctx, interpret=True)


@register_impl("prefill_attn", "flash", priority=5,
               auto_eligible=lambda c: c.get("backend") == "tpu")
def _prefill_flash_impl(q, ctx: PrefillAttnContext):
    return _packed_flash_attention(q, ctx.k_cache, ctx.v_cache,
                                   ctx.token_seq, ctx.token_pos,
                                   ctx.block_tables, ctx.block_size,
                                   alibi=ctx.alibi, window=ctx.window)


@register_impl("prefill_attn", "xla", priority=0)
def _prefill_xla_impl(q, ctx: PrefillAttnContext):
    return _paged_attention(q, ctx.k_cache, ctx.v_cache, ctx.token_seq,
                            ctx.token_pos, ctx.block_tables, ctx.block_size,
                            alibi=ctx.alibi, window=ctx.window)


# decode_attn kind: one-token-per-slot steady state (the reference's
# blocked_flash decode path) — the same registry surface as prefill
def _decode_dispatch(impl_name):
    def fn(q, ctx):
        from ...ops.paged_attention import paged_decode_attention

        return paged_decode_attention(
            q, ctx.k_cache, ctx.v_cache, ctx.block_tables, ctx.seq_lens,
            block_size=ctx.block_size, impl=impl_name, alibi=ctx.alibi,
            window=ctx.window)
    return fn


class DecodeAttnContext(NamedTuple):
    k_cache: Any
    v_cache: Any
    block_tables: Any
    seq_lens: Any
    block_size: int
    alibi: Any
    window: Optional[int]


register_impl("decode_attn", "pallas", priority=10,
              auto_eligible=lambda c: c.get("backend") == "tpu")(
    _decode_dispatch("pallas"))
register_impl("decode_attn", "pallas_interpret", priority=-10,
              auto_eligible=lambda c: False)(
    _decode_dispatch("pallas_interpret"))
register_impl("decode_attn", "xla", priority=0)(_decode_dispatch("xla"))


def ragged_forward(model, params: Any, kv: BlockedKV, tokens, token_seq,
                   token_pos, block_tables, last_tok_idx,
                   atom_qidx=None, atom_pos0=None, atom_qlen=None,
                   atom_tables=None, atom_inv=None, *, block_size: int,
                   attn_impl: str = "auto"
                   ) -> Tuple[jnp.ndarray, BlockedKV]:
    """Flat-token forward. Returns (per-slot last-token logits [S, V], new kv).

    ``model``: a ``models.CausalLM`` — its stacked-layer params drive a
    ``lax.scan`` here exactly as in training (``models/transformer.py``).
    """
    cfg = model.config
    assert cfg.scan_layers, "ragged engine requires scan_layers param layout"
    bs = block_size
    num_slots = kv.num_slots
    t = tokens.shape[0]
    s = block_tables.shape[0]
    ab, window = _arch_bias(cfg)

    pad = token_seq >= s  # padding sentinel from RaggedBatch
    # flat destination slot per token; padded tokens scatter out-of-range (drop)
    dest_block = block_tables[jnp.minimum(token_seq, s - 1),
                              token_pos // bs]
    dest = jnp.where(pad, num_slots, dest_block * bs + token_pos % bs)

    x = _embed(params, tokens, token_pos, cfg)

    def layer(x, inp):
        p, k_cache, v_cache = inp
        p = _dequant(p, x.dtype)

        # resolved through the pluggable registry (module_registry.py — the
        # reference's module_registry + heuristics seam). Static per trace:
        # atom presence and backend are trace-time constants.
        spec = select_impl("prefill_attn", attn_impl, {
            "backend": jax.default_backend(),
            "has_atoms": atom_qidx is not None,
        })

        def attn_fn(y):
            nonlocal k_cache, v_cache
            q, k, v = _qkv(p["attn"], y, cfg, t)
            q, k = _positionize(cfg, q, k, token_pos)
            d_pool = k_cache.shape[-1]
            q = _lane_pad(q, d_pool, is_q=True)
            k, v = _lane_pad(k, d_pool), _lane_pad(v, d_pool)
            k_cache = k_cache.at[dest].set(k.astype(k_cache.dtype),
                                           mode="drop")
            v_cache = v_cache.at[dest].set(v.astype(v_cache.dtype),
                                           mode="drop")
            ctx = PrefillAttnContext(
                k_cache=k_cache, v_cache=v_cache, token_seq=token_seq,
                token_pos=token_pos, block_tables=block_tables,
                block_size=bs, alibi=ab, window=window,
                atom_qidx=atom_qidx, atom_pos0=atom_pos0,
                atom_qlen=atom_qlen, atom_tables=atom_tables,
                atom_inv=atom_inv)
            return spec.fn(q, ctx)[..., :cfg.head_dim]

        x = _block(cfg, p, x, attn_fn)
        return x, (k_cache, v_cache)

    x, (nk, nv) = jax.lax.scan(layer, x, (params["layers"], kv.k, kv.v))

    x = norm(x, params["final_norm"], cfg)
    h_last = x[last_tok_idx]  # [S, d] — logits_gather
    logits = _unembed(params, h_last, cfg)
    return logits.astype(jnp.float32), BlockedKV(nk, nv)


def build_ragged_forward_fn(model, block_size: int, attn_impl: str = "auto"):
    """Jitted, shape-stable forward (compiled once per engine)."""
    fn = partial(ragged_forward, model, block_size=block_size,
                 attn_impl=attn_impl)
    return jax.jit(fn, donate_argnums=(1,))


# ------------------------------------------------------------ decode fast path
def decode_forward(model, params: Any, kv: BlockedKV, tokens, positions,
                   block_tables, active, *, block_size: int,
                   attn_impl: str = "auto") -> Tuple[jnp.ndarray, BlockedKV]:
    """All-decode forward: ONE token per slot, attention via the Pallas paged
    decode kernel (``ops/paged_attention`` — the ``blocked_flash`` analog).

    ``tokens``/``positions``/``active``: [S]; positions = tokens already
    cached (the new token writes slot ``positions[s]``). This is the program
    serving spends most of its life in, so it gets the kernel; mixed
    prefill+decode batches take :func:`ragged_forward`.
    """
    cfg = model.config
    bs = block_size
    num_slots = kv.num_slots
    s = tokens.shape[0]
    ab, window = _arch_bias(cfg)

    dest_block = jnp.take_along_axis(
        block_tables, (positions // bs)[:, None], axis=1)[:, 0]
    dest = jnp.where(active, dest_block * bs + positions % bs, num_slots)
    seq_lens = jnp.where(active, positions + 1, 0)

    x = _embed(params, tokens, positions, cfg)

    def layer(x, inp):
        p, k_cache, v_cache = inp
        p = _dequant(p, x.dtype)

        spec = select_impl("decode_attn", attn_impl,
                           {"backend": jax.default_backend()})

        def attn_fn(y):
            nonlocal k_cache, v_cache
            q, k, v = _qkv(p["attn"], y, cfg, s)
            q, k = _positionize(cfg, q, k, positions)
            d_pool = k_cache.shape[-1]
            q = _lane_pad(q, d_pool, is_q=True)
            k, v = _lane_pad(k, d_pool), _lane_pad(v, d_pool)
            k_cache = k_cache.at[dest].set(k.astype(k_cache.dtype),
                                           mode="drop")
            v_cache = v_cache.at[dest].set(v.astype(v_cache.dtype),
                                           mode="drop")
            return spec.fn(q, DecodeAttnContext(
                k_cache=k_cache, v_cache=v_cache, block_tables=block_tables,
                seq_lens=seq_lens, block_size=bs, alibi=ab,
                window=window))[..., :cfg.head_dim]

        x = _block(cfg, p, x, attn_fn)
        return x, (k_cache, v_cache)

    x, (nk, nv) = jax.lax.scan(layer, x, (params["layers"], kv.k, kv.v))
    x = norm(x, params["final_norm"], cfg)
    logits = _unembed(params, x, cfg)
    return logits.astype(jnp.float32), BlockedKV(nk, nv)


def build_decode_forward_fn(model, block_size: int, attn_impl: str = "auto"):
    fn = partial(decode_forward, model, block_size=block_size,
                 attn_impl=attn_impl)
    return jax.jit(fn, donate_argnums=(1,))


# ------------------------------------------- device-resident multi-step decode
def decode_multi_forward(model, params: Any, kv: BlockedKV, logits0,
                         positions, block_tables, active, steps_left, rng,
                         temperature, top_p, eos_tok, *,
                         block_size: int, num_steps: int, samp_struct,
                         max_context: int, attn_impl: str = "auto"):
    """Up to ``num_steps`` fused decode iterations in ONE jitted program.

    Serving's steady state (every live sequence decoding, nothing waiting)
    pays one host round trip per token in the reference's serving loop
    (``inference/v2/engine_v2.py:107`` — MII re-enters ``put`` per
    iteration). Here the whole loop body — sample from logits, append the
    token's KV through the paged-decode forward, advance positions —
    runs under ``lax.while_loop`` on device, so K tokens per sequence
    cost ONE dispatch and ONE [K, S] host transfer.

    Per-slot retirement mirrors the host loop exactly: a slot samples
    (emitting the token), decrements its budget, then retires on budget
    exhaustion, EOS, or the context cap — the EOS/terminal token is
    emitted but never appended, matching ``InferenceEngineV2.generate``.
    The loop exits early once every slot has retired, so a large
    ``num_steps`` costs nothing on short tails.

    ``logits0``: [S, V] last-token logits each slot drained with;
    ``steps_left``: [S] per-slot new-token budgets. ``samp_struct`` is
    ``SamplingParams.structure`` — the compile-relevant sampling shape;
    ``temperature``/``top_p``/``eos_tok`` (int32 scalar, -1 = no EOS) stay
    traced so one compiled program serves every setting of them. With
    ``do_sample=True`` the rng split tree differs from the per-token host
    loop (one split per device step here vs one per host round there), so
    sampled streams are not bit-identical across ``decode_steps_per_
    dispatch`` settings — greedy decoding is, and is what the parity tests
    pin. Returns
    ``(tokens [num_steps, S] int32 with -1 for retired-slot steps,
    final logits [S, V], final positions [S], final active [S],
    final steps_left [S], new kv)``.
    """
    from ..sampling import SamplingParams, sample_token as _sample

    do_sample, top_k, use_top_p = samp_struct
    sampling = SamplingParams(do_sample, temperature, top_k,
                              top_p if use_top_p else 1.0)
    s = positions.shape[0]
    buf0 = jnp.full((num_steps, s), -1, jnp.int32)

    def cond(carry):
        step, _buf, _kv, _lg, _pos, act, _sl, _rng = carry
        return jnp.logical_and(step < num_steps, jnp.any(act))

    def body(carry):
        step, buf, kv, logits, pos, act, sl, rng = carry
        rng, sub = jax.random.split(rng)
        tok = _sample(logits, sub, sampling)               # [S]
        buf = buf.at[step].set(jnp.where(act, tok, -1))
        sl = jnp.where(act, sl - 1, sl)
        done = sl <= 0
        done = jnp.logical_or(done,
                              jnp.logical_and(eos_tok >= 0, tok == eos_tok))
        done = jnp.logical_or(done, pos >= max_context)
        append = jnp.logical_and(act, jnp.logical_not(done))
        new_logits, kv = decode_forward(
            model, params, kv, tok, pos, block_tables, append,
            block_size=block_size, attn_impl=attn_impl)
        logits = jnp.where(append[:, None], new_logits, logits)
        pos = jnp.where(append, pos + 1, pos)
        return step + 1, buf, kv, logits, pos, append, sl, rng

    carry = (jnp.int32(0), buf0, kv, logits0.astype(jnp.float32),
             positions, active, steps_left, rng)
    (_, buf, kv, logits, pos, act, sl, _) = jax.lax.while_loop(
        cond, body, carry)
    return buf, logits, pos, act, sl, kv


def build_decode_multi_fn(model, block_size: int, num_steps: int,
                          samp_struct, max_context: int,
                          attn_impl: str = "auto"):
    """Jitted K-step decode program — compiled once per (K, sampling
    STRUCTURE); temperature/top_p/eos are runtime operands."""
    fn = partial(decode_multi_forward, model, block_size=block_size,
                 num_steps=num_steps, samp_struct=samp_struct,
                 max_context=max_context, attn_impl=attn_impl)
    return jax.jit(fn, donate_argnums=(1,))
