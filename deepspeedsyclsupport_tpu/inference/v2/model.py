"""Ragged forward over the paged KV cache.

The compute core of the v2 engine — the role of the reference's CUDA ragged
kernel set (``inference/v2/kernels/ragged_ops/``):

* ``linear_blocked_kv_rotary`` — fused QKV + RoPE + paged-KV append → here the
  qkv einsums + :func:`apply_rope` + one scatter into the flat slot axis.
* ``blocked_flash`` (attention over ragged atoms) → :func:`_paged_attention`,
  an exact XLA implementation gathering each slot's block-table-resolved KV.
  (A Pallas blocked-flash variant is the planned fast path; this is the
  correctness reference the kernel will be tested against, the same
  kernel-vs-reference pattern the CUDA tests use, SURVEY.md §4.)
* ``logits_gather`` — only each sequence's last scheduled token reaches the
  unembedding matmul (``engine_v2.py`` forward tail).

Operates on ONE flat token stream [T] with per-token (seq-slot, position)
routing — batch composition never changes the compiled program.

Reuses the training model's parameters and sublayer math (``models/layers.py``)
— the weight-sharing the reference needs separate inference containers for.
"""
from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .kv_cache import BlockedKV
from ...models.layers import apply_rope, glu_mlp, rms_norm


def _dequant(p, dtype):
    """ZeRO-Inference: materialize int8 QuantTensor leaves per layer."""
    from ...compression.quantize import dequantize_tree

    return dequantize_tree(p, dtype)


def _mlp(p, y, cfg):
    """Per-layer MLP over flat tokens [T, D]: dense GLU, or exact top-k MoE
    via grouped GEMMs (the moe_scatter/cutlass-multi-GEMM/moe_gather analog,
    ``parallel/moe.moe_mlp_nodrop``)."""
    if cfg.any_moe:
        from ...parallel.moe import moe_mlp_nodrop

        return moe_mlp_nodrop(p["moe"], y, cfg)
    return glu_mlp(p["mlp"], y[None], cfg)[0]


def _paged_attention(q, k_cache, v_cache, token_seq, token_pos, block_tables,
                     block_size: int):
    """q: [T, H, D]; caches: [num_slots, KVH, D] (flat slot axis);
    block_tables: [S, Bps]. Returns [T, H, D].

    Each token's query attends to its sequence's KV at positions <= its own.
    Per-sequence KV is materialized by resolving the block table to flat slot
    ids and gathering — O(S · max_ctx) memory, the XLA-correctness baseline the
    Pallas kernel will replace with true block-sparse streaming.
    """
    t, h, d = q.shape
    s, bps = block_tables.shape
    max_ctx = bps * block_size
    kvh = k_cache.shape[1]

    # seq-relative position j lives in flat slot table[j // bs] * bs + j % bs
    j = jnp.arange(max_ctx)
    slot_of_pos = block_tables[:, j // block_size] * block_size + (j % block_size)
    k_seq = k_cache[slot_of_pos]  # [S, max_ctx, KVH, D]
    v_seq = v_cache[slot_of_pos]

    seq_clip = jnp.minimum(token_seq, s - 1)  # padded tokens: any valid row
    k_tok = k_seq[seq_clip]  # [T, max_ctx, KVH, D]
    v_tok = v_seq[seq_clip]
    if kvh != h:
        rep = h // kvh
        k_tok = jnp.repeat(k_tok, rep, axis=2)
        v_tok = jnp.repeat(v_tok, rep, axis=2)

    scale = 1.0 / np.sqrt(d)
    logits = jnp.einsum("thd,tchd->thc", q.astype(jnp.float32),
                        k_tok.astype(jnp.float32)) * scale
    mask = (j[None, :] <= token_pos[:, None])[:, None, :]  # causal over own seq
    logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("thc,tchd->thd", probs, v_tok.astype(jnp.float32))
    return out.astype(q.dtype)


def _packed_flash_attention(q, k_cache, v_cache, token_seq, token_pos,
                            block_tables, block_size: int):
    """Chunked-prefill attention through the Pallas flash kernel.

    The fix for the O(T·max_ctx) per-token KV gather of
    :func:`_paged_attention`: KV is gathered once per SEQUENCE
    ([S, max_ctx] resolved from the block table), flattened into one packed
    stream with per-slot segment ids + positions, and the flat token
    queries attend through ``flash_attention``'s ragged cross-attention
    mode — per-sequence boundaries from q/kv segment ids, causality in
    position space, logits streamed (never materialized). This is the
    TTFT-critical path (reference ``blocked_flash`` over ragged atoms).
    """
    from ...ops.flash_attention import flash_attention

    t, h, d = q.shape
    s, bps = block_tables.shape
    bs = block_size
    max_ctx = bps * bs

    j = jnp.arange(max_ctx)
    slot_of_pos = block_tables[:, j // bs] * bs + (j % bs)
    k_seq = k_cache[slot_of_pos]  # [S, max_ctx, KVH, D] — once per sequence
    v_seq = v_cache[slot_of_pos]
    kvh = k_cache.shape[1]
    k_flat = k_seq.reshape(1, s * max_ctx, kvh, d)
    v_flat = v_seq.reshape(1, s * max_ctx, kvh, d)
    kv_seg = jnp.repeat(jnp.arange(s, dtype=jnp.int32), max_ctx)[None]
    kv_pos = jnp.tile(jnp.arange(max_ctx, dtype=jnp.int32), s)[None]
    # pad tokens carry token_seq == S, matching no kv segment → fully masked
    out = flash_attention(q[None], k_flat, v_flat, causal=True,
                          segment_ids=token_seq[None].astype(jnp.int32),
                          kv_segment_ids=kv_seg,
                          q_positions=token_pos[None].astype(jnp.int32),
                          kv_positions=kv_pos)
    return out[0]


def ragged_forward(model, params: Any, kv: BlockedKV, tokens, token_seq,
                   token_pos, block_tables, last_tok_idx, *, block_size: int,
                   attn_impl: str = "auto"
                   ) -> Tuple[jnp.ndarray, BlockedKV]:
    """Flat-token forward. Returns (per-slot last-token logits [S, V], new kv).

    ``model``: a ``models.CausalLM`` — its stacked-layer params drive a
    ``lax.scan`` here exactly as in training (``models/transformer.py``).
    """
    cfg = model.config
    assert cfg.scan_layers, "ragged engine requires scan_layers param layout"
    bs = block_size
    num_slots = kv.num_slots
    t = tokens.shape[0]
    s = block_tables.shape[0]

    pad = token_seq >= s  # padding sentinel from RaggedBatch
    # flat destination slot per token; padded tokens scatter out-of-range (drop)
    dest_block = block_tables[jnp.minimum(token_seq, s - 1),
                              token_pos // bs]
    dest = jnp.where(pad, num_slots, dest_block * bs + token_pos % bs)

    x = jnp.take(params["embed"]["embedding"], tokens, axis=0)
    x = x.astype(jnp.dtype(cfg.dtype))

    def layer(x, inp):
        p, k_cache, v_cache = inp
        p = _dequant(p, x.dtype)
        y = rms_norm(x, p["attn_norm"]["scale"], cfg.rms_norm_eps)
        q = jnp.einsum("td,dq->tq", y, p["attn"]["wq"]).reshape(
            t, cfg.num_heads, cfg.head_dim)
        k = jnp.einsum("td,dk->tk", y, p["attn"]["wk"]).reshape(
            t, cfg.num_kv_heads, cfg.head_dim)
        v = jnp.einsum("td,dk->tk", y, p["attn"]["wv"]).reshape(
            t, cfg.num_kv_heads, cfg.head_dim)
        # RoPE in [B=1, S=T] layout
        q = apply_rope(q[None], token_pos[None], cfg.rope_theta)[0]
        k = apply_rope(k[None], token_pos[None], cfg.rope_theta)[0]
        k_cache = k_cache.at[dest].set(k.astype(k_cache.dtype), mode="drop")
        v_cache = v_cache.at[dest].set(v.astype(v_cache.dtype), mode="drop")
        impl = attn_impl
        if impl == "auto":
            impl = ("flash" if jax.default_backend() == "tpu" else "xla")
        if impl == "flash":
            attn = _packed_flash_attention(q, k_cache, v_cache, token_seq,
                                           token_pos, block_tables, bs)
        else:
            attn = _paged_attention(q, k_cache, v_cache, token_seq,
                                    token_pos, block_tables, bs)
        x = (x + jnp.einsum("tq,qd->td", attn.reshape(t, cfg.q_dim),
                            p["attn"]["wo"])).astype(x.dtype)
        y2 = rms_norm(x, p["mlp_norm"]["scale"], cfg.rms_norm_eps)
        h = _mlp(p, y2, cfg)
        return (x + h).astype(x.dtype), (k_cache, v_cache)

    x, (nk, nv) = jax.lax.scan(layer, x, (params["layers"], kv.k, kv.v))

    x = rms_norm(x, params["final_norm"]["scale"], cfg.rms_norm_eps)
    h_last = x[last_tok_idx]  # [S, d] — logits_gather
    if cfg.tie_embeddings:
        logits = jnp.einsum("sd,vd->sv", h_last,
                            params["embed"]["embedding"].astype(h_last.dtype))
    else:
        logits = jnp.einsum("sd,dv->sv", h_last,
                            params["lm_head"]["kernel"].astype(h_last.dtype))
    return logits.astype(jnp.float32), BlockedKV(nk, nv)


def build_ragged_forward_fn(model, block_size: int, attn_impl: str = "auto"):
    """Jitted, shape-stable forward (compiled once per engine)."""
    fn = partial(ragged_forward, model, block_size=block_size,
                 attn_impl=attn_impl)
    return jax.jit(fn, donate_argnums=(1,))


# ------------------------------------------------------------ decode fast path
def decode_forward(model, params: Any, kv: BlockedKV, tokens, positions,
                   block_tables, active, *, block_size: int,
                   attn_impl: str = "auto") -> Tuple[jnp.ndarray, BlockedKV]:
    """All-decode forward: ONE token per slot, attention via the Pallas paged
    decode kernel (``ops/paged_attention`` — the ``blocked_flash`` analog).

    ``tokens``/``positions``/``active``: [S]; positions = tokens already
    cached (the new token writes slot ``positions[s]``). This is the program
    serving spends most of its life in, so it gets the kernel; mixed
    prefill+decode batches take :func:`ragged_forward`.
    """
    from ...ops.paged_attention import paged_decode_attention

    cfg = model.config
    bs = block_size
    num_slots = kv.num_slots
    s = tokens.shape[0]

    dest_block = jnp.take_along_axis(
        block_tables, (positions // bs)[:, None], axis=1)[:, 0]
    dest = jnp.where(active, dest_block * bs + positions % bs, num_slots)
    seq_lens = jnp.where(active, positions + 1, 0)

    x = jnp.take(params["embed"]["embedding"], tokens, axis=0)
    x = x.astype(jnp.dtype(cfg.dtype))

    def layer(x, inp):
        p, k_cache, v_cache = inp
        p = _dequant(p, x.dtype)
        y = rms_norm(x, p["attn_norm"]["scale"], cfg.rms_norm_eps)
        q = jnp.einsum("sd,dq->sq", y, p["attn"]["wq"]).reshape(
            s, cfg.num_heads, cfg.head_dim)
        k = jnp.einsum("sd,dk->sk", y, p["attn"]["wk"]).reshape(
            s, cfg.num_kv_heads, cfg.head_dim)
        v = jnp.einsum("sd,dk->sk", y, p["attn"]["wv"]).reshape(
            s, cfg.num_kv_heads, cfg.head_dim)
        q = apply_rope(q[None], positions[None], cfg.rope_theta)[0]
        k = apply_rope(k[None], positions[None], cfg.rope_theta)[0]
        k_cache = k_cache.at[dest].set(k.astype(k_cache.dtype), mode="drop")
        v_cache = v_cache.at[dest].set(v.astype(v_cache.dtype), mode="drop")
        attn = paged_decode_attention(q, k_cache, v_cache, block_tables,
                                      seq_lens, block_size=bs, impl=attn_impl)
        x2 = (x + jnp.einsum("sq,qd->sd", attn.reshape(s, cfg.q_dim),
                             p["attn"]["wo"])).astype(x.dtype)
        y2 = rms_norm(x2, p["mlp_norm"]["scale"], cfg.rms_norm_eps)
        h = _mlp(p, y2, cfg)
        return (x2 + h).astype(x.dtype), (k_cache, v_cache)

    x, (nk, nv) = jax.lax.scan(layer, x, (params["layers"], kv.k, kv.v))
    x = rms_norm(x, params["final_norm"]["scale"], cfg.rms_norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum("sd,vd->sv", x,
                            params["embed"]["embedding"].astype(x.dtype))
    else:
        logits = jnp.einsum("sd,dv->sv", x,
                            params["lm_head"]["kernel"].astype(x.dtype))
    return logits.astype(jnp.float32), BlockedKV(nk, nv)


def build_decode_forward_fn(model, block_size: int, attn_impl: str = "auto"):
    fn = partial(decode_forward, model, block_size=block_size,
                 attn_impl=attn_impl)
    return jax.jit(fn, donate_argnums=(1,))
