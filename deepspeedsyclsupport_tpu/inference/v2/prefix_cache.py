"""Cross-request KV prefix cache: a block-aligned prefix trie over the
paged pool.

The fleet router already co-locates same-tenant / same-prompt-head streams
"for future prefix reuse" (PR 13); this module is the engine side. The
blocked pool was shaped for it (Ragged Paged Attention, PAPERS.md):
attention reads KV through per-sequence block tables, so N streams can
point their leading table entries at the SAME physical blocks — a prefix
hit converts most of a prompt's prefill cost into a block-table copy and
chunked prefill starts at the first uncached token.

Design (docs/serving.md "prefix reuse"):

* **block alignment** — only FULL blocks are indexed, and a probe only
  matches whole blocks, so a stream's writable frontier (positions ≥ its
  ``cached_prefix_len``) is always at or past the first block it owns
  exclusively. Writes therefore never land in a shared block; the
  engine's copy-on-write (``_ensure_writable``) is defense-in-depth, not
  the steady-state path.
* **chained hashes** — the trie key for block *i* is
  ``H(key(i-1) ‖ tokens[i*B:(i+1)*B])``: one hash identifies the whole
  prefix up to and including block *i*, so lookup is a flat dict probe
  per block, no tree walk, and an interior divergence can never alias.
* **pinning** — an indexed block holds one allocator reference (the
  "index pin"), so it outlives the stream that produced it; index
  eviction (LRU beyond ``max_pinned_blocks``) and allocator-pressure
  :meth:`reclaim` release that pin through the same refcounted path as
  every other holder. ``min_block_hits`` > 1 defers the pin until a
  block's hash has been offered that many times (don't pin one-off
  prompts).
* **scope** — ``"tenant"`` (default) keys the trie per tenant, so one
  tenant's prompts are never visible to another's probes; ``"global"``
  shares across tenants (single-tenant deployments).

Everything here is host-side bookkeeping; the only device interaction is
indirect, through the allocator refcounts that keep pinned blocks out of
the free list.
"""
import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_GLOBAL_SCOPE = "*"


def chain_hash(prev: bytes, tokens: Sequence[int]) -> bytes:
    """Key for the block holding ``tokens``, chained on the previous
    block's key — identifies the entire prefix, not just this block."""
    return hashlib.sha1(
        prev + np.asarray(tokens, np.int64).tobytes()).digest()


class PrefixCache:
    """Block-aligned, tenant-scoped prefix trie over a
    :class:`~.ragged.BlockedAllocator`'s pool.

    The engine owns the instance (``engine.prefix_cache``, installed via
    ``engine.install_prefix_cache`` — normally by ``ServingSession`` from
    ``ServingPolicyConfig.prefix_cache``). Counters are plain ints; the
    serving layer surfaces them as ``Serve/prefix.*``.
    """

    def __init__(self, allocator, block_size: int, *,
                 scope: str = "tenant", min_block_hits: int = 1,
                 max_pinned_blocks: Optional[int] = None):
        if scope not in ("tenant", "global"):
            raise ValueError(f"scope must be tenant|global, got {scope!r}")
        if min_block_hits < 1:
            raise ValueError(f"min_block_hits must be >= 1, got "
                             f"{min_block_hits}")
        if max_pinned_blocks is not None and max_pinned_blocks < 1:
            raise ValueError(f"max_pinned_blocks must be >= 1 or None, got "
                             f"{max_pinned_blocks}")
        self.allocator = allocator
        self.block_size = int(block_size)
        self.scope = scope
        self.min_block_hits = int(min_block_hits)
        # default cap: half the pool — the cache must never be able to pin
        # the whole pool against live streams even before reclaim pressure
        self.max_pinned_blocks = (max(1, allocator.num_blocks // 2)
                                  if max_pinned_blocks is None
                                  else int(max_pinned_blocks))
        # (scope_key, chain_hash) -> physical block id; insertion order is
        # recency (move_to_end on every probe touch) — the LRU for both the
        # pin cap and allocator-pressure reclaim
        self._index: "OrderedDict[Tuple[str, bytes], int]" = OrderedDict()
        # hashes seen but not yet pinned (min_block_hits > 1): observation
        # counts only — no block id is stored, so a stale entry can never
        # dangle into reused storage
        self._cand: Dict[Tuple[str, bytes], int] = {}
        self.counters: Dict[str, int] = {
            "hits": 0, "misses": 0, "tokens_saved": 0, "blocks_shared": 0,
            "cow_copies": 0, "pins": 0, "unpins": 0}

    # --------------------------------------------------------------- keys
    def _scope_key(self, tenant: str) -> str:
        return tenant if self.scope == "tenant" else _GLOBAL_SCOPE

    def _walk(self, tokens: Sequence[int], tenant: str,
              touch: bool) -> Tuple[List[int], List[bytes]]:
        """Longest indexed block-aligned prefix of ``tokens``. Capped at
        ``len(tokens) - 1`` so at least one token always runs a forward —
        the stream needs logits to decode from."""
        sk = self._scope_key(tenant)
        limit = max(0, (len(tokens) - 1) // self.block_size)
        blocks: List[int] = []
        hashes: List[bytes] = []
        h = b""
        for i in range(limit):
            h = chain_hash(h, tokens[i * self.block_size:
                                     (i + 1) * self.block_size])
            b = self._index.get((sk, h))
            if b is None:
                break
            if touch:
                self._index.move_to_end((sk, h))
            blocks.append(b)
            hashes.append(h)
        return blocks, hashes

    # -------------------------------------------------------------- probe
    def probe(self, tokens: Sequence[int],
              tenant: str = "default") -> Tuple[List[int], List[bytes], int]:
        """Admission-time lookup: ``(blocks, hashes, cached_len)`` for the
        longest cached block-aligned prefix (possibly empty). Counts a hit
        or miss and refreshes the matched entries' recency. The CALLER
        maps the blocks (``allocator.retain`` + block-table entries) —
        the cache itself takes no new references on a probe."""
        blocks, hashes, = self._walk(tokens, tenant, touch=True)
        cached = len(blocks) * self.block_size
        if blocks:
            self.counters["hits"] += 1
            self.counters["tokens_saved"] += cached
            self.counters["blocks_shared"] += len(blocks)
        else:
            self.counters["misses"] += 1
        return blocks, hashes, cached

    def peek(self, tokens: Sequence[int], tenant: str = "default") -> int:
        """Cached-prefix length WITHOUT counters or recency touches — the
        admission gate's pricing input (``n_prefill − cached_prefix_len``),
        called speculatively for requests that may never be admitted."""
        blocks, _ = self._walk(tokens, tenant, touch=False)
        return len(blocks) * self.block_size

    # ------------------------------------------------------------- insert
    def offer(self, tenant: str, chain_h: bytes, block: int) -> bool:
        """Offer one freshly-FULL block for indexing (engine commit path).
        Returns True when the block is now pinned in the index. Repeated
        offers of an already-indexed hash only refresh recency — first
        writer wins, so N streams sharing a prefix converge on one
        physical copy."""
        key = (self._scope_key(tenant), chain_h)
        if key in self._index:
            self._index.move_to_end(key)
            return True
        if self.min_block_hits > 1:
            seen = self._cand.get(key, 0) + 1
            if seen < self.min_block_hits:
                self._cand[key] = seen
                return False
            self._cand.pop(key, None)
        # the index is a holder: the pin keeps the block id valid (never
        # recycled) for as long as the entry lives
        self.allocator.retain([block])
        self.counters["pins"] += 1
        self._index[key] = block
        while len(self._index) > self.max_pinned_blocks:
            self._unpin(next(iter(self._index)))
        return True

    # ----------------------------------------------------------- eviction
    def _unpin(self, key: Tuple[str, bytes]) -> None:
        block = self._index.pop(key)
        self.allocator.release([block])
        self.counters["unpins"] += 1

    def reclaim(self, n_blocks: int) -> int:
        """Allocator-pressure valve (``allocator.reclaim_cb``): release up
        to ``n_blocks`` COLD UNSHARED pins — LRU entries whose block has no
        holder besides the index — and report how many came free. Entries
        still mapped by a live stream (refcount > 1) are skipped: unpinning
        them frees nothing and only forgets a provably-hot prefix."""
        freed = 0
        for key in list(self._index):
            if freed >= n_blocks:
                break
            if self.allocator.refcount(self._index[key]) == 1:
                self._unpin(key)
                freed += 1
        return freed

    def reclaimable(self) -> int:
        """Pins :meth:`reclaim` could surrender right now (refcount 1 —
        no live stream maps them): the engine's admission check counts
        these as free KV headroom."""
        return sum(1 for b in self._index.values()
                   if self.allocator.refcount(b) == 1)

    def invalidate(self, tenant: Optional[str] = None) -> int:
        """Drop (and unpin) every entry — or one tenant's under tenant
        scope. The blunt instrument for tests and operator resets."""
        keys = [k for k in self._index
                if tenant is None or k[0] == self._scope_key(tenant)]
        for k in keys:
            self._unpin(k)
        if tenant is None:
            self._cand.clear()
        else:
            sk = self._scope_key(tenant)
            self._cand = {k: v for k, v in self._cand.items() if k[0] != sk}
        return len(keys)

    # ------------------------------------------------------------ reporting
    def note_cow(self, n: int = 1) -> None:
        self.counters["cow_copies"] += n

    @property
    def pinned_blocks(self) -> int:
        return len(self._index)

    @property
    def hit_ratio(self) -> float:
        lookups = self.counters["hits"] + self.counters["misses"]
        return self.counters["hits"] / lookups if lookups else 0.0

    def stats(self) -> Dict[str, float]:
        return {**self.counters, "pinned_blocks": self.pinned_blocks,
                "hit_ratio": round(self.hit_ratio, 4)}
