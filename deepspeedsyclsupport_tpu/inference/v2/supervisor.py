"""Serving-plane fault tolerance: request journal + replica supervisor.

The training plane has had structured resilience contracts since PR 1/PR 6
(rc 217 preemption, rc 218 collective hang, crc32 pod commits); this module
mirrors them onto the v2 serving engine, which an MII-style frontend keeps
alive for weeks — one wedged decode step or engine crash must cost the
affected streams a re-prefill, not every in-flight stream its output:

* :class:`RequestJournal` — every admitted request's immutable prompt, SLA
  fields and emitted-token watermark as a rank-local JSONL (one
  flushed-per-record stream riding the ``FlightRecorder``/``JsonlMonitor``
  machinery from ``monitor/telemetry.py``), so in-flight state survives the
  process. The journal is written *before* tokens are released to the
  caller, which is what makes replay exactly-once: a token the client saw
  is on disk, a token not on disk was never delivered.
* :func:`load_journal` / :func:`recover_requests` — rebuild the in-flight
  request set from one or more incarnations' journals (truncation-salvaged:
  a torn tail line is expected for a crash) and replay it into a fresh
  :class:`~.serving.ServingSession` from each stream's watermark. TTFT is
  already burned, so replay re-gates on the rate SLA only (the PR 4 requeue
  rule); provably-unmeetable streams are shed with terminal accounting
  (``Serve/recovery.replay_sheds``), the rest re-prefill prompt+prefix and
  continue — zero duplicate, zero missing tokens.
* :class:`ReplicaSupervisor` — a serving-flavored
  :class:`~...elasticity.elastic_agent.DSElasticAgent`: restarts a
  dead/hung engine worker (rc 219 ``SERVE_HANG_EXIT_CODE`` — the
  stuck-decode watchdog's structured exit — is its own restart class,
  never billed as a crash), exposes health/readiness (heartbeat-derived
  state file) and drains before stopping: a SIGTERM to the supervisor
  forwards to the worker, which finishes its live streams and exits 0
  instead of being killed mid-decode.
* a worker CLI (``python -m deepspeedsyclsupport_tpu.inference.v2.supervisor
  --worker --spec spec.json``) — the minimal journaled serving loop the
  two-process chaos tests (and operators smoke-testing a replica) drive.

See ``docs/serving.md`` ("failure contract") for rc-219 semantics, the
journal format and the replay-vs-shed decision table.
"""
import argparse
import glob as _glob
import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ...comm.watchdog import SERVE_HANG_EXIT_CODE  # noqa: F401 (re-export)
from ...elasticity.elastic_agent import DSElasticAgent
from ...utils.logging import logger


# =========================================================================
# Request journal (write side)
# =========================================================================


class RequestJournal:
    """Rank-local JSONL request journal: admission, emission watermarks and
    terminal outcomes, flushed per record so the stream is truthful at any
    crash point.

    Record names (``kind: "event"`` in the shared flight-recorder schema,
    so ``tools/trace_report.py`` parses the stream unmodified):

    * ``serve/admit`` — immutable prompt + SLA fields; ``replayed: true``
      entries carry the ``out`` prefix recovered from a prior incarnation
      (the watermark the new stream continues from).
    * ``serve/emit`` — tokens released to the caller this event, plus the
      cumulative ``emitted`` watermark.
    * ``serve/close`` — terminal: ``done | eos | context | evicted |
      shed:<why> | replay_shed``. A request with an admit and no close is
      *in flight* — the replay set.

    The journal also doubles as the serve watchdog's telemetry sink
    (:attr:`recorder` / :meth:`dump`), so ``serve/arm``/``serve/hang``
    deadline records land in the same on-disk stream the post-mortem reads.
    """

    def __init__(self, path: str, flush_interval: int = 1):
        from ...monitor.monitor import JsonlMonitor
        from ...monitor.telemetry import FlightRecorder

        self.path = path
        self.recorder = FlightRecorder(capacity=256)
        self._jsonl = JsonlMonitor(path=path, flush_interval=flush_interval)
        self._jsonl.attach_recorder(self.recorder)
        self._closed = False
        self.recorder.record(
            "meta", "serve_journal/start",
            data={"version": 1, "pid": os.getpid(),
                  "attempt": os.environ.get("DSTPU_ELASTIC_ATTEMPT", "0")})

    # ------------------------------------------------------------- writing
    def admit(self, uid: int, tokens: Sequence[int], max_new_tokens: int, *,
              tenant: str = "default", rate_sla: float = 0.0,
              ttft_sla_s: Optional[float] = None,
              out: Sequence[int] = (), replayed: bool = False) -> None:
        self.recorder.record(
            "event", "serve/admit",
            data={"uid": int(uid), "tokens": [int(t) for t in tokens],
                  "max_new_tokens": int(max_new_tokens), "tenant": tenant,
                  "rate_sla": float(rate_sla),
                  **({"ttft_sla_s": float(ttft_sla_s)}
                     if ttft_sla_s is not None else {}),
                  **({"out": [int(t) for t in out], "replayed": True}
                     if replayed else {})})

    def emit(self, uid: int, tokens: Sequence[int], emitted: int) -> None:
        self.recorder.record(
            "event", "serve/emit",
            data={"uid": int(uid), "tokens": [int(t) for t in tokens],
                  "emitted": int(emitted)})

    def close_request(self, uid: int, reason: str) -> None:
        self.recorder.record("event", "serve/close",
                             data={"uid": int(uid), "reason": reason})

    def stage(self, uid: int, stage: str, dur: Optional[float] = None,
              **data: Any) -> None:
        """``serve/stage`` lifecycle-edge record (request-time attribution:
        ``monitor/reqtrace.py`` joins these into per-request span trees).
        Rides the same flushed stream as admit/emit/close — no second
        transport, and the recorder's wall ``t`` is the one clock base the
        offline join orders on. ``stage`` must be declared in
        ``reqtrace.SERVE_STAGES`` (dslint's ``undeclared-stage-name`` rule
        enforces literals at lint time; this validates dynamic calls).
        ``uid`` −1 marks session-scope records (decode rounds carry the
        scheduled uid list in ``data`` instead)."""
        from ...monitor.reqtrace import check_stage

        check_stage(stage)
        self.recorder.record(
            "event", "serve/stage",
            data={"uid": int(uid), "stage": stage,
                  **({"dur": float(dur)} if dur is not None else {}),
                  **data})

    # ------------------------------------------------- watchdog sink duties
    def dump(self, reason: str = "manual") -> None:
        """Telemetry-compatible flush hook (the serve watchdog calls
        ``telemetry.dump(...)`` before exiting rc 219)."""
        self.recorder.dump(reason)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._jsonl.close()
        except Exception as e:  # journal teardown must never kill serving
            logger.warning("request journal close failed: %s", e)


# =========================================================================
# Journal recovery (read side)
# =========================================================================


@dataclass
class ReplayRequest:
    """One request's journaled state, merged across incarnations."""

    uid: int
    tokens: List[int]
    max_new_tokens: int
    tenant: str = "default"
    rate_sla: float = 0.0
    out: List[int] = field(default_factory=list)  # emitted-token watermark
    closed: bool = False
    reason: str = ""

    @property
    def in_flight(self) -> bool:
        return not self.closed


def _journal_files(paths: Any) -> List[str]:
    """Expand file / directory / glob / list inputs into journal files,
    oldest incarnation first (mtime, then name — attempt-suffixed names
    from one supervisor tick can share an mtime granule)."""
    if isinstance(paths, (list, tuple)):
        out: List[str] = []
        for p in paths:
            out.extend(_journal_files(p))
        seen: set = set()
        uniq = [p for p in out if not (p in seen or seen.add(p))]
        return sorted(uniq, key=lambda p: (os.path.getmtime(p), p))
    if os.path.isdir(paths):
        found = _glob.glob(os.path.join(paths, "journal_rank*.jsonl"))
    elif _glob.has_magic(paths):
        found = _glob.glob(paths)
    else:
        found = [paths] if os.path.exists(paths) else []
    return sorted(found, key=lambda p: (os.path.getmtime(p), p))


def load_journal(paths: Any) -> Tuple[Dict[int, ReplayRequest], float]:
    """Merge journal stream(s) into per-uid replay states.

    Returns ``(states, last_t)`` where ``last_t`` is the newest wall
    timestamp seen across all records (0.0 if none) — the
    time-to-recover baseline. A torn final line (crash mid-write) is
    skipped, not fatal: everything before it was flushed durably.
    """
    states: Dict[int, ReplayRequest] = {}
    last_t = 0.0
    for path in _journal_files(paths):
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            logger.warning("journal %s unreadable (%s); skipped", path, e)
            continue
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail — expected for a crash dump
            last_t = max(last_t, float(rec.get("t", 0.0)))
            name = rec.get("name")
            data = rec.get("data") or {}
            if "uid" not in data:
                continue
            uid = int(data["uid"])
            if name == "serve/admit":
                # an admit RESETS the state: a replayed admit carries the
                # prefix recovered so far; emits that follow continue it
                states[uid] = ReplayRequest(
                    uid=uid, tokens=list(data.get("tokens", [])),
                    max_new_tokens=int(data.get("max_new_tokens", 0)),
                    tenant=data.get("tenant", "default"),
                    rate_sla=float(data.get("rate_sla", 0.0)),
                    out=list(data.get("out", [])))
            elif name == "serve/emit" and uid in states:
                states[uid].out.extend(int(t) for t in data.get("tokens", []))
            elif name == "serve/close" and uid in states:
                states[uid].closed = True
                states[uid].reason = data.get("reason", "")
    return states, last_t


def recover_requests(session: Any, states: Dict[int, ReplayRequest],
                     last_t: float = 0.0) -> Dict[str, Any]:
    """Replay every in-flight journaled request into ``session`` from its
    emitted-token watermark; returns the recovery summary.

    Closed requests are skipped (their output is already delivered and on
    disk). Each in-flight request goes through
    :meth:`~.serving.ServingSession.replay` — rate-SLA re-gate only,
    terminal shed accounting for unmeetable ones. The recovery duration
    (now − newest pre-crash journal record) lands in the
    ``Serve/recovery.time_to_recover_s`` histogram.
    """
    summary: Dict[str, Any] = {"replayed": [], "shed": [], "completed": [],
                               "skipped_closed": [],
                               "time_to_recover_s": None}
    for uid in sorted(states):
        st = states[uid]
        if st.closed:
            summary["skipped_closed"].append(uid)
            continue
        outcome = session.replay(uid, st.tokens, st.max_new_tokens,
                                 emitted_tokens=st.out, tenant=st.tenant,
                                 rate_sla=st.rate_sla)
        key = {"replayed": "replayed", "shed": "shed",
               "completed": "completed"}[outcome]
        summary[key].append(uid)
    if last_t > 0:
        # wall-clock on purpose: the baseline is a DEAD process's wall
        # timestamp — monotonic clocks don't survive the process
        dt = max(0.0, time.time() - last_t)  # dslint: allow(wall-clock-in-step-path)
        summary["time_to_recover_s"] = round(dt, 3)
        if getattr(session, "_metrics", None) is not None:
            session._metrics.histogram(
                "Serve/recovery.time_to_recover_s").observe(dt)
    if summary["replayed"] or summary["shed"] or summary["completed"]:
        logger.info("journal recovery: %d replayed, %d shed, %d already "
                    "complete, %d closed (t_recover=%ss)",
                    len(summary["replayed"]), len(summary["shed"]),
                    len(summary["completed"]), len(summary["skipped_closed"]),
                    summary["time_to_recover_s"])
    return summary


def reconstruct_outputs(states: Dict[int, ReplayRequest]) -> Dict[int, List[int]]:
    """Per-uid generated-token sequences as the client saw them (the
    journal's emit stream IS the delivery record) — what the chaos tests
    compare against an uninterrupted run for token-sequence equality."""
    return {uid: list(st.out) for uid, st in states.items()}


# =========================================================================
# Replica supervisor
# =========================================================================


class ReplicaSupervisor(DSElasticAgent):
    """Keep one serving replica alive: restart on crash/hang, drain on stop.

    A serving-flavored :class:`DSElasticAgent`: per-cause restart
    accounting (rc 219 stuck-decode hangs are their own class — bounded by
    ``serve_hang_limit``, never billed against ``restart_limit``), plus

    * **drain-before-stop** — :meth:`install_drain_handler` registers a
      store-only SIGTERM/SIGINT handler; the supervising loop forwards the
      signal to the worker, which finishes its live streams (its own
      drain contract) and exits 0 within ``drain_grace`` — SIGKILL only
      past the grace. No relaunch follows a drain.
    * **health/readiness probe** — ``health_file`` is atomically rewritten
      with ``{"state", "worker_pid", "attempt", "ready", "t"}`` at every
      poll; ``ready`` is derived from the worker's telemetry heartbeat
      freshness when a heartbeat watch is configured (a readiness gate a
      load balancer can poll without touching the worker).
    """

    def __init__(self, cmd: Sequence[str], *,
                 health_file: Optional[str] = None,
                 drain_grace: float = 30.0,
                 poll_s: float = 0.2,
                 **kw):
        kw.setdefault("restart_limit", 3)
        super().__init__(cmd, {"elasticity": {"enabled": False}}, **kw)
        self.health_file = health_file
        self.drain_grace = float(drain_grace)
        self.poll_s = float(poll_s)
        self.drained = False
        # store-only flag a SIGTERM handler may set (async-signal-safe:
        # the supervising loop drains it — never the handler itself)
        self._drain_pending = False

    # ------------------------------------------------------------- signals
    def install_drain_handler(self,
                              signals: Iterable[int] = (signal.SIGTERM,
                                                        signal.SIGINT)
                              ) -> None:
        """Main-thread-only (CPython): SIGTERM/SIGINT request a drain."""
        for s in signals:
            signal.signal(s, self._on_drain_signal)

    def _on_drain_signal(self, signum, frame) -> None:
        # attribute store ONLY — see runtime/resilience.py for why a
        # handler must not log, lock or touch subprocess state
        self._drain_pending = True

    # -------------------------------------------------------------- health
    def _write_health(self, state: str, pid: Optional[int],
                      rc: Optional[int] = None) -> None:
        if not self.health_file:
            return
        ready = False
        if state == "serving":
            from ...monitor.telemetry import Heartbeat

            ages = [Heartbeat.age(p) for p in self._heartbeat_files()]
            ages = [a for a in ages if a is not None]
            if self.heartbeat_timeout is not None:
                ready = bool(ages) and max(ages) <= self.heartbeat_timeout
            else:  # no watch configured: a live worker is ready
                ready = True
        rec = {"state": state, "worker_pid": pid, "ready": ready,
               "attempt": (self.restart_count + self.preemption_count
                           + self.comm_hang_count + self.serve_hang_count),
               # wall timestamp: the probe reader is another process
               "t": time.time()}  # dslint: allow(wall-clock-in-step-path)
        if rc is not None:
            rec["rc"] = rc
        tmp = f"{self.health_file}.tmp{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(self.health_file) or ".",
                        exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(rec, f)
            os.replace(tmp, self.health_file)
        except OSError as e:  # probe failure must never kill supervision
            logger.warning("health file write failed: %s", e)

    # -------------------------------------------------------------- launch
    def _launch(self, env: Dict[str, str]) -> int:
        """One worker attempt under the serving contract: poll for exit,
        refresh the health probe, escalate a stale heartbeat exactly like
        the base agent, and honor a pending drain request by forwarding
        SIGTERM and waiting out ``drain_grace``."""
        for path in self._heartbeat_files():
            try:  # a leftover beat from the last incarnation is stale
                os.unlink(path)
            except OSError:
                pass
        launched_at = time.monotonic()
        proc = subprocess.Popen(self.cmd, env=env)
        self._write_health("serving", proc.pid)
        hang_signaled = False
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            if self._drain_pending and not self.drained:
                self.drained = True
                self._stop_requested = True  # no relaunch after a drain
                self._write_health("draining", proc.pid)
                logger.info("replica supervisor: drain requested — "
                            "forwarding SIGTERM to worker pid %d", proc.pid)
                proc.terminate()
                try:
                    rc = proc.wait(timeout=self.drain_grace)
                except subprocess.TimeoutExpired:
                    logger.error("replica supervisor: worker did not drain "
                                 "within %.1fs — killing", self.drain_grace)
                    proc.kill()
                    rc = proc.wait()
                break
            if (self.heartbeat_file is not None
                    and self.heartbeat_timeout is not None
                    and not hang_signaled
                    and self._heartbeat_stale(launched_at)):
                from ...monitor.monitor import resilience_counters

                hang_signaled = True
                self.hang_count += 1
                resilience_counters.incr("hang_restarts")
                logger.error("replica supervisor: heartbeat stale > %.1fs — "
                             "worker hung; stack-dumping then killing pid %d",
                             self.heartbeat_timeout, proc.pid)
                if hasattr(signal, "SIGUSR1"):
                    try:
                        proc.send_signal(signal.SIGUSR1)
                    except OSError:  # pragma: no cover - died under us
                        pass
                    self._sleep(self.hang_grace)
                if proc.poll() is None:
                    proc.terminate()
                    try:
                        proc.wait(timeout=self.hang_grace)
                    except subprocess.TimeoutExpired:  # pragma: no cover
                        proc.kill()
                rc = proc.wait()
                break
            self._write_health("serving", proc.pid)
            self._sleep(self.poll_s)
        if rc is None:  # pragma: no cover - defensive
            rc = proc.wait()
        self._write_health("stopped" if (rc == 0 or self.drained)
                           else "restarting", None, rc)
        return rc


# =========================================================================
# Worker CLI (the journaled serving loop the chaos tests drive)
# =========================================================================


def journal_path(journal_dir: str, rank: int = 0,
                 attempt: Any = None) -> str:
    """Per-incarnation journal filename — the ONE place the
    ``journal_rank<r>.att<N>.jsonl`` convention lives (``_journal_files``
    discovers it, the worker and bench construct it). ``attempt`` defaults
    to this incarnation's ``DSTPU_ELASTIC_ATTEMPT``. Under a fleet pool,
    ``DSTPU_FLEET_GEN`` (the supervisor *generation* — bumped on every
    pool respawn) namespaces the attempt so a respawned supervisor's
    attempt 0 never appends to a dead generation's file — appending would
    scramble ``_journal_files``'s oldest-first mtime merge."""
    if attempt is None:
        attempt = os.environ.get("DSTPU_ELASTIC_ATTEMPT", "0")
    gen = os.environ.get("DSTPU_FLEET_GEN")
    if gen is not None:
        attempt = f"{gen}.{attempt}"
    return os.path.join(journal_dir, f"journal_rank{rank}.att{attempt}.jsonl")


def serve_worker(spec_path: str) -> int:
    """Minimal journaled serving replica: build the engine from a JSON
    spec, recover in-flight requests from prior incarnations' journals,
    serve the spec's request list to completion, write the reconstructed
    per-uid outputs, exit 0.

    Spec keys: ``model`` (name, default "tiny"), ``dtype``, ``engine``
    (``RaggedInferenceConfig`` dict), ``policy`` (``ServingPolicyConfig``
    dict — ``journal_path`` is filled in per incarnation), ``journal_dir``
    (required), ``out`` (output JSON path), ``requests``:
    ``[{"uid", "tokens", "max_new_tokens", "tenant"?, "rate_sla"?}]``.

    Fleet mode (``inference/v2/fleet``) adds: ``spool_dir`` — serve
    request files a router drops there (``replayed: true`` entries go
    through :meth:`~.serving.ServingSession.replay`; replica-side sheds
    are journaled admit+close so the router observes closure);
    ``stop_file`` — exit 0 once it exists and everything is drained;
    ``recover`` (default true) — replay prior incarnations' journals at
    startup. Streams claimed by a router failover
    (``fleet/failover_claim.json`` in the journal dir) are never
    recovered or re-ingested here — they belong to a surviving replica.
    """
    with open(spec_path) as f:
        spec = json.load(f)
    journal_dir = spec["journal_dir"]
    os.makedirs(journal_dir, exist_ok=True)

    from ...models import build_model
    from ...monitor.telemetry import Heartbeat
    from .config import ServingPolicyConfig
    from .engine_v2 import InferenceEngineV2
    from .fleet.failover import read_claims
    from .serving import ServingSession

    model = build_model(spec.get("model", "tiny"),
                        dtype=spec.get("dtype", "float32"))
    params = model.init_params()
    eng = InferenceEngineV2(model, params, config=spec.get("engine", {}))
    jpath = journal_path(journal_dir)
    policy = ServingPolicyConfig.from_config(
        {**spec.get("policy", {}), "journal_path": jpath})
    # recover BEFORE constructing the session so the fresh journal's first
    # records are the replayed admits (prior incarnations stay read-only)
    prior = [p for p in _journal_files(journal_dir) if p != jpath]
    states, last_t = load_journal(prior)
    claim = read_claims(journal_dir)
    # router-claimed streams were failed over to a surviving replica —
    # recovering them here would double-serve (the exactly-once contract)
    recoverable = {u: st for u, st in states.items() if not claim.covers(u)}
    session = ServingSession(eng, policy)
    if spec.get("recover", True):
        summary = recover_requests(session, recoverable, last_t)
    else:
        summary = {"replayed": [], "shed": [], "completed": [],
                   "skipped_closed": sorted(recoverable),
                   "time_to_recover_s": None}
    # journaled, claimed, replayed or replay-shed — never resubmit
    handled = set(states) | {int(u) for u in claim.uids}
    heartbeat = Heartbeat(os.path.join(journal_dir, "heartbeat_rank0.json"),
                          interval_s=0.2)
    # drain contract: SIGTERM = stop ADMITTING (spec resubmits AND spool
    # ingestion) and finish live streams — store-only handler, drained by
    # the loop
    drain = {"pending": False}

    def _on_term(signum, frame):
        drain["pending"] = True

    signal.signal(signal.SIGTERM, _on_term)

    outcomes: Dict[int, str] = {}

    def _admit(r: Dict[str, Any]) -> None:
        uid = int(r["uid"])
        if uid in handled:
            return
        handled.add(uid)
        sp = r.get("spooled_t")
        if sp is not None:
            # replica spool-ingestion edge: how long the request file sat
            # in the spool before this loop picked it up (wall stamps on
            # both sides — the router's _spool writes spooled_t)
            session.note_stage(
                uid, "spool_wait",
                dur=max(0.0, time.time() - float(sp)))  # dslint: allow(wall-clock-in-step-path)
        if r.get("replayed"):
            outcomes[uid] = session.replay(
                uid, r["tokens"], int(r["max_new_tokens"]),
                emitted_tokens=r.get("out", ()),
                tenant=r.get("tenant", "default"),
                rate_sla=r.get("rate_sla"))
            return
        outcomes[uid] = session.submit(
            uid, r["tokens"], int(r["max_new_tokens"]),
            tenant=r.get("tenant", "default"),
            ttft_sla_s=r.get("ttft_sla_s"),
            rate_sla=r.get("rate_sla"))
        if outcomes[uid] == "shed" and session.journal is not None:
            # submit-time sheds are synchronous to a LOCAL caller, but a
            # router only sees the journal — give it the terminal record
            session.journal.admit(uid, r["tokens"],
                                  int(r["max_new_tokens"]),
                                  tenant=r.get("tenant", "default"),
                                  rate_sla=r.get("rate_sla") or 0.0)
            session.journal.close_request(uid, "shed:replica")

    for r in spec.get("requests", []):
        _admit(r)

    spool_dir = spec.get("spool_dir")
    stop_file = spec.get("stop_file")
    consumed: set = set()
    spool_seen = {"mtime": -1}

    def _ingest_spool(force: bool = False) -> int:
        """Submit new spool files in sequence order; returns how many.
        The scan is gated on the directory's mtime — this runs every
        scheduler tick, and re-listing (plus re-parsing the claim file)
        for a spool that has not changed is pure waste in the decode hot
        loop. ``force`` bypasses the gate (the stop check, and a periodic
        sweep covering coarse-mtime filesystems where a rename inside the
        same timestamp granule would otherwise be invisible)."""
        try:
            mtime = os.stat(spool_dir).st_mtime_ns
        except OSError:
            return 0
        if not force and mtime == spool_seen["mtime"]:
            return 0
        try:
            names = sorted(os.listdir(spool_dir))
        except OSError:
            return 0
        fresh = [nm for nm in names
                 if nm.endswith(".json") and nm not in consumed]
        if not fresh:
            spool_seen["mtime"] = mtime
            return 0
        n = 0
        retry = False
        fresh_claim = read_claims(journal_dir)
        for name in fresh:
            try:
                with open(os.path.join(spool_dir, name)) as f:
                    r = json.load(f)
            except (OSError, ValueError):
                retry = True
                continue  # racing the atomic rename — retry next pass
            consumed.add(name)
            uid = int(r["uid"])
            if fresh_claim.covers(uid):
                handled.add(uid)
                continue  # failed over elsewhere while we were down
            if uid not in handled:
                _admit(r)
                n += 1
        if not retry:  # a deferred file keeps the scan hot until it lands
            spool_seen["mtime"] = mtime
        return n

    prom_path = os.path.join(journal_dir, "metrics_rank0.prom")
    rounds = 0
    if spool_dir:
        while True:
            if not drain["pending"]:
                _ingest_spool(force=(rounds % 64 == 0))
            events = session.step() if not session.idle else []
            rounds += 1
            heartbeat.beat(rounds)
            if rounds % 512 == 0:
                # serving-plane textfile export: same atomic-rename
                # contract as the training side's Telemetry.export_textfile
                session.export_metrics(prom_path)
            if drain["pending"]:
                if session.idle:
                    break
                continue
            if stop_file and os.path.exists(stop_file) and session.idle:
                # one last ingest (forced): a request spooled between the
                # previous pass and the stop marker must not strand
                if not _ingest_spool(force=True):
                    break
                continue
            if not events:
                time.sleep(0.002)
    else:
        while not session.idle:
            events = session.step()
            rounds += 1
            heartbeat.beat(rounds)
            if not events:
                time.sleep(0.001)
    session.export_metrics(prom_path)
    session.close()
    # the journal (all incarnations) is the delivery record — reconstruct
    # the full per-uid sequences from it so the output survives any number
    # of crash/replay cycles
    final_states, _ = load_journal(journal_dir)
    outputs = reconstruct_outputs(final_states)
    out_path = spec.get("out")
    if out_path:
        tmp = f"{out_path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"outputs": {str(u): t for u, t in outputs.items()},
                       "recovery": summary,
                       "closed": {str(u): st.reason
                                  for u, st in final_states.items()
                                  if st.closed},
                       "stats": session.stats(),
                       "recovery_counters": dict(session.recovery_counters),
                       "drained": drain["pending"]}, f)
        os.replace(tmp, out_path)
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI — supervisor mode (default) spawns and supervises the worker::

        python -m deepspeedsyclsupport_tpu.inference.v2.supervisor \\
            --spec spec.json [--restart-limit N] [--serve-hang-limit N] \\
            [--health-file health.json] [--heartbeat-timeout S]

    ``--worker`` runs the serving loop itself (the supervisor's child)."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--spec", required=True,
                    help="worker spec JSON (see serve_worker docstring)")
    ap.add_argument("--worker", action="store_true",
                    help="run the serving worker loop (child mode)")
    ap.add_argument("--restart-limit", type=int, default=3)
    ap.add_argument("--serve-hang-limit", type=int, default=None,
                    help="consecutive stuck-decode exits (rc 219) before "
                         "the supervisor gives up (default: unbounded)")
    ap.add_argument("--storm-limit", type=int, default=None)
    ap.add_argument("--backoff-seconds", type=float, default=0.5)
    ap.add_argument("--drain-grace", type=float, default=30.0)
    ap.add_argument("--health-file", default=None)
    ap.add_argument("--heartbeat-timeout", type=float, default=None)
    args = ap.parse_args(argv)
    if args.worker:
        return serve_worker(args.spec)
    with open(args.spec) as f:
        spec = json.load(f)
    journal_dir = spec["journal_dir"]
    sup = ReplicaSupervisor(
        [sys.executable, "-m",
         "deepspeedsyclsupport_tpu.inference.v2.supervisor",
         "--worker", "--spec", args.spec],
        restart_limit=args.restart_limit,
        serve_hang_limit=args.serve_hang_limit,
        storm_limit=args.storm_limit,
        backoff_seconds=args.backoff_seconds,
        drain_grace=args.drain_grace,
        health_file=args.health_file
        or os.path.join(journal_dir, "health.json"),
        heartbeat_file=os.path.join(journal_dir, "heartbeat_rank0.json"),
        heartbeat_timeout=args.heartbeat_timeout)
    sup.install_drain_handler()
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
