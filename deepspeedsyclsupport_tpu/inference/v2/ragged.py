"""Ragged-batch state: block allocator, sequence descriptors, batch metadata.

Analogs of the reference's ``inference/v2/ragged/`` host-side machinery:

* :class:`BlockedAllocator` — ``ragged/blocked_allocator.py`` free-list of KV
  blocks (there a torch int32 linked list; here a plain Python free list — this
  is host bookkeeping, never on device).
* :class:`SequenceDescriptor` — ``ragged/sequence_descriptor.py``
  (``DSSequenceDescriptor``): tokens seen/scheduled, owned KV blocks.
* :class:`RaggedBatch` — ``ragged/ragged_wrapper.py`` (``RaggedBatchWrapper``):
  the per-forward metadata arrays, built once on host and shipped to device as
  one transfer (the reference stages the same arrays into pinned host buffers).

Static shapes: every array is padded to (max_tokens, max_sequences,
blocks_per_seq) so ONE compiled XLA program serves every batch composition —
the TPU equivalent of the reference building variable-size batches eagerly.
"""
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class BlockedAllocator:
    """Refcounted KV block free-list (reference ``ragged/blocked_allocator.py``
    plus vLLM-style per-block reference counts for cross-request sharing).

    Serving-loop callers (the scheduler's chunk admission, the fused-decode
    pre-fund) go through :meth:`try_allocate`: exhaustion — real or
    injected (``DSTPU_FAULT_INJECTION`` ``kv_alloc_fail``) — answers
    ``None`` so the engine surfaces structured backpressure (the sequence
    stays pending / falls back to the evicting per-token path) instead of
    an exception tearing down the whole serving loop. :meth:`allocate`
    keeps the raising contract for callers that pre-checked.

    Sharing contract (prefix cache, docs/serving.md "prefix reuse"): a
    freshly allocated block has refcount 1; every additional holder
    (another stream's block table, the prefix index's pin) must
    :meth:`retain` it, and every holder releases through
    :meth:`release`/:meth:`free` — the block returns to the free list only
    when its LAST holder lets go, so eviction/preempt/failover all route
    through the same refcounted release and can never tear a shared block
    out from under a live stream. ``reclaim_cb`` (installed with the
    prefix cache) is the pressure valve: a shortfall asks the cache to
    unpin cold unshared blocks before the allocator reports exhaustion.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError("need at least one block")
        self._free: List[int] = list(range(num_blocks))
        self._refs: List[int] = [0] * num_blocks
        self.num_blocks = num_blocks
        # pressure hook: called with the block shortfall before allocation
        # fails; returns how many blocks it freed (prefix_cache.reclaim)
        self.reclaim_cb = None

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def logical_blocks(self) -> int:
        """Sum of refcounts: block-table entries across all holders. With
        sharing this exceeds the physical ``num_blocks - free_blocks``."""
        return sum(self._refs)

    @property
    def shared_blocks(self) -> int:
        """Physical blocks with more than one holder."""
        return sum(1 for r in self._refs if r > 1)

    def refcount(self, block: int) -> int:
        if not 0 <= block < self.num_blocks:
            raise ValueError(f"refcount of invalid block {block}")
        return self._refs[block]

    def _relieve(self, n: int) -> None:
        if n > len(self._free) and self.reclaim_cb is not None:
            self.reclaim_cb(n - len(self._free))

    def try_allocate(self, n: int) -> Optional[List[int]]:
        """``allocate`` that reports exhaustion (or an injected allocation
        fault) as ``None`` instead of raising — the serving engine's
        backpressure seam."""
        self._relieve(n)
        if n > len(self._free):
            return None
        if n > 0:
            from ...utils.fault_injection import get_fault_injector

            if get_fault_injector().should_fail_kv_alloc():
                return None
        out, self._free = self._free[:n], self._free[n:]
        for b in out:
            self._refs[b] = 1
        return out

    def allocate(self, n: int) -> List[int]:
        self._relieve(n)
        if n > len(self._free):
            raise RuntimeError(
                f"KV cache exhausted: want {n} blocks, {len(self._free)} free")
        out, self._free = self._free[:n], self._free[n:]
        for b in out:
            self._refs[b] = 1
        return out

    def retain(self, blocks: Sequence[int]) -> None:
        """Add one holder to each LIVE block (mapping a cached prefix into
        a new stream's block table; pinning a block into the prefix
        index). Retaining a free block is a bug — it would resurrect
        storage another allocation may already own."""
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"retaining invalid block {b}")
            if self._refs[b] < 1:
                raise ValueError(f"retain of free block {b}")
        for b in blocks:
            self._refs[b] += 1

    def release(self, blocks: Sequence[int]) -> None:
        """Drop one holder per block; a block returns to the free list only
        at refcount zero. Releasing a free block raises — double free is
        impossible by construction, shared or not."""
        for b in blocks:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"freeing invalid block {b}")
            if self._refs[b] < 1:
                raise ValueError(f"double free of block {b}")
            self._refs[b] -= 1
            if self._refs[b] == 0:
                self._free.append(b)

    # the reference's name; every legacy caller (flush/preempt/failover)
    # routes through the refcounted release
    free = release


@dataclass(eq=False)  # identity semantics: descriptors live in scheduler sets
class SequenceDescriptor:
    """Per-sequence serving state (reference ``DSSequenceDescriptor``)."""

    uid: int
    pending: List[int] = field(default_factory=list)  # tokens awaiting forward
    n_cached: int = 0                                 # tokens with KV in cache
    blocks: List[int] = field(default_factory=list)   # owned KV block ids
    last_logits: Optional[np.ndarray] = None          # set when pending drains
    # --- prefix-cache state (inference/v2/prefix_cache.py) ---------------
    cached_prefix_len: int = 0  # tokens adopted from the prefix cache at
    #                             admission (block-aligned; positions/
    #                             sampling stay exact because token_pos
    #                             continues from n_cached)
    history: List[int] = field(default_factory=list)  # tokens committed to
    #                             KV, in position order (prefix-hash input)
    block_hashes: List[bytes] = field(default_factory=list)  # chained hash
    #                             per FULL block (prefix-trie keys)
    last_scheduled: int = -1   # engine forward-tick of the last chunk (LRU
    #                            eviction + prefill round-robin fairness)
    # --- SLA budget (serving.py admission gate / scheduler slack ordering).
    # All timestamps share one monotonic clock base (time.perf_counter by
    # default — the session's ``clock``); absolute wall time never enters.
    arrival_s: float = 0.0          # when the request was submitted
    deadline_s: Optional[float] = None  # absolute TTFT deadline (None = no SLA)
    rate_sla: float = 0.0           # required decode tokens/s (0 = none)
    tenant: str = "default"         # fairness-budget key
    target_new_tokens: int = 0      # requested generation length
    emitted: int = 0                # decode tokens delivered so far
    first_token_s: Optional[float] = None  # when the first token landed
    last_service_s: float = -1.0    # clock stamp of the last scheduled chunk
    #                                 (starvation aging in slack ordering)

    @property
    def needs_tokens(self) -> int:
        return len(self.pending)

    def blocks_needed(self, new_tokens: int, block_size: int) -> int:
        total = self.n_cached + new_tokens
        want = -(-total // block_size)  # ceil
        return max(0, want - len(self.blocks))


@dataclass
class RaggedBatch:
    """One forward's metadata (reference ``RaggedBatchWrapper``): flat token
    stream + per-token routing + per-sequence block tables. All padded."""

    tokens: np.ndarray        # [T] int32
    token_seq: np.ndarray     # [T] int32, slot id; padded entries = max_sequences
    token_pos: np.ndarray     # [T] int32 position within sequence
    block_tables: np.ndarray  # [S, blocks_per_seq] int32
    last_tok_idx: np.ndarray  # [S] int32 index into tokens of each slot's last chunk token
    seq_active: np.ndarray    # [S] bool
    uids: List[int]           # slot -> uid (host only)
    # atom decomposition (reference atom_builder, ragged_ops/): fixed-size
    # single-sequence q tiles for the ragged paged-attention kernel
    atom_qidx: Optional[np.ndarray] = None    # [A, BQ] packed-row gather idx
    atom_pos0: Optional[np.ndarray] = None    # [A] first q position
    atom_qlen: Optional[np.ndarray] = None    # [A] valid rows (0 = dead atom)
    atom_tables: Optional[np.ndarray] = None  # [A, Bps] owning block-table row
    atom_inv: Optional[np.ndarray] = None     # [T] packed row -> a*BQ + off

    @property
    def current_tokens(self) -> int:
        return int((self.token_seq < len(self.seq_active)).sum())


def build_ragged_batch(chunks: Sequence[Tuple[SequenceDescriptor, int]],
                       max_tokens: int, max_sequences: int,
                       blocks_per_seq: int,
                       atom_q: Optional[int] = None) -> RaggedBatch:
    """Assemble metadata for scheduled ``(descriptor, n_tokens)`` chunks.

    The chunk's tokens are ``desc.pending[:n_tokens]``; positions continue from
    ``desc.n_cached``. Mirrors ``RaggedBatchWrapper.insert_sequence`` +
    ``finalize``.
    """
    if len(chunks) > max_sequences:
        raise ValueError(f"{len(chunks)} chunks > max_sequences {max_sequences}")
    T, S = max_tokens, max_sequences
    tokens = np.zeros((T,), np.int32)
    token_seq = np.full((T,), S, np.int32)   # S = padding sentinel
    token_pos = np.zeros((T,), np.int32)
    block_tables = np.zeros((S, blocks_per_seq), np.int32)
    last_tok = np.zeros((S,), np.int32)
    active = np.zeros((S,), bool)
    uids: List[int] = []

    cursor = 0
    for slot, (desc, n) in enumerate(chunks):
        assert n >= 1 and n <= len(desc.pending)
        if cursor + n > T:
            raise ValueError("token budget overflow — scheduler bug")
        tokens[cursor:cursor + n] = desc.pending[:n]
        token_seq[cursor:cursor + n] = slot
        token_pos[cursor:cursor + n] = np.arange(desc.n_cached,
                                                 desc.n_cached + n)
        block_tables[slot, :len(desc.blocks)] = desc.blocks
        last_tok[slot] = cursor + n - 1
        active[slot] = True
        uids.append(desc.uid)
        cursor += n

    atoms = {}
    if atom_q:
        # atoms: ≤atom_q-row single-sequence q tiles (reference atom_builder).
        # Worst case sum(ceil(n_i/BQ)) ≤ S + T//BQ; slot A_max-1 is reserved
        # DEAD (qlen 0) so padded packed rows gather a guaranteed-zero output
        BQ = atom_q
        A_max = S + T // BQ + 1
        atom_qidx = np.zeros((A_max, BQ), np.int32)
        atom_pos0 = np.zeros((A_max,), np.int32)
        atom_qlen = np.zeros((A_max,), np.int32)
        atom_tables = np.zeros((A_max, blocks_per_seq), np.int32)
        atom_inv = np.full((T,), (A_max - 1) * BQ, np.int32)
        a = 0
        cur = 0
        for slot, (desc, n) in enumerate(chunks):
            pos0 = desc.n_cached
            k = 0
            while k * BQ < n:
                ql = min(BQ, n - k * BQ)
                rows = cur + k * BQ + np.arange(ql)
                atom_qidx[a, :ql] = rows
                atom_pos0[a] = pos0 + k * BQ
                atom_qlen[a] = ql
                atom_tables[a] = block_tables[slot]
                atom_inv[rows] = a * BQ + np.arange(ql)
                a += 1
                k += 1
            cur += n
        assert a <= A_max - 1, "atom overflow — builder bug"
        atoms = dict(atom_qidx=atom_qidx, atom_pos0=atom_pos0,
                     atom_qlen=atom_qlen, atom_tables=atom_tables,
                     atom_inv=atom_inv)
    return RaggedBatch(tokens, token_seq, token_pos, block_tables, last_tok,
                       active, uids, **atoms)
