"""Replica pool: process lifecycle + the process-backed router endpoint.

A :class:`ProcessReplica` is one supervised serving replica — a
:class:`~..supervisor.ReplicaSupervisor` process whose worker runs the
journaled serving loop in **spool mode** (``supervisor.serve_worker`` with
``spool_dir`` set). The router talks to it exclusively through the
filesystem, which is also the fault boundary:

* requests IN: atomically-renamed JSON files in ``spool/`` (the worker
  ingests them in sequence order; consumed uids are recorded by the
  journal, so a restart never double-serves);
* tokens/outcomes OUT: the request-journal JSONL stream, tailed
  incrementally (``serve/emit`` → token events, ``serve/close`` →
  finish/shed) — the journal already IS the delivery record, so the
  transport adds no second source of truth;
* health: the supervisor's atomic ``health.json`` probe (readiness from
  heartbeat freshness; ``draining`` during the PR 11 drain window).

:class:`ReplicaPool` orchestrates N of them: start/stop, **rolling
restart** (drain one replica at a time — the router steers new work away
the moment ``health.json`` says draining — then respawn and wait ready
before touching the next), and hot respawn of replicas whose supervisor
gave up. Worker crashes inside a living supervisor restart through the
existing elastic machinery without the pool doing anything.
"""
import glob as _glob
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from .failover import atomic_write_json as _atomic_write_json
from .router import FleetEvent, FleetRequest, ReplicaEndpoint
from ..supervisor import ReplayRequest
from ....utils.logging import logger


class _JournalTail:
    """Incremental reader over a journal dir's ``journal_rank*.jsonl``
    files: returns only records appended since the last call, tolerating
    torn tails (a partial line stays buffered until its newline lands)."""

    def __init__(self, journal_dir: str):
        self.journal_dir = journal_dir
        self._offsets: Dict[str, int] = {}
        self._partial: Dict[str, str] = {}

    def read_new(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        pattern = os.path.join(self.journal_dir, "journal_rank*.jsonl")
        for path in sorted(_glob.glob(pattern),
                           key=lambda p: (os.path.getmtime(p), p)):
            try:
                with open(path) as f:
                    f.seek(self._offsets.get(path, 0))
                    chunk = f.read()
                    self._offsets[path] = f.tell()
            except OSError:
                continue
            if not chunk:
                continue
            buf = self._partial.get(path, "") + chunk
            lines = buf.split("\n")
            self._partial[path] = lines[-1]
            for line in lines[:-1]:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
        return out


class ProcessReplica(ReplicaEndpoint):
    """One supervised replica process behind the router's endpoint seam.

    ``root`` holds everything the replica owns::

        root/spec.json      worker spec (journal/spool/health paths inside)
        root/journal/       request journals + heartbeat + failover claim
        root/spool/         inbound request files (router-written)
        root/health.json    supervisor readiness probe
        root/stop           stop marker (worker exits when idle)
    """

    def __init__(self, replica_id: str, root: str,
                 spec: Optional[Dict[str, Any]] = None, *,
                 supervisor_args: Sequence[str] = (),
                 env: Optional[Dict[str, str]] = None,
                 dead_after_s: float = 5.0,
                 python: str = sys.executable):
        self.replica_id = str(replica_id)
        self.root = root
        self.journal_dir = os.path.join(root, "journal")
        self.spool_dir = os.path.join(root, "spool")
        self.health_file = os.path.join(root, "health.json")
        self.spec_path = os.path.join(root, "spec.json")
        self.stop_file = os.path.join(root, "stop")
        self.supervisor_args = list(supervisor_args)
        self.extra_env = dict(env or {})
        self.dead_after_s = float(dead_after_s)
        self.python = python
        self.proc: Optional[subprocess.Popen] = None
        self.generation = -1
        self._expected_down = False
        self._tail = _JournalTail(self.journal_dir)
        self._seq = 0
        self._admitted: set = set()
        self._closed: set = set()
        os.makedirs(self.journal_dir, exist_ok=True)
        os.makedirs(self.spool_dir, exist_ok=True)
        spec = dict(spec or {})
        # the worker's fleet contract: serve the spool, probe-able health,
        # journals under journal_dir, stop marker honored
        spec.setdefault("model", "tiny")
        spec["journal_dir"] = self.journal_dir
        spec["spool_dir"] = self.spool_dir
        spec["stop_file"] = self.stop_file
        spec.setdefault("out", os.path.join(root, "out.json"))
        self.spec = spec
        self.max_live = int((spec.get("engine") or {})
                            .get("max_sequences", 64))
        _atomic_write_json(self.spec_path, spec)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        """Spawn (or respawn) the supervisor. Each generation gets its own
        journal namespace (``DSTPU_FLEET_GEN``) so ``load_journal``'s
        oldest-first merge stays correct across respawns."""
        if self.proc is not None and self.proc.poll() is None:
            raise RuntimeError(f"replica {self.replica_id} already running")
        self.generation += 1
        self._expected_down = False
        try:
            os.unlink(self.stop_file)
        except OSError:
            pass
        env = dict(os.environ)
        env.update(self.extra_env)
        env["DSTPU_FLEET_GEN"] = str(self.generation)
        # the worker must import this package even when the pool runs from
        # an unrelated cwd (tests, operators driving a checkout)
        import deepspeedsyclsupport_tpu as _pkg

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        cmd = [self.python, "-m",
               "deepspeedsyclsupport_tpu.inference.v2.supervisor",
               "--spec", self.spec_path,
               "--health-file", self.health_file,
               "--heartbeat-timeout", "30",
               *self.supervisor_args]
        # own session: a hard kill() can take the worker down with the
        # supervisor instead of orphaning it mid-decode
        self.proc = subprocess.Popen(cmd, env=env, start_new_session=True)
        logger.info("replica %s: supervisor pid %d (gen %d)",
                    self.replica_id, self.proc.pid, self.generation)

    def drain(self) -> None:
        """Request the PR 11 drain: SIGTERM to the supervisor, which
        forwards to the worker; live streams finish, health goes
        ``draining`` → ``stopped``, no relaunch."""
        self._expected_down = True
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()

    def request_stop(self) -> None:
        """Graceful idle stop: the worker exits 0 once its streams and
        spool are drained (no signal involved)."""
        self._expected_down = True
        with open(self.stop_file, "w") as f:
            f.write("stop")

    def kill(self) -> None:
        """Hard replica death (chaos path): SIGKILL the supervisor's whole
        session — worker included — leaving journals truthfully unclosed."""
        self._expected_down = False
        if self.proc is not None and self.proc.poll() is None:
            try:
                os.killpg(os.getpgid(self.proc.pid), signal.SIGKILL)
            except (OSError, ProcessLookupError):
                self.proc.kill()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        if self.proc is None:
            return None
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    # --------------------------------------------------------------- health
    def health(self) -> Dict[str, Any]:
        try:
            with open(self.health_file) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def ready(self) -> bool:
        h = self.health()
        if h.get("state") != "serving" or not h.get("ready"):
            return False
        # staleness gate: a probe the supervisor stopped refreshing is a
        # probe nobody should trust (cross-process wall stamp by contract)
        t = h.get("t")
        return t is not None and \
            time.time() - float(t) <= self.dead_after_s  # dslint: allow(wall-clock-in-step-path) cross-process probe freshness

    def draining(self) -> bool:
        return self.health().get("state") == "draining"

    def dead(self) -> bool:
        """Failover-eligible: the supervisor is gone (or its probe went
        stale) and the pool was not taking it down on purpose. A replica
        mid-drain or mid-respawn keeps its streams — the local restart
        path replays them more cheaply than a cross-replica re-prefill."""
        if self._expected_down:
            return False
        if self.proc is not None and self.proc.poll() is not None:
            return True
        h = self.health()
        t = h.get("t")
        if t is None:
            return False  # never came up: not up to the router to bury it
        return time.time() - float(t) > self.dead_after_s  # dslint: allow(wall-clock-in-step-path) cross-process probe freshness

    # ------------------------------------------------------------ transport
    def _spool(self, payload: Dict[str, Any]) -> None:
        self._seq += 1
        # stamp the hand-off time: the worker's `spool_wait` stage is the
        # gap between this write and its admit-side pickup
        payload = {**payload, "spooled_t": time.time()}  # dslint: allow(wall-clock-in-step-path) cross-process spool latency
        name = f"req_{self._seq:06d}_{payload['uid']}.json"
        _atomic_write_json(os.path.join(self.spool_dir, name), payload)

    def submit(self, req: FleetRequest) -> str:
        self._spool({"uid": req.uid, "tokens": list(req.tokens),
                     "max_new_tokens": req.max_new_tokens,
                     "tenant": req.tenant,
                     **({"ttft_sla_s": req.ttft_sla_s}
                        if req.ttft_sla_s is not None else {}),
                     "rate_sla": req.rate_sla})
        return "dispatched"

    def replay(self, rr: ReplayRequest) -> str:
        self._spool({"uid": rr.uid, "tokens": list(rr.tokens),
                     "max_new_tokens": rr.max_new_tokens,
                     "tenant": rr.tenant, "rate_sla": rr.rate_sla,
                     "replayed": True, "out": list(rr.out)})
        return "dispatched"

    def load(self) -> Dict[str, int]:
        # journal-derived estimate: admits seen minus closes seen (queued
        # depth is replica-internal; the backlog estimate in the router's
        # views covers the un-prefilled share)
        return {"live": len(self._admitted - self._closed), "queued": 0}

    def poll_events(self) -> List[FleetEvent]:
        out: List[FleetEvent] = []
        for rec in self._tail.read_new():
            name = rec.get("name")
            data = rec.get("data") or {}
            uid = data.get("uid")
            if uid is None:
                continue
            uid = int(uid)
            t = float(rec.get("t", 0.0))
            if name == "serve/admit":
                self._admitted.add(uid)
            elif name == "serve/emit":
                out.append(FleetEvent("token", uid, t,
                                      replica_id=self.replica_id,
                                      tokens=[int(x) for x in
                                              data.get("tokens", [])]))
            elif name == "serve/close":
                self._closed.add(uid)
                reason = data.get("reason", "")
                kind = "shed" if (reason == "replay_shed"
                                  or reason.startswith("shed")) else "finish"
                out.append(FleetEvent(kind, uid, t,
                                      replica_id=self.replica_id,
                                      reason=reason))
        return out


class ReplicaPool:
    """Start/stop/drain orchestration over N :class:`ProcessReplica`s."""

    def __init__(self, replicas: Sequence[ProcessReplica]):
        self.replicas: Dict[str, ProcessReplica] = {
            r.replica_id: r for r in replicas}
        if len(self.replicas) != len(replicas):
            raise ValueError("replica ids must be unique")

    def start(self) -> None:
        for r in self.replicas.values():
            r.start()

    def wait_ready(self, timeout: float = 120.0,
                   poll_s: float = 0.1) -> bool:
        """Block until every live replica probes ready (engine built, first
        heartbeat fresh). False on timeout."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(r.ready() for r in self.replicas.values()
                   if r.proc is not None and r.proc.poll() is None):
                if any(r.proc is not None and r.proc.poll() is None
                       for r in self.replicas.values()):
                    return True
            time.sleep(poll_s)
        return False

    def stop(self, timeout: float = 60.0) -> Dict[str, Optional[int]]:
        """Graceful fleet stop: stop markers first (workers exit when
        idle), drain (SIGTERM) past half the budget, SIGKILL at the end."""
        for r in self.replicas.values():
            r.request_stop()
        deadline = time.monotonic() + timeout
        rcs: Dict[str, Optional[int]] = {}
        terminated = False
        while time.monotonic() < deadline:
            live = [r for r in self.replicas.values()
                    if r.proc is not None and r.proc.poll() is None]
            if not live:
                break
            if not terminated and deadline - time.monotonic() < timeout / 2:
                terminated = True
                for r in live:
                    r.drain()
            time.sleep(0.1)
        for rid, r in self.replicas.items():
            if r.proc is not None and r.proc.poll() is None:
                r.kill()
            rcs[rid] = r.wait(timeout=5.0)
        return rcs

    def respawn(self, replica_id: str) -> None:
        """Bring a down replica back (new generation). The restarted
        worker replays its UNCLAIMED journaled streams itself; claimed
        ones belong to whoever failed them over."""
        r = self.replicas[replica_id]
        if r.proc is not None and r.proc.poll() is None:
            raise RuntimeError(f"replica {replica_id} is still running")
        r.start()

    def rolling_restart(self, wait_ready_s: float = 120.0,
                        poll_s: float = 0.1) -> None:
        """Drain→stop→respawn→ready, one replica at a time. The router
        needs no hook: health goes ``draining`` (out of rotation) the
        moment the supervisor sees the SIGTERM, and back to ``serving``
        once the respawned worker heartbeats."""
        for rid in sorted(self.replicas):
            r = self.replicas[rid]
            if r.proc is None or r.proc.poll() is not None:
                continue
            logger.info("rolling restart: draining replica %s", rid)
            r.drain()
            deadline = time.monotonic() + wait_ready_s
            while r.proc.poll() is None and time.monotonic() < deadline:
                time.sleep(poll_s)
            if r.proc.poll() is None:
                logger.error("rolling restart: replica %s did not drain in "
                             "%.0fs — killing", rid, wait_ready_s)
                r.kill()
                r.wait(timeout=10.0)
            r.start()
            deadline = time.monotonic() + wait_ready_s
            while not r.ready() and time.monotonic() < deadline:
                time.sleep(poll_s)
            if not r.ready():
                raise RuntimeError(
                    f"rolling restart: replica {rid} not ready within "
                    f"{wait_ready_s}s of respawn")
            logger.info("rolling restart: replica %s back in rotation", rid)
