"""Fleet router: edge admission, affinity placement, health gating,
journal-based cross-replica failover.

One :class:`FleetRouter` fronts N replicas behind a uniform
:class:`ReplicaEndpoint` seam — in-process sessions
(:class:`LocalReplica`, what the bench's CPU-sim fleet and the unit tests
drive) and supervised worker processes
(:class:`~.pool.ProcessReplica`) route identically. The router never
touches engine internals: it observes each replica through the SAME
artifacts an operator has — the ``health.json`` readiness probe and the
request-journal stream — so everything here keeps working when the
replica is a process on another core (or, with a shared filesystem,
another host).

Clocks: the router runs on **wall time**. Its observations join
timestamps from other processes (journal records, health probes), and a
monotonic clock does not survive a process boundary — the same tradeoff
``supervisor.recover_requests`` documents.
"""
import hashlib
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..serving import CapacityModel
from ..supervisor import ReplayRequest
from ....utils.logging import logger

#: ``Fleet/*`` names this module emits (declared in
#: ``monitor.telemetry.EVENT_NAMES``; per-replica members ride the
#: ``Fleet/replica.`` prefix family). Full literals on purpose — the
#: static event-name lint resolves each against the registry (the
#: ``Serve/recovery.*`` convention).
FLEET_COUNTERS = ("Fleet/routed", "Fleet/shed", "Fleet/completed",
                  "Fleet/affinity_hits")
_FAILOVER_COUNTERS = {"deaths": "Fleet/failover.deaths",
                      "replays": "Fleet/failover.replays",
                      "replay_sheds": "Fleet/failover.replay_sheds"}
FLEET_FAILOVER = (_FAILOVER_COUNTERS["deaths"],
                  _FAILOVER_COUNTERS["replays"],
                  _FAILOVER_COUNTERS["replay_sheds"])
FLEET_GAUGES = ("Fleet/replicas_ready", "Fleet/inflight",
                "Fleet/slo.ttft_miss_frac", "Fleet/slo.shed_frac",
                "Fleet/slo.burn_rate")
FLEET_HISTOGRAMS = ("Fleet/routed_ttft_s",)
FLEET_EVENT_NAMES = (FLEET_COUNTERS + FLEET_FAILOVER + FLEET_GAUGES
                     + FLEET_HISTOGRAMS)


@dataclass
class FleetRequest:
    """One request at the fleet edge (immutable routing view)."""

    uid: int
    tokens: List[int]
    max_new_tokens: int
    tenant: str = "default"
    ttft_sla_s: Optional[float] = None
    rate_sla: float = 0.0
    #: explicit co-location key; None derives one per ``FleetConfig.affinity``
    affinity_key: Optional[str] = None


@dataclass
class FleetEvent:
    """One observable fleet outcome: ``token`` / ``finish`` / ``shed``,
    stamped with the replica that produced it (``replica_id`` is empty for
    edge sheds — no replica ever saw the request)."""

    kind: str
    uid: int
    t: float
    replica_id: str = ""
    tokens: List[int] = field(default_factory=list)
    reason: str = ""


@dataclass
class FleetConfig:
    """Router policy knobs (see ``docs/serving.md`` "fleet control plane")."""

    admission: str = "sla"          # "sla" (edge gate projects) | "none"
    sla_headroom: float = 1.15      # safety factor on projected TTFT
    rate_feasibility_margin: float = 0.8   # same semantics as the replica gate
    affinity: str = "tenant"        # "tenant" | "prompt" | "none"
    affinity_prefix_tokens: int = 16  # prompt-head window hashed for "prompt"
    #: seconds of health staleness before a replica is declared dead and its
    #: journaled in-flight streams fail over to survivors
    dead_after_s: float = 5.0
    telemetry: bool = True
    ewma_alpha: float = 0.25
    prefill_tok_s_prior: float = 1000.0
    decode_step_s_prior: float = 0.05
    #: router flight-recorder JSONL (``fleet/route``/``fleet/death``/
    #: ``fleet/failover`` records + the final metrics dump) — what
    #: ``tools/trace_report.py --fleet`` reads. None = no stream.
    log_path: Optional[str] = None
    #: sliding window (s) for the ``Fleet/slo.*`` burn gauges
    slo_window_s: float = 60.0
    #: allowed bad-request fraction in the window; burn = worst_frac / budget
    slo_budget: float = 0.05

    def __post_init__(self):
        if self.admission not in ("sla", "none"):
            raise ValueError(f"admission must be sla|none, got "
                             f"{self.admission!r}")
        if self.affinity not in ("tenant", "prompt", "none"):
            raise ValueError(f"affinity must be tenant|prompt|none, got "
                             f"{self.affinity!r}")
        if self.dead_after_s <= 0:
            raise ValueError(f"dead_after_s must be > 0, got "
                             f"{self.dead_after_s}")
        if self.slo_window_s <= 0:
            raise ValueError(f"slo_window_s must be > 0, got "
                             f"{self.slo_window_s}")
        if not 0 < self.slo_budget <= 1:
            raise ValueError(f"slo_budget must be in (0, 1], got "
                             f"{self.slo_budget}")


class ReplicaEndpoint:
    """What the router needs from one replica — implemented by
    :class:`LocalReplica` (in-process) and :class:`~.pool.ProcessReplica`
    (supervised worker process). All methods are host-side and cheap."""

    replica_id: str = ""
    journal_dir: Optional[str] = None
    max_live: Optional[int] = None  # structural stream slots (placement cap)

    def ready(self) -> bool:  # in rotation?
        raise NotImplementedError

    def draining(self) -> bool:
        return False

    def dead(self) -> bool:   # failover-eligible?
        raise NotImplementedError

    def load(self) -> Dict[str, int]:  # {"live": int, "queued": int}
        raise NotImplementedError

    def submit(self, req: FleetRequest) -> str:
        """"admitted" | "queued" | "shed" | "dispatched" (async transport:
        the outcome arrives later through the journal stream)."""
        raise NotImplementedError

    def replay(self, rr: ReplayRequest) -> str:
        """"replayed" | "shed" | "completed" | "dispatched"."""
        raise NotImplementedError

    def advance(self) -> None:
        """Give an in-process replica a scheduling round (no-op for a
        worker process, which advances itself)."""

    def poll_events(self) -> List[FleetEvent]:
        raise NotImplementedError

    def prefix_stats(self) -> Optional[Dict[str, float]]:
        """Engine-reported prefix-cache counters (``hits``/``misses``/
        ``tokens_saved``/``hit_ratio``/...), or None when the replica has
        no cache (or the transport cannot report) — what the router joins
        with its placement-side ``Fleet/affinity_hits`` to tell REALIZED
        reuse from mere co-location."""
        return None


class LocalReplica(ReplicaEndpoint):
    """In-process replica: one :class:`~..serving.ServingSession` behind the
    endpoint seam. ``kill()`` emulates a hard replica death (engine KV and
    session state dropped, journal left UNclosed — exactly what a crash
    leaves on disk), which is how the bench's CPU-sim fleet injects its
    mid-sweep fault."""

    def __init__(self, replica_id: str, session, *,
                 journal_dir: Optional[str] = None):
        self.replica_id = str(replica_id)
        self.session = session
        self.journal_dir = journal_dir
        self.max_live = int(session.eng.config.max_sequences)
        self._alive = True
        self._buf: List[FleetEvent] = []
        # session events are stamped on the session clock (perf_counter);
        # fleet observations join cross-process wall timestamps, so map
        # them through a fixed offset taken at construction
        self._wall_offset = time.time() - self.session.clock()  # dslint: allow(wall-clock-in-step-path) cross-process fleet clock

    def ready(self) -> bool:
        return self._alive

    def dead(self) -> bool:
        return not self._alive

    def load(self) -> Dict[str, int]:
        if not self._alive:
            return {"live": 0, "queued": 0}
        return {"live": len(self.session.running),
                "queued": len(self.session.queue)}

    def submit(self, req: FleetRequest) -> str:
        return self.session.submit(
            req.uid, req.tokens, req.max_new_tokens, tenant=req.tenant,
            ttft_sla_s=req.ttft_sla_s, rate_sla=req.rate_sla)

    def replay(self, rr: ReplayRequest) -> str:
        return self.session.replay(
            rr.uid, rr.tokens, rr.max_new_tokens, emitted_tokens=rr.out,
            tenant=rr.tenant, rate_sla=rr.rate_sla)

    def advance(self) -> None:
        if not self._alive:
            return
        for ev in self.session.step():
            self._buf.append(FleetEvent(
                ev.kind, ev.uid, ev.t + self._wall_offset,
                replica_id=self.replica_id, tokens=list(ev.tokens),
                reason=ev.reason))

    def poll_events(self) -> List[FleetEvent]:
        out, self._buf = self._buf, []
        return out

    def prefix_stats(self) -> Optional[Dict[str, float]]:
        return self.session.prefix_stats() if self._alive else None

    def kill(self) -> None:
        """Hard death: drop engine KV + session state, keep the journal
        stream truthfully un-closed (the failover manager's input)."""
        if not self._alive:
            return
        self._alive = False
        eng = self.session.eng
        eng.flush(list(eng.seqs))
        if self.session.watchdog is not None:
            try:
                self.session.watchdog.stop()
            except Exception:
                pass

    def close(self) -> None:
        self._alive = False
        self.session.close()


@dataclass
class _Flight:
    """Router-side bookkeeping for one routed request."""

    req: FleetRequest
    replica_id: str
    routed_t: float
    first_token_t: Optional[float] = None
    last_emit_t: Optional[float] = None
    emitted: int = 0
    replays: int = 0


def slack_affinity_placement(req: FleetRequest, candidates: List[Tuple[str, Dict[str, Any]]],
                             sticky_id: Optional[str]) -> str:
    """Default placement: the sticky affinity target when it has headroom,
    else the replica with the smallest projected wait (prefill backlog at
    its measured prefill rate + live streams at its measured step time) —
    i.e. the one that leaves the request the most SLA slack.

    ``candidates`` is ``[(replica_id, view)]`` where ``view`` carries
    ``live``, ``queued``, ``backlog_tokens``, ``max_live``,
    ``prefill_tok_s`` and ``decode_step_s``. Pluggable: pass any callable
    with this signature as ``FleetRouter(placement=...)``.
    """
    def headroom(view) -> bool:
        cap = view.get("max_live")
        return cap is None or view["live"] + view["queued"] < cap

    if sticky_id is not None:
        for rid, view in candidates:
            if rid == sticky_id and headroom(view):
                return rid

    def wait_s(view) -> float:
        return (view["backlog_tokens"] / max(view["prefill_tok_s"], 1e-9)
                + view["live"] * view["decode_step_s"])

    with_room = [(rid, v) for rid, v in candidates if headroom(v)]
    pool = with_room or candidates
    return min(pool, key=lambda rv: (wait_s(rv[1]), rv[0]))[0]


class FleetRouter:
    """Routes requests across replicas; owns fleet-edge admission, sticky
    affinity, per-replica capacity observation, and cross-replica failover.

    The driving loop calls :meth:`submit` for arrivals and :meth:`poll`
    every tick; ``poll`` advances in-process replicas, ingests replica
    events (updating the per-replica capacity models and the routed-TTFT
    histogram), detects replica deaths and fails their journaled in-flight
    streams over to survivors. All returned :class:`FleetEvent` streams are
    what a frontend delivers to clients.
    """

    def __init__(self, replicas: Sequence[ReplicaEndpoint],
                 config: Optional[FleetConfig] = None, *,
                 placement: Callable = slack_affinity_placement,
                 clock: Callable[[], float] = time.time):  # dslint: allow(wall-clock-in-step-path) cross-process fleet clock
        self.cfg = config or FleetConfig()
        self.replicas: Dict[str, ReplicaEndpoint] = {
            r.replica_id: r for r in replicas}
        if len(self.replicas) != len(replicas):
            raise ValueError("replica ids must be unique")
        self.placement = placement
        self.clock = clock
        self.caps: Dict[str, CapacityModel] = {
            rid: CapacityModel(self.cfg.prefill_tok_s_prior,
                               self.cfg.decode_step_s_prior,
                               self.cfg.ewma_alpha)
            for rid in self.replicas}
        self.flights: Dict[int, _Flight] = {}
        self._sticky: Dict[str, str] = {}
        self._dead: set = set()
        #: in-memory mirror of the router stream — journal-record-shaped
        #: dicts the bench's per-load-point request-waterfall join drains
        #: (``monitor.reqtrace`` reads the same shape off disk)
        self.trace_log: deque = deque(maxlen=65536)
        self._slo_ttft: deque = deque()   # (t, ok) at first token
        self._slo_shed: deque = deque()   # (t, shed) at edge verdict
        self._poll_n = 0
        self.counters: Dict[str, int] = {
            "routed": 0, "shed": 0, "completed": 0, "affinity_hits": 0}
        self.failover_counters: Dict[str, int] = {
            "deaths": 0, "replays": 0, "replay_sheds": 0}
        self.per_replica: Dict[str, Dict[str, int]] = {
            rid: {"routed": 0, "tokens": 0, "shed": 0, "completed": 0,
                  "failover_in": 0}
            for rid in self.replicas}
        if self.cfg.telemetry:
            from ....monitor.telemetry import metrics_registry as _mr

            self._metrics = _mr
        else:
            self._metrics = None
        self._rec = None
        self._jsonl = None
        if self.cfg.log_path:
            from ....monitor.monitor import JsonlMonitor
            from ....monitor.telemetry import FlightRecorder

            self._rec = FlightRecorder(capacity=256)
            self._jsonl = JsonlMonitor(path=self.cfg.log_path,
                                       flush_interval=1)
            self._jsonl.attach_recorder(self._rec)
            self._rec.record("meta", "fleet/start",
                             data={"replicas": sorted(self.replicas)})

    # ------------------------------------------------------------- plumbing
    def _record(self, name: str, data: Dict[str, Any]) -> None:
        # the in-memory ring always mirrors the stream (the bench joins it
        # without a log_path); the flight recorder only when configured
        self.trace_log.append({"name": name, "t": self.clock(),
                               "data": dict(data)})
        if self._rec is not None:
            self._rec.record("event", name, data=data)

    def _stage(self, uid: int, stage: str, **data: Any) -> None:
        """Stamp one ``fleet/stage`` lifecycle record (uid −1 = fleet
        scope). Stage names are validated against the
        ``monitor.reqtrace`` registry — the join refuses typos."""
        from ....monitor.reqtrace import check_stage

        check_stage(stage, fleet=True)
        self._record("fleet/stage", {"uid": int(uid), "stage": stage,
                                     **data})

    def drain_trace(self) -> List[Dict[str, Any]]:
        """Return and clear the in-memory router stream mirror."""
        out = list(self.trace_log)
        self.trace_log.clear()
        return out

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n
        if self._metrics is not None:
            self._metrics.counter(f"Fleet/{name}").incr(n)

    def _count_failover(self, name: str, n: int = 1) -> None:
        self.failover_counters[name] = \
            self.failover_counters.get(name, 0) + n
        if self._metrics is not None:
            self._metrics.counter(_FAILOVER_COUNTERS[name]).incr(n)

    def close(self) -> None:
        """Flush the router stream (metrics snapshot included) — idempotent."""
        if self._rec is not None:
            try:
                self.export_metrics()
            except Exception:
                pass
            try:
                self._rec.dump("fleet_close")
            except Exception:
                pass
        if self._jsonl is not None:
            try:
                self._jsonl.close()
            except Exception as e:
                logger.warning("fleet router log close failed: %s", e)
            self._jsonl = None
            self._rec = None

    # ------------------------------------------------------------- rotation
    def rotation(self) -> List[str]:
        """Replica ids currently eligible for NEW work: ready, not
        draining, not declared dead. Stale-health replicas fall out here
        long before the failover grace declares them dead."""
        return [rid for rid, r in self.replicas.items()
                if rid not in self._dead and r.ready() and not r.draining()]

    def _views(self, rids: List[str]) -> List[Tuple[str, Dict[str, Any]]]:
        out = []
        for rid in rids:
            r = self.replicas[rid]
            ld = r.load()
            cap = self.caps[rid]
            backlog = sum(
                len(f.req.tokens) for f in self.flights.values()
                if f.replica_id == rid and f.first_token_t is None)
            out.append((rid, {
                "live": ld["live"], "queued": ld["queued"],
                "backlog_tokens": backlog, "max_live": r.max_live,
                "prefill_tok_s": cap.prefill_tok_s,
                "decode_step_s": cap.decode_step_s}))
        return out

    def _affinity_key(self, req: FleetRequest) -> Optional[str]:
        if req.affinity_key is not None:
            return req.affinity_key
        if self.cfg.affinity == "tenant":
            return f"tenant:{req.tenant}"
        if self.cfg.affinity == "prompt":
            head = ",".join(str(t) for t in
                            req.tokens[:self.cfg.affinity_prefix_tokens])
            return "prompt:" + hashlib.sha1(head.encode()).hexdigest()[:12]
        return None

    # ------------------------------------------------------------ admission
    def submit(self, req: FleetRequest,
               now: Optional[float] = None) -> Tuple[str, Optional[str]]:
        """Fleet-edge gate + placement. Returns ``(outcome, replica_id)``
        where outcome is ``"routed"`` or ``"shed"`` (edge shed: no replica
        ever queues the request — the client learns in O(1))."""
        if req.uid in self.flights:
            raise ValueError(f"uid {req.uid} is already routed")
        now = self.clock() if now is None else now
        rids = self.rotation()
        if not rids:
            return self._edge_shed(req, now, "no_ready_replica")
        views = self._views(rids)
        if self.cfg.admission == "sla":
            # rate feasibility against the BEST replica: a per-stream rate
            # no replica's measured decode step can deliver is never
            # meetable — same margin semantics as the replica-local gate
            best_rate = max(self.caps[rid].decode_tok_s_best for rid in rids)
            if req.rate_sla > 0 and best_rate \
                    < self.cfg.rate_feasibility_margin * req.rate_sla:
                return self._edge_shed(req, now, "rate_unmeetable")
            # TTFT projection on the LEAST-backlogged candidate: if even it
            # cannot land the first token inside the deadline, no placement
            # can — shed at the edge instead of letting a replica queue it
            if req.ttft_sla_s is not None:
                eta = min(
                    self.cfg.sla_headroom
                    * (v["backlog_tokens"] + len(req.tokens))
                    / max(v["prefill_tok_s"], 1e-9)
                    + v["live"] * v["decode_step_s"]
                    for _rid, v in views)
                if eta > req.ttft_sla_s:
                    return self._edge_shed(req, now, "deadline_unmeetable")
        self._stage(req.uid, "edge_gate", verdict="admit",
                    n_prompt=len(req.tokens))
        key = self._affinity_key(req)
        sticky = self._sticky.get(key) if key is not None else None
        rid = self.placement(req, views, sticky)
        if rid not in self.replicas:
            raise ValueError(f"placement returned unknown replica {rid!r}")
        if rid == sticky:
            self._count("affinity_hits")
        if key is not None:
            self._sticky[key] = rid
        self._stage(req.uid, "placement", replica=rid,
                    sticky=bool(rid == sticky))
        outcome = self.replicas[rid].submit(req)
        if outcome == "shed":
            # replica-local gate disagreed (structural edge case): terminal
            self._count("shed")
            self.per_replica[rid]["shed"] += 1
            self._slo_shed.append((now, True))
            self._record("fleet/shed", {"uid": req.uid, "replica": rid,
                                        "reason": "replica_gate"})
            return "shed", rid
        self.flights[req.uid] = _Flight(req=req, replica_id=rid,
                                        routed_t=now)
        self._count("routed")
        self.per_replica[rid]["routed"] += 1
        self._slo_shed.append((now, False))
        self._record("fleet/route",
                     {"uid": req.uid, "replica": rid, "tenant": req.tenant,
                      **({"key": key} if key is not None else {})})
        return "routed", rid

    def _edge_shed(self, req: FleetRequest, now: float,
                   reason: str) -> Tuple[str, Optional[str]]:
        self._count("shed")
        self._slo_shed.append((now, True))
        self._stage(req.uid, "edge_gate", verdict="shed", reason=reason)
        self._record("fleet/shed", {"uid": req.uid, "reason": reason})
        return "shed", None

    # ------------------------------------------------------------- stepping
    def poll(self, now: Optional[float] = None) -> List[FleetEvent]:
        """One router tick: advance in-process replicas, ingest replica
        events, refresh capacity observations, detect deaths and fail
        their in-flight streams over. Returns the tick's delivery stream
        (edge-shed events are returned by :meth:`submit` directly)."""
        now = self.clock() if now is None else now
        for rid in self.rotation():
            self.replicas[rid].advance()
        out: List[FleetEvent] = []
        for rid, r in self.replicas.items():
            for ev in r.poll_events():
                self._ingest(rid, ev, now)
                out.append(ev)
        for rid, r in self.replicas.items():
            if rid in self._dead or not r.dead():
                continue
            out.extend(self.failover(rid, now))
        self._flush_gauges(now)
        self._poll_n += 1
        if self.cfg.log_path and self._poll_n % 512 == 0:
            self.export_metrics()
        return out

    def _ingest(self, rid: str, ev: FleetEvent, now: float) -> None:
        fl = self.flights.get(ev.uid)
        if ev.kind == "token":
            self.per_replica[rid]["tokens"] += len(ev.tokens)
            if fl is None:
                return
            if fl.first_token_t is None:
                fl.first_token_t = ev.t
                self.caps[rid].record_prefill(
                    len(fl.req.tokens), max(ev.t - fl.routed_t, 1e-9))
                if fl.replays == 0:
                    self._observe("Fleet/routed_ttft_s", ev.t - fl.routed_t)
                    if fl.req.ttft_sla_s is not None:
                        self._slo_ttft.append(
                            (ev.t,
                             ev.t - fl.routed_t <= fl.req.ttft_sla_s))
            elif fl.last_emit_t is not None:
                self.caps[rid].record_decode(
                    len(ev.tokens), max(ev.t - fl.last_emit_t, 1e-9))
            fl.last_emit_t = ev.t
            fl.emitted += len(ev.tokens)
        elif ev.kind == "finish":
            self.per_replica[rid]["completed"] += 1
            self._count("completed")
            self.flights.pop(ev.uid, None)
        elif ev.kind == "shed":
            self.per_replica[rid]["shed"] += 1
            if ev.reason == "replay_shed":
                self._count_failover("replay_sheds")
            self._count("shed")
            self.flights.pop(ev.uid, None)

    # ------------------------------------------------------------- failover
    def mark_dead(self, replica_id: str,
                  now: Optional[float] = None) -> List[FleetEvent]:
        """Operator/driver override: declare a replica dead NOW (the bench's
        injected kill) and run failover without waiting for the health
        grace."""
        if replica_id in self._dead:
            return []
        return self.failover(replica_id, self.clock() if now is None
                             else now)

    def failover(self, replica_id: str, now: float) -> List[FleetEvent]:
        """Journal-based cross-replica failover of one dead replica: claim
        its journals (exactly-once across router restarts), merge with the
        router's own routed-but-never-admitted flights, and re-admit every
        in-flight stream on a surviving replica from its emitted-token
        watermark. Streams no survivor can take are shed terminally."""
        from .failover import claim_in_flight

        self._dead.add(replica_id)
        self._count_failover("deaths")
        ep = self.replicas[replica_id]
        self._record("fleet/death", {"replica": replica_id})
        logger.warning("fleet router: replica %s dead — failing over its "
                       "in-flight streams", replica_id)
        states: Dict[int, ReplayRequest] = {}
        if ep.journal_dir:
            states = claim_in_flight(ep.journal_dir, claimer="router")
        # routed to the dead replica but never journal-admitted there (the
        # request died in transport): resubmit from scratch — no token was
        # ever delivered, so a fresh admit loses nothing. Claim these uids
        # too: a respawned worker must skip their stale spool files.
        lost = []
        for uid, fl in self.flights.items():
            if fl.replica_id == replica_id and uid not in states:
                states[uid] = ReplayRequest(
                    uid=uid, tokens=list(fl.req.tokens),
                    max_new_tokens=fl.req.max_new_tokens,
                    tenant=fl.req.tenant, rate_sla=fl.req.rate_sla)
                lost.append(uid)
        if lost and ep.journal_dir:
            from .failover import claim_uids

            claim_uids(ep.journal_dir, lost, claimer="router")
        self._stage(-1, "failover_claim", replica=replica_id,
                    claimed=sorted(states), lost_in_transport=sorted(lost))
        events: List[FleetEvent] = []
        for uid in sorted(states):
            st = states[uid]
            events.extend(self._failover_one(uid, st, now))
        return events

    def _failover_one(self, uid: int, st: ReplayRequest,
                      now: float) -> List[FleetEvent]:
        rids = self.rotation()
        fl = self.flights.get(uid)
        if not rids:
            self._count_failover("replay_sheds")
            self._count("shed")
            self.flights.pop(uid, None)
            self._record("fleet/failover",
                         {"uid": uid, "outcome": "shed",
                          "reason": "no_surviving_replica"})
            return [FleetEvent("shed", uid, now,
                               reason="failover:no_surviving_replica")]
        views = self._views(rids)
        rid = self.placement(
            FleetRequest(uid=uid, tokens=st.tokens,
                         max_new_tokens=st.max_new_tokens, tenant=st.tenant,
                         rate_sla=st.rate_sla),
            views, None)
        outcome = self.replicas[rid].replay(st)
        self._record("fleet/failover",
                     {"uid": uid, "replica": rid, "outcome": outcome,
                      "watermark": len(st.out)})
        if outcome == "shed":
            # terminal, counted by _ingest for async transports; local
            # replay answers synchronously so count here
            self._count_failover("replay_sheds")
            self._count("shed")
            self.per_replica[rid]["shed"] += 1
            self.flights.pop(uid, None)
            return [FleetEvent("shed", uid, now, replica_id=rid,
                               reason="replay_shed")]
        if outcome == "completed":
            self._count("completed")
            self.per_replica[rid]["completed"] += 1
            self.flights.pop(uid, None)
            return [FleetEvent("finish", uid, now, replica_id=rid,
                               reason="done")]
        # replayed (sync) or dispatched (async): the stream continues on
        # the survivor from its watermark
        self._count_failover("replays")
        self.per_replica[rid]["failover_in"] += 1
        self._stage(uid, "replay_segment", replica=rid,
                    watermark=len(st.out))
        if fl is None:
            fl = _Flight(req=FleetRequest(
                uid=uid, tokens=list(st.tokens),
                max_new_tokens=st.max_new_tokens, tenant=st.tenant,
                rate_sla=st.rate_sla), replica_id=rid, routed_t=now)
            self.flights[uid] = fl
        fl.replica_id = rid
        fl.replays += 1
        fl.emitted = len(st.out)
        # the first token on the survivor is a REPLAY landing, not a fresh
        # TTFT — skip the routed-TTFT histogram, and re-base routed_t to
        # NOW so the survivor's prefill sample measures ITS re-prefill, not
        # the dead replica's whole lifetime (which would crater the
        # survivor's capacity model and edge-shed everything after it)
        fl.routed_t = now
        fl.first_token_t = None
        fl.last_emit_t = None
        return []

    # ------------------------------------------------------------ reporting
    def _observe(self, name: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.histogram(name).observe(value)

    def _slo_snapshot(self, now: float) -> Tuple[float, float, float]:
        """Sliding-window SLO burn: (ttft_miss_frac, shed_frac, burn_rate)
        over the last ``cfg.slo_window_s`` seconds. Burn is the worse of
        the two bad-fractions over the configured error budget — >1 means
        the fleet is spending budget faster than the SLO allows."""
        cut = now - self.cfg.slo_window_s
        for dq in (self._slo_ttft, self._slo_shed):
            while dq and dq[0][0] < cut:
                dq.popleft()
        miss = (sum(1 for _t, ok in self._slo_ttft if not ok)
                / len(self._slo_ttft)) if self._slo_ttft else 0.0
        shed = (sum(1 for _t, s in self._slo_shed if s)
                / len(self._slo_shed)) if self._slo_shed else 0.0
        return miss, shed, max(miss, shed) / self.cfg.slo_budget

    def export_metrics(self, path: Optional[str] = None) -> Optional[str]:
        """Write the Prometheus textfile snapshot (atomic rename, same
        contract as the training exporter). Defaults to
        ``metrics_router.prom`` beside ``cfg.log_path``."""
        if self._metrics is None:
            return None
        if path is None:
            if not self.cfg.log_path:
                return None
            path = os.path.join(os.path.dirname(self.cfg.log_path) or ".",
                                "metrics_router.prom")
        from ....monitor.telemetry import export_metrics_textfile

        return export_metrics_textfile(
            path, self._metrics.snapshot(), labels={"role": "router"},
            extra_counters={f"fleet_{k}": v for k, v in
                            self.counters.items()})

    def _flush_gauges(self, now: Optional[float] = None) -> None:
        if self._metrics is None:
            return
        self._metrics.gauge("Fleet/replicas_ready").set(len(self.rotation()))
        self._metrics.gauge("Fleet/inflight").set(len(self.flights))
        if now is not None:
            miss, shed, burn = self._slo_snapshot(now)
            self._metrics.gauge("Fleet/slo.ttft_miss_frac").set(miss)
            self._metrics.gauge("Fleet/slo.shed_frac").set(shed)
            self._metrics.gauge("Fleet/slo.burn_rate").set(burn)
        for rid, r in self.replicas.items():
            ld = r.load()
            self._metrics.gauge(f"Fleet/replica.{rid}.live").set(ld["live"])
            self._metrics.gauge(
                f"Fleet/replica.{rid}.queued").set(ld["queued"])
            ps = r.prefix_stats()
            if ps is not None:
                # engine-reported reuse per replica (the Fleet/replica.
                # prefix family covers the data-dependent member names) —
                # the counterpart of the placement-side affinity_hits
                self._metrics.gauge(
                    f"Fleet/replica.{rid}.prefix_hits").set(ps["hits"])
                self._metrics.gauge(
                    f"Fleet/replica.{rid}.prefix_hit_ratio").set(
                        ps["hit_ratio"])
                self._metrics.gauge(
                    f"Fleet/replica.{rid}.prefix_tokens_saved").set(
                        ps["tokens_saved"])

    @property
    def idle(self) -> bool:
        return not self.flights

    def realized_reuse(self) -> Optional[Dict[str, Any]]:
        """Join placement-side affinity with engine-reported prefix reuse.

        ``Fleet/affinity_hits`` alone only proves the router SENT
        same-key requests to the same replica; whether the engine
        actually reused KV is the replicas' ``Serve/prefix.*`` story.
        Returns None when no replica reports a prefix cache. The joined
        view answers the operator question the placement counter cannot:
        "is sticky placement converting into skipped prefill?"
        """
        per: Dict[str, Dict[str, float]] = {}
        for rid, r in self.replicas.items():
            ps = r.prefix_stats()
            if ps is not None:
                per[rid] = ps
        if not per:
            return None
        hits = sum(int(p["hits"]) for p in per.values())
        misses = sum(int(p["misses"]) for p in per.values())
        lookups = hits + misses
        return {"affinity_hits": self.counters.get("affinity_hits", 0),
                "prefix_hits": hits,
                "prefix_lookups": lookups,
                "prefix_hit_ratio": round(hits / lookups, 4) if lookups
                else 0.0,
                "tokens_saved": sum(int(p["tokens_saved"])
                                    for p in per.values()),
                "per_replica": per}

    def stats(self) -> Dict[str, Any]:
        """Counters + per-replica breakdown for bench lines and operators."""
        out = {**self.counters,
               **{f"failover_{n}": v
                  for n, v in self.failover_counters.items()},
               "inflight": len(self.flights),
               "replicas_ready": len(self.rotation()),
               "replicas_dead": sorted(self._dead),
               "per_replica": {rid: dict(c)
                               for rid, c in self.per_replica.items()}}
        reuse = self.realized_reuse()
        if reuse is not None:
            out["realized_reuse"] = reuse
        return out

    def summary_events(self, step: Optional[int] = None) -> List[Tuple]:
        """Scalar ``Fleet/*`` events, registry-validated (strict safe)."""
        from ....monitor.telemetry import check_events

        ev = [(f"Fleet/{n}", float(v), step)
              for n, v in self.counters.items()]
        ev += [(_FAILOVER_COUNTERS[n], float(v), step)
               for n, v in self.failover_counters.items()]
        ev += [("Fleet/replicas_ready", float(len(self.rotation())), step),
               ("Fleet/inflight", float(len(self.flights)), step)]
        miss, shed, burn = self._slo_snapshot(self.clock())
        ev += [("Fleet/slo.ttft_miss_frac", miss, step),
               ("Fleet/slo.shed_frac", shed, step),
               ("Fleet/slo.burn_rate", burn, step)]
        if self._metrics is not None:
            for name in FLEET_HISTOGRAMS:
                hist = self._metrics.histogram(name)
                if not hist.count:
                    continue
                for q, value in hist.quantiles().items():
                    if value is not None:
                        ev.append((f"{name}/{q}", float(value), step))
        return check_events(ev)
