"""Fleet driver CLI — spawn a replica pool, route a request list, survive
replica deaths, write the merged delivery record.

::

    python -m deepspeedsyclsupport_tpu.inference.v2.fleet --spec fleet.json

Spec keys:

* ``root`` — fleet directory (one subdir per replica + ``router.jsonl``)
* ``n_replicas`` — pool size
* ``worker`` — per-replica worker spec (``model``/``dtype``/``engine``/
  ``policy``/``recover``; journal/spool/health paths are filled in)
* ``supervisor_args`` — extra ``ReplicaSupervisor`` CLI args (e.g.
  ``["--restart-limit", "0"]`` so a crashed replica stays dead and its
  streams fail over instead of restarting locally)
* ``env`` — per-replica env overrides keyed by replica index as a string
  (fault injection rides here)
* ``router`` — :class:`~.router.FleetConfig` fields
* ``requests`` — ``[{"uid", "tokens", "max_new_tokens", ...}]``
* ``out`` — merged-output JSON path; ``timeout_s`` — wall bound

The merged output's token sequences come from the fleet-wide journal merge
(:func:`~..supervisor.load_journal` across every replica's journal dir) —
the journals are the delivery record, so the output is exact no matter how
many deaths/failovers the run survived.
"""
import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from .pool import ProcessReplica, ReplicaPool
from .router import FleetConfig, FleetRequest, FleetRouter
from ..supervisor import load_journal, reconstruct_outputs
from ....utils.logging import logger


def fleet_journal_files(root: str, n_replicas: int) -> List[str]:
    """Every replica's journal files under a fleet root (mtime-ordered by
    ``load_journal`` itself)."""
    return [os.path.join(root, f"replica{i}", "journal")
            for i in range(n_replicas)]


def run_fleet(spec: Dict[str, Any]) -> Dict[str, Any]:
    root = spec["root"]
    n = int(spec.get("n_replicas", 2))
    os.makedirs(root, exist_ok=True)
    per_env = {str(k): dict(v) for k, v in (spec.get("env") or {}).items()}
    common_env = per_env.pop("*", {})  # env for every replica; per-index
    #                                    entries override (fault injection)
    replicas = [
        ProcessReplica(
            str(i), os.path.join(root, f"replica{i}"),
            dict(spec.get("worker") or {}),
            supervisor_args=spec.get("supervisor_args") or (),
            env={**common_env, **per_env.get(str(i), {})},
            dead_after_s=float((spec.get("router") or {})
                               .get("dead_after_s", 5.0)))
        for i in range(n)]
    pool = ReplicaPool(replicas)
    rcfg = FleetConfig(**{**(spec.get("router") or {}),
                          "log_path": (spec.get("router") or {}).get(
                              "log_path",
                              os.path.join(root, "router.jsonl"))})
    router = FleetRouter(replicas, rcfg)
    timeout_s = float(spec.get("timeout_s", 300.0))
    pool.start()
    try:
        if not pool.wait_ready(timeout=timeout_s):
            raise RuntimeError("fleet: replicas never became ready")
        pending = [FleetRequest(
            uid=int(r["uid"]), tokens=[int(t) for t in r["tokens"]],
            max_new_tokens=int(r["max_new_tokens"]),
            tenant=r.get("tenant", "default"),
            ttft_sla_s=r.get("ttft_sla_s"),
            rate_sla=float(r.get("rate_sla", 0.0)))
            for r in spec.get("requests", [])]
        closed: Dict[int, str] = {}
        deadline = time.monotonic() + timeout_s
        while pending or not router.idle:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet: timed out with {len(router.flights)} stream(s) "
                    f"in flight ({len(pending)} unsubmitted)")
            while pending:
                req = pending.pop(0)
                outcome, _rid = router.submit(req)
                if outcome == "shed":
                    closed[req.uid] = "shed:edge"
            for ev in router.poll():
                if ev.kind in ("finish", "shed"):
                    closed[ev.uid] = ev.reason or ev.kind
            time.sleep(0.02)
        stats = router.stats()
    finally:
        router.close()
        pool.stop(timeout=60.0)
    # ground truth: the fleet-wide journal merge (replayed admits carry the
    # watermark prefix, so cross-replica streams reconstruct exactly)
    states, _ = load_journal(fleet_journal_files(root, n))
    outputs = reconstruct_outputs(states)
    result = {
        "outputs": {str(u): t for u, t in outputs.items()},
        "closed": {str(u): st.reason for u, st in states.items()
                   if st.closed},
        "edge": {str(u): r for u, r in closed.items()},
        "router": stats,
    }
    out_path = spec.get("out")
    if out_path:
        tmp = f"{out_path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(result, f)
        os.replace(tmp, out_path)
    logger.info("fleet: %d request(s) done — %d routed, %d shed, "
                "%d failover replay(s)", len(states), stats["routed"],
                stats["shed"], stats["failover_replays"])
    return result


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Drive a multi-process serving fleet from a spec.")
    ap.add_argument("--spec", required=True, help="fleet spec JSON")
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    run_fleet(spec)
    return 0


if __name__ == "__main__":
    sys.exit(main())
