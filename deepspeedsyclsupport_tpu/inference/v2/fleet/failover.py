"""Journal-based cross-replica failover: the claim protocol.

A dead replica's request journals name its in-flight streams (admit
without close — the PR 11 contract). Two parties could replay them: the
router (onto a *surviving* replica — this package's headline) and the
replica's own supervisor-restarted worker (the PR 11 single-replica path).
The **claim file** arbitrates so every stream is replayed exactly once:

* the router writes ``failover_claim.json`` into the dead replica's
  journal dir *before* re-admitting anything — atomically, carrying the
  claimed uids;
* a restarted worker's recovery (and its spool ingestion) reads the claim
  file and skips claimed uids — they are someone else's streams now;
* a second router pass (or a restarted router) over the same journal dir
  sees its own prior claims and replays nothing twice.

The router only claims once a replica is *dead* per the decision table in
``docs/serving.md`` (supervisor process gone, or health stale past
``dead_after_s``) — a replica that is merely restarting keeps its streams
and replays them locally, which is cheaper than a cross-replica re-prefill
when the restart wins the race.
"""
import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List

from ..supervisor import ReplayRequest, load_journal
from ....utils.logging import logger

CLAIM_FILE = "failover_claim.json"


@dataclass
class FailoverClaim:
    """On-disk claim record: uid → claimer, plus the wall stamp of each
    claim batch (cross-process by definition, hence wall clock)."""

    uids: Dict[str, str] = field(default_factory=dict)
    stamped: List[float] = field(default_factory=list)

    def covers(self, uid: int) -> bool:
        return str(uid) in self.uids


def _claim_path(journal_dir: str) -> str:
    return os.path.join(journal_dir, CLAIM_FILE)


def read_claims(journal_dir: str) -> FailoverClaim:
    """Parse the claim file (empty claim when absent/corrupt — a torn
    claim write never blocks recovery, it just risks a local replay that
    the atomic-rename protocol below prevents anyway)."""
    try:
        with open(_claim_path(journal_dir)) as f:
            d = json.load(f)
        return FailoverClaim(uids=dict(d.get("uids", {})),
                             stamped=list(d.get("stamped", [])))
    except (OSError, ValueError):
        return FailoverClaim()


def atomic_write_json(path: str, payload: Dict) -> None:
    """tmp+rename JSON write — the one copy of the idiom the fleet's
    on-disk protocol files (claims, spool requests, specs) all ride."""
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def write_claims(journal_dir: str, claim: FailoverClaim) -> None:
    atomic_write_json(_claim_path(journal_dir),
                      {"uids": claim.uids, "stamped": claim.stamped})


def claim_in_flight(journal_dir: str, *,
                    claimer: str = "router") -> Dict[int, ReplayRequest]:
    """Load the dead replica's journals, return the in-flight streams not
    yet claimed, and durably claim them for ``claimer``.

    The claim is written BEFORE the caller replays anything: if the
    claimer dies mid-failover, a successor sees the claim and the streams
    stay with the (dead) claimer rather than being replayed twice — the
    conservative side of exactly-once. Closed streams and previously
    claimed uids are never returned.
    """
    states, _last_t = load_journal(journal_dir)
    claim = read_claims(journal_dir)
    fresh = {uid: st for uid, st in states.items()
             if not st.closed and not claim.covers(uid)}
    if not fresh:
        return {}
    for uid in fresh:
        claim.uids[str(uid)] = claimer
    claim.stamped.append(time.time())  # dslint: allow(wall-clock-in-step-path) cross-process claim stamp
    try:
        write_claims(journal_dir, claim)
    except OSError as e:
        # without a durable claim the restarted worker may also replay —
        # refuse to double-serve: better to leave the streams to the
        # local-restart path than to emit duplicate tokens
        logger.error("failover: cannot write claim in %s (%s) — leaving "
                     "streams to the local-restart path", journal_dir, e)
        return {}
    logger.info("failover: claimed %d in-flight stream(s) in %s for %s",
                len(fresh), journal_dir, claimer)
    return fresh


def claim_uids(journal_dir: str, uids, *, claimer: str = "router") -> None:
    """Claim uids that never reached the replica's journal (requests lost
    in transport — spooled but unconsumed at death). A respawned worker
    must skip their spool files: the claimer resubmitted them elsewhere."""
    claim = read_claims(journal_dir)
    new = [u for u in uids if not claim.covers(u)]
    if not new:
        return
    for uid in new:
        claim.uids[str(uid)] = claimer
    claim.stamped.append(time.time())  # dslint: allow(wall-clock-in-step-path) cross-process claim stamp
    try:
        write_claims(journal_dir, claim)
    except OSError as e:  # best effort: transport loss is already terminal
        logger.warning("failover: cannot extend claim in %s: %s",
                       journal_dir, e)
