"""Serving fleet control plane over N replica serving processes.

PR 4 gave one replica an SLA admission gate and PR 11 made that replica
crash-replayable; this package coordinates **many** of them — the layer the
TPU serving deployments profiled in "Fine-Tuning and Serving Gemma on
Cloud TPU" (PAPERS.md) put user-visible goodput behind: fleet-level
routing, lifecycle and failover, not single-engine throughput.

* :mod:`.router` — :class:`FleetRouter`: fleet-edge admission (the
  per-replica ``CapacityModel`` math aggregated across ready replicas, so
  hopeless requests shed at the edge before any replica queues), placement
  by SLA slack + measured capacity + tenant/session **affinity** (sticky
  keys so same-tenant streams co-locate for future prefix reuse; policy
  pluggable), and health gating (stale heartbeat or draining replicas drop
  out of rotation).
* :mod:`.pool` — :class:`ReplicaPool`: start/stop/drain orchestration over
  the PR 11 :class:`~..supervisor.ReplicaSupervisor` drain contract —
  rolling restart drains one replica at a time while the router steers new
  work away; crashed workers hot-respawn through the supervisor's existing
  elastic machinery, and the pool respawns supervisors that give up.
* :mod:`.failover` — journal-based **cross-replica** failover: when a
  replica dies for good, the router loads its request journals and
  re-admits each in-flight stream on a *surviving* replica from its
  emitted-token watermark (context rebuilt prompt+prefix, exactly-once
  closes) — recovery time is routing latency, not restart latency.
* :mod:`.cli` — ``python -m deepspeedsyclsupport_tpu.inference.v2.fleet
  --spec fleet.json``: the multi-process fleet loop the chaos e2e drives.

``Fleet/*`` telemetry (strict registry) and the offline view live in
``monitor/telemetry.py`` and ``tools/trace_report.py --fleet``. See
``docs/serving.md`` ("fleet control plane") for the failover decision
table and the rolling-restart protocol.
"""
from .failover import (FailoverClaim, claim_in_flight,  # noqa: F401
                       claim_uids, read_claims)
from .pool import ProcessReplica, ReplicaPool  # noqa: F401
from .router import (FleetConfig, FleetEvent, FleetRequest,  # noqa: F401
                     FleetRouter, LocalReplica, ReplicaEndpoint,
                     slack_affinity_placement)
