"""Blocked (paged) KV cache on device.

Analog of ``BlockedKVCache`` (``inference/v2/ragged/kv_cache.py``): a pool of
fixed-size KV blocks; sequences own arbitrary block lists, indirected through
block tables. Layout [L, num_blocks * block_size, KVH, D] — flat slot axis so
(de)referencing a slot is ``block_id * block_size + offset`` with one gather /
scatter, which XLA lowers to efficient dynamic-slice traffic on TPU.
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import RaggedInferenceConfig


class BlockedKV(NamedTuple):
    k: jnp.ndarray  # [L, num_blocks*block_size, KVH, D]
    v: jnp.ndarray

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]


def lane_padded_head_dim(head_dim: int, pad) -> int:
    """Mosaic constraint: the paged kernels DMA-slice the pool, and slice
    shapes must be lane-tile (128) aligned — head dims below/off 128 fail to
    compile on real TPU silicon ("Slice shape along dimension 2 must be
    aligned to tiling (128)"). The pool is therefore allocated with the head
    dim rounded up to the lane width on TPU; q/k/v are zero-padded at the
    attention seam (q pre-scaled by sqrt(d_pad/d) to compensate the impls'
    1/sqrt(trailing-dim) softmax scale) and the output sliced back, which
    leaves scores mathematically identical. ``pad`` None/0 = auto (128 on
    TPU, none
    elsewhere). HBM note: a d=64 model pays 2x KV pool for kernel decode."""
    import jax

    if pad in (None, 0):
        pad = 128 if jax.default_backend() == "tpu" else 1
    return -(-head_dim // pad) * pad


def init_blocked_kv(model_config, cfg: RaggedInferenceConfig) -> BlockedKV:
    d = lane_padded_head_dim(model_config.head_dim,
                             getattr(cfg, "head_dim_lane_pad", None))
    shape = (model_config.num_layers, cfg.num_blocks * cfg.block_size,
             model_config.num_kv_heads, d)
    return BlockedKV(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def kv_pool_stats(kv: BlockedKV, allocator) -> dict:
    """Occupancy + footprint of the paged pool, shape-only (no host sync):
    the ``Serve/kv_occupancy`` gauge's source and the operator's answer to
    "is the pool the bottleneck" — ``occupancy`` is the PHYSICAL fraction
    of blocks held by anyone (streams or the prefix index), while
    ``logical_occupancy`` prices every block-table entry at full cost
    (sum of refcounts / total): the gap between the two is exactly the HBM
    the prefix cache's cross-request sharing is saving. ``pool_bytes``
    counts BOTH k and v arrays at the (possibly lane-padded) allocated
    head dim."""
    total = allocator.num_blocks
    free = allocator.free_blocks
    physical = total - free
    # plain free-list allocators (no refcounts) degenerate to logical ==
    # physical, shared == 0 — the pre-sharing report
    logical = int(getattr(allocator, "logical_blocks", physical))
    shared = int(getattr(allocator, "shared_blocks", 0))
    per_slot = int(np.prod(kv.k.shape[2:])) * kv.k.dtype.itemsize \
        * kv.k.shape[0]
    return {"blocks_total": total, "blocks_free": free,
            "blocks_physical": physical, "blocks_logical": logical,
            "blocks_shared": shared,
            "occupancy": 1.0 - free / total,
            "logical_occupancy": logical / total,
            "pool_bytes": 2 * per_slot * kv.num_slots}


def build_block_copy_fn(block_size: int):
    """Jitted copy of one KV block (both k and v) to a fresh block — the
    copy-on-write seam for the prefix cache. ``src``/``dst`` are traced
    int32 operands, so ONE compiled program serves every block pair; the
    pool is donated (the copy is an in-place update as far as the caller
    is concerned)."""

    def _copy(kv: BlockedKV, src, dst) -> BlockedKV:
        L, _, H, D = kv.k.shape
        sizes = (L, block_size, H, D)
        ks = jax.lax.dynamic_slice(kv.k, (0, src * block_size, 0, 0), sizes)
        vs = jax.lax.dynamic_slice(kv.v, (0, src * block_size, 0, 0), sizes)
        return BlockedKV(
            jax.lax.dynamic_update_slice(kv.k, ks, (0, dst * block_size, 0, 0)),
            jax.lax.dynamic_update_slice(kv.v, vs, (0, dst * block_size, 0, 0)))

    return jax.jit(_copy, donate_argnums=0)
