"""Blocked (paged) KV cache on device.

Analog of ``BlockedKVCache`` (``inference/v2/ragged/kv_cache.py``): a pool of
fixed-size KV blocks; sequences own arbitrary block lists, indirected through
block tables. Layout [L, num_blocks * block_size, KVH, D] — flat slot axis so
(de)referencing a slot is ``block_id * block_size + offset`` with one gather /
scatter, which XLA lowers to efficient dynamic-slice traffic on TPU.
"""
from typing import NamedTuple

import jax.numpy as jnp

from .config import RaggedInferenceConfig


class BlockedKV(NamedTuple):
    k: jnp.ndarray  # [L, num_blocks*block_size, KVH, D]
    v: jnp.ndarray

    @property
    def num_slots(self) -> int:
        return self.k.shape[1]


def init_blocked_kv(model_config, cfg: RaggedInferenceConfig) -> BlockedKV:
    shape = (model_config.num_layers, cfg.num_blocks * cfg.block_size,
             model_config.num_kv_heads, model_config.head_dim)
    return BlockedKV(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
