"""Ragged engine configuration.

Analog of ``DSStateManagerConfig`` / ``RaggedInferenceEngineConfig``
(``inference/v2/ragged/manager_configs.py``): the same knob families — KV block
geometry, ragged batch budgets, sequence limits.
"""
import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Union

import jax.numpy as jnp


@dataclass
class RaggedInferenceConfig:
    block_size: int = 64            # KV tokens per block (reference KV_BLOCK_SIZE)
    max_tokens_per_batch: int = 768  # SplitFuse token budget (max_ragged_batch_size)
    max_sequences: int = 64         # concurrent seqs per forward (max_ragged_sequence_count)
    max_context: int = 2048         # per-sequence KV budget (max_context)
    num_blocks: Optional[int] = None  # total KV pool; default sized for half the
    # worst case (continuous batching overcommits, like the reference's
    # memory_config-driven cache sizing). HBM sizing note: each LIVE
    # sequence also pins one device-resident logits row (V floats at the
    # serving dtype) until flush — budget ~V*4B*max_sequences alongside
    # the KV pool
    dtype: Any = jnp.bfloat16
    seed: int = 0
    quantize_weights: bool = False   # ZeRO-Inference int8/int4 layer weights
    quant_group_size: int = 64
    quant_bits: int = 8              # 8 or 4 (packed)
    # mixed/prefill-batch attention impl, resolved through the pluggable
    # registry (module_registry.py): "auto" or any registered name —
    # built-ins: kernel (ragged paged-attention Pallas; atoms), flash
    # (packed flash over gathered KV), xla (exact reference),
    # kernel_interpret (debug); user-registered names work too
    prefill_attn: str = "auto"
    # decode (one-token-per-slot) attention impl: "auto" or a registered
    # decode_attn name (built-ins: pallas, xla, pallas_interpret)
    decode_attn: str = "auto"
    atom_q_size: Optional[int] = None  # q rows per atom (default ≤128)
    # serving policy (VERDICT r3 weak #6 — FIFO + longest-evict only):
    # bound on the token-budget share prompts may take in a forward that
    # also decodes (ITL protection under prompt bursts; 1.0 = off)
    max_prefill_fraction: float = 1.0
    # KV-pressure eviction victim: longest_context (truncation-biased,
    # default) | lru (least-recently-scheduled) | newest (LIFO backoff) |
    # slack (least SLA slack — most likely to miss anyway; docs/serving.md)
    eviction_policy: str = "longest_context"
    # steady-state decode fusion: when every live sequence is decoding and
    # nothing is waiting, run up to this many decode steps (forward +
    # on-device sample + paged-KV append + position advance) inside ONE
    # jitted while_loop, returning all sampled tokens in a single host
    # transfer. 1 = one host-scheduled forward per token (the reference's
    # per-iteration MII loop, ``engine_v2.py:107``); >1 amortizes host
    # scheduling + dispatch across K tokens — the steady-state analog of
    # the reference's ragged-kernel amortization
    decode_steps_per_dispatch: int = 1
    # KV-pool head-dim lane alignment (kv_cache.lane_padded_head_dim):
    # None = auto (round up to 128 on TPU — Mosaic DMA slices must be
    # lane-tile aligned; no padding elsewhere); an int forces that multiple.
    # HBM note: a d=64 model pays 2x KV pool on TPU for kernel decode.
    head_dim_lane_pad: Optional[int] = None

    def __post_init__(self):
        if not isinstance(self.prefill_attn, str) or not self.prefill_attn:
            raise ValueError(
                f"prefill_attn must name a registered implementation or "
                f"'auto', got {self.prefill_attn!r}")
        # names resolve against the pluggable registry at engine build
        # (module_registry.py) — not a closed enum, so user-registered
        # implementations are selectable from the same config key
        if not 0.0 < self.max_prefill_fraction <= 1.0:
            raise ValueError(f"max_prefill_fraction must be in (0, 1], got "
                             f"{self.max_prefill_fraction}")
        if self.eviction_policy not in ("longest_context", "lru", "newest",
                                        "slack"):
            raise ValueError(f"eviction_policy must be longest_context|lru|"
                             f"newest|slack, got {self.eviction_policy!r}")
        if self.atom_q_size is None:
            self.atom_q_size = min(128, self.max_tokens_per_batch)
        if self.atom_q_size < 1:
            raise ValueError(f"atom_q_size must be >= 1, got "
                             f"{self.atom_q_size}")
        if self.decode_steps_per_dispatch < 1:
            raise ValueError(f"decode_steps_per_dispatch must be >= 1, got "
                             f"{self.decode_steps_per_dispatch}")
        if self.quant_bits not in (4, 8):
            raise ValueError(f"quant_bits must be 4 or 8, got "
                             f"{self.quant_bits}")
        if self.num_blocks is None:
            per_seq = math.ceil(self.max_context / self.block_size)
            self.num_blocks = max(per_seq, self.max_sequences * per_seq // 2)
        if self.max_context % self.block_size:
            raise ValueError("max_context must be a multiple of block_size")

    @property
    def blocks_per_seq(self) -> int:
        return self.max_context // self.block_size

    @classmethod
    def from_config(cls, config: Optional[Dict] = None, **kw):
        cfg = dict(config or {})
        cfg.update(kw)
        if isinstance(cfg.get("dtype"), str):
            from ..config import _DTYPES

            cfg["dtype"] = _DTYPES[cfg["dtype"].lower()]
        known = set(cls.__dataclass_fields__)
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(f"unknown ragged config keys: {sorted(unknown)}")
        return cls(**cfg)


@dataclass
class ServingPolicyConfig:
    """SLA serving-policy knobs (``serving.ServingSession`` — see
    ``docs/serving.md`` for the overload-behavior contract these encode).

    The reference's FastGen SLA is two-part per request: first token within
    a TTFT bound AND a sustained decode token rate. Under overload the
    policy's job is to keep the *admitted* streams meeting that SLA by
    queueing or shedding new arrivals, preempting the lowest-slack stream
    when the KV pool exhausts, and ordering work by slack — instead of the
    admit-everyone collapse (r05: 100% SLA miss at 10 clients).
    """

    # --- admission gate -------------------------------------------------
    admission: str = "sla"     # "sla" (project deadlines) | "none" (FIFO —
    #                            queue on structural limits only)
    ttft_sla_s: Optional[float] = None  # default TTFT deadline per request
    #                                     (None = requests carry no deadline
    #                                     unless submit() sets one)
    token_rate_sla: float = 0.0   # per-stream decode tokens/s target
    shed_policy: str = "queue"    # "queue": hold unadmittable requests until
    #                               their deadline is provably unmeetable;
    #                               "reject": shed immediately when not
    #                               admissible at submit time
    max_queue_s: float = 30.0     # queued longer than this is shed outright
    sla_headroom: float = 1.15    # safety factor on projected service times
    rate_feasibility_margin: float = 0.8  # shed on rate ONLY when the
    #   measured per-stream decode rate is clearly below the SLA
    #   (measured < margin * required): the EWMA breathes several percent
    #   under load, and a borderline stream still delivers ~SLA — TTFT
    #   projection is the overload valve, this check only catches
    #   hardware-can-never-do-it targets
    # --- overload eviction ---------------------------------------------
    preempt_policy: str = "reject"  # KV-exhaustion victim handling:
    #                                 "reject" (finish with partial output) |
    #                                 "requeue" (re-prefill later; its SLA is
    #                                 re-projected at re-admission)
    # --- batch composition ----------------------------------------------
    tenant_token_budget: Optional[Union[int, Dict[str, int]]] = None
    #   max prefill tokens one tenant may take per scheduling round (int =
    #   every tenant; dict keys tenants, "*" = default; None = no cap)
    aging_weight: float = 2.0     # starvation aging: seconds of slack credit
    #                               per second a chunk waits unserved
    # --- capacity model (EWMA priors; measured values take over) --------
    ewma_alpha: float = 0.25
    prefill_tok_s_prior: float = 1000.0
    decode_step_s_prior: float = 0.05
    # telemetry: emit Serve/* metrics through monitor.telemetry
    telemetry: bool = True
    # --- fault tolerance (docs/serving.md "failure contract") -----------
    # request journal: every admitted request's immutable prompt, SLA
    # fields and emitted-token watermark as a rank-local JSONL (flushed
    # per record), so in-flight state survives the process and a replica
    # supervisor can replay from the watermark. None = no journal.
    journal_path: Optional[str] = None
    # stuck-decode watchdog: arm a deadline around each scheduling round's
    # device dispatches; on expiry dump stacks, flush the journal/telemetry
    # and exit rc 219 (SERVE_HANG_EXIT_CODE) — the serving twin of the
    # rc-218 collective-hang contract
    watchdog_enabled: bool = False
    watchdog_deadline_s: float = 60.0
    watchdog_warmup_deadline_s: Optional[float] = None  # default 10x: the
    #   first round compiles (prefill + sampler + fused rungs)
    watchdog_poll_s: float = 0.25
    # structured backpressure: consecutive no-progress scheduling rounds
    # (no events, no dispatches) with live streams before the session
    # preempts the lowest-slack stream to un-wedge the batch — the KV
    # exhaustion self-healing valve (never an exception out of step())
    stall_patience_rounds: int = 3
    # --- cross-request prefix cache (docs/serving.md "prefix reuse") ----
    # None = off. A dict installs engine.prefix_cache at session build:
    #   enabled:           bool, default True (False keeps the dict but
    #                      skips installation — A/B switch)
    #   scope:             "tenant" (default; probes never cross tenants)
    #                      | "global"
    #   min_block_hits:    offers of a block hash before it is pinned
    #                      (default 1 — pin on first commit)
    #   max_pinned_blocks: index pin cap (default: half the KV pool)
    prefix_cache: Optional[Dict[str, Any]] = None
    # --- request-time attribution (docs/observability.md) ---------------
    # serve/stage lifecycle records in the journal + the in-memory
    # trace_log ring monitor/reqtrace.py joins into per-request waterfalls
    trace_stages: bool = True
    # SLO burn accounting (Serve/slo.* gauges): sliding-window length and
    # the error budget the burn rate is priced against (miss_frac/budget)
    slo_window_s: float = 60.0
    slo_budget: float = 0.05
    extra: Dict[str, Any] = field(default_factory=dict)  # forward-compat bag

    def __post_init__(self):
        if self.admission not in ("sla", "none"):
            raise ValueError(f"admission must be sla|none, got "
                             f"{self.admission!r}")
        if self.shed_policy not in ("queue", "reject"):
            raise ValueError(f"shed_policy must be queue|reject, got "
                             f"{self.shed_policy!r}")
        if self.preempt_policy not in ("reject", "requeue"):
            raise ValueError(f"preempt_policy must be reject|requeue, got "
                             f"{self.preempt_policy!r}")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha must be in (0, 1], got "
                             f"{self.ewma_alpha}")
        if self.sla_headroom < 1.0:
            raise ValueError(f"sla_headroom must be >= 1.0, got "
                             f"{self.sla_headroom}")
        if not 0.0 < self.rate_feasibility_margin <= 1.0:
            raise ValueError(f"rate_feasibility_margin must be in (0, 1], "
                             f"got {self.rate_feasibility_margin}")
        if self.ttft_sla_s is not None and self.ttft_sla_s <= 0:
            raise ValueError(f"ttft_sla_s must be positive, got "
                             f"{self.ttft_sla_s}")
        if self.watchdog_deadline_s <= 0 or self.watchdog_poll_s <= 0:
            raise ValueError(
                f"watchdog deadline_s/poll_s must be > 0, got "
                f"{self.watchdog_deadline_s}/{self.watchdog_poll_s}")
        if self.watchdog_warmup_deadline_s is not None \
                and self.watchdog_warmup_deadline_s < self.watchdog_deadline_s:
            raise ValueError(
                f"watchdog_warmup_deadline_s "
                f"({self.watchdog_warmup_deadline_s}) must be >= "
                f"watchdog_deadline_s ({self.watchdog_deadline_s}): the "
                f"first round includes compilation")
        if self.stall_patience_rounds < 1:
            raise ValueError(f"stall_patience_rounds must be >= 1, got "
                             f"{self.stall_patience_rounds}")
        if self.slo_window_s <= 0:
            raise ValueError(f"slo_window_s must be > 0, got "
                             f"{self.slo_window_s}")
        if not 0.0 < self.slo_budget <= 1.0:
            raise ValueError(f"slo_budget must be in (0, 1], got "
                             f"{self.slo_budget}")
        if self.prefix_cache is not None:
            known = {"enabled", "scope", "min_block_hits",
                     "max_pinned_blocks"}
            unknown = set(self.prefix_cache) - known
            if unknown:
                raise ValueError(f"unknown prefix_cache keys: "
                                 f"{sorted(unknown)} (known: {sorted(known)})")
            scope = self.prefix_cache.get("scope", "tenant")
            if scope not in ("tenant", "global"):
                raise ValueError(f"prefix_cache.scope must be tenant|global, "
                                 f"got {scope!r}")
            if int(self.prefix_cache.get("min_block_hits", 1)) < 1:
                raise ValueError("prefix_cache.min_block_hits must be >= 1")
            mpb = self.prefix_cache.get("max_pinned_blocks")
            if mpb is not None and int(mpb) < 1:
                raise ValueError("prefix_cache.max_pinned_blocks must be "
                                 ">= 1 or None")

    @classmethod
    def from_config(cls, config: Optional[Dict] = None, **kw):
        cfg = dict(config or {})
        cfg.update(kw)
        known = set(cls.__dataclass_fields__)
        unknown = set(cfg) - known
        if unknown:
            raise ValueError(
                f"unknown serving policy keys: {sorted(unknown)}")
        return cls(**cfg)
