"""FastGen-class ragged serving engine (reference: ``deepspeed/inference/v2/``).

Continuous batching with a paged (blocked) KV cache and Dynamic-SplitFuse token
scheduling:

* :mod:`.config` — engine knobs (``inference/v2/ragged/manager_configs.py``)
* :mod:`.ragged` — ``BlockedAllocator`` free-list, sequence descriptors, and the
  host-built ragged batch metadata (``inference/v2/ragged/``)
* :mod:`.kv_cache` — blocked KV arrays on device (``ragged/kv_cache.py``)
* :mod:`.model` — ragged forward over the paged cache (the role of the CUDA
  ``ragged_ops`` kernel set: ``linear_blocked_kv_rotary``, ``blocked_flash``,
  ``logits_gather``)
* :mod:`.scheduler` — Dynamic SplitFuse token-budget scheduler with
  slack-ordered (deadline-driven) chunk composition
* :mod:`.engine_v2` — ``InferenceEngineV2`` with the ``put/query/flush/
  can_schedule`` contract (``inference/v2/engine_v2.py:107-237``)
* :mod:`.serving` — SLA-aware serving policy layer (admission control,
  capacity model, overload-graceful eviction; ``docs/serving.md``)
* :mod:`.prefix_cache` — cross-request KV prefix cache: block-aligned
  prefix trie over the paged pool, refcount-shared blocks, copy-on-write
  (``docs/serving.md`` "prefix reuse")
* :mod:`.supervisor` — serving-plane fault tolerance: request journal,
  crash-replay recovery, replica supervisor, rc-219 stuck-decode contract
  (``docs/serving.md`` "failure contract")
* :mod:`.fleet` — fleet control plane over N supervised replicas: affinity
  router with fleet-edge admission, replica pool lifecycle (rolling
  restart, hot respawn), journal-based cross-replica failover
  (``docs/serving.md`` "fleet control plane")
"""
from .config import RaggedInferenceConfig, ServingPolicyConfig  # noqa: F401
from .engine_v2 import InferenceEngineV2  # noqa: F401
from .prefix_cache import PrefixCache  # noqa: F401
from .ragged import BlockedAllocator, RaggedBatch, SequenceDescriptor  # noqa: F401
from .serving import CapacityModel, ServeEvent, ServingSession  # noqa: F401
from .supervisor import (RequestJournal, ReplayRequest,  # noqa: F401
                         ReplicaSupervisor, SERVE_HANG_EXIT_CODE,
                         load_journal, reconstruct_outputs,
                         recover_requests)
from .fleet import (FleetConfig, FleetRequest, FleetRouter,  # noqa: F401
                    LocalReplica, ProcessReplica, ReplicaPool)


def build_hf_engine(path: str, **config) -> "InferenceEngineV2":
    """FastGen entry point over a local HF checkpoint directory (reference
    ``inference/v2/engine_factory.py:123`` ``build_hf_engine``: HF name →
    policy → engine): loads the checkpoint through the per-family ingestion
    maps (``checkpoint/hf.py``) and serves it with the ragged engine.
    Engine knobs (max_tokens_per_batch, block_size, ...) ride ``config``."""
    import os

    if not os.path.isdir(path):
        raise FileNotFoundError(
            f"build_hf_engine expects a local checkpoint directory, got "
            f"{path!r} (hub names are not downloaded here)")
    from ...checkpoint.hf import load_hf_checkpoint

    import jax.numpy as jnp

    dtype = config.pop("dtype", "bfloat16")
    model, params = load_hf_checkpoint(path, dtype=dtype)
    # the model's compute-dtype hint follows the serving dtype (load casts
    # the params; the config drives activation dtypes)
    model.config.dtype = jnp.dtype(dtype).name if not isinstance(dtype, str) \
        else dtype
    return InferenceEngineV2(model, params, config=config, dtype=dtype)
