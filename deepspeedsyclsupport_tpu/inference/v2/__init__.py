"""FastGen-class ragged serving engine (reference: ``deepspeed/inference/v2/``).

Continuous batching with a paged (blocked) KV cache and Dynamic-SplitFuse token
scheduling:

* :mod:`.config` — engine knobs (``inference/v2/ragged/manager_configs.py``)
* :mod:`.ragged` — ``BlockedAllocator`` free-list, sequence descriptors, and the
  host-built ragged batch metadata (``inference/v2/ragged/``)
* :mod:`.kv_cache` — blocked KV arrays on device (``ragged/kv_cache.py``)
* :mod:`.model` — ragged forward over the paged cache (the role of the CUDA
  ``ragged_ops`` kernel set: ``linear_blocked_kv_rotary``, ``blocked_flash``,
  ``logits_gather``)
* :mod:`.scheduler` — Dynamic SplitFuse token-budget scheduler
* :mod:`.engine_v2` — ``InferenceEngineV2`` with the ``put/query/flush/
  can_schedule`` contract (``inference/v2/engine_v2.py:107-237``)
"""
from .config import RaggedInferenceConfig  # noqa: F401
from .engine_v2 import InferenceEngineV2  # noqa: F401
from .ragged import BlockedAllocator, RaggedBatch, SequenceDescriptor  # noqa: F401
