"""InferenceEngineV2 — continuous-batching ragged serving.

Analog of ``InferenceEngineV2`` (``inference/v2/engine_v2.py``): the same
``put / query / flush / can_schedule`` contract over a paged KV cache, plus a
:meth:`generate` convenience loop that plays the role MII's serving loop plays
above the reference engine.

Data flow per :meth:`put` (reference ``engine_v2.py:107`` → §3.5 call stack):
host scheduler picks chunks → ``RaggedBatch`` metadata built and shipped →
ONE jitted ragged forward (QKV+RoPE+paged-append, blocked attention, MLP,
logits gather) → last-token logits land back in each sequence descriptor.
"""
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import RaggedInferenceConfig
from .kv_cache import init_blocked_kv
from .model import build_ragged_forward_fn
from .ragged import BlockedAllocator, SequenceDescriptor, build_ragged_batch
from .scheduler import schedule_chunks
from ..params import place_inference_params
from ..sampling import SamplingParams, sample_token_dyn
from ...comm.topology import MeshTopology, build_topology
from ...utils.logging import log_dist


@dataclasses.dataclass(frozen=True)
class AdmissionResult:
    """Structured admission decision (reference ``can_schedule:179`` returns
    schedulability for the serving layer to back off on — this names WHO was
    rejected and WHY instead of a bare bool)."""
    admitted: Tuple[int, ...]
    reasons: Dict[int, str]  # per rejected uid

    @property
    def rejected(self) -> Tuple[int, ...]:
        return tuple(self.reasons)

    def __bool__(self) -> bool:
        return not self.reasons


class PutResult(Dict[int, jax.Array]):
    """:meth:`InferenceEngineV2.put`'s return: the {uid: last-token logits}
    mapping (drop-in for the plain dict earlier rounds returned) plus the
    admission outcome, so schedulers see partial rejection without an
    exception tearing down the whole batch."""
    admission: AdmissionResult


class InferenceEngineV2:
    def __init__(self, model, params, config: Optional[dict] = None,
                 topology: Optional[MeshTopology] = None, **kw):
        self.config = (config if isinstance(config, RaggedInferenceConfig)
                       else RaggedInferenceConfig.from_config(config, **kw))
        cfg = self.config
        self.model = model
        self.topology = topology or build_topology(dp=-1)

        rules = getattr(model, "sharding_rules", None)
        self.params, _ = place_inference_params(params, self.topology, rules,
                                                cfg.dtype)
        if cfg.quantize_weights and "layers" in self.params:
            # ZeRO-Inference: int8 layer weights, dequantized per layer
            # inside the ragged scan (model.py _dequant)
            from ...compression.quantize import quantize_tree

            stacked = bool(getattr(model.config, "scan_layers", False))
            self.params = dict(self.params)
            # no donation: placement may alias caller-held arrays (see
            # InferenceEngine._quantize_weights)
            self.params["layers"] = jax.jit(
                lambda t: quantize_tree(t, cfg.quant_group_size,
                                        stacked=stacked,
                                        bits=cfg.quant_bits))(
                self.params["layers"])

        self.kv = init_blocked_kv(model.config, cfg)
        self.allocator = BlockedAllocator(cfg.num_blocks)
        self.seqs: Dict[int, SequenceDescriptor] = {}
        # SLA layer (serving.ServingSession) installs a scheduler.SlackPolicy
        # here; put() then orders chunks by slack instead of arrival. None =
        # the pre-SLA least-recently-served ordering.
        self.slack_policy = None
        # cross-request prefix cache (install_prefix_cache). None = every
        # stream prefills its full prompt (the pre-sharing behavior).
        self.prefix_cache = None
        self._copy_block = None  # jitted CoW block copy, built lazily
        self._tick = 0  # forward counter (LRU eviction / prefill fairness)
        self._forward = build_ragged_forward_fn(model, cfg.block_size,
                                                attn_impl=cfg.prefill_attn)
        self._decode_forward = None  # built lazily (kernel path)
        # (K, sampling STRUCTURE) -> jitted K-step program; temperature/
        # top_p/eos are traced operands so they never force a recompile.
        # Bounded LRU: each entry is a full compiled model program
        from collections import OrderedDict

        self._decode_multi: "OrderedDict[Any, Any]" = OrderedDict()
        self._decode_multi_cap = 16
        self.host_dispatches = 0  # host-scheduled device dispatches (bench)
        self._rng = jax.random.PRNGKey(cfg.seed)
        # only the sampling STRUCTURE is static; temperature/top_p are
        # operands (sweeping them reuses one compiled sampler)
        self._sample_fn = jax.jit(sample_token_dyn, static_argnums=(4,))
        # atoms feed only impls that declare needs_atoms — decide ONCE
        # whether that path runs so prefill forwards skip the host atom
        # build + five-array transfer when it cannot (registry metadata;
        # "auto" resolves against an atoms-present context)
        from .module_registry import select_impl as _sel

        try:
            spec = _sel("prefill_attn", cfg.prefill_attn,
                        {"backend": jax.default_backend(),
                         "has_atoms": True})
        except KeyError as e:
            # get_impl's message already names the registered impls
            raise ValueError(str(e)) from e
        self._use_atoms = bool(spec.metadata.get("needs_atoms"))
        log_dist(f"ragged engine: {cfg.num_blocks} KV blocks × {cfg.block_size} "
                 f"tokens, budget {cfg.max_tokens_per_batch} tok/fwd, "
                 f"≤{cfg.max_sequences} seqs")

    # ----------------------------------------------------------- persistence
    def serialize(self, save_path: str) -> None:
        """Model snapshot (reference ``engine_v2.serialize:237``: flattened
        params + metadata + pickled config): the placed (de-quantized if
        ZeRO-Inference was on) parameter tree plus both configs, reloadable
        with :meth:`deserialize` into a fresh engine."""
        import dataclasses

        from ...checkpoint.engine import save_tree

        from ...models.config import ModelConfig

        if not isinstance(getattr(self.model, "config", None), ModelConfig):
            raise TypeError(
                f"serialize() supports models carrying a ModelConfig "
                f"(models.CausalLM family); got {type(self.model).__name__} "
                f"— fail at save, not with a confusing load-time error")
        params = self.params
        if self.config.quantize_weights and "layers" in params:
            from ...compression.quantize import dequantize_tree

            params = dict(params)
            params["layers"] = jax.jit(
                lambda t: dequantize_tree(t, jnp.dtype(self.config.dtype))
            )(params["layers"])
        eng_cfg = dataclasses.asdict(self.config)
        eng_cfg["dtype"] = str(jnp.dtype(eng_cfg["dtype"]))  # JSON-safe
        meta = {"model_class": type(self.model).__name__,
                "model_config": dataclasses.asdict(self.model.config),
                "engine_config": eng_cfg}
        save_tree(save_path, {"params": params}, meta)
        log_dist(f"serialized ragged engine model to {save_path}")

    @classmethod
    def deserialize(cls, save_path: str,
                    topology: Optional[MeshTopology] = None,
                    **config_overrides) -> "InferenceEngineV2":
        """Rebuild an engine from :meth:`serialize` output (the reference
        pairs this with its pickled ``ds_model_config``)."""
        import json as _json
        import os as _os

        from ...checkpoint.engine import META_FILE, load_tree
        from ...models.config import ModelConfig
        from ...models.transformer import CausalLM

        with open(_os.path.join(save_path, META_FILE)) as f:
            meta = _json.load(f)
        cls_name = meta.get("model_class", "CausalLM")
        if cls_name != "CausalLM":
            raise TypeError(f"snapshot was serialized from {cls_name}; "
                            f"deserialize() rebuilds CausalLM models only")
        model = CausalLM(ModelConfig(**meta["model_config"]))
        example = jax.eval_shape(model.init_params, jax.random.PRNGKey(0))
        # sharded restore: leaves stream straight onto the serving mesh (the
        # resharding-on-load path) — never staged whole on one device
        from ...runtime import zero as zero_lib

        topology = topology or build_topology(dp=-1)
        sh = zero_lib.tree_param_shardings(
            example, topology, stage=0,
            extra_rules=getattr(model, "sharding_rules", None))
        state, _ = load_tree(save_path, {"params": (example, sh)})
        eng_cfg = dict(meta.get("engine_config", {}))
        eng_cfg.update(config_overrides)
        return cls(model, state["params"], config=eng_cfg,
                   topology=topology)

    # --------------------------------------------------------------- warmup
    def warmup(self, fused_ladder: bool = False) -> None:
        """Compile the prefill and decode programs in BOTH KV-sharding
        states before serving. The first jitted forward returns a donated
        KV cache whose sharding differs from ``init_blocked_kv``'s
        placement, so each program's SECOND call in that state is the one
        that compiles the steady-state variant — without this, the first
        real requests pay two spurious recompiles (measured ~1.7s each on
        the CPU sim; worse on TPU). ``fused_ladder=True`` additionally
        compiles EVERY fused-decode rung {K/2, ..., 2}, not just K — a
        serving bench must not pay a mid-run compile when a short tail
        first selects a smaller rung (off by default: tests and callers
        that never hit the fused path shouldn't pay log2(K) compiles)."""
        cfg = self.config
        uid = -(1 << 40) - 1   # reserved: below any sane caller uid
        # leave room for the 4 follow-up tokens within max_context
        n = max(2, min(cfg.max_tokens_per_batch - 1, cfg.max_context - 4, 8))
        steps = ([[1] * n],                    # prefill, state A
                 [[2]],                        # decode path, state A
                 [[2, 2]],                     # prefill path, state B
                 [[2]])                        # decode path, state B
        out = None
        for toks in steps:
            out = self.put([uid], toks)
            if uid not in out and out.admission.rejected:
                self.flush([uid])
                raise RuntimeError(
                    f"warmup could not admit its sequence — call warmup() "
                    f"on an idle engine ({dict(out.admission.reasons)})")
        if cfg.decode_steps_per_dispatch > 1:
            # compile the fused K-step steady-state program too, for
            # generate()'s default greedy/no-eos config (non-default sampling
            # STRUCTURES still compile on first use). Restart from a fresh
            # 1-token sequence so context headroom never truncates the two
            # dispatches below the full K the serving loop will use
            k = cfg.decode_steps_per_dispatch
            self.flush([uid])
            self.put([uid], [[2]])
            running = {uid: 2 * k + 1}
            for _ in range(2):
                if uid not in running:
                    break
                self._decode_multi_dispatch(running, SamplingParams(), None,
                                            jax.random.PRNGKey(0))
            if (k, SamplingParams().structure) not in self._decode_multi:
                log_dist(f"warmup: fused decode program (K={k}) not "
                         f"pre-compiled — KV pool too small to pre-fund it; "
                         f"first steady-state generate() will compile")
            if fused_ladder:
                # mirror the serve-time rung sequence (max(2, rung // 2)
                # stepping) so EVERY program the dispatch can select is
                # compiled here — for non-power-of-two K the naive
                # `rung //= 2` walk skips the 2-rung the pressure
                # fallback snaps to
                rung = k
                while rung > 2:
                    rung = max(2, rung // 2)
                    self.flush([uid])
                    self.put([uid], [[2]])
                    # k_cap pins the ladder top at `rung`, forcing its
                    # compile (a bare budget of `rung` steps would be
                    # routed back to the already-compiled K program by
                    # the prefer-compiled rung walk)
                    self._decode_multi_dispatch({uid: rung},
                                                SamplingParams(), None,
                                                jax.random.PRNGKey(0),
                                                k_cap=rung)
        self.flush([uid])
        self.host_dispatches = 0  # counter measures serving, not warmup

    # ------------------------------------------------------------- scheduling
    def can_schedule(self, uids: Sequence[int],
                     lengths: Sequence[int]) -> bool:
        """Admission check (reference ``can_schedule:179``): sequence slots,
        per-seq context limit, and worst-case KV block pressure."""
        return not self.check_schedule(uids, lengths).rejected

    def check_schedule(self, uids: Sequence[int],
                       lengths: Sequence[int],
                       cached_prefix: Optional[Dict[int, int]] = None
                       ) -> "AdmissionResult":
        """Per-uid admission (the structured form of ``can_schedule``):
        greedily admits uids in caller order while sequence slots, per-seq
        context, and worst-case KV block pressure allow, and names the limit
        that rejected each of the rest — so an external scheduler can back
        off per sequence instead of all-or-nothing.

        ``cached_prefix`` maps a NEW uid to the prefix-cache token count
        (``prefix_cache.peek``) its prompt would adopt at admission: those
        block-aligned tokens map to SHARED blocks, so the KV-pressure check
        prices the request at its novel blocks only — a prefix hit admits
        work the cold check would reject. The context and slot checks are
        unaffected (shared tokens still occupy context)."""
        cfg = self.config
        slots = len(self.seqs)
        free = self.allocator.free_blocks
        if self.prefix_cache is not None:
            # cold unshared index pins surrender to allocation pressure
            # (allocator.reclaim_cb), so the KV check counts them as free —
            # otherwise a pool full of stale pins would reject admissions
            # that would in fact allocate fine
            free += self.prefix_cache.reclaimable()
        admitted: List[int] = []
        rejected: Dict[int, str] = {}
        seen: set = set()
        for u, n in zip(uids, lengths):
            if u in seen:
                # a repeated uid's second entry would be checked against
                # pre-call descriptor state (its first entry's tokens
                # invisible), letting pending exceed max_context and wedge
                # the sequence — one entry per uid per call, by contract
                rejected[u] = "duplicate uid in one call (merge the token " \
                              "lists or put() sequentially)"
                continue
            seen.add(u)
            d = self.seqs.get(u)
            # undrained pending tokens count toward context/KV demand too
            cached = (d.n_cached + len(d.pending)) if d else 0
            have = len(d.blocks) if d else 0
            if cached + n > cfg.max_context:
                rejected[u] = (f"context: {cached}+{n} tokens exceeds "
                               f"max_context {cfg.max_context}")
                continue
            if d is None and slots + 1 > cfg.max_sequences:
                rejected[u] = f"slots: engine at max_sequences {cfg.max_sequences}"
                continue
            shared = 0
            if d is None and cached_prefix:
                # block-aligned cached prefix → that many leading blocks
                # arrive shared instead of allocated (cap mirrors the
                # probe's ≥1-novel-token rule)
                shared = min(int(cached_prefix.get(u, 0)),
                             max(0, n - 1)) // cfg.block_size
            want = max(0, -(-(cached + n) // cfg.block_size) - have - shared)
            if want > free:
                rejected[u] = (f"kv: needs {want} blocks, "
                               f"{free} free in the pool")
                continue
            free -= want
            if d is None:
                slots += 1
            admitted.append(u)
        return AdmissionResult(tuple(admitted), dict(rejected))

    # -------------------------------------------------------------------- put
    def put(self, uids: Sequence[int],
            tokens_list: Sequence[Sequence[int]],
            strict: bool = False, drain: bool = True) -> "PutResult":
        """Enqueue tokens and run ragged forwards over what fits.

        Returns a :class:`PutResult`: {uid: last-token logits [V]} for
        sequences whose pending input fully drained this pass (reference
        returns logits the same way; partial prompt chunks stay pending for
        the next put), carrying ``.admission`` with any rejected uids and
        per-uid reasons. Over-budget uids are rejected structurally, not by
        exception — raise only under ``strict=True``. ``drain=False`` runs
        at most ONE scheduler pass + forward (the granularity an external
        serving loop — or a TTFT benchmark — drives the engine at); the
        default drains every pending token before returning.

        With a prefix cache installed, each FRESH uid's prompt is probed at
        admission: matched block-aligned prefix blocks are mapped (shared)
        into its block table, only the novel tail is enqueued, and the
        KV-pressure check prices the request at its novel blocks — chunked
        prefill enters at the first uncached token with positions exact
        (``token_pos`` continues from ``n_cached``)."""
        cfg = self.config
        cached_peek: Dict[int, int] = {}
        if self.prefix_cache is not None:
            for uid, toks in zip(uids, tokens_list):
                if toks and self.seqs.get(uid) is None:
                    pk = self.prefix_cache.peek(toks)
                    if pk:
                        cached_peek[uid] = pk
        admission = self.check_schedule(uids, [len(t) for t in tokens_list],
                                        cached_prefix=cached_peek or None)
        if strict and admission.rejected:
            raise RuntimeError(
                f"cannot schedule batch: {dict(admission.reasons)} "
                f"(strict=True; default is structured rejection)")
        admitted_set = set(admission.admitted)
        enqueued: set = set()
        for uid, toks in zip(uids, tokens_list):
            if uid not in admitted_set or uid in enqueued:
                continue  # duplicate occurrences were rejected, not admitted
            enqueued.add(uid)
            d = self.seqs.get(uid)
            skip = 0
            if d is None:
                d = self.seqs[uid] = SequenceDescriptor(uid=uid)
                if self.prefix_cache is not None and toks:
                    skip = self.map_cached_prefix(uid, toks)
            d.pending.extend(int(t) for t in toks[skip:])
            d.last_logits = None

        out = PutResult()
        out.admission = admission
        while True:
            chunks = schedule_chunks(
                list(self.seqs.values()), self.allocator,
                max_tokens=cfg.max_tokens_per_batch,
                max_sequences=cfg.max_sequences, block_size=cfg.block_size,
                max_context=cfg.max_context,
                max_prefill_fraction=cfg.max_prefill_fraction,
                policy=self.slack_policy)
            if not chunks:
                break
            if self.prefix_cache is not None:
                for d, n in chunks:
                    self._ensure_writable(d, n)
            logits = self._run(chunks)
            self._tick += 1
            served_s = time.perf_counter()  # aging base for slack ordering
            for slot, (d, n) in enumerate(chunks):
                d.last_scheduled = self._tick
                d.last_service_s = served_s
                if self.prefix_cache is not None:
                    d.history.extend(int(t) for t in d.pending[:n])
                del d.pending[:n]
                d.n_cached += n
                if self.prefix_cache is not None:
                    self._commit_prefix(d)
                if not d.pending:
                    d.last_logits = logits[slot]
                    out[d.uid] = d.last_logits
            if not drain:
                break
            if all(not d.pending for d in self.seqs.values()):
                break
        return out

    def _evict_index(self, uids: Sequence[int]) -> int:
        """Victim index under the configured ``eviction_policy``:
        longest_context truncates the sequence closest to done anyway; lru
        sheds whoever the scheduler served least recently; newest backs off
        the latest admit (LIFO — protects old sequences' sunk KV cost);
        slack sheds the sequence with the least SLA slack — it is the most
        likely to miss its deadline anyway, so freeing its blocks preserves
        the goodput of the rest (ties fall back to longest context)."""
        policy = self.config.eviction_policy
        if policy == "lru":
            return min(range(len(uids)),
                       key=lambda i: self.seqs[uids[i]].last_scheduled)
        if policy == "newest":
            return max(range(len(uids)),
                       key=lambda i: self.seqs[uids[i]].last_scheduled)
        if policy == "slack":
            from .scheduler import slack_of

            now = time.perf_counter()
            return min(range(len(uids)),
                       key=lambda i: (slack_of(self.seqs[uids[i]], now),
                                      -self.seqs[uids[i]].n_cached))
        return max(range(len(uids)),
                   key=lambda i: self.seqs[uids[i]].n_cached)

    def ensure_seq(self, uid: int, **fields) -> SequenceDescriptor:
        """Create (or fetch) the descriptor for ``uid`` and set SLA fields
        (deadline_s, rate_sla, tenant, ...) BEFORE any tokens are enqueued —
        the serving layer's hook so the very first scheduler pass already
        orders this sequence by its slack. Unknown fields raise."""
        d = self.seqs.get(uid)
        if d is None:
            d = self.seqs[uid] = SequenceDescriptor(uid=uid)
        for name, value in fields.items():
            if not hasattr(d, name):
                raise AttributeError(
                    f"SequenceDescriptor has no SLA field {name!r}")
            setattr(d, name, value)
        return d

    # ---------------------------------------------------------- prefix cache
    def install_prefix_cache(self, *, scope: str = "tenant",
                             min_block_hits: int = 1,
                             max_pinned_blocks: Optional[int] = None):
        """Build and wire the cross-request prefix cache
        (:class:`~.prefix_cache.PrefixCache`): probes at admission map
        cached block-aligned prompt prefixes into new streams' block
        tables, committed full blocks are indexed, and the allocator's
        pressure valve reclaims cold pins. Idempotent — an installed cache
        is returned as-is (a session re-installing must not drop the
        index)."""
        from .prefix_cache import PrefixCache

        if self.prefix_cache is None:
            self.prefix_cache = PrefixCache(
                self.allocator, self.config.block_size, scope=scope,
                min_block_hits=min_block_hits,
                max_pinned_blocks=max_pinned_blocks)
            self.allocator.reclaim_cb = self.prefix_cache.reclaim
        return self.prefix_cache

    def uninstall_prefix_cache(self) -> None:
        """Tear the prefix cache down: every index pin released back to
        the pool, pressure valve unwired. The cache-off arm of an A/B on
        a shared engine (and tests) — live streams keep their mapped
        blocks (they hold their own references)."""
        if self.prefix_cache is not None:
            self.prefix_cache.invalidate()
            self.allocator.reclaim_cb = None
            self.prefix_cache = None

    def map_cached_prefix(self, uid: int, tokens: Sequence[int],
                          tenant: Optional[str] = None) -> int:
        """Probe the prefix cache for ``tokens``'s block-aligned head and
        map the matched blocks into ``uid``'s (fresh) block table: the
        blocks are retained (shared), ``n_cached``/``cached_prefix_len``
        advance past them, and the caller enqueues only the novel tail —
        chunked prefill enters at the first uncached token. Returns the
        cached token count (0 on miss, no cache, or a non-fresh stream).

        Exactness: positions, sampling and the fused-decode pre-fund all
        derive from ``n_cached``, so a mapped prefix is indistinguishable
        from a prefilled one; the probe always leaves ≥ 1 token novel so
        the stream still runs a forward to produce logits."""
        pc = self.prefix_cache
        if pc is None or not tokens:
            return 0
        d = self.seqs.get(uid)
        if d is not None and (d.n_cached or d.pending or d.blocks):
            return 0  # only a fresh stream can adopt a mapped prefix
        if tenant is None:
            tenant = d.tenant if d is not None else "default"
        blocks, hashes, cached = pc.probe(tokens, tenant)
        if not cached:
            return 0
        if d is None:
            d = self.seqs[uid] = SequenceDescriptor(uid=uid, tenant=tenant)
        self.allocator.retain(blocks)
        d.blocks = list(blocks)
        d.n_cached = cached
        d.cached_prefix_len = cached
        d.history = [int(t) for t in tokens[:cached]]
        d.block_hashes = list(hashes)
        return cached

    def _commit_prefix(self, d: SequenceDescriptor) -> None:
        """Index every newly-FULL block of ``d`` (called after a forward
        advances ``n_cached`` — the block's KV is committed at that
        point). Chain hashes extend the descriptor's running chain so each
        block hashes the entire prefix behind it."""
        from .prefix_cache import chain_hash

        pc = self.prefix_cache
        bs = self.config.block_size
        full = min(len(d.history), d.n_cached) // bs
        while len(d.block_hashes) < full:
            i = len(d.block_hashes)
            prev = d.block_hashes[-1] if d.block_hashes else b""
            h = chain_hash(prev, d.history[i * bs:(i + 1) * bs])
            d.block_hashes.append(h)
            if i < len(d.blocks):
                pc.offer(d.tenant, h, d.blocks[i])

    def _ensure_writable(self, d: SequenceDescriptor, n_new: int) -> None:
        """Copy-on-write guard before ``n_new`` KV appends at
        ``d.n_cached``: any block in the write range still shared
        (refcount > 1) is copied to a fresh block first and the table
        entry repointed. With block-aligned sharing the write frontier
        never sits inside a shared block — full indexed blocks receive no
        writes — so this is defense-in-depth; a triggered copy is counted
        (``Serve/prefix.cow_copies``) and a copy that CANNOT allocate is
        an invariant breach worth a loud failure, not silent corruption
        of another stream's context."""
        if self.prefix_cache is None or n_new < 1 or not d.blocks:
            return
        alloc = self.allocator
        bs = self.config.block_size
        first = d.n_cached // bs
        last = (d.n_cached + n_new - 1) // bs
        for bi in range(first, min(last + 1, len(d.blocks))):
            b = d.blocks[bi]
            if alloc.refcount(b) <= 1:
                continue
            got = alloc.try_allocate(1)
            if got is None:
                raise RuntimeError(
                    f"copy-on-write: no free block to unshare block {b} of "
                    f"uid {d.uid} — block-aligned sharing should never "
                    f"write a shared block (scheduler/prefix-cache bug)")
            if self._copy_block is None:
                from .kv_cache import build_block_copy_fn

                self._copy_block = build_block_copy_fn(bs)
            self.kv = self._copy_block(self.kv, jnp.int32(b),
                                       jnp.int32(got[0]))
            alloc.release([b])
            d.blocks[bi] = got[0]
            self.prefix_cache.note_cow()

    def preempt(self, uid: int) -> Optional[SequenceDescriptor]:
        """Overload-graceful eviction: release ``uid``'s KV blocks and slot
        but RETURN the descriptor (emitted count and SLA budget intact, KV
        state reset) so the serving layer can requeue it for a fresh prefill
        or reject it with partial output — instead of the whole batch
        stalling on an exhausted pool. Shared blocks only lose this
        stream's reference — the prefix index and other streams keep
        theirs (the refcounted-release contract)."""
        d = self.seqs.pop(uid, None)
        if d is None:
            return None
        self.allocator.free(d.blocks)
        d.blocks = []
        d.n_cached = 0
        d.cached_prefix_len = 0
        d.history = []
        d.block_hashes = []
        d.pending.clear()
        d.last_logits = None
        d.last_scheduled = -1
        return d

    def _run(self, chunks) -> jax.Array:
        cfg = self.config
        if all(n == 1 and d.n_cached > 0 for d, n in chunks):
            return self._run_decode(chunks)  # kernel fast path
        batch = build_ragged_batch(
            chunks, cfg.max_tokens_per_batch, cfg.max_sequences,
            cfg.blocks_per_seq,
            atom_q=cfg.atom_q_size if self._use_atoms else None)
        atom_args = ()
        if self._use_atoms:
            atom_args = (jnp.asarray(batch.atom_qidx),
                         jnp.asarray(batch.atom_pos0),
                         jnp.asarray(batch.atom_qlen),
                         jnp.asarray(batch.atom_tables),
                         jnp.asarray(batch.atom_inv))
        logits, self.kv = self._forward(
            self.params, self.kv, jnp.asarray(batch.tokens),
            jnp.asarray(batch.token_seq), jnp.asarray(batch.token_pos),
            jnp.asarray(batch.block_tables), jnp.asarray(batch.last_tok_idx),
            *atom_args)
        self.host_dispatches += 1
        # DEVICE-resident: per-slot rows are sliced on device and only
        # fetched when a caller materializes them (query()/np.asarray) —
        # generate()'s sampler consumes them without a host round trip
        return logits[:len(chunks)]

    def _slot_arrays(self, descs):
        """Per-slot decode metadata padded to max_sequences — the ONE
        assembly both the per-token and fused decode paths ship to device
        (position, block table, live mask per slot)."""
        cfg = self.config
        s_max = cfg.max_sequences
        positions = np.zeros((s_max,), np.int32)
        tables = np.zeros((s_max, cfg.blocks_per_seq), np.int32)
        active = np.zeros((s_max,), bool)
        for slot, d in enumerate(descs):
            positions[slot] = d.n_cached
            tables[slot, :len(d.blocks)] = d.blocks
            active[slot] = True
        return positions, tables, active

    def _run_decode(self, chunks) -> jax.Array:
        """Pure-decode batches (serving's steady state) route through the
        Pallas paged-attention program (``ops/paged_attention``)."""
        from .model import build_decode_forward_fn

        cfg = self.config
        if self._decode_forward is None:
            self._decode_forward = build_decode_forward_fn(
                self.model, cfg.block_size, attn_impl=cfg.decode_attn)
        positions, tables, active = self._slot_arrays(
            [d for d, _n in chunks])
        tokens = np.zeros((cfg.max_sequences,), np.int32)
        for slot, (d, _n) in enumerate(chunks):
            tokens[slot] = d.pending[0]
        logits, self.kv = self._decode_forward(
            self.params, self.kv, jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(tables), jnp.asarray(active))
        self.host_dispatches += 1
        # DEVICE-resident: per-slot rows are sliced on device and only
        # fetched when a caller materializes them (query()/np.asarray) —
        # generate()'s sampler consumes them without a host round trip
        return logits[:len(chunks)]

    def _decode_multi_dispatch(self, running: Dict[int, int],
                               sp: "SamplingParams",
                               eos_token_id: Optional[int],
                               rng: jax.Array,
                               k_cap: Optional[int] = None
                               ) -> Optional[Dict[int, List[int]]]:
        """Steady-state fused decode: up to K tokens per live sequence in ONE
        device dispatch (``model.decode_multi_forward``).

        ``running`` maps each live uid (input fully drained) to its remaining
        new-token budget; it is updated in place, and retired sequences are
        flushed. Returns {uid: emitted tokens} — or ``None`` when the KV pool
        cannot pre-fund ≥2 steps for the worst case, in which case the caller
        falls back to the per-token path (which evicts under pressure).

        K selection walks the compiled ladder {K, K/2, ..., 2} (bounding the
        program cache to log2(K) entries) and picks the smallest rung
        covering the LARGEST number of steps any live sequence can still
        absorb (budget ∧ context headroom) — one dispatch drains the whole
        tail even below full occupancy, where the old fixed-K gate left the
        per-token path paying a host round trip per token
        (``host_dispatches_per_token`` ≈ 0.77 at light load, r05). Overshoot
        is cheap: the device loop exits as soon as every slot retires.
        ``k_cap`` lets a serving layer bound the dispatch (e.g. to the slack
        of a queued request) without forking the ladder.

        KV blocks for the worst-case K appends are allocated up front so the
        block tables are loop-invariant on device; a retiring sequence's
        unused blocks are released by its flush.
        """
        from .model import build_decode_multi_fn

        cfg = self.config
        uids = list(running)
        k = cfg.decode_steps_per_dispatch
        if k_cap is not None:
            cap = max(2, int(k_cap))
            while k > 2 and k > cap:
                k = max(2, k // 2)  # snap DOWN the rung ladder: an
                #   arbitrary cap value must select a compiled program,
                #   never compile a fresh K mid-serve (floor 2: an odd
                #   rung halving to 1 would silently disable fusion)
        absorb = max((min(running[u],
                          max(0, cfg.max_context - self.seqs[u].n_cached))
                      for u in uids), default=0)
        if absorb < 1:
            return None
        # rung ladder {k, ..., 2}: snap to the smallest rung covering the
        # longest tail, then prefer the smallest ALREADY-COMPILED rung —
        # an uncompiled smaller program is never worth a mid-run compile
        # (the larger program early-exits once every slot retires), and a
        # plain-warmup() caller only has K itself compiled
        ladder = [k]
        while ladder[-1] > 2:
            ladder.append(max(2, ladder[-1] // 2))
        i = max((j for j, r in enumerate(ladder) if r >= absorb), default=0)
        while i > 0 and (ladder[i], sp.structure) not in self._decode_multi:
            i -= 1
        k = ladder[i]

        def _wants(k_steps: int) -> List[int]:
            out = []
            for u in uids:
                d = self.seqs[u]
                appends = min(k_steps, running[u],
                              max(0, cfg.max_context - d.n_cached))
                out.append(d.blocks_needed(appends, cfg.block_size))
            return out

        wants = _wants(k)
        while sum(wants) > self.allocator.free_blocks and k > 2:
            k = max(2, k // 2)  # odd K: still try K=2 before giving up
            wants = _wants(k)
        if k < 2 or sum(wants) > self.allocator.free_blocks:
            return None
        for u, w in zip(uids, wants):
            if w:
                got = self.allocator.try_allocate(w)
                if got is None:
                    # pool exhausted under us (injected kv_alloc_fail or
                    # bookkeeping drift): fall back to the per-token path,
                    # which evicts under pressure — blocks already handed
                    # to earlier uids stay owned by their sequences (used
                    # next append or reclaimed by their flush), so no
                    # unwinding is needed and nothing raises mid-serve
                    return None
                self.seqs[u].blocks.extend(got)
        if self.prefix_cache is not None:
            for u in uids:
                d = self.seqs[u]
                self._ensure_writable(
                    d, min(k, running[u],
                           max(0, cfg.max_context - d.n_cached)))

        key = (k, sp.structure)
        fn = self._decode_multi.get(key)
        if fn is None:
            fn = self._decode_multi[key] = build_decode_multi_fn(
                self.model, cfg.block_size, k, sp.structure,
                cfg.max_context, attn_impl=cfg.decode_attn)
            while len(self._decode_multi) > self._decode_multi_cap:
                self._decode_multi.popitem(last=False)
        else:
            self._decode_multi.move_to_end(key)
        s_max = cfg.max_sequences
        n = len(uids)
        positions, tables, active = self._slot_arrays(
            [self.seqs[u] for u in uids])
        steps_left = np.zeros((s_max,), np.int32)
        steps_left[:n] = [running[u] for u in uids]
        stacked = jnp.stack([self.seqs[u].last_logits for u in uids])
        logits0 = jnp.zeros((s_max, stacked.shape[-1]),
                            jnp.float32).at[:n].set(stacked)

        toks_d, logits_f, pos_f, act_f, sl_f, self.kv = fn(
            self.params, self.kv, logits0, jnp.asarray(positions),
            jnp.asarray(tables), jnp.asarray(active),
            jnp.asarray(steps_left), rng,
            jnp.float32(sp.temperature), jnp.float32(sp.top_p),
            jnp.int32(-1 if eos_token_id is None else eos_token_id))
        self.host_dispatches += 1
        self._tick += k
        # ONE host transfer for the K×S token block + the small state rows
        toks = np.asarray(toks_d)
        pos_h = np.asarray(pos_f)
        act_h = np.asarray(act_f)
        sl_h = np.asarray(sl_f)
        emitted: Dict[int, List[int]] = {}
        served_s = time.perf_counter()
        for i, u in enumerate(uids):
            d = self.seqs[u]
            emitted[u] = [int(t) for t in toks[:, i] if t >= 0]
            d.n_cached = int(pos_h[i])
            d.last_scheduled = self._tick
            d.last_service_s = served_s
            d.emitted += len(emitted[u])
            if self.prefix_cache is not None:
                # committed tokens this dispatch = sampled tokens appended
                # to KV; clamp to n_cached (an early-retiring slot appends
                # nothing past its final position)
                d.history.extend(emitted[u])
                del d.history[d.n_cached:]
                self._commit_prefix(d)
            if act_h[i]:
                running[u] = int(sl_h[i])
                d.last_logits = logits_f[i]
            else:
                del running[u]
                self.flush([u])
        return emitted

    # ------------------------------------------------------------ query/flush
    def query(self, uid: int) -> Optional[jax.Array]:
        """Last-token logits if the uid's input has drained (reference
        ``query:153``). DEVICE-resident (a jax array): ``np.asarray`` it to
        materialize on host; device consumers (samplers) use it without a
        host round trip."""
        d = self.seqs.get(uid)
        return None if d is None else d.last_logits

    def flush(self, uids: Sequence[int]) -> None:
        """Release sequences and their KV blocks (reference ``flush:228``)."""
        for uid in uids:
            d = self.seqs.pop(uid, None)
            if d is not None:
                self.allocator.free(d.blocks)

    # --------------------------------------------------------------- generate
    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 rng: Optional[jax.Array] = None) -> List[List[int]]:
        """Continuous-batching loop (the MII role above the reference engine).

        Each iteration issues ONE fused put: every drained sequence's next
        decode token plus as many waiting prompts as FIFO admission allows —
        the SplitFuse fusion the scheduler is built for. Sequences retire on
        EOS, length, or the context cap (truncation, not failure); under KV
        pressure the longest-context sequence is evicted so decode always
        progresses.
        """
        cfg = self.config
        sp = SamplingParams(do_sample, float(temperature), int(top_k),
                            float(top_p))
        if rng is None:
            self._rng, rng = jax.random.split(self._rng)
        for p in prompts:
            if len(p) > cfg.max_context:
                raise ValueError(f"prompt of {len(p)} tokens can never fit "
                                 f"max_context {cfg.max_context}")
        results: Dict[int, List[int]] = {i: [] for i in range(len(prompts))}
        waiting = [(i, list(p)) for i, p in enumerate(prompts) if p]
        running: Dict[int, int] = {}  # uid -> remaining new-token budget
        uid_base = 1 << 20  # avoid colliding with caller uids in shared engines

        while waiting or running:
            # 0. steady state — every live sequence decoding and nothing
            # admissible from the backlog (queue empty, or its head can't be
            # admitted anyway — engine saturated): fuse up to K decode steps
            # into one device dispatch (sample + paged-KV append + position
            # advance all on device); fall through to the per-token path on
            # KV pressure (it evicts) or mixed state
            backlog_stuck = bool(waiting) and not self.can_schedule(
                [uid_base + waiting[0][0]], [len(waiting[0][1])])
            if (cfg.decode_steps_per_dispatch > 1 and running
                    and (not waiting or backlog_stuck)
                    and all(self.query(u) is not None for u in running)):
                rng, sub = jax.random.split(rng)
                emitted = self._decode_multi_dispatch(running, sp,
                                                      eos_token_id, sub)
                if emitted is not None:
                    for uid, toks in emitted.items():
                        results[uid - uid_base].extend(toks)
                    continue
            # 1. one batched sample over every drained sequence
            put_uids: List[int] = []
            put_toks: List[List[int]] = []
            drained = [(u, self.query(u)) for u in list(running)]
            drained = [(u, lg) for u, lg in drained if lg is not None]
            if drained:
                rng, sub = jax.random.split(rng)
                # logits are device-resident: stack + sample stay on device;
                # only the sampled token ids (one int per sequence) cross to
                # the host — not 2×V floats per sequence per step
                toks = np.asarray(self._sample_fn(
                    jnp.stack([lg for _, lg in drained]), sub,
                    jnp.float32(sp.temperature), jnp.float32(sp.top_p),
                    sp.structure))
                self.host_dispatches += 1  # the sampler is a dispatch too
                for (uid, _), tok in zip(drained, toks):
                    tok = int(tok)
                    results[uid - uid_base].append(tok)
                    running[uid] -= 1
                    done = (running[uid] <= 0
                            or (eos_token_id is not None and tok == eos_token_id)
                            or self.seqs[uid].n_cached >= cfg.max_context)
                    if done:  # context-capped seqs truncate, not crash
                        del running[uid]
                        self.flush([uid])
                    else:
                        put_uids.append(uid)
                        put_toks.append([tok])
            # 2. KV pressure: evict per the configured policy until the rest
            # fit (reference-scale serving needs more than longest-evict —
            # VERDICT r3 weak #6)
            while put_uids and not self.can_schedule(put_uids,
                                                     [1] * len(put_uids)):
                k = self._evict_index(put_uids)
                uid = put_uids.pop(k)
                put_toks.pop(k)
                del running[uid]
                self.flush([uid])
            # 3. FIFO admission, fused into the SAME put as the decode tokens
            while waiting:
                idx, ptoks = waiting[0]
                cand_u = put_uids + [uid_base + idx]
                cand_t = put_toks + [ptoks]
                if not self.can_schedule(cand_u, [len(t) for t in cand_t]):
                    break
                waiting.pop(0)
                put_uids, put_toks = cand_u, cand_t
                running[uid_base + idx] = max_new_tokens
            if not put_uids:
                if not running and waiting:
                    raise RuntimeError(
                        "nothing schedulable on an empty engine — prompts "
                        "exceed KV pool limits; raise num_blocks/max_context")
                continue
            self.put(put_uids, put_toks)
        return [results[i] for i in range(len(prompts))]
