from .quantize import (QuantConfig, dequantize_int8, fake_quant,  # noqa: F401
                       quantize_int8)
from .compress import (apply_layer_reduction, compress,  # noqa: F401
                       get_compression_config)
from .qat import QATScheduler, apply_qat, parse_qat_config  # noqa: F401
