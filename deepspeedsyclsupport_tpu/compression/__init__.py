from .quantize import (QuantConfig, dequantize_int8, fake_quant,  # noqa: F401
                       quantize_int8)
from .compress import compress, get_compression_config  # noqa: F401
