"""Quantization primitives: QAT fake-quant + int8 pack/unpack.

Analogs of the reference's quantization stack:

* training fake-quant (``deepspeed/compression/basic_layer.py`` QuantAct/
  Embedding/LinearLayer_Compress; kernels ``csrc/quantization/fake_quantizer.cu``)
  → :func:`fake_quant` with a straight-through estimator, pure jnp (XLA fuses
  the round-trip into the surrounding ops — the fusion the CUDA kernel exists
  to provide).
* int8 symmetric blockwise (de)quantize (``csrc/quantization/quantize.cu`` /
  ``dequantize.cu``) → :func:`quantize_int8` / :func:`dequantize_int8`, the
  building block the quantized collectives (ZeRO++ qwZ/qgZ analogs,
  ``comm/quantized.py``) ride on.
"""
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclass
class QuantConfig:
    bits: int = 8
    symmetric: bool = True
    group_size: int = -1  # -1: per-tensor; else blockwise along last dim


def fake_quant(x: jnp.ndarray, bits: int = 8, symmetric: bool = True
               ) -> jnp.ndarray:
    """Quantize-dequantize with straight-through gradients (QAT)."""
    q, scale, zero = _affine_params(x, bits, symmetric)
    y = (q - zero) * scale
    # STE: forward quantized value, backward identity
    return x + jax.lax.stop_gradient(y - x)


def _affine_params(x, bits: int, symmetric: bool):
    levels = 2 ** bits
    if symmetric:
        amax = jnp.max(jnp.abs(x)) + 1e-12
        scale = amax / (levels / 2 - 1)
        q = jnp.clip(jnp.round(x / scale), -(levels // 2 - 1), levels // 2 - 1)
        return q, scale, 0.0
    lo, hi = jnp.min(x), jnp.max(x)
    scale = (hi - lo + 1e-12) / (levels - 1)
    zero = jnp.round(-lo / scale)
    q = jnp.clip(jnp.round(x / scale) + zero, 0, levels - 1)
    return q, scale, zero


def quantize_int8(x: jnp.ndarray, group_size: int = -1
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8: returns (q int8, scales fp32). Blockwise over the last
    dim when ``group_size > 0`` (the layout comm quantization needs: one scale
    per ICI transfer chunk, reference ``swizzled_quantize.cu``)."""
    if group_size and group_size > 0:
        shape = x.shape
        assert shape[-1] % group_size == 0, (shape, group_size)
        xg = x.reshape(*shape[:-1], shape[-1] // group_size, group_size)
        amax = jnp.max(jnp.abs(xg), axis=-1, keepdims=True) + 1e-12
        scale = (amax / 127.0).astype(jnp.float32)
        q = jnp.clip(jnp.round(xg / scale), -127, 127).astype(jnp.int8)
        return q.reshape(shape), scale.squeeze(-1)
    amax = jnp.max(jnp.abs(x)) + 1e-12
    scale = (amax / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jnp.ndarray, scale: jnp.ndarray, group_size: int = -1,
                    dtype=jnp.float32) -> jnp.ndarray:
    if group_size and group_size > 0:
        shape = q.shape
        qg = q.reshape(*shape[:-1], shape[-1] // group_size, group_size)
        out = qg.astype(jnp.float32) * scale[..., None]
        return out.reshape(shape).astype(dtype)
    return (q.astype(jnp.float32) * scale).astype(dtype)
