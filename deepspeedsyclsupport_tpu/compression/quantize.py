"""Quantization primitives: QAT fake-quant + int8 pack/unpack.

Analogs of the reference's quantization stack:

* training fake-quant (``deepspeed/compression/basic_layer.py`` QuantAct/
  Embedding/LinearLayer_Compress; kernels ``csrc/quantization/fake_quantizer.cu``)
  → :func:`fake_quant` with a straight-through estimator, pure jnp (XLA fuses
  the round-trip into the surrounding ops — the fusion the CUDA kernel exists
  to provide).
* int8 symmetric blockwise (de)quantize (``csrc/quantization/quantize.cu`` /
  ``dequantize.cu``) → :func:`quantize_int8` / :func:`dequantize_int8`, the
  building block the quantized collectives (ZeRO++ qwZ/qgZ analogs,
  ``comm/quantized.py``) ride on.
"""
from dataclasses import dataclass
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclass
class QuantConfig:
    bits: int = 8
    symmetric: bool = True
    group_size: int = -1  # -1: per-tensor; else blockwise along last dim


def fake_quant(x: jnp.ndarray, bits: int = 8, symmetric: bool = True
               ) -> jnp.ndarray:
    """Quantize-dequantize with straight-through gradients (QAT)."""
    q, scale, zero = _affine_params(x, bits, symmetric)
    y = (q - zero) * scale
    # STE: forward quantized value, backward identity
    return x + jax.lax.stop_gradient(y - x)


def _affine_params(x, bits: int, symmetric: bool):
    levels = 2 ** bits
    if symmetric:
        amax = jnp.max(jnp.abs(x)) + 1e-12
        scale = amax / (levels / 2 - 1)
        q = jnp.clip(jnp.round(x / scale), -(levels // 2 - 1), levels // 2 - 1)
        return q, scale, 0.0
    lo, hi = jnp.min(x), jnp.max(x)
    scale = (hi - lo + 1e-12) / (levels - 1)
    zero = jnp.round(-lo / scale)
    q = jnp.clip(jnp.round(x / scale) + zero, 0, levels - 1)
    return q, scale, zero


def quantize_int8(x: jnp.ndarray, group_size: int = -1
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8: returns (q int8, scales fp32). Blockwise over the last
    dim when ``group_size > 0`` (the layout comm quantization needs: one scale
    per ICI transfer chunk, reference ``swizzled_quantize.cu``)."""
    if group_size and group_size > 0:
        shape = x.shape
        assert shape[-1] % group_size == 0, (shape, group_size)
        xg = x.reshape(*shape[:-1], shape[-1] // group_size, group_size)
        amax = jnp.max(jnp.abs(xg), axis=-1, keepdims=True) + 1e-12
        scale = (amax / 127.0).astype(jnp.float32)
        q = jnp.clip(jnp.round(xg / scale), -127, 127).astype(jnp.int8)
        return q.reshape(shape), scale.squeeze(-1)
    amax = jnp.max(jnp.abs(x)) + 1e-12
    scale = (amax / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jnp.ndarray, scale: jnp.ndarray, group_size: int = -1,
                    dtype=jnp.float32) -> jnp.ndarray:
    if group_size and group_size > 0:
        shape = q.shape
        qg = q.reshape(*shape[:-1], shape[-1] // group_size, group_size)
        out = qg.astype(jnp.float32) * scale[..., None]
        return out.reshape(shape).astype(dtype)
    return (q.astype(jnp.float32) * scale).astype(dtype)


def quantize_int4(x: jnp.ndarray, group_size: int = -1):
    """Symmetric int4 packed two-per-byte (reference
    ``csrc/quantization/quantize_intX.cu``): values in [-7, 7], biased to
    nibbles, low nibble = even element. Last dim must be even. Returns
    (packed uint8 [..., n/2], scales fp32)."""
    n = x.shape[-1]
    if n % 2:
        raise ValueError(f"int4 packing needs an even last dim, got {n}")
    if group_size and group_size > 0:
        shape = x.shape
        assert shape[-1] % group_size == 0, (shape, group_size)
        xg = x.reshape(*shape[:-1], shape[-1] // group_size, group_size)
        amax = jnp.max(jnp.abs(xg), axis=-1, keepdims=True) + 1e-12
        scale = (amax / 7.0).astype(jnp.float32)
        q = jnp.clip(jnp.round(xg / scale), -7, 7).astype(jnp.int32)
        q = q.reshape(shape)
        scale = scale.squeeze(-1)
    else:
        amax = jnp.max(jnp.abs(x)) + 1e-12
        scale = (amax / 7.0).astype(jnp.float32)
        q = jnp.clip(jnp.round(x / scale), -7, 7).astype(jnp.int32)
    nib = (q + 8).astype(jnp.uint8)             # 1..15
    lo, hi = nib[..., 0::2], nib[..., 1::2]
    return (lo | (hi << 4)).astype(jnp.uint8), scale


def dequantize_int4(packed: jnp.ndarray, scale: jnp.ndarray,
                    group_size: int = -1, dtype=jnp.float32) -> jnp.ndarray:
    b = packed.astype(jnp.int32)
    lo = (b & 0xF) - 8
    hi = ((b >> 4) & 0xF) - 8
    q = jnp.stack([lo, hi], axis=-1).reshape(*packed.shape[:-1],
                                             packed.shape[-1] * 2)
    if group_size and group_size > 0:
        shape = q.shape
        qg = q.reshape(*shape[:-1], shape[-1] // group_size, group_size)
        out = qg.astype(jnp.float32) * scale[..., None]
        return out.reshape(shape).astype(dtype)
    return (q.astype(jnp.float32) * scale).astype(dtype)


@jax.tree_util.register_pytree_node_class
class QuantTensor:
    """int8 weight + blockwise fp32 scales, as ONE pytree node.

    The ZeRO-Inference weight format (reference
    ``deepspeed/inference/quantization/``: quantized parameters living in the
    module until the moment of use). Because it is a pytree node whose
    children are the two arrays, a stacked ``[L, ...]`` quantized leaf
    threads through ``lax.scan`` like any other — each layer's slice arrives
    as a ``QuantTensor`` and is dequantized *inside* the scan body, so at
    most one layer's weights exist dequantized at a time.
    """

    def __init__(self, q, scale, group_size: int, bits: int = 8):
        self.q = q
        self.scale = scale
        self.group_size = int(group_size)
        self.bits = int(bits)

    @property
    def shape(self):
        if self.bits == 4:  # packed two-per-byte on the last dim
            return self.q.shape[:-1] + (self.q.shape[-1] * 2,)
        return self.q.shape

    def dequantize(self, dtype=jnp.bfloat16):
        if self.bits == 4:
            return dequantize_int4(self.q, self.scale,
                                   group_size=self.group_size, dtype=dtype)
        return dequantize_int8(self.q, self.scale,
                               group_size=self.group_size, dtype=dtype)

    def tree_flatten(self):
        return (self.q, self.scale), (self.group_size, self.bits)

    @classmethod
    def tree_unflatten(cls, aux, children):
        group_size, bits = aux if isinstance(aux, tuple) else (aux, 8)
        return cls(children[0], children[1], group_size, bits)

    def __repr__(self):
        return (f"QuantTensor(q={self.q.shape}, scale={self.scale.shape}, "
                f"group={self.group_size})")


def quantize_leaf(x, group_size: int = 64, bits: int = 8) -> "QuantTensor":
    """Blockwise int8 quantization of one weight (last-dim groups; one scale
    per row when the last dim doesn't divide — the scale must keep the
    leading dims so stacked [L, ...] leaves stay scan-sliceable)."""
    x = jnp.asarray(x)
    gs = group_size if (group_size > 0 and x.ndim
                        and x.shape[-1] % group_size == 0) else x.shape[-1]
    if bits == 4 and x.shape[-1] % 2 == 0 and gs % 2 == 0:
        q, scale = quantize_int4(x.astype(jnp.float32), group_size=gs)
        return QuantTensor(q, scale, gs, bits=4)
    q, scale = quantize_int8(x.astype(jnp.float32), group_size=gs)
    return QuantTensor(q, scale, gs)


def dequantize_tree(tree, dtype=jnp.bfloat16):
    """Materialize any ``QuantTensor`` leaves (no-op for plain trees)."""
    return jax.tree_util.tree_map(
        lambda x: x.dequantize(dtype) if isinstance(x, QuantTensor) else x,
        tree, is_leaf=lambda x: isinstance(x, QuantTensor))


def quantize_tree(tree, group_size: int = 64, min_size: int = 4096,
                  stacked: bool = False, bits: int = 8):
    """Quantize matrix-shaped floating leaves with ``>= min_size`` elements.

    Small or 1-D leaves — norm scales, biases — stay full precision, like
    the reference keeps non-GEMM weights fp. ``stacked=True`` treats the
    leading dim as the scan layer axis: both the size threshold and the
    matrix-rank test apply per layer, so a stacked ``[L, hidden]`` norm
    scale is (correctly) left alone.
    """
    import numpy as _np

    def maybe(x):
        if isinstance(x, QuantTensor):
            return x
        shape = _np.shape(x)
        body = shape[1:] if (stacked and len(shape) > 1) else shape
        if (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
                and len(body) >= 2 and _np.prod(body) >= min_size):
            return quantize_leaf(x, group_size, bits=bits)
        return x

    return jax.tree_util.tree_map(maybe, tree)
