"""Config-driven model compression.

Analog of ``deepspeed/compression/compress.py`` (``init_compression`` /
``redundancy_clean``): a ``compression_training`` config section selects
techniques applied to matching parameter groups. The reference rewrites torch
modules in place; here compression is a pure tree→tree transform over the
params pytree, matched by leaf path (the same module-name globbing semantics).

Supported (round 1): ``weight_quantization`` (post-training, via
``quantize.fake_quant``) and ``sparse_pruning`` (magnitude). Structured head/
row pruning and layer reduction are config-validated but deferred.
"""
import fnmatch
from typing import Any, Dict

import jax
import jax.numpy as jnp

from .quantize import fake_quant
from ..utils.logging import logger


def get_compression_config(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Extract + default the ``compression_training`` section (reference
    ``deepspeed/compression/config.py``)."""
    c = dict(cfg.get("compression_training", {}))
    out = {}
    wq = dict(c.get("weight_quantization", {}))
    if wq:
        shared = dict(wq.get("shared_parameters", {}))
        out["weight_quantization"] = {
            "enabled": bool(shared.get("enabled", True)),
            "groups": [  # per-group settings, like the reference
                {"bits": int(dict(g.get("params", {})).get("target_bits", 8)),
                 "modules": list(g.get("modules", ["*"]))}
                for g in map(dict,
                             dict(wq.get("different_groups", {})).values())
            ] or [{"bits": 8, "modules": ["*"]}],
        }
    sp = dict(c.get("sparse_pruning", {}))
    if sp:
        shared = dict(sp.get("shared_parameters", {}))
        out["sparse_pruning"] = {
            "enabled": bool(shared.get("enabled", True)),
            "groups": [
                {"density": float(dict(g.get("params", {})).get(
                    "dense_ratio", 0.5)),
                 "modules": list(g.get("modules", ["*"]))}
                for g in map(dict,
                             dict(sp.get("different_groups", {})).values())
            ] or [{"density": 0.5, "modules": ["*"]}],
        }
    for k in ("row_pruning", "head_pruning", "channel_pruning",
              "layer_reduction"):
        if c.get(k, {}) and dict(c[k]).get("shared_parameters",
                                           {}).get("enabled", False):
            logger.warning("compression technique %r not yet implemented on "
                           "TPU build; ignored", k)
    return out


def _modules(section, default):
    mods = []
    for g in dict(section.get("different_groups", {})).values():
        mods.extend(dict(g).get("modules", []))
    return mods or default


def compress(params: Any, config: Dict[str, Any]) -> Any:
    """Apply configured compression to matching leaves; returns a new tree
    (reference ``init_compression`` + ``redundancy_clean`` collapsed: no module
    surgery, just math on leaves)."""
    cc = get_compression_config(config)
    if not cc:
        return params

    def visit(path, leaf):
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(leaf.dtype,
                                                            jnp.floating):
            return leaf
        if leaf.ndim < 2:
            return leaf  # norms/biases stay exact, like the reference
        name = "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                        for k in path)
        wq = cc.get("weight_quantization")
        if wq and wq["enabled"]:
            for g in wq["groups"]:  # first matching group wins
                if _match(name, g["modules"]):
                    leaf = fake_quant(leaf, bits=g["bits"])
                    break
        sp = cc.get("sparse_pruning")
        if sp and sp["enabled"]:
            for g in sp["groups"]:
                if _match(name, g["modules"]):
                    k = max(1, int(leaf.size * g["density"]))
                    thresh = jnp.sort(jnp.abs(leaf).ravel())[-k]
                    leaf = jnp.where(jnp.abs(leaf) >= thresh, leaf,
                                     jnp.zeros_like(leaf))
                    break
        return leaf

    return jax.tree_util.tree_map_with_path(visit, params)


def _match(name: str, patterns) -> bool:
    return any(fnmatch.fnmatch(name, p) or p in name for p in patterns)
