"""Config-driven model compression.

Analog of ``deepspeed/compression/compress.py`` (``init_compression`` /
``redundancy_clean``): a ``compression_training`` config section selects
techniques applied to matching parameter groups. The reference rewrites torch
modules in place; here compression is a pure tree→tree transform over the
params pytree, matched by leaf path (the same module-name globbing semantics).

Techniques: ``weight_quantization`` (post-training, via
``quantize.fake_quant``), ``sparse_pruning`` (unstructured magnitude),
``row_pruning`` / ``channel_pruning`` (structured output/input-dim masking,
reference ``basic_layer.LinearLayer_Compress`` row/channel masks),
``head_pruning`` (whole attention heads by output-projection importance,
reference head-mask path), and ``layer_reduction`` (student keeps a chosen
subset of teacher layers — shape-CHANGING, see :func:`apply_layer_reduction`).

Orientation note: torch ``nn.Linear`` stores ``[out, in]``; our einsums
contract ``[in, out]``. The reference's "row pruning" removes OUTPUT rows,
which here is the LAST axis; "channel pruning" removes input channels — our
second-to-last axis.
"""
import fnmatch
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .quantize import fake_quant
from ..utils.logging import log_dist, logger


def _groups(section: Dict, param_key: str, default, cast) -> List[Dict]:
    out = []
    for g in map(dict, dict(section.get("different_groups", {})).values()):
        p = dict(g.get("params", {}))
        out.append({param_key: cast(p.get(param_key, default)),
                    "modules": list(g.get("modules", ["*"]))})
    return out or [{param_key: default, "modules": ["*"]}]


def get_compression_config(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Extract + default the ``compression_training`` section (reference
    ``deepspeed/compression/config.py``)."""
    c = dict(cfg.get("compression_training", {}))
    out: Dict[str, Any] = {}
    wq = dict(c.get("weight_quantization", {}))
    if wq:
        shared = dict(wq.get("shared_parameters", {}))
        out["weight_quantization"] = {
            "enabled": bool(shared.get("enabled", True)),
            "groups": [
                {"bits": int(dict(g.get("params", {})).get("target_bits", 8)),
                 "modules": list(g.get("modules", ["*"]))}
                for g in map(dict,
                             dict(wq.get("different_groups", {})).values())
            ] or [{"bits": 8, "modules": ["*"]}],
        }
    sp = dict(c.get("sparse_pruning", {}))
    if sp:
        shared = dict(sp.get("shared_parameters", {}))
        out["sparse_pruning"] = {
            "enabled": bool(shared.get("enabled", True)),
            "groups": _groups(sp, "dense_ratio", 0.5, float),
        }
    rp = dict(c.get("row_pruning", {}))
    if rp:
        out["row_pruning"] = {
            "enabled": bool(dict(rp.get("shared_parameters",
                                        {})).get("enabled", True)),
            "groups": _groups(rp, "dense_ratio", 0.5, float),
        }
    cp = dict(c.get("channel_pruning", {}))
    if cp:
        out["channel_pruning"] = {
            "enabled": bool(dict(cp.get("shared_parameters",
                                        {})).get("enabled", True)),
            "groups": _groups(cp, "dense_ratio", 0.5, float),
        }
    hp = dict(c.get("head_pruning", {}))
    if hp:
        shared = dict(hp.get("shared_parameters", {}))
        out["head_pruning"] = {
            "enabled": bool(shared.get("enabled", True)),
            "num_heads": int(shared.get("num_heads", 0)),
            "groups": _groups(hp, "dense_ratio", 0.5, float),
        }
        if out["head_pruning"]["enabled"] and not out["head_pruning"]["num_heads"]:
            raise ValueError("head_pruning needs shared_parameters.num_heads "
                             "(the reference requires it too)")
    lr = dict(c.get("layer_reduction", {}))
    if lr and bool(lr.get("enabled", False)):
        out["layer_reduction"] = {
            "enabled": True,
            "keep_number_layer": lr.get("keep_number_layer"),
            "teacher_layer": list(lr.get("teacher_layer", [])),
        }
    return out


def _topk_mask(scores: jnp.ndarray, density: float) -> jnp.ndarray:
    """Boolean keep-mask over the last axis of ``scores`` (top-k by value)."""
    n = scores.shape[-1]
    k = max(1, int(round(n * density)))
    thresh = jnp.sort(scores, axis=-1)[..., -k][..., None]
    return scores >= thresh


def compress(params: Any, config: Dict[str, Any]) -> Any:
    """Apply configured shape-PRESERVING compression to matching leaves;
    returns a new tree (reference ``init_compression``: masks, not surgery —
    the shape-changing ``layer_reduction`` lives in
    :func:`apply_layer_reduction`)."""
    cc = get_compression_config(config)
    if not cc:
        return params
    if cc.get("layer_reduction", {}).get("enabled"):
        logger.warning(
            "layer_reduction is enabled but compress() is shape-preserving "
            "— call compression.apply_layer_reduction(model_config, "
            "params, config) to build the student")

    # Head pruning derives ONE per-module keep mask from the attention
    # OUTPUT projection (reference: the head mask lives on the output
    # matrix) and applies it to wq/wk/wv/wo alike — per-matrix masks would
    # keep disjoint head sets and zero the whole attention output.
    head_masks: Dict[str, Any] = {}
    hp = cc.get("head_pruning")
    if hp and hp["enabled"]:
        for kp, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            name = "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                            for k in kp)
            if not name.endswith("wo") or getattr(leaf, "ndim", 0) < 2:
                continue
            for g in hp["groups"]:
                if _match(name, g["modules"]):
                    mask = _head_keep_mask(leaf, hp["num_heads"],
                                           g["dense_ratio"])
                    if mask is not None:
                        head_masks[name.rsplit("/", 1)[0]] = mask
                    break

    def visit(path, leaf):
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(leaf.dtype,
                                                            jnp.floating):
            return leaf
        if leaf.ndim < 2:
            return leaf  # norms/biases stay exact, like the reference
        name = "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                        for k in path)
        wq = cc.get("weight_quantization")
        if wq and wq["enabled"]:
            for g in wq["groups"]:  # first matching group wins
                if _match(name, g["modules"]):
                    leaf = fake_quant(leaf, bits=g["bits"])
                    break
        sp = cc.get("sparse_pruning")
        if sp and sp["enabled"]:
            for g in sp["groups"]:
                if _match(name, g["modules"]):
                    k = max(1, int(leaf.size * g["dense_ratio"]))
                    thresh = jnp.sort(jnp.abs(leaf).ravel())[-k]
                    leaf = jnp.where(jnp.abs(leaf) >= thresh, leaf,
                                     jnp.zeros_like(leaf))
                    break
        rp = cc.get("row_pruning")
        if rp and rp["enabled"]:
            for g in rp["groups"]:
                if _match(name, g["modules"]):
                    # output-dim (last axis) structured mask by L1 norm
                    imp = jnp.abs(leaf).sum(axis=-2)
                    keep = _topk_mask(imp, g["dense_ratio"])
                    leaf = leaf * keep[..., None, :].astype(leaf.dtype)
                    break
        cp = cc.get("channel_pruning")
        if cp and cp["enabled"]:
            for g in cp["groups"]:
                if _match(name, g["modules"]):
                    # input-dim (second-to-last axis) structured mask
                    imp = jnp.abs(leaf).sum(axis=-1)
                    keep = _topk_mask(imp, g["dense_ratio"])
                    leaf = leaf * keep[..., :, None].astype(leaf.dtype)
                    break
        if head_masks:
            parent, _, suffix = name.rpartition("/")
            mask = head_masks.get(parent)
            if mask is not None and suffix in ("wq", "wk", "wv", "wo"):
                leaf = _apply_head_mask(
                    name, leaf, mask, hp["num_heads"],
                    axis=-2 if suffix == "wo" else -1)
        return leaf

    return jax.tree_util.tree_map_with_path(visit, params)


def _head_keep_mask(wo: jnp.ndarray, num_heads: int,
                    density: float) -> Optional[jnp.ndarray]:
    """Per-layer keep mask from the output projection ``[..., H·hd, d]``:
    stacked leaves ``[L, H·hd, d]`` give an independent ``[L, H]`` mask per
    layer (a head can matter in layer 0 and be dead in layer 11)."""
    h_dim = wo.shape[-2]
    if h_dim % num_heads:
        logger.warning("head_pruning: wo dim %d not divisible by num_heads "
                       "%d; module skipped", h_dim, num_heads)
        return None
    hd = h_dim // num_heads
    shaped = wo.reshape(wo.shape[:-2] + (num_heads, hd, wo.shape[-1]))
    imp = jnp.abs(shaped).sum(axis=(-2, -1))     # [..., H]
    return _topk_mask(imp, density)


def _apply_head_mask(name: str, leaf: jnp.ndarray, keep: jnp.ndarray,
                     num_heads: int, axis: int) -> jnp.ndarray:
    """Zero the pruned heads' slices along ``axis`` (head-major blocks)."""
    h_dim = leaf.shape[axis]
    if h_dim % num_heads:
        # GQA k/v projections have fewer kv heads than the q mask covers —
        # the wo mask already zeroes those heads' contribution
        logger.warning("head_pruning: %s dim %d not divisible by num_heads "
                       "%d; left unmasked (wo mask still silences the "
                       "pruned heads)", name, h_dim, num_heads)
        return leaf
    hd = h_dim // num_heads
    moved = jnp.moveaxis(leaf, axis, -1)
    shaped = moved.reshape(moved.shape[:-1] + (num_heads, hd))
    if keep.ndim == 2:        # stacked per-layer mask [L, H]
        k = keep.reshape((keep.shape[0],)
                         + (1,) * (shaped.ndim - 3)
                         + (num_heads, 1))
    else:
        k = keep.reshape((1,) * (shaped.ndim - 2) + (num_heads, 1))
    shaped = shaped * k.astype(leaf.dtype)
    return jnp.moveaxis(shaped.reshape(moved.shape), -1, axis)


def apply_layer_reduction(model_config, params: Any,
                          config: Dict[str, Any]) -> Tuple[Any, Any]:
    """Layer reduction (reference ``compression/compress.py``
    ``student_initialization``): the student keeps ``teacher_layer``'s
    layers (or the first ``keep_number_layer``), initialized from the
    teacher — a shape-CHANGING transform, so it returns
    ``(new_model_config, new_params)`` instead of masking in place.

    Works on the stacked-layer layout (``params['layers']`` leaves lead
    with the layer dim).
    """
    cc = get_compression_config(config).get("layer_reduction")
    if not cc or not cc["enabled"]:
        return model_config, params
    n_layers = model_config.num_layers
    keep = cc["teacher_layer"] or list(range(cc["keep_number_layer"] or
                                             n_layers))
    if cc["keep_number_layer"] and len(keep) != cc["keep_number_layer"]:
        raise ValueError(
            f"teacher_layer {keep} inconsistent with keep_number_layer "
            f"{cc['keep_number_layer']}")
    bad = [i for i in keep if not 0 <= i < n_layers]
    if bad:
        raise ValueError(f"teacher_layer indices {bad} out of range for "
                         f"{n_layers} layers")
    new_params = dict(params)
    if isinstance(params["layers"], (list, tuple)):
        # per-layer list layout (scan_layers=False): select entries
        new_params["layers"] = [params["layers"][i] for i in keep]
    else:
        idx = jnp.asarray(keep, jnp.int32)
        new_params["layers"] = jax.tree_util.tree_map(
            lambda leaf: jnp.take(leaf, idx, axis=0), params["layers"])
    import dataclasses

    new_cfg = dataclasses.replace(model_config, num_layers=len(keep))
    log_dist(f"layer_reduction: student keeps teacher layers {keep} "
             f"({n_layers} → {len(keep)})")
    return new_cfg, new_params


def _match(name: str, patterns) -> bool:
    return any(fnmatch.fnmatch(name, p) or p in name for p in patterns)
