"""Quantization-aware training with a progressive bit schedule.

Analog of the reference's training-time quantizer
(``deepspeed/runtime/quantize.py:14`` ``Quantizer`` + the
``compression_training.weight_quantization`` config surface,
``compression/constants.py``): weights train against their quantized
values, and precision anneals — starting at ``start_bits``, dropping one
bit each time the (doubling) quantization period elapses until
``target_bits`` (``compute_quantization``, ``runtime/quantize.py:129``).

The torch reference mutates the fp16 weight copies between steps; here the
fp32 master stays exact and the per-forward COMPUTE copy is fake-quantized
with straight-through gradients (``quantize.fake_quant``) — the same
training dynamics, no weight mutation. Bit changes are trace-time
constants: each drop recompiles the step once (the random-LTD pattern).
"""
import fnmatch
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .quantize import fake_quant
from ..utils.logging import log_dist

__all__ = ["QATScheduler", "parse_qat_config", "apply_qat"]


@dataclass
class _Group:
    modules: List[str]
    start_bits: int
    target_bits: int
    period: int          # steps until the next one-bit drop (doubles)
    current_bits: int = 0
    next_drop: int = 0   # absolute step of the next drop

    def __post_init__(self):
        self.current_bits = self.start_bits


@dataclass
class QATScheduler:
    """Progressive precision schedule over parameter groups."""
    groups: List[_Group]
    schedule_offset: int = 0
    symmetric: bool = True
    verbose: bool = False
    _started: bool = field(default=False, repr=False)

    def update(self, step: int) -> Tuple[Dict[int, int], bool]:
        """Advance to ``step``; returns ({group-index: bits}, changed)."""
        changed = False
        if step >= self.schedule_offset and not self._started:
            self._started = True
            changed = True  # quantization switches ON this step
            for g in self.groups:
                g.next_drop = step + g.period
        if self._started:
            for g in self.groups:
                while (g.current_bits > g.target_bits
                       and step >= g.next_drop):
                    g.current_bits -= 1
                    g.period *= 2  # reference: input.q_period <<= 1
                    g.next_drop = step + g.period
                    changed = True
                    if self.verbose:
                        log_dist(f"QAT: group {g.modules} -> "
                                 f"{g.current_bits} bits (period "
                                 f"{g.period}) at step {step}")
        bits = ({i: g.current_bits for i, g in enumerate(self.groups)}
                if self._started else {})
        return bits, changed

    def state_dict(self) -> Dict[str, Any]:
        return {"started": self._started,
                "groups": [(g.current_bits, g.period, g.next_drop)
                           for g in self.groups]}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self._started = bool(sd["started"])
        for g, (bits, period, nxt) in zip(self.groups, sd["groups"]):
            g.current_bits, g.period, g.next_drop = int(bits), int(period), \
                int(nxt)


def parse_qat_config(raw: Dict[str, Any]) -> Optional[QATScheduler]:
    """``compression_training.weight_quantization`` with
    ``quantize_weight_in_forward`` → a scheduler (None when absent/off).
    Reference keys: shared_parameters {enabled, quantize_weight_in_forward,
    schedule_offset, quantize_verbose, quantization_type}; different_groups
    params {start_bits, target_bits, quantization_period}."""
    wq = dict(dict(raw.get("compression_training", {}))
              .get("weight_quantization", {}))
    shared = dict(wq.get("shared_parameters", {}))
    if not shared.get("enabled", False) or \
            not shared.get("quantize_weight_in_forward", False):
        return None
    groups = []
    for g in map(dict, dict(wq.get("different_groups", {})).values()):
        p = dict(g.get("params", {}))
        groups.append(_Group(
            modules=list(g.get("modules", ["*"])),
            start_bits=int(p.get("start_bits", 16)),
            target_bits=int(p.get("target_bits", 8)),
            period=int(p.get("quantization_period", 1000) or 1)))
    if not groups:
        groups = [_Group(modules=["*"], start_bits=16, target_bits=8,
                         period=1000)]
    return QATScheduler(
        groups=groups,
        schedule_offset=int(shared.get("schedule_offset", 0)),
        symmetric=str(shared.get("quantization_type",
                                 "symmetric")) != "asymmetric",
        verbose=bool(shared.get("quantize_verbose", False)))


def apply_qat(params: Any, bits_by_group: Dict[int, int],
              groups: List[_Group], symmetric: bool = True) -> Any:
    """STE fake-quantize matching >=2-D weight leaves at their group's
    current bits (first matching group wins, reference group semantics).
    Bits are PYTHON ints — trace-time constants."""
    if not bits_by_group:
        return params

    def visit(path, leaf):
        if not isinstance(leaf, jnp.ndarray) or leaf.ndim < 2 or \
                not jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf
        name = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        for i, g in enumerate(groups):
            if any(fnmatch.fnmatch(name, pat) or pat in name
                   for pat in g.modules):
                return fake_quant(leaf, bits_by_group[i],
                                  symmetric=symmetric)
        return leaf

    return jax.tree_util.tree_map_with_path(visit, params)
