from . import layer  # noqa: F401
