"""Import-path compat: ``deepspeed.moe.layer.MoE`` (reference
``deepspeed/moe/layer.py:16``).

The reference's MoE is a torch module wrapping gate+experts; here MoE is
a CONFIG property of the flagship model (``ModelConfig.num_experts`` →
``parallel/moe.moe_mlp`` inside the layer scan). This shim carries the
reference constructor surface and resolves it onto that config, so ported
model-construction code type-checks and documents its intent; the
functional dispatch path is ``parallel.moe.moe_mlp``.
"""
from typing import Any, Optional

from ..parallel.moe import moe_mlp, topk_gating  # noqa: F401


class MoE:
    """Reference ``deepspeed.moe.layer.MoE`` constructor surface. Use the
    captured fields to build a ``ModelConfig`` (num_experts,
    num_experts_per_tok=k, capacity_factor...) — the engine's scan-based
    MoE path replaces the module-tree wrapping."""

    def __init__(self, hidden_size: int, expert: Any = None,
                 num_experts: int = 1, ep_size: int = 1, k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4, use_residual: bool = False,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True, use_rts: bool = True,
                 use_tutel: bool = False,
                 enable_expert_tensor_parallelism: bool = False,
                 top2_2nd_expert_sampling: bool = True):
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.k = k
        self.capacity_factor = capacity_factor
        self.use_residual = use_residual
        from ..utils.logging import logger

        # knobs with no ModelConfig mapping must not be silently eaten —
        # a ported Residual-MoE/noisy-gate model would otherwise build a
        # materially different architecture without a word
        if use_residual:
            logger.warning("MoE(use_residual=True) has no TPU-build "
                           "equivalent yet; building a standard top-k MoE")
        if noisy_gate_policy not in (None, "None"):
            logger.warning("MoE noisy_gate_policy=%r ignored (router_jitter"
                           " in ModelConfig is the supported noise knob)",
                           noisy_gate_policy)
        if not drop_tokens:
            logger.warning("MoE(drop_tokens=False): training uses the "
                           "capacity path; the no-drop grouped-GEMM path "
                           "serves inference (parallel/moe.moe_mlp_nodrop)")

    def model_config_kwargs(self) -> dict:
        """The ModelConfig fields this MoE spec maps to."""
        return {"num_experts": self.num_experts,
                "num_experts_per_tok": self.k,
                "capacity_factor": self.capacity_factor}
