from .comm import (
    all_gather,
    all_reduce,
    all_to_all, hierarchical_all_to_all,
    axis_index,
    axis_size,
    barrier,
    broadcast,
    get_device_count,
    get_local_rank,
    get_rank,
    get_world_size,
    init_distributed,
    is_initialized,
    pmean,
    ppermute,
    reduce_scatter,
    send_recv_next,
    send_recv_prev,
)
from .comms_logging import CommsLogger, comms_logger, get_comms_logger
from .topology import (
    AXIS_ORDER,
    MeshTopology,
    build_topology,
    get_world_topology,
    reset_world_topology,
    set_world_topology,
)
