"""Quantized collectives — ZeRO++ communication analogs.

Reference (SURVEY.md §2.4 ZeRO++ row):

* **qwZ** — quantized-weight all-gather: ZeRO-3's param gather ships int8
  blocks instead of fp16 (``CUDAQuantizer``, ``partition_parameters.py:679``;
  kernel ``csrc/quantization/swizzled_quantize.cu`` arranges scales per
  communication chunk). Here: :func:`quantized_all_gather`.
* **qgZ** — quantized-gradient reduce: fused all-to-all + dequant-reduce
  (``all_to_all_quant_reduce``, ``runtime/comm/coalesced_collectives.py``;
  kernel ``csrc/quantization/quant_reduce.cu``). Here:
  :func:`all_to_all_quant_reduce`.

Both are shard_map-level ops: XLA's automatic SPMD collectives can't be
intercepted, so quantized transport is an EXPLICIT choice at the call site
(e.g. a manual FSDP gather or the gradient sync of a shard_map DP loop). The
int8 payload + fp32 per-block scales travel as separate arrays — the same wire
split the swizzled CUDA layout achieves, with XLA free to overlap both
transfers on ICI.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..compression.quantize import dequantize_int8, quantize_int8


def _block_quant(x: jnp.ndarray, group_size: int
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Flatten + pad to a multiple of group_size, blockwise int8 quantize."""
    flat = x.reshape(-1)
    pad = (-flat.size) % group_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    q, s = quantize_int8(flat, group_size=group_size)
    return q, s, pad


def quantized_all_gather(x: jnp.ndarray, axis_name: str,
                         group_size: int = 256,
                         dtype=None, axis_index_groups=None) -> jnp.ndarray:
    """All-gather with int8 transport (qwZ). Use inside shard_map.

    Local shard [n, ...] → [W·n, ...] along dim 0, where W = axis size (or
    the group size when ``axis_index_groups`` scopes the gather — the hpZ
    intra-node hop).
    ~4× less ICI traffic than fp32 gather (int8 payload + 1 fp32 scale per
    ``group_size`` elements).
    """
    dtype = dtype or x.dtype
    q, s, pad = _block_quant(x, group_size)
    qg = lax.all_gather(q, axis_name,            # int8 on the wire
                        axis_index_groups=axis_index_groups)
    sg = lax.all_gather(s, axis_name,
                        axis_index_groups=axis_index_groups)
    deq = dequantize_int8(qg, sg, group_size=group_size, dtype=dtype)
    if pad:
        deq = deq[:, :-pad]
    w = deq.shape[0]
    return deq.reshape((w * x.shape[0],) + x.shape[1:])


def all_to_all_quant_reduce(x: jnp.ndarray, axis_name: str,
                            group_size: int = 256) -> jnp.ndarray:
    """Quantized reduce-scatter mean via all-to-all (qgZ). Use inside shard_map.

    Local [W·n, ...] (W gradient chunks, one per rank) → this rank's mean chunk
    [n, ...]. Single-hop all-to-all of int8 chunks, then dequant + mean — the
    one-shot hierarchy-free form of the reference's fused quant_reduce.
    """
    w = lax.psum(1, axis_name)
    assert x.shape[0] % w == 0, (x.shape, w)
    n = x.shape[0] // w
    chunks = x.reshape((w, n) + x.shape[1:])
    flat = chunks.reshape(w, -1)
    pad = (-flat.shape[1]) % group_size
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((w, pad), flat.dtype)], axis=1)
    q, s = quantize_int8(flat, group_size=group_size)
    # one chunk to each peer; receive one chunk from each peer
    qt = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=False)
    st = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=False)
    deq = dequantize_int8(qt, st, group_size=group_size, dtype=jnp.float32)
    if pad:
        deq = deq[:, :-pad]
    mean = deq.mean(axis=0)
    return mean.reshape((n,) + x.shape[1:]).astype(x.dtype)


def sign_compress(corrected: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """1-bit compression operator: (sign int8, fp32 scale, residual).

    ``corrected`` is the error-feedback-corrected tensor; the residual feeds
    the next step. Zero maps to +1 so dequantization is exactly
    ``scale · sign`` (one convention everywhere — local and wire paths must
    agree or error feedback breaks)."""
    scale = jnp.mean(jnp.abs(corrected))
    sign = jnp.where(corrected >= 0, jnp.int8(1), jnp.int8(-1))
    residual = corrected - scale * sign.astype(corrected.dtype)
    return sign, scale, residual


def compressed_allreduce(x: jnp.ndarray, error: jnp.ndarray, axis_name: str
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """1-bit error-feedback allreduce (reference
    ``NcclBackend.compressed_allreduce``, ``runtime/comm/nccl.py:51``; the
    engine of 1-bit Adam/LAMB). Use inside shard_map.

    Sends sign bits (int8 on the wire) + one fp32 scale per rank; the local
    compression residue feeds back into the next call, so the *sequence* of
    allreduces is unbiased even though each one is 1-bit.

    Returns (averaged tensor, new error feedback).
    """
    corrected = x + error
    sign, scale, new_error = sign_compress(corrected)
    signs_g = lax.all_gather(sign, axis_name)        # [W, ...] int8 wire
    scales_g = lax.all_gather(scale, axis_name)      # [W] fp32
    avg = jnp.tensordot(scales_g, signs_g.astype(jnp.float32), axes=1) \
        / signs_g.shape[0]
    return avg.astype(x.dtype), new_error
