"""Collectives façade.

TPU-native analog of ``deepspeed/comm/comm.py`` (module-level collectives with the
``@timed_op`` profiling wrapper, ops at ``comm.py:222-521``, ``init_distributed:604``)
and the backends behind it (``comm/torch.py:99`` TorchBackend → NCCL,
``comm/ccl.py:34`` CCLBackend → oneCCL).

Design shift: the reference's collectives are *eager library calls* on torch tensors;
ours are *traced primitives* — ``jax.lax.{psum, all_gather, psum_scatter, all_to_all,
ppermute}`` over named mesh axes — that XLA lowers onto ICI/DCN and overlaps with
compute automatically. The façade therefore has two layers:

1. **Named-axis ops** (this module): thin wrappers usable inside ``shard_map``/``pjit``
   bodies, carrying the reference façade's op vocabulary, comms logging, and per-op
   kill-switch env flags (reference ``comm/torch.py:13-17`` ``DS_COMM_*_OFF``).
2. **Process bootstrap**: ``init_distributed()`` maps to
   ``jax.distributed.initialize`` (the analog of ``torch.distributed.init_process_group``
   rendezvous at ``comm/comm.py:604``), with env-based discovery.

The SPMD partitioner also inserts collectives implicitly from sharding specs; this
façade is for the *explicit* paths (pipeline p2p, MoE all-to-all, Ulysses, ZeRO grad
reduce inside shard_map) and for tests/debugging.
"""
import math
import os
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .comms_logging import comms_logger

__all__ = [
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all", "hierarchical_all_to_all", "ppermute",
    "broadcast", "pmean", "axis_size", "axis_index", "send_recv_next",
    "send_recv_prev", "init_distributed", "is_initialized", "barrier",
    "get_world_size", "get_rank", "get_local_rank", "get_device_count",
    # reference-surface parity (root-based ops, p2p, coalesced, aliases)
    "reduce", "gather", "scatter", "p2p", "send", "recv",
    "all_reduce_coalesced", "all_gather_coalesced",
    "all_gather_into_tensor", "reduce_scatter_tensor", "all_to_all_single",
    "inference_all_reduce", "monitored_barrier", "new_group",
    "get_global_rank", "get_world_group", "get_all_ranks_from_group",
    "destroy_process_group",
]

_INITIALIZED = False
_DEFAULT_SLURM_PORT = 29500  # coordinator port when srun env names no port


# ---------------------------------------------------------------------------
# kill switches (reference: DS_COMM_{REDUCE_SCATTER,ALL_GATHER,...}_OFF,
# comm/torch.py:13-17) — turn a collective into identity for fault isolation.
# ---------------------------------------------------------------------------
def _off(op: str) -> bool:
    return os.environ.get(f"DSTPU_COMM_{op}_OFF", "").lower() in ("1", "true", "yes")


def _nbytes(x) -> int:
    try:
        return math.prod(int(s) for s in x.shape) * jnp.dtype(x.dtype).itemsize
    except Exception:
        return 0


def _log(op: str, axis, x):
    comms_logger.append(op, axis, _nbytes(x), tuple(getattr(x, "shape", ())))


# ---------------------------------------------------------------------------
# named-axis collectives (use inside shard_map / pjit with a Mesh installed)
# ---------------------------------------------------------------------------
def all_reduce(x, axis_name, op: str = "sum"):
    """Sum/max/min-reduce across a mesh axis (reference: ``comm.all_reduce``,
    ``comm/comm.py:494``)."""
    if _off("ALL_REDUCE"):
        return x
    _log("all_reduce", axis_name, x)
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op in ("avg", "mean"):
        return lax.pmean(x, axis_name)
    raise ValueError(f"unsupported reduce op {op!r}")


def pmean(x, axis_name):
    if _off("ALL_REDUCE"):
        return x
    _log("all_reduce_mean", axis_name, x)
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name, axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` across the mesh axis (reference:
    ``all_gather_into_tensor``, ``comm/comm.py:320``)."""
    if _off("ALL_GATHER"):
        return x
    _log("all_gather", axis_name, x)
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis: int = 0):
    """Sum-reduce then scatter along ``axis`` (reference: ``reduce_scatter_tensor``,
    ``comm/comm.py:357``; ZeRO's grad-shard primitive ``stage_1_and_2.py:1004``)."""
    if _off("REDUCE_SCATTER"):
        return x
    _log("reduce_scatter", axis_name, x)
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def all_to_all(x, axis_name, split_axis: int, concat_axis: int, tiled: bool = True):
    """All-to-all (reference: ``all_to_all_single``, ``comm/comm.py:430``; the MoE
    dispatch primitive ``moe/sharded_moe.py:95`` and Ulysses ``sequence/layer.py:15``)."""
    if _off("ALL_TO_ALL"):
        return x
    _log("all_to_all", axis_name, x)
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def hierarchical_all_to_all(x, axis_name, group_size: int,
                            split_axis: int = 0, concat_axis: int = 0):
    """Two-hop all-to-all: intra-group exchange first, then inter-group.

    Drop-in equivalent of ``all_to_all(x, axis, split, concat, tiled=True)``
    decomposed the way the reference's hierarchical MoE dispatch does it
    (``utils/groups.py:356`` ``_get_local_all_to_all_group``): with N ranks
    in groups of ``group_size`` (a TPU slice / a node), every rank first
    exchanges within its group over fast links (ICI), then one exchange
    crosses groups (DCN) — cross-group messages per device drop from
    ``N − group_size`` to ``N / group_size − 1``, which is what makes MoE
    routing viable across slices.
    """
    if _off("ALL_TO_ALL"):
        return x
    n = lax.axis_size(axis_name)
    gs = int(group_size)
    if n % gs:
        raise ValueError(f"axis size {n} not divisible by group_size {gs}")
    ng = n // gs
    if gs == 1 or ng == 1:
        return all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)
    _log("hierarchical_all_to_all", axis_name, x)
    if x.shape[split_axis] % n:
        raise ValueError(f"split dim {x.shape[split_axis]} not divisible "
                         f"by axis size {n}")
    # parts [tg, tl, ...]: chunk (tg, tl) is destined for rank tg·gs + tl
    parts = jnp.moveaxis(
        x.reshape(x.shape[:split_axis] + (n, x.shape[split_axis] // n)
                  + x.shape[split_axis + 1:]), split_axis, 0)
    parts = parts.reshape((ng, gs) + parts.shape[1:])
    intra = [[g * gs + l for l in range(gs)] for g in range(ng)]
    inter = [[g * gs + l for g in range(ng)] for l in range(gs)]
    # hop 1 (ICI): z[tg, sl, ...] = source (G, sl)'s chunk (tg, my_l)
    z = lax.all_to_all(parts, axis_name, split_axis=1, concat_axis=1,
                       axis_index_groups=intra)
    # hop 2 (DCN): w[sg, sl, ...] = source (sg, sl)'s chunk (my_g, my_l)
    w = lax.all_to_all(z, axis_name, split_axis=0, concat_axis=0,
                       axis_index_groups=inter)
    w = w.reshape((n,) + w.shape[2:])           # source-major, = plain a2a
    out = jnp.moveaxis(w, 0, concat_axis)
    return out.reshape(out.shape[:concat_axis]
                       + (out.shape[concat_axis]
                          * out.shape[concat_axis + 1],)
                       + out.shape[concat_axis + 2:])


def ppermute(x, axis_name, perm: Sequence[tuple]):
    """Point-to-point permutation — the TPU p2p primitive under pipeline parallelism
    (reference: ``runtime/pipe/p2p.py`` send/recv)."""
    if _off("P2P"):
        return x
    _log("ppermute", axis_name, x)
    return lax.ppermute(x, axis_name, perm=perm)


def send_recv_next(x, axis_name, n: Optional[int] = None, wrap: bool = True):
    """Shift +1 along a mesh axis (stage i → i+1).

    ``wrap=True`` is a full ring (stage 0 receives stage n-1's value — collective
    rotations, ring attention). ``wrap=False`` drops the wraparound edge; ppermute
    zero-fills unlisted destinations, so stage 0 receives zeros — the pipeline p2p
    contract (reference: ``runtime/pipe/p2p.py`` send/recv to stage+1).
    """
    n = n or lax.axis_size(axis_name)
    pairs = [(i, (i + 1) % n) for i in range(n if wrap else n - 1)]
    return ppermute(x, axis_name, pairs)


def send_recv_prev(x, axis_name, n: Optional[int] = None, wrap: bool = True):
    """Shift -1 along a mesh axis (stage i → i-1); see :func:`send_recv_next`."""
    n = n or lax.axis_size(axis_name)
    pairs = [(i, (i - 1) % n) for i in (range(n) if wrap else range(1, n))]
    return ppermute(x, axis_name, pairs)


def broadcast(x, axis_name, src: int = 0):
    """Broadcast src's shard to all members of the axis (reference: ``comm.broadcast``,
    ``comm/comm.py:224``; engine param broadcast ``engine.py:1052``)."""
    if _off("BROADCAST"):
        return x
    _log("broadcast", axis_name, x)
    # ppermute is a strict permutation, so broadcast is select-then-psum: non-src
    # shards are replaced by zeros *before* the sum so NaN/Inf garbage on non-src
    # ranks (e.g. uninitialized params awaiting the broadcast) cannot poison it.
    contrib = jnp.where(lax.axis_index(axis_name) == src, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name)


def axis_size(axis_name) -> int:
    return lax.axis_size(axis_name)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


# ---------------------------------------------------------------------------
# process bootstrap (reference: init_distributed comm/comm.py:604)
# ---------------------------------------------------------------------------
def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     auto_mpi_discovery: bool = True,
                     dist_init_required: Optional[bool] = None) -> bool:
    """Initialize multi-host JAX runtime.

    Single-host (the common test/bench path) is a no-op: JAX already sees all local
    devices. Multi-host reads env — JAX-native vars or the reference's
    RANK/WORLD_SIZE/MASTER_ADDR convention set by its launcher
    (``launcher/launch.py:132``) — and calls ``jax.distributed.initialize``.
    ``auto_mpi_discovery`` mirrors ``mpi_discovery`` (``comm/comm.py:673``) by reading
    OMPI env vars when the torch-style ones are absent.
    """
    global _INITIALIZED
    if _INITIALIZED or dist_init_required is False:
        return False

    env = os.environ
    coord = coordinator_address or env.get("COORDINATOR_ADDRESS")
    nprocs = num_processes if num_processes is not None else _int_env("NUM_PROCESSES")
    pid = process_id if process_id is not None else _int_env("PROCESS_ID")

    # torch-style env:// convention (reference launcher sets these)
    if coord is None and "MASTER_ADDR" in env:
        port = env.get("MASTER_PORT", "1234")
        coord = f"{env['MASTER_ADDR']}:{port}"
        nprocs = nprocs if nprocs is not None else _int_env("WORLD_SIZE")
        pid = pid if pid is not None else _int_env("RANK")

    # MPI discovery (reference: comm/comm.py:673). MPI env gives size/rank; the
    # coordinator must still be a bare host:port that process 0 can bind
    # (the ORTE HNP URI is mpirun's daemon, not a usable coordinator), so we
    # require DSTPU_COORDINATOR/MASTER_ADDR alongside MPI env.
    if auto_mpi_discovery and "OMPI_COMM_WORLD_SIZE" in env:
        nprocs = nprocs if nprocs is not None else int(env["OMPI_COMM_WORLD_SIZE"])
        pid = pid if pid is not None else int(env["OMPI_COMM_WORLD_RANK"])
        if coord is None and nprocs and nprocs > 1:
            raise RuntimeError(
                "MPI launch detected but no coordinator address; set MASTER_ADDR/"
                "MASTER_PORT (or COORDINATOR_ADDRESS) to a host:port on rank 0")
    # PMI convention (MPICH / Intel MPI / MVAPICH launchers export PMI_RANK)
    if auto_mpi_discovery and nprocs is None and "PMI_SIZE" in env:
        nprocs = int(env["PMI_SIZE"])
        pid = pid if pid is not None else int(env.get("PMI_RANK", 0))
        if coord is None and nprocs > 1:
            raise RuntimeError(
                "PMI launch detected but no coordinator address; set "
                "MASTER_ADDR/MASTER_PORT to a host:port on rank 0")
    # SLURM srun convention (reference: SlurmRunner relies on srun's env).
    # Gated on SLURM_STEP_ID — set only for srun-launched steps — so a bare
    # `python train.py` inside an sbatch allocation (which still exports
    # SLURM_NTASKS) is NOT mistaken for a distributed launch and left to
    # initialize single-process.
    if auto_mpi_discovery and nprocs is None and "SLURM_NTASKS" in env \
            and "SLURM_STEP_ID" in env:
        nprocs = int(env["SLURM_NTASKS"])
        pid = pid if pid is not None else int(env.get("SLURM_PROCID", 0))
        if coord is None and nprocs > 1:
            # first host of the allocation is the conventional coordinator
            nodelist = env.get("SLURM_JOB_NODELIST") or env.get("SLURM_NODELIST")
            if nodelist and "[" not in nodelist:
                coord = f"{nodelist.split(',')[0]}:{_DEFAULT_SLURM_PORT}"
            else:
                raise RuntimeError(
                    "SLURM launch detected but no coordinator address and "
                    "the nodelist is compressed; set MASTER_ADDR/MASTER_PORT "
                    "(or COORDINATOR_ADDRESS)")

    if coord is None or not nprocs or nprocs <= 1:
        _INITIALIZED = True
        return False

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nprocs,
                               process_id=pid)
    _INITIALIZED = True
    return True


def _int_env(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def is_initialized() -> bool:
    return _INITIALIZED


def get_world_size() -> int:
    """Number of participating *processes* (controllers).

    Note the semantic shift from the reference: torch launches one process per
    device, so its world_size == device count. JAX is single-controller per host;
    the SPMD width (device count) lives on the topology
    (``MeshTopology.world_size()``) / :func:`get_device_count`. Rank and
    world_size here are consistently process-level.
    """
    return jax.process_count()


def get_rank() -> int:
    """This process's rank in [0, get_world_size())."""
    return jax.process_index()


def get_device_count() -> int:
    """Global number of devices across all processes (reference's world_size)."""
    from ..accelerator import get_accelerator

    return get_accelerator().device_count()


def get_local_rank() -> int:
    """Rank within this host (reference: LOCAL_RANK env set per-process by
    ``launcher/launch.py``). JAX is one process per host, so this is the
    launcher-provided LOCAL_RANK when present, else 0."""
    v = os.environ.get("LOCAL_RANK")
    return int(v) if v is not None else 0


def barrier():
    """Host-level barrier (reference: ``comm.barrier``, ``comm/comm.py:411``).

    Under a single controller this drains async dispatch; under multi-controller it
    performs a tiny psum across all devices, which cannot complete until every
    process has joined.
    """
    if jax.process_count() == 1:
        jax.effects_barrier()
        return
    x = jnp.ones((jax.local_device_count(),))
    jax.block_until_ready(
        jax.pmap(lambda v: lax.psum(v, "i"), axis_name="i")(x))


# ======================================================================
# Reference-surface parity: root-based ops, p2p, coalesced variants and
# torch-compat aliases (reference comm/comm.py public API). Under SPMD
# every rank executes the same program, so "root" semantics become
# value-selection: non-root ranks get a defined value (documented per op)
# instead of being bystanders.
# ======================================================================
def reduce(x, axis_name, dst: int = 0):
    """Sum-reduce onto ``dst`` (reference ``comm.reduce``): the reduced
    value lands on rank ``dst``; every other rank keeps its input (the
    closest SPMD analog of torch's in-place root semantics)."""
    total = all_reduce(x, axis_name)   # honors the ALL_REDUCE kill switch
    return jnp.where(lax.axis_index(axis_name) == dst, total, x)


def gather(x, axis_name, dst: int = 0):
    """Gather shards onto ``dst`` (reference ``comm.gather``). SPMD has no
    bystanders, so EVERY rank receives the stacked [world, ...] result —
    rank ``dst`` reads it, others may ignore it (XLA DCEs unused outputs).
    """
    del dst  # root semantics dissolve under SPMD; kept for API parity
    return all_gather(x, axis_name, tiled=False)  # stacked [world, ...]


def scatter(x, axis_name, src: int = 0):
    """Scatter rank ``src``'s leading-dim shards (reference
    ``comm.scatter``): input [world, ...] on ``src``; every rank returns
    its own [...] shard."""
    full = broadcast(x, axis_name, src=src)  # broadcast logs the transfer
    n = lax.axis_size(axis_name)
    if full.shape[0] != n:
        # dynamic_index_in_dim would CLAMP a short leading dim, silently
        # delivering the wrong shard — reject like the reference does for a
        # wrong-length scatter_list
        raise ValueError(f"scatter input leading dim {full.shape[0]} != "
                         f"axis size {n}")
    return lax.dynamic_index_in_dim(full, lax.axis_index(axis_name), 0,
                                    keepdims=False)


def p2p(x, src: int, dst: int, axis_name):
    """Point-to-point transfer (reference ``send``/``recv`` pair, one
    collective under SPMD): rank ``dst`` returns rank ``src``'s value,
    every other rank keeps its own."""
    moved = ppermute(x, axis_name, [(src, dst)])  # honors the P2P switch
    return jnp.where(lax.axis_index(axis_name) == dst, moved, x)


def send(x, dst: int, axis_name, src: Optional[int] = None):
    """Reference ``comm.send``. SPMD is collective: the matching recv is
    the SAME call on the receiving rank, so ``send``/``recv`` both map to
    :func:`p2p`. ``src`` is REQUIRED — "the caller's rank" is not a
    static value under jit."""
    if src is None:
        raise ValueError("SPMD send needs the static source rank: "
                         "send(x, dst, axis, src=<rank>) — or use p2p()")
    return p2p(x, src, dst, axis_name)


def recv(x, src: int, axis_name, dst: Optional[int] = None):
    """Reference ``comm.recv`` — see :func:`send`."""
    if dst is None:
        raise ValueError("SPMD recv needs the static destination rank: "
                         "recv(x, src, axis, dst=<rank>) — or use p2p()")
    return p2p(x, src, dst, axis_name)


def all_reduce_coalesced(tensors, axis_name):
    """Reference ``all_reduce_coalesced``: one call over a list/pytree.
    XLA fuses the resulting psums, which is exactly what torch's
    coalescing manager buys."""
    return jax.tree_util.tree_map(lambda t: all_reduce(t, axis_name),
                                  tensors)


def all_gather_coalesced(tensors, axis_name):
    """Reference ``all_gather_coalesced`` over a list/pytree."""
    return jax.tree_util.tree_map(lambda t: all_gather(t, axis_name),
                                  tensors)


# ----- torch-compat aliases (reference keeps both spellings alive) -----
def all_gather_into_tensor(x, axis_name):
    """Reference ``all_gather_into_tensor`` (tensor-form all_gather)."""
    return all_gather(x, axis_name)


def reduce_scatter_tensor(x, axis_name):
    """Reference ``reduce_scatter_tensor`` (tensor-form reduce_scatter)."""
    return reduce_scatter(x, axis_name)


def all_to_all_single(x, axis_name, split_axis: int = 0,
                      concat_axis: int = 0, **kw):
    """Reference ``all_to_all_single`` (torch splits/concats dim 0
    implicitly — same defaults here)."""
    return all_to_all(x, axis_name, split_axis=split_axis,
                      concat_axis=concat_axis, **kw)


def inference_all_reduce(x, axis_name):
    """Reference ``inference_all_reduce`` (the op-builder fast path; XLA's
    psum IS the fast path here)."""
    return all_reduce(x, axis_name)


def monitored_barrier(timeout=None):
    """Reference ``monitored_barrier`` — barrier + log (straggler
    attribution needs no special path when XLA collectives deadlock
    loudly)."""
    del timeout
    _log("monitored_barrier", "world", jnp.zeros(()))
    barrier()


def get_global_rank(group=None, group_rank: int = 0) -> int:
    """Reference ``get_global_rank``: resolve a group-relative rank for a
    :func:`new_group` rank list (``None`` = the world group, where group
    rank == global rank). Mesh-axis groups need mesh coordinates — raise
    rather than return a plausible-looking wrong rank."""
    if isinstance(group, _RankGroup):
        return group.ranks[group_rank]
    if group is None:
        return group_rank
    raise TypeError(
        f"get_global_rank needs a new_group() handle or None, got "
        f"{group!r} — for mesh axes, derive ranks from the topology mesh "
        f"coordinates instead")


def get_world_group():
    """Reference ``get_world_group``. Rank domain: DEVICE ranks — the same
    domain every collective src/dst in this module uses (a single
    controller drives all local devices, so process ranks would make the
    world group [0] while ranks 0..7 participate in collectives)."""
    return _RankGroup(tuple(range(get_device_count())))


def get_all_ranks_from_group(group=None):
    """Reference ``get_all_ranks_from_group``."""
    if group is None:
        group = get_world_group()
    return list(group.ranks)


class _RankGroup:
    """Lightweight process-group handle (reference ``new_group`` returns a
    torch ProcessGroup). Collectives over arbitrary rank subsets are a
    MESH property under SPMD — build a topology whose axis holds these
    ranks (``build_topology``) instead of passing the handle to a
    collective."""

    def __init__(self, ranks):
        self.ranks = tuple(int(r) for r in ranks)

    def size(self) -> int:
        return len(self.ranks)

    def __repr__(self):
        return f"_RankGroup(ranks={self.ranks})"


def new_group(ranks):
    """Reference ``comm.new_group``. Returns a rank-list handle for
    bookkeeping APIs (:func:`get_global_rank`,
    :func:`get_all_ranks_from_group`); express subset COLLECTIVES as mesh
    axes (see :class:`_RankGroup`)."""
    return _RankGroup(ranks)


def destroy_process_group(group=None):
    """Reference ``destroy_process_group`` — jax.distributed teardown for
    the world group, no-op for sub-groups."""
    global _INITIALIZED
    if group is None or isinstance(group, _RankGroup) and \
            len(group.ranks) == get_device_count():
        try:
            jax.distributed.shutdown()
        except Exception:  # single-controller / already down
            pass
        _INITIALIZED = False  # torch parity: is_initialized() goes False
