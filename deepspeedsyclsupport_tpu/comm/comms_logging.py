"""Communication op logging.

Analog of the reference's comms logger (``deepspeed/utils/comms_logging.py`` +
``@timed_op`` wrapper at ``comm/comm.py:101-142``): per-op counts, message sizes, and a
``log_summary()`` table.

Timing semantics differ by construction: the reference times every eager NCCL call;
under XLA, collectives are fused into one compiled program, so per-op wall-clock is only
visible to the profiler. What we can and do record losslessly at *trace* time is the op
mix — name, mesh axis, message bytes, call count — which is what the reference's summary
table mostly shows. Wall-clock per collective comes from ``jax.profiler`` traces
(see ``profiling/``).
"""
import threading
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class _OpRecord:
    count: int = 0
    total_bytes: int = 0
    shapes: List[tuple] = field(default_factory=list)


class CommsLogger:
    """Trace-time collective op recorder (reference: ``utils/comms_logging.py``)."""

    def __init__(self, enabled: bool = False, verbose: bool = False, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.debug = debug
        self._lock = threading.Lock()
        self._records: Dict[str, _OpRecord] = defaultdict(_OpRecord)

    def configure(self, enabled: Optional[bool] = None, verbose: Optional[bool] = None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose

    def append(self, op_name: str, axis_name, nbytes: int, shape: tuple):
        if not self.enabled:
            return
        key = f"{op_name}[{axis_name}]"
        with self._lock:
            rec = self._records[key]
            rec.count += 1
            rec.total_bytes += nbytes
            if self.debug:
                rec.shapes.append(shape)
        if self.verbose:
            from ..utils.logging import logger

            logger.info("comm op: %s | bytes: %d | shape: %s", key, nbytes, shape)

    def log_summary(self) -> str:
        """Render a summary table (reference: ``log_summary`` via ``comm/comm.py:422``)."""
        lines = [f"{'op':<40}{'count':>10}{'total MB':>14}"]
        with self._lock:
            for key in sorted(self._records):
                rec = self._records[key]
                lines.append(f"{key:<40}{rec.count:>10}{rec.total_bytes / 2**20:>14.2f}")
        table = "\n".join(lines)
        from ..utils.logging import logger

        logger.info("\n%s", table)
        return table

    def reset(self):
        with self._lock:
            self._records.clear()

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {k: {"count": v.count, "total_bytes": v.total_bytes}
                    for k, v in self._records.items()}


comms_logger = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return comms_logger
