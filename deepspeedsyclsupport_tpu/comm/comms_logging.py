"""Communication op logging.

Analog of the reference's comms logger (``deepspeed/utils/comms_logging.py`` +
``@timed_op`` wrapper at ``comm/comm.py:101-142``): per-op counts, message sizes, and a
``log_summary()`` table.

Timing semantics differ by construction: the reference times every eager NCCL call;
under XLA, collectives are fused into one compiled program, so per-op wall-clock is only
visible to the profiler. What we can and do record losslessly at *trace* time is the op
mix — name, mesh axis, message bytes, call count — which is what the reference's summary
table mostly shows. Wall-clock per collective comes from ``jax.profiler`` traces
(see ``profiling/``).
"""
import threading
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class _OpRecord:
    count: int = 0
    total_bytes: int = 0
    shapes: List[tuple] = field(default_factory=list)


class CommsLogger:
    """Trace-time collective op recorder (reference: ``utils/comms_logging.py``)."""

    def __init__(self, enabled: bool = False, verbose: bool = False, debug: bool = False):
        self.enabled = enabled
        self.verbose = verbose
        self.debug = debug
        self._lock = threading.Lock()
        self._records: Dict[str, _OpRecord] = defaultdict(_OpRecord)
        self._wall: Dict[str, float] = {}

    def configure(self, enabled: Optional[bool] = None, verbose: Optional[bool] = None):
        if enabled is not None:
            self.enabled = enabled
        if verbose is not None:
            self.verbose = verbose

    def append(self, op_name: str, axis_name, nbytes: int, shape: tuple):
        if not self.enabled:
            return
        key = f"{op_name}[{axis_name}]"
        with self._lock:
            rec = self._records[key]
            rec.count += 1
            rec.total_bytes += nbytes
            if self.debug:
                rec.shapes.append(shape)
        if self.verbose:
            from ..utils.logging import logger

            logger.info("comm op: %s | bytes: %d | shape: %s", key, nbytes, shape)

    def record_hlo(self, summary: Dict[str, Dict], tag: str) -> None:
        """Merge a post-compile collective summary (``hlo_comms``) under
        ``xla::`` keys. Idempotent per (op, tag): re-recording the same
        compiled program replaces rather than double-counts."""
        with self._lock:
            for op, s in summary.items():
                rec = self._records[f"xla::{op}[{tag}]"]
                rec.count = s["count"]
                rec.total_bytes = s["total_bytes"]

    def record_wall(self, name: str, seconds: float) -> None:
        """Accumulate wall-clock against a name (engine step timing) — the
        basis of the straggler columns."""
        with self._lock:
            self._wall[name] = self._wall.get(name, 0.0) + seconds

    def log_summary(self, show_straggler: bool = False) -> str:
        """Render a summary table (reference: ``log_summary`` via
        ``comm/comm.py:422``; ``show_straggler`` analog of
        ``utils/comms_logging.py:108``). Straggler semantics under SPMD:
        per-op latency is invisible (collectives fuse into one program), so
        the columns compare each HOST's accumulated step wall-clock —
        min/max across the controllers; a host far above min is the
        straggler."""
        lines = [f"{'op':<44}{'count':>10}{'total MB':>14}"]
        with self._lock:
            for key in sorted(self._records):
                rec = self._records[key]
                lines.append(f"{key:<44}{rec.count:>10}"
                             f"{rec.total_bytes / 2**20:>14.2f}")
            wall = dict(self._wall)
        if show_straggler:
            lines.append("")
            lines.append(f"{'wall-clock (per host)':<44}{'self s':>10}"
                         f"{'min s':>10}{'max s':>10}")
            for name, mine, lo, hi in self._straggler_rows(wall):
                lines.append(f"{name:<44}{mine:>10.3f}{lo:>10.3f}"
                             f"{hi:>10.3f}")
        table = "\n".join(lines)
        from ..utils.logging import logger

        logger.info("\n%s", table)
        return table

    @staticmethod
    def _straggler_rows(wall: Dict[str, float]):
        """[(name, self, min, max)] across controllers, via ONE collective.

        COLLECTIVE CONTRACT: under multiple controllers every host must call
        ``log_summary(show_straggler=True)`` together (like any collective)
        with the SAME set of timed names — a rank-0-only call would hang at
        the gather. Name-set agreement is verified by gathering a digest in
        the same call; disagreement raises instead of silently misaligning
        columns."""
        import jax
        import numpy as np

        names = sorted(wall)
        vals = np.asarray([wall[n] for n in names], np.float64)
        if jax.process_count() == 1:
            return [(n, wall[n], wall[n], wall[n]) for n in names]
        import hashlib

        from jax.experimental import multihost_utils

        digest = np.frombuffer(hashlib.sha256(
            "|".join(names).encode()).digest()[:8], np.uint64)[0]
        gathered = multihost_utils.process_allgather(
            {"digest": digest, "vals": vals})
        if not (np.asarray(gathered["digest"]) ==
                gathered["digest"][0]).all():
            raise RuntimeError(
                "show_straggler: hosts timed different op names — every "
                "controller must record the same wall-clock keys")
        allv = np.asarray(gathered["vals"])  # [hosts, names]
        return [(n, wall[n], float(allv[:, i].min()),
                 float(allv[:, i].max())) for i, n in enumerate(names)]

    def summary_events(self, step: int):
        """Per-op monitor events under the declared ``Comm/`` family
        (``monitor/telemetry.py`` EVENT_PREFIXES) — how the comms island
        feeds the shared observability stream. Keys are sanitized to the
        ``Group/name`` charset (``all-reduce[data]`` → ``all-reduce.data``)."""
        import re as _re

        events = []
        with self._lock:
            for key in sorted(self._records):
                rec = self._records[key]
                name = _re.sub(r"[^\w.\-]", ".", key).strip(".")
                events.append((f"Comm/{name}/count", rec.count, step))
                events.append((f"Comm/{name}/bytes", rec.total_bytes, step))
        return events

    def reset(self):
        with self._lock:
            self._records.clear()
            self._wall.clear()

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {k: {"count": v.count, "total_bytes": v.total_bytes}
                    for k, v in self._records.items()}


comms_logger = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return comms_logger
