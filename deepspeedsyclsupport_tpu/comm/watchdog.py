"""Collective hang watchdog: deadline-stamped dispatch, structured rc-218.

At pod scale the dominant non-crash failure is the *silent* hang: one rank
stalls (bad host, stuck IO, kernel livelock) before an all-reduce, and every
sibling spins inside the collective forever — no exception, no exit code,
nothing for a supervisor to react to until a generic timeout guesses. This
module turns that into a structured contract:

* The engine **arms** the watchdog immediately before dispatching a step's
  collective phase and **disarms** it once the step's results are back. The
  arm stamps a ``comm/arm`` record (step + deadline + rank) into the flight
  recorder; the existing per-step ``step`` span is the post-dispatch record
  — so the pair survives on disk even when the process is killed mid-hang,
  and ``tools/pod_report.py`` can name the rank that *never armed* (never
  arrived) vs the ranks that armed and waited.
* A daemon thread polls the armed deadline. On expiry it force-writes a
  ``faulthandler`` all-thread stack dump (the main thread is wedged inside
  XLA — it cannot report itself), records a ``comm/hang`` event, flushes
  the flight recorder, bumps ``Resilience/comm_hang_aborts`` and exits the
  process with :data:`COMM_HANG_EXIT_CODE` (rc 218).
* The elastic agent (``elasticity/elastic_agent.py``) recognizes rc 218 as
  a *comm hang*: counted and restarted distinctly from a crash (rc≠0) and
  a preemption (rc 217), and the whole pod is torn down promptly instead
  of waiting for siblings to cascade.

The first armed step covers compilation (jit cache miss inside the dispatch
call), so it gets ``warmup_deadline_s``; every later step uses
``deadline_s``. Exit is ``os._exit`` by design: the main thread is stuck in
a C extension and ``sys.exit`` from a sibling thread would never unwind it.

Async-dispatch caveat: without ``telemetry.sync_timing`` the armed window
covers the dispatch call, and a purely device-side hang is detected when
XLA's bounded in-flight queue blocks a *later* dispatch inside its armed
window — rc 218 still fires within ~deadline of the queue filling, but the
attributed step can trail the wedged one by the queue depth. Enable
``sync_timing`` for exact-step windows (trades the dispatch/compute
overlap — the <5% overhead guard runs without it).
"""
import os
import threading
import time
from typing import Any, Callable, Optional, Tuple

from ..utils.logging import logger

# Distinguished "a collective deadline expired" exit code: adjacent to the
# preemption contract's 217, outside the shell's 126/127/128+N ranges, and
# mirrored by the elastic agent's per-cause restart accounting.
COMM_HANG_EXIT_CODE = 218

# Distinguished "a serving decode dispatch expired its deadline" exit code
# (the serving-plane sibling of 218 — `inference/v2/supervisor.py` and the
# elastic agent count it as its own restart class; docs/serving.md's
# failure contract). Defined here next to its training-plane twin so the
# supervisor/agent import stays jax-free.
SERVE_HANG_EXIT_CODE = 219


class CollectiveWatchdog:
    """Deadline watch over one engine's collective phase.

    ``arm``/``disarm`` are step-path calls: one attribute store plus one
    flight-recorder append each (<5% overhead guard in the tier-1 suite
    covers them). The hot-path state is a single tuple attribute —
    GIL-atomic to publish, so the poller thread never needs the step path
    to take a lock.

    The deadline/abort machinery is plane-agnostic: the serving layer
    (``inference/v2/serving.py``) runs the SAME class around its decode
    dispatches with ``exit_code=SERVE_HANG_EXIT_CODE``, its own resilience
    counter and ``serve/arm``/``serve/hang`` record names — one watchdog
    implementation, two structured-exit contracts (rc 218 / rc 219).
    """

    def __init__(self, deadline_s: float, warmup_deadline_s: Optional[float]
                 = None, poll_s: float = 0.25, rank: int = 0,
                 telemetry: Any = None, stack_path: Optional[str] = None,
                 exit_fn: Optional[Callable[[int], None]] = None,
                 exit_code: int = COMM_HANG_EXIT_CODE,
                 abort_counter: str = "comm_hang_aborts",
                 arm_name: str = "comm/arm", hang_name: str = "comm/hang",
                 what: str = "collective"):
        if deadline_s <= 0:
            raise ValueError(f"watchdog deadline_s must be > 0, "
                             f"got {deadline_s}")
        self.deadline_s = float(deadline_s)
        # the first dispatch compiles; default warmup allowance is 10x
        self.warmup_deadline_s = float(warmup_deadline_s
                                       if warmup_deadline_s is not None
                                       else 10.0 * deadline_s)
        self.poll_s = float(poll_s)
        self.rank = int(rank)
        self.telemetry = telemetry
        self.stack_path = stack_path
        self._exit_fn = exit_fn or os._exit
        self.exit_code = int(exit_code)
        self.abort_counter = abort_counter
        self.arm_name = arm_name
        self.hang_name = hang_name
        self.what = what
        #: (step, armed_at_monotonic, deadline_s) while a collective phase
        #: is in flight, else None — published with one attribute store
        self._inflight: Optional[Tuple[int, float, float]] = None
        self._completed_once = False
        self._fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ step path
    def arm(self, step: int, deadline_s: Optional[float] = None) -> float:
        """Pre-dispatch stamp: record the deadline and publish the in-flight
        marker. Returns the deadline used."""
        d = float(deadline_s if deadline_s is not None else
                  (self.deadline_s if self._completed_once
                   else self.warmup_deadline_s))
        rec = self._recorder()
        if rec is not None:
            rec.record("event", self.arm_name, step=step,
                       data={"deadline_s": d, "rank": self.rank})
        self._inflight = (int(step), time.monotonic(), d)
        return d

    def disarm(self, step: int) -> None:
        """Post-dispatch stamp: the step's results are back — the per-step
        ``step`` span the engine records right after is the durable post
        record, so disarm itself writes nothing."""
        self._inflight = None
        self._completed_once = True

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "CollectiveWatchdog":
        if self._thread is None:
            self._thread = threading.Thread(target=self._watch, daemon=True,
                                            name="dstpu-comm-watchdog")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll_s + 1.0)
            self._thread = None

    # ------------------------------------------------------------- watching
    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            s = self._inflight
            if s is None:
                continue
            step, armed_at, deadline = s
            waited = time.monotonic() - armed_at
            if waited <= deadline:
                continue
            # re-check identity: a disarm/arm race between reads must not
            # fire on a step that actually completed
            if self._inflight is not s:
                continue
            self._fire(step, waited, deadline)
            return

    def _fire(self, step: int, waited: float, deadline: float) -> None:
        if self._fired:  # pragma: no cover - defensive re-entry guard
            return
        self._fired = True
        from ..monitor.monitor import resilience_counters

        resilience_counters.incr(self.abort_counter)
        logger.error(
            "%s watchdog: step %d in flight %.1fs > deadline %.1fs "
            "— rank %d declares a hang; dumping stacks and exiting "
            "rc=%d", self.what, step, waited, deadline, self.rank,
            self.exit_code)
        self._dump_stacks()
        rec = self._recorder()
        if rec is not None:
            try:
                rec.record("event", self.hang_name, step=step,
                           data={"waited_s": round(waited, 3),
                                 "deadline_s": deadline, "rank": self.rank})
            except Exception:  # pragma: no cover - never block the exit
                pass
        if self.telemetry is not None:
            try:  # force the ring (arm records included) onto disk
                self.telemetry.dump(self.hang_name.replace("/", "_"))
            except Exception as e:  # pragma: no cover
                logger.warning("watchdog telemetry dump failed: %s", e)
        self._exit_fn(self.exit_code)

    def _dump_stacks(self) -> None:
        """All-thread faulthandler dump: the main thread is wedged inside a
        collective and cannot report itself."""
        import faulthandler

        try:
            if self.stack_path:
                with open(self.stack_path, "a") as f:
                    label = self.arm_name.split("/", 1)[0]  # comm | serve
                    f.write(f"\n=== {label} watchdog fired "
                            f"(rank {self.rank}, pid {os.getpid()}) ===\n")
                    f.flush()
                    faulthandler.dump_traceback(file=f, all_threads=True)
            else:
                faulthandler.dump_traceback(all_threads=True)
        except Exception as e:  # pragma: no cover - diagnostics best-effort
            logger.warning("watchdog stack dump failed: %s", e)

    def _recorder(self):
        t = self.telemetry
        return None if t is None else getattr(t, "recorder", None)
