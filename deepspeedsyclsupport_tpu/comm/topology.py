"""Named-axis device mesh topology.

TPU-native analog of the reference's process-group topology layer:

* ``deepspeed/utils/groups.py:51-560`` — lazy creation of data/model/expert/sequence
  parallel process groups with accessors (``_get_data_parallel_group`` etc.).
* ``deepspeed/runtime/pipe/topology.py:12,251`` — ``ProcessTopology`` /
  ``PipelineParallelGrid`` mapping ranks onto (pipe, data, model) axes.

Where the reference materializes NCCL/oneCCL communicators per group, the TPU design
materializes **one** :class:`jax.sharding.Mesh` with named axes; XLA derives every
"group" from sharding specs, and collectives ride ICI/DCN automatically. The axis order
encodes physical locality: the innermost (fastest-varying) axes land on adjacent chips
(ICI neighbors), the outermost on DCN.  Tensor parallelism is the most
latency-sensitive, so ``model`` is innermost; ``pipe`` tolerates DCN, so it is outermost.

Axis vocabulary (superset of the reference's pipe/data/model):

===========  =====================================================================
``data``     pure data parallel (gradient psum)                 [engine.py:1903]
``fsdp``     ZeRO parameter/grad/optimizer sharding             [zero/stage*.py]
``pipe``     pipeline stages                                    [runtime/pipe/]
``expert``   expert parallel for MoE                            [moe/sharded_moe.py]
``seq``      Ulysses sequence parallel                          [sequence/layer.py]
``model``    tensor parallel (Megatron-style mpu)               [module_inject/auto_tp.py]
===========  =====================================================================
"""
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# Outer-to-inner physical layout order (outermost = DCN-tolerant).
AXIS_ORDER: Tuple[str, ...] = ("pipe", "data", "fsdp", "expert", "seq", "model")

_WORLD_TOPOLOGY: Optional["MeshTopology"] = None


@dataclass
class MeshTopology:
    """One named mesh carrying every parallelism axis.

    Analog of ``PipelineParallelGrid`` (reference ``topology.py:251``) generalized to
    all six axes. ``axis_sizes`` maps axis name → size; any axis may be absent
    (size 1). At most one axis may be ``-1`` meaning "consume remaining devices".
    """

    axis_sizes: Dict[str, int]
    devices: Optional[Sequence[Any]] = None
    _mesh: Any = field(default=None, repr=False)

    def __post_init__(self):
        from jax.sharding import Mesh

        if self.devices is not None:
            devs = list(self.devices)
        else:
            # Route through the accelerator seam (SURVEY.md §1 invariant: every
            # device touch goes through get_accelerator()) so DSTPU_ACCELERATOR=cpu
            # builds the mesh from virtual host devices even when a real TPU is the
            # default jax backend.
            from ..accelerator import get_accelerator

            devs = get_accelerator().devices()
        n = len(devs)
        sizes = {ax: int(self.axis_sizes.get(ax, 1)) for ax in AXIS_ORDER}
        unknown = set(self.axis_sizes) - set(AXIS_ORDER)
        if unknown:
            raise ValueError(f"Unknown mesh axes {unknown}; valid: {AXIS_ORDER}")
        wild = [ax for ax, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError("At most one axis may be -1 (auto-fill)")
        fixed = int(np.prod([s for s in sizes.values() if s != -1]))
        if wild:
            if n % fixed != 0:
                raise ValueError(
                    f"Device count {n} not divisible by fixed axes product {fixed}")
            sizes[wild[0]] = n // fixed
        total = int(np.prod(list(sizes.values())))
        if total != n:
            raise ValueError(
                f"Mesh axes {sizes} multiply to {total} but {n} devices are visible")
        self.axis_sizes = sizes
        grid = np.asarray(devs).reshape([sizes[ax] for ax in AXIS_ORDER])
        self._mesh = Mesh(grid, AXIS_ORDER)

    # ------------------------------------------------------------------ mesh access
    @property
    def mesh(self):
        return self._mesh

    def __enter__(self):
        return self._mesh.__enter__()

    def __exit__(self, *a):
        return self._mesh.__exit__(*a)

    def axis_size(self, axis: str) -> int:
        return self.axis_sizes[axis]

    # Accessor parity with deepspeed/utils/groups.py ---------------------------
    def get_data_parallel_world_size(self) -> int:
        """DP replicas = data × fsdp (ZeRO shards are still data-parallel replicas
        from the model's point of view, matching the reference where ZeRO partitions
        *within* the DP group)."""
        return self.axis_sizes["data"] * self.axis_sizes["fsdp"]

    def get_model_parallel_world_size(self) -> int:
        return self.axis_sizes["model"]

    def get_pipe_parallel_world_size(self) -> int:
        return self.axis_sizes["pipe"]

    def get_expert_parallel_world_size(self) -> int:
        return self.axis_sizes["expert"]

    def get_sequence_parallel_world_size(self) -> int:
        return self.axis_sizes["seq"]

    def get_fsdp_world_size(self) -> int:
        return self.axis_sizes["fsdp"]

    def world_size(self) -> int:
        return int(np.prod(list(self.axis_sizes.values())))

    # ------------------------------------------------------------------ sharding
    def sharding(self, *spec_axes) -> Any:
        """NamedSharding for a PartitionSpec given per-dimension axis names.

        ``topo.sharding(('data','fsdp'), None, 'model')`` shards dim0 over data+fsdp,
        replicates dim1, shards dim2 over model.
        """
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self._mesh, PartitionSpec(*spec_axes))

    def replicated(self) -> Any:
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self._mesh, PartitionSpec())

    @property
    def batch_axes(self) -> Tuple[str, ...]:
        """Axes over which the global batch is split (data + fsdp)."""
        return tuple(ax for ax in ("data", "fsdp") if self.axis_sizes[ax] > 1) or ("data",)

    def data_sharding(self, ndim: int) -> Any:
        """Standard input-batch sharding: dim0 over (data, fsdp), rest replicated."""
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self._mesh, PartitionSpec(("data", "fsdp"),
                                                       *([None] * (ndim - 1))))


def build_topology(dp: int = -1,
                   fsdp: int = 1,
                   tp: int = 1,
                   pp: int = 1,
                   ep: int = 1,
                   sp: int = 1,
                   devices: Optional[Sequence[Any]] = None) -> MeshTopology:
    """Build and install the world topology (reference: ``groups.initialize()``,
    ``deepspeed/utils/groups.py:51``)."""
    topo = MeshTopology(
        axis_sizes={"data": dp, "fsdp": fsdp, "model": tp, "pipe": pp,
                    "expert": ep, "seq": sp},
        devices=devices,
    )
    set_world_topology(topo)
    return topo


def set_world_topology(topo: MeshTopology) -> None:
    global _WORLD_TOPOLOGY
    _WORLD_TOPOLOGY = topo


def get_world_topology() -> MeshTopology:
    global _WORLD_TOPOLOGY
    if _WORLD_TOPOLOGY is None:
        _WORLD_TOPOLOGY = build_topology()
    return _WORLD_TOPOLOGY


def reset_world_topology() -> None:
    global _WORLD_TOPOLOGY
    _WORLD_TOPOLOGY = None
