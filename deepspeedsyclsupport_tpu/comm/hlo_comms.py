"""Post-compile accounting of XLA-inserted collectives.

The façade logger (``comms_logging.py``) sees only EXPLICIT collective
calls; under SPMD most traffic — every stage-2/3 all-gather and
reduce-scatter the partitioner inserts — never passes through it. This
module closes that gap (reference: per-op logging in ``comm/comm.py:101``
has the same blind spot for its fused paths, which is why its
``log_summary`` is authoritative there and ours must read the compiled
program): walk the optimized HLO of a compiled step and tally every
collective op's payload bytes.

The parse works on the compiled module text (``Compiled.as_text()``) —
stable, version-robust fields: result shape, opcode, replica_groups.
"""
import re
from collections import defaultdict
from typing import Any, Dict, List, Optional

import numpy as np

# opcodes that move data between devices (start/done pairs counted once)
COLLECTIVE_OPS = (
    "all-gather", "all-reduce", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast", "ragged-all-to-all",
)

_DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "s8": 1, "u2": 1, "u4": 1, "u8": 1,
    "s16": 2, "u16": 2, "s32": 4, "u32": 4, "s64": 8, "u64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1, "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"=\s*((?:\([^)]*\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?))\s+"
    r"(" + "|".join(COLLECTIVE_OPS) + r")(-start|-done)?\(")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[^}]*\}(?:,\{[^}]*\})*\}|\[[0-9,]+\]<=\[[0-9,]+\])")


def _shape_bytes(shape_text: str) -> int:
    """Total bytes of a shape expression — 'f32[8,128]{1,0}' or a tuple
    '(bf16[4,2], u32[4])'."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue  # token like an opcode; shapes only
        n = 1
        if dims:
            n = int(np.prod([int(d) for d in dims.split(",")]))
        total += n * _DTYPE_BYTES[dtype]
    return total


def _group_size(line: str) -> Optional[int]:
    m = _GROUPS_RE.search(line)
    if not m:
        return None
    text = m.group(1)
    if text.startswith("{{"):
        first = text[2:].split("}", 1)[0]
        return len([t for t in first.split(",") if t.strip()])
    # iota form [N,M]<=[...]: groups of size M
    dims = text[1:].split("]", 1)[0].split(",")
    return int(dims[-1])


def parse_collectives(hlo_text: str) -> List[Dict[str, Any]]:
    """Every data-moving collective in a compiled HLO module."""
    out = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.search(line)
        if not m:
            continue
        shape_text, opcode, phase = m.group(1), m.group(2), m.group(3)
        if phase == "-done":
            continue  # the -start carries the payload; count pairs once
        if phase == "-start" and shape_text.startswith("("):
            # async start results are (aliased operand(s), output): only the
            # LAST tuple element is the payload actually moved — counting
            # the whole tuple would ~double every async collective
            shape_text = shape_text.rstrip(")").rsplit(",", 1)[-1].strip()
        out.append({
            "op": opcode,
            "bytes": _shape_bytes(shape_text),
            "shape": shape_text.split("{")[0],
            "group_size": _group_size(line),
        })
    return out


def summarize_collectives(hlo_text: str) -> Dict[str, Dict[str, Any]]:
    """{opcode: {count, total_bytes, example_shape, group_size}}."""
    summary: Dict[str, Dict[str, Any]] = defaultdict(
        lambda: {"count": 0, "total_bytes": 0, "example_shape": None,
                 "group_size": None})
    for rec in parse_collectives(hlo_text):
        s = summary[rec["op"]]
        s["count"] += 1
        s["total_bytes"] += rec["bytes"]
        if s["example_shape"] is None or rec["bytes"] > _shape_bytes(
                s["example_shape"] or ""):
            s["example_shape"] = rec["shape"]
        if rec["group_size"]:
            s["group_size"] = rec["group_size"]
    return dict(summary)


def summarize_compiled(compiled) -> Dict[str, Dict[str, Any]]:
    """Summary from a ``jax.stages.Compiled`` (or anything with
    ``as_text()``)."""
    return summarize_collectives(compiled.as_text())
