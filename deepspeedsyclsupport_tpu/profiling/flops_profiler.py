"""FLOPS profiler.

Analog of ``FlopsProfiler`` (``deepspeed/profiling/flops_profiler/profiler.py:28``,
1348 LoC). The reference monkey-patches ``torch.nn.functional`` and installs module
hooks to count MACs at runtime; under JAX the program is a closed jaxpr, so the
count is STATIC analysis — walk the jaxpr for an exact per-primitive breakdown and
cross-check with XLA's own ``cost_analysis`` on the compiled executable. No hooks,
no patching, no runtime overhead.

Engine integration mirrors the reference's ``flops_profiler_profile_step``
(``engine.py:1793,2190``): at the configured step the engine profiles its jitted
train function and logs total GFLOPs, parameter count, and achieved TFLOPS.
"""
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist


@dataclass
class Profile:
    total_flops: float                 # analytical, fwd(+bwd if grad traced)
    total_params: int
    by_primitive: Dict[str, float] = field(default_factory=dict)
    xla_flops: Optional[float] = None  # compiler's own count, when available

    def flops_str(self) -> str:
        return _human(self.total_flops, "FLOPs")

    def summary(self, top: int = 10) -> str:
        lines = [f"params: {_human(self.total_params, '')}",
                 f"flops:  {self.flops_str()}"]
        if self.xla_flops:
            lines.append(f"xla cost_analysis flops: "
                         f"{_human(self.xla_flops, 'FLOPs')}")
        worst = sorted(self.by_primitive.items(), key=lambda kv: -kv[1])[:top]
        width = max((len(k) for k, _ in worst), default=0)
        for k, v in worst:
            share = 100.0 * v / max(self.total_flops, 1.0)
            lines.append(f"  {k:<{width}} {_human(v, 'FLOPs'):>12} "
                         f"({share:4.1f}%)")
        return "\n".join(lines)


def _human(x: float, unit: str) -> str:
    for scale, pfx in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(x) >= scale:
            return f"{x / scale:.2f} {pfx}{unit}"
    return f"{x:.0f} {unit}"


# --------------------------------------------------------------- jaxpr walking
def _dot_flops(eqn) -> float:
    """2 × (batch · M · N · K) for dot_general."""
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    dnums = eqn.params["dimension_numbers"]
    (lc, rc), (lb, rb) = dnums
    batch = np.prod([lhs.shape[d] for d in lb], dtype=float) if lb else 1.0
    contract = np.prod([lhs.shape[d] for d in lc], dtype=float) if lc else 1.0
    m = np.prod([lhs.shape[d] for d in range(lhs.ndim)
                 if d not in lc and d not in lb], dtype=float)
    n = np.prod([rhs.shape[d] for d in range(rhs.ndim)
                 if d not in rc and d not in rb], dtype=float)
    return 2.0 * batch * m * n * contract


def _elementwise_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    return float(np.prod(out.shape, dtype=float)) if out.shape else 1.0


def _reduction_flops(eqn) -> float:
    """Reductions/scans cost ~one op per INPUT element, not per output."""
    inp = eqn.invars[0].aval
    return float(np.prod(inp.shape, dtype=float)) if inp.shape else 1.0


_REDUCTIONS = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
               "cumsum", "cumlogsumexp", "cummax", "cummin", "cumprod",
               "argmax", "argmin"}


_CHEAP = {"add", "sub", "mul", "div", "max", "min", "neg", "abs", "exp", "log",
          "tanh", "logistic", "rsqrt", "sqrt", "pow", "integer_pow", "erf",
          "select_n", "clamp", "sign", "floor", "ceil", "round", "cos", "sin",
          "square", "reciprocal", "logaddexp", "atan2", "expm1", "log1p"}


def eqn_flops(eqn) -> Optional[float]:
    """Analytic FLOPs of ONE leaf equation, or ``None`` for primitives
    this model doesn't cost (data movement, control flow). Shared by the
    per-primitive totals below and the per-region roofline partition
    (``analysis/roofline.py``) so both count with identical rules."""
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_flops(eqn)
    if name == "conv_general_dilated":
        out = eqn.outvars[0].aval
        rhs = eqn.invars[1].aval
        dn = eqn.params["dimension_numbers"]
        k_spatial = np.prod([rhs.shape[d] for d in dn.rhs_spec[2:]],
                            dtype=float)
        cin = rhs.shape[dn.rhs_spec[1]]
        return 2.0 * np.prod(out.shape, dtype=float) * k_spatial * cin
    if name in _REDUCTIONS:
        return _reduction_flops(eqn)
    if name in _CHEAP:
        return _elementwise_flops(eqn)
    return None


def count_jaxpr_flops(jaxpr, by: Optional[Dict[str, float]] = None,
                      mult: float = 1.0) -> Dict[str, float]:
    """Per-primitive FLOP count over the recursive equation stream
    (``analysis/jaxpr_walk.py`` — scan bodies multiply by static trip
    count; cond sums every branch, an over-approximation that is ~exact
    for the skip-vs-run pattern where the skip branch is empty)."""
    from ..analysis.jaxpr_walk import iter_eqns

    by = by if by is not None else {}
    for eqn, eq_mult in iter_eqns(jaxpr, mult):
        f = eqn_flops(eqn)
        if f is not None:
            name = eqn.primitive.name
            by[name] = by.get(name, 0.0) + f * eq_mult
    return by


# ------------------------------------------------------------------ public API
def profile_fn(fn: Callable, *args, static_argnums=(), xla_check: bool = False,
               **kwargs) -> Profile:
    """Profile any jittable callable on example args (shapes matter, values
    don't — tracing only, nothing executes on device).

    ``xla_check=True`` additionally COMPILES ``fn`` to read XLA's own
    ``cost_analysis`` — a full compile of the program (minutes for big train
    steps), so it is opt-in and never used by the engine hook. Note XLA counts
    loop bodies once (trip counts ignored), so the analytical number is the
    meaningful one.
    """
    closed = jax.make_jaxpr(fn, static_argnums=static_argnums)(*args, **kwargs)
    by = count_jaxpr_flops(closed.jaxpr)
    total = float(sum(by.values()))
    n_params = int(sum(np.prod(np.shape(a), dtype=np.int64)
                       for a in jax.tree_util.tree_leaves(args[0])
                       )) if args else 0
    xla = None
    if xla_check:
        try:
            cost = jax.jit(fn, static_argnums=static_argnums).lower(
                *args, **kwargs).compile().cost_analysis()
            if cost:
                xla = float(cost.get("flops", 0.0)) or None
        except Exception:  # cost analysis is best-effort (backend-dependent)
            pass
    return Profile(total_flops=total, total_params=n_params,
                   by_primitive=by, xla_flops=xla)


def get_model_profile(model, batch_size: int = 1, seq_len: int = 128,
                      params: Any = None) -> Profile:
    """Model-level convenience (reference ``get_model_profile``): profiles one
    forward of a ``models.CausalLM``-protocol model."""
    import jax.numpy as jnp

    params = params if params is not None else model.init_params()
    ids = jnp.zeros((batch_size, seq_len), jnp.int32)
    return profile_fn(lambda p, x: model.apply(p, x), params, ids)


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler`` lifecycle:
    start/stop/print at ``flops_profiler_profile_step``)."""

    def __init__(self, engine):
        self.engine = engine
        self.profile: Optional[Profile] = None

    def maybe_profile(self, train_fn, args: Tuple) -> None:
        cfg = self.engine.config.flops_profiler
        if not cfg.enabled or self.engine.global_steps != cfg.profile_step:
            return
        self.profile = profile_fn(train_fn, *args)
        text = ("flops profiler @ step "
                f"{self.engine.global_steps}:\n{self.profile.summary()}")
        if cfg.output_file:
            with open(cfg.output_file, "w") as f:
                f.write(text + "\n")
        log_dist(text)
