"""Multinode launch backends — pdsh / OpenMPI / MPICH / Intel-MPI / SLURM /
MVAPICH command-line generation.

Analog of ``deepspeed/launcher/multinode_runner.py:18-460``: each runner
class knows how to turn (hostfile world, user script, exports) into the one
fan-out command its scheduler understands. The reference spawns one process
per GPU through its per-node ``launch.py``; under JAX's multi-controller
model one process per HOST drives all local chips, so every runner here
launches exactly ``len(hosts)`` processes (or ``procs_per_node`` for
CPU-sim worlds) and relies on ``comm.init_distributed``'s env discovery —
torch-style MASTER_ADDR/RANK, OMPI_*, PMI_*, SLURM_* — to rendezvous
(reference ``mpi_discovery``, ``comm/comm.py:673``).

Selected via ``dstpu --launcher {ssh,pdsh,openmpi,mpich,impi,slurm,mvapich}``;
``--launcher_args`` passes scheduler-specific flags through verbatim.
"""
import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import quote, split
from typing import Dict, List, Tuple

PDSH_MAX_FAN_OUT = 1024


class MultiNodeRunner(ABC):
    """One launch backend (reference ``MultiNodeRunner``,
    ``launcher/multinode_runner.py:18``)."""

    def __init__(self, args, hosts: List[Tuple[str, int]]):
        self.args = args
        self.hosts = hosts
        self.exports: Dict[str, str] = {}
        self.validate_args()

    @property
    def name(self) -> str:
        return self.__class__.__name__.replace("Runner", "").lower()

    @abstractmethod
    def backend_exists(self) -> bool:
        """Whether the backend binary is on PATH."""

    @abstractmethod
    def get_cmd(self) -> List[str]:
        """The single fan-out command launching the whole world."""

    def add_export(self, key: str, value: str) -> None:
        self.exports[key.strip()] = str(value).strip()

    def validate_args(self) -> None:
        pass

    # ------------------------------------------------------------- helpers
    @property
    def procs_per_node(self) -> int:
        return max(getattr(self.args, "num_procs", 1), 1)

    @property
    def world_size(self) -> int:
        return len(self.hosts) * self.procs_per_node

    @property
    def master_addr(self) -> str:
        return self.args.master_addr or self.hosts[0][0]

    def rendezvous_exports(self) -> Dict[str, str]:
        """Coordinator env every process needs; ranks come from the
        scheduler's own env (PMI/OMPI/SLURM discovery)."""
        return {"MASTER_ADDR": self.master_addr,
                "MASTER_PORT": str(self.args.master_port),
                **self.exports}

    def user_cmd(self) -> List[str]:
        cmd = [sys.executable, "-u"]
        if self.args.module:
            cmd.append("-m")
        return cmd + [self.args.user_script] + list(self.args.user_args)


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out (reference ``PDSHRunner:51``): one ssh-backed remote
    shell per host; ranks are derived from each host's position via the
    %n token replaced per-node by pdsh."""

    def backend_exists(self) -> bool:
        return bool(shutil.which("pdsh"))

    def get_cmd(self) -> List[str]:
        if self.procs_per_node != 1:
            raise ValueError("pdsh launches one controller per host; "
                             "num_procs>1 is a CPU-sim (ssh/local) feature")
        active = ",".join(h for h, _ in self.hosts)
        pdsh = ["pdsh", "-S", "-f", str(PDSH_MAX_FAN_OUT), "-w", active] \
            + split(self.args.launcher_args or "")
        env = dict(self.rendezvous_exports())
        env["WORLD_SIZE"] = str(self.world_size)
        env["LOCAL_RANK"] = "0"
        exports = "".join(f"export {k}={quote(v)}; " for k, v in env.items())
        # pdsh replaces %n with the node's index in -w order = its rank
        remote = (exports + "export RANK=%n; "
                  + f"cd {quote(os.path.abspath(os.getcwd()))}; "
                  + " ".join(quote(c) for c in self.user_cmd()))
        return pdsh + [remote]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun/ORTE (reference ``OpenMPIRunner:117``); ranks discovered from
    OMPI_COMM_WORLD_RANK by ``comm.init_distributed``."""

    def __init__(self, args, hosts):
        super().__init__(args, hosts)
        self.add_export("UCX_TLS", "tcp")

    def backend_exists(self) -> bool:
        return bool(shutil.which("ompi_info"))

    def get_cmd(self) -> List[str]:
        cmd = ["mpirun", "-n", str(self.world_size),
               "--host", ",".join(f"{h}:{self.procs_per_node}"
                                  for h, _ in self.hosts),
               "--mca", "btl", "^openib"] \
            + split(self.args.launcher_args or "")
        for k, v in self.rendezvous_exports().items():
            cmd += ["-x", f"{k}={v}"]
        return cmd + self.user_cmd()


class MPICHRunner(MultiNodeRunner):
    """Hydra mpirun (reference ``MPICHRunner:170``); PMI_RANK discovery."""

    def backend_exists(self) -> bool:
        return bool(shutil.which("mpirun"))

    def get_cmd(self) -> List[str]:
        cmd = ["mpirun", "-np", str(self.world_size),
               "-hosts", ",".join(h for h, _ in self.hosts),
               "-ppn", str(self.procs_per_node)] \
            + split(self.args.launcher_args or "")
        for k, v in self.rendezvous_exports().items():
            cmd += ["-genv", k, str(v)]
        return cmd + self.user_cmd()


class IMPIRunner(MPICHRunner):
    """Intel MPI (reference ``IMPIRunner:241``) — Hydra-compatible flags
    plus the I_MPI fabric pin the reference sets."""

    def __init__(self, args, hosts):
        super().__init__(args, hosts)
        self.add_export("I_MPI_FABRICS", "shm:ofi")

    def backend_exists(self) -> bool:
        return bool(shutil.which("mpiexec.hydra") or shutil.which("mpirun"))


class SlurmRunner(MultiNodeRunner):
    """srun (reference ``SlurmRunner:326``); SLURM_PROCID discovery."""

    def backend_exists(self) -> bool:
        return bool(shutil.which("sinfo"))

    def get_cmd(self) -> List[str]:
        cmd = ["srun", "-n", str(self.world_size),
               "--nodes", str(len(self.hosts)),
               "--ntasks-per-node", str(self.procs_per_node),
               "--nodelist", ",".join(h for h, _ in self.hosts)] \
            + split(self.args.launcher_args or "")
        exports = "--export=ALL"
        for k, v in self.rendezvous_exports().items():
            exports += f",{k}={v}"
        return cmd + [exports] + self.user_cmd()


class MVAPICHRunner(MPICHRunner):
    """MVAPICH2 (reference ``MVAPICHRunner:374``) — Hydra flags plus the
    MV2 env the reference pins for its fast path."""

    def __init__(self, args, hosts):
        super().__init__(args, hosts)
        self.add_export("MV2_SMP_USE_CMA", "0")
        self.add_export("MV2_DEBUG_SHOW_BACKTRACE", "1")

    def backend_exists(self) -> bool:
        if not shutil.which("mpiname"):
            return False
        try:
            import subprocess

            out = subprocess.run(["mpiname"], capture_output=True, text=True,
                                 timeout=5).stdout
            return "MVAPICH" in out
        except Exception:
            return False


RUNNERS = {
    "pdsh": PDSHRunner,
    "openmpi": OpenMPIRunner,
    "mpich": MPICHRunner,
    "impi": IMPIRunner,
    "slurm": SlurmRunner,
    "mvapich": MVAPICHRunner,
}


def build_runner(name: str, args, hosts: List[Tuple[str, int]]
                 ) -> MultiNodeRunner:
    if name not in RUNNERS:
        raise ValueError(f"unknown launcher {name!r}; choose from "
                         f"{['ssh'] + sorted(RUNNERS)}")
    return RUNNERS[name](args, hosts)
