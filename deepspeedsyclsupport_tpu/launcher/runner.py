"""Distributed launcher CLI.

Analog of the ``deepspeed`` CLI (``launcher/runner.py:388`` multi-node
orchestrator → per-node ``launcher/launch.py:132`` process spawner). The
reference's job: parse a hostfile, compute the world layout, ssh/pdsh to every
node, spawn one process per accelerator with RANK/LOCAL_RANK/WORLD_SIZE env,
and reap children on SIGTERM.

TPU shift: JAX is multi-controller — ONE process per host drives all local
chips, and ``jax.distributed.initialize`` replaces the env:// rendezvous. So
the launcher spawns one worker per node entry (or per ``--num_procs`` for
CPU-sim runs), wiring:

* ``DSTPU_COORDINATOR`` / ``JAX_COORDINATOR_ADDRESS`` — coordinator host:port
* ``DSTPU_PROCESS_ID`` / ``JAX_PROCESS_ID`` + ``JAX_NUM_PROCESSES``

``comm.init_distributed`` reads these (the same contract the reference's
launcher has with ``deepspeed.init_distributed``). Remote nodes get generated
ssh command lines (``--dry_run`` prints them; actual fan-out is deferred to
the cluster scheduler on TPU pods, where GKE/xmanager owns process placement).
"""
import argparse
import os
import shlex
import signal
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger

DEFAULT_MASTER_PORT = 29500


def parse_hostfile(path: str) -> List[Tuple[str, int]]:
    """Reference hostfile format: ``hostname slots=N`` per line
    (``launcher/runner.py`` ``fetch_hostfile``)."""
    out: List[Tuple[str, int]] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=", 1)[1])
            out.append((host, slots))
    if not out:
        raise ValueError(f"hostfile {path} has no host entries")
    return out


def build_world(args) -> List[Dict[str, str]]:
    """Per-process env blocks (the reference's RANK/WORLD_SIZE assembly in
    ``launcher/launch.py``, recast for one-controller-per-host)."""
    if args.hostfile:
        hosts = parse_hostfile(args.hostfile)
    else:
        hosts = [("localhost", 1)] * args.num_nodes
    if args.include:
        keep = set(args.include.split(","))
        hosts = [h for h in hosts if h[0] in keep]
    if args.exclude:
        drop = set(args.exclude.split(","))
        hosts = [h for h in hosts if h[0] not in drop]
    if not hosts:
        raise ValueError("no hosts remain after include/exclude filtering")

    coordinator = f"{args.master_addr or hosts[0][0]}:{args.master_port}"
    world = []
    n = len(hosts) * max(args.num_procs, 1)
    pid = 0
    for host, _slots in hosts:
        for local in range(max(args.num_procs, 1)):
            world.append({
                "host": host,
                # names comm.init_distributed reads directly
                "COORDINATOR_ADDRESS": coordinator,
                "NUM_PROCESSES": str(n),
                "PROCESS_ID": str(pid),
                # reference-compat env:// convention (init_distributed's
                # fallback, and what user scripts ported from upstream read)
                "MASTER_ADDR": coordinator.rsplit(":", 1)[0],
                "MASTER_PORT": coordinator.rsplit(":", 1)[1],
                "RANK": str(pid),
                "WORLD_SIZE": str(n),
                "LOCAL_RANK": str(local),
            })
            pid += 1
    return world


def _command(args, env: Dict[str, str]) -> List[str]:
    cmd = [sys.executable]
    if args.module:
        cmd.append("-m")
    cmd.append(args.user_script)
    cmd += args.user_args
    if getattr(args, "bind_cores_to_rank", False):
        # numa binding prefix (reference utils/numa.get_numactl_cmd +
        # launcher --bind_cores_to_rank): carve this rank's core slice
        from ..utils.numa import (check_for_numactl, get_numactl_cmd,
                                  parse_range_list)

        remote = env["host"] not in ("localhost", "127.0.0.1")
        core_list = getattr(args, "bind_core_list", None)
        if remote:
            # the launcher cannot see a remote host's /sys topology — an
            # explicit core list is the only sound basis, and membind is
            # skipped (numa-node ids would be the launcher's, not theirs)
            if not core_list:
                raise ValueError(
                    "--bind_cores_to_rank on remote hosts requires "
                    "--bind_core_list (the launcher cannot read the remote "
                    "NUMA topology)")
            prefix, _ = get_numactl_cmd(
                core_list, max(args.num_procs, 1), int(env["LOCAL_RANK"]),
                numa_nodes=[parse_range_list(core_list)])
        else:
            if not getattr(args, "dry_run", False) and not check_for_numactl():
                raise RuntimeError("--bind_cores_to_rank needs the numactl "
                                   "binary on PATH")
            prefix, _ = get_numactl_cmd(core_list, max(args.num_procs, 1),
                                        int(env["LOCAL_RANK"]))
        cmd = prefix + cmd
    if env["host"] not in ("localhost", "127.0.0.1"):
        exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items()
                           if k != "host")
        return ["ssh", env["host"], f"cd {shlex.quote(os.getcwd())} && "
                f"{exports} {' '.join(shlex.quote(c) for c in cmd)}"]
    return cmd


def main(argv=None) -> int:
    # allow_abbrev=False: the elastic branch re-invokes this launcher with
    # the elastic flags STRIPPED by exact name — an abbreviated flag
    # (--elastic) would survive the strip and recurse the agent forever
    p = argparse.ArgumentParser(
        prog="dstpu", description="deepspeedsyclsupport_tpu launcher "
        "(reference: the `deepspeed` CLI)", allow_abbrev=False)
    p.add_argument("--hostfile", default=None)
    p.add_argument("--num_nodes", "-N", type=int, default=1)
    p.add_argument("--num_procs", type=int, default=1,
                   help="processes per node (CPU-sim/multi-controller tests)")
    p.add_argument("--include", default=None, help="comma list of hosts")
    p.add_argument("--exclude", default=None)
    p.add_argument("--master_addr", default=None)
    p.add_argument("--master_port", type=int, default=DEFAULT_MASTER_PORT)
    p.add_argument("--module", "-m", action="store_true")
    p.add_argument("--launcher", default="ssh",
                   help="multinode backend: ssh (built-in fan-out, default) "
                        "or pdsh/openmpi/mpich/impi/slurm/mvapich "
                        "(reference launcher/multinode_runner.py)")
    p.add_argument("--launcher_args", default="",
                   help="extra flags passed through to the backend verbatim")
    p.add_argument("--elastic_training", action="store_true",
                   help="supervise under the elastic agent: re-discover "
                        "membership and restart on worker failure "
                        "(reference --elastic_training)")
    p.add_argument("--min_elastic_nodes", type=int, default=1)
    p.add_argument("--max_elastic_nodes", type=int, default=-1)
    p.add_argument("--deepspeed_config", default=None,
                   help="JSON config consulted by the elastic agent for "
                        "the elasticity batch math (also reachable from "
                        "user_args)")
    p.add_argument("--bind_cores_to_rank", action="store_true",
                   help="numactl-bind each local rank to its core slice "
                        "(reference --bind_cores_to_rank)")
    p.add_argument("--bind_core_list", default=None,
                   help="core list to carve (e.g. '0-31,64-95'); default all")
    p.add_argument("--dry_run", action="store_true",
                   help="print the per-process commands and exit")
    p.add_argument("user_script")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    if args.elastic_training:
        # wrap THIS launcher invocation (minus the elastic flags) under the
        # restart-supervising agent (reference: DSElasticAgent via
        # launcher/runner.py --elastic_training, elasticity/elastic_agent.py)
        import json as _json

        from ..elasticity.elastic_agent import DSElasticAgent

        raw = list(argv) if argv is not None else sys.argv[1:]
        inner, skip = [], False
        for tok in raw:
            if skip:
                skip = False
                continue
            if tok == "--elastic_training":
                continue
            if tok in ("--min_elastic_nodes", "--max_elastic_nodes"):
                skip = True
                continue
            if tok.startswith(("--min_elastic_nodes=",
                               "--max_elastic_nodes=")):
                continue
            inner.append(tok)
        cfg_path = args.deepspeed_config
        if cfg_path is None:
            for i, tok in enumerate(args.user_args):
                if tok == "--deepspeed_config" and \
                        i + 1 < len(args.user_args):
                    cfg_path = args.user_args[i + 1]
                    break
                if tok.startswith("--deepspeed_config="):
                    cfg_path = tok.split("=", 1)[1]
                    break
        ds_config = {}
        if cfg_path:
            with open(cfg_path) as f:
                ds_config = _json.load(f)
        agent = DSElasticAgent(
            [sys.executable, "-m",
             "deepspeedsyclsupport_tpu.launcher.runner"] + inner,
            ds_config, min_nodes=args.min_elastic_nodes,
            max_nodes=args.max_elastic_nodes, hostfile=args.hostfile)
        return agent.run()

    if args.launcher != "ssh":
        from .multinode_runner import build_runner

        hosts = (parse_hostfile(args.hostfile) if args.hostfile
                 else [("localhost", 1)] * args.num_nodes)
        runner = build_runner(args.launcher, args, hosts)
        cmd = runner.get_cmd()
        if args.dry_run:
            print(" ".join(shlex.quote(c) for c in cmd))
            return 0
        if not runner.backend_exists():
            raise RuntimeError(
                f"--launcher {args.launcher}: backend binary not found on "
                f"PATH (try --dry_run to inspect the command)")
        proc = subprocess.Popen(cmd, env={**os.environ},
                                start_new_session=True,
                                preexec_fn=_child_preexec)
        return supervise([proc])

    world = build_world(args)
    if args.dry_run:
        for env in world:
            cmd = _command(args, env)
            print(f"[{env['host']}:{env['PROCESS_ID']}] "
                  + " ".join(shlex.quote(c) for c in cmd))
        return 0
    procs = launch_world(args, world)
    return supervise(procs)


def launch_world(args, world: List[Dict[str, str]],
                 popen=subprocess.Popen) -> List[subprocess.Popen]:
    """Spawn every world entry (local exec or ssh fan-out — the reference's
    ``runner.py:388`` pdsh/ssh launch). Each child starts in its OWN
    process group so :func:`supervise` can reap the whole tree; ``popen``
    is injectable for stub-executor tests."""
    procs: List[subprocess.Popen] = []
    for env in world:
        cmd = _command(args, env)
        full_env = {**os.environ, **{k: v for k, v in env.items()
                                     if k != "host"}}
        procs.append(popen(cmd, env=full_env, start_new_session=True,
                           preexec_fn=_child_preexec))
    return procs


def _child_preexec():  # pragma: no cover - runs in the forked child
    """PR_SET_PDEATHSIG (Linux): if the LAUNCHER dies without running its
    handlers (SIGKILL, crash between spawn and supervise), each direct
    child still gets SIGTERM — new-session children would otherwise be
    orphaned holding the chips. No-op off Linux."""
    try:
        import ctypes

        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(1, signal.SIGTERM)  # PR_SET_PDEATHSIG = 1
    except Exception:
        pass


def _wait_all(procs: List[subprocess.Popen], grace: float) -> bool:
    """Poll until every proc is reaped or the window closes."""
    import time

    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs):
            return True
        time.sleep(0.05)
    return all(p.poll() is not None for p in procs)


def _terminate_tree(procs: List[subprocess.Popen],
                    grace: float = 5.0) -> None:
    """SIGTERM every child's process GROUP, escalate to SIGKILL after the
    grace window (reference ``launcher/launch.py:118``: terminate_process_
    tree on SIGTERM — children of children must not survive the launcher).
    """
    for p in procs:
        if p.poll() is None:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
    if _wait_all(procs, grace):
        return
    for p in procs:
        if p.poll() is None:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
    # SIGKILL delivery is asynchronous: on a loaded machine the child may
    # not be reapable for whole scheduler quanta after killpg() returns.
    # Callers (supervise fail-fast, the tests) rely on poll() being
    # conclusive once this returns, so wait the grace window again.
    _wait_all(procs, grace)


def supervise(procs: List[subprocess.Popen], grace: float = 5.0,
              poll_interval: float = 0.2) -> int:
    """Fail-fast supervision: SIGINT/SIGTERM fan out to every process
    group, and the first non-zero exit tears the world down (the
    reference's any-rank-failure semantics, ``launch.py`` main loop)."""
    import time

    pending_sig: List[Optional[int]] = [None]  # slot store; loop drains it

    def _on_signal(signum, frame):
        # store-only handler (the runtime/resilience.py contract, enforced
        # by dslint signal-handler-safety): logging here can deadlock on
        # the lock the interrupted frame holds, and _terminate_tree sleeps
        # up to `grace` seconds — both belong in the supervision loop
        pending_sig[0] = signum

    prev_int = signal.signal(signal.SIGINT, _on_signal)
    prev_term = signal.signal(signal.SIGTERM, _on_signal)
    try:
        while True:
            if pending_sig[0] is not None:
                signum = pending_sig[0]
                logger.warning("launcher: signal %d — terminating process "
                               "trees", signum)
                _terminate_tree(procs, grace)
                # a worker that caught the signal and exited by contract
                # keeps its rc: PREEMPTION_EXIT_CODE (217) must reach the
                # elastic agent for free-restart accounting, and any other
                # deliberate non-zero exit beats the generic 128+signum
                from ..runtime.resilience import PREEMPTION_EXIT_CODE

                codes = [p.poll() for p in procs]
                if any(c == PREEMPTION_EXIT_CODE for c in codes):
                    return PREEMPTION_EXIT_CODE
                bad = next((c for c in codes if c not in (None, 0)
                            and c > 0), None)
                return bad if bad is not None else 128 + signum
            codes = [p.poll() for p in procs]
            bad = next((c for c in codes if c not in (None, 0)), None)
            if bad is not None:
                alive = sum(c is None for c in codes)
                if alive:
                    logger.error(
                        "launcher: a process exited rc=%d; terminating the "
                        "remaining %d (fail-fast)", bad, alive)
                    _terminate_tree(procs, grace)
                return bad
            if all(c == 0 for c in codes):
                return 0
            time.sleep(poll_interval)
    finally:
        signal.signal(signal.SIGINT, prev_int)
        signal.signal(signal.SIGTERM, prev_term)


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
