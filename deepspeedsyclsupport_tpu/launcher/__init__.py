from .runner import main  # noqa: F401
