"""Parallel shell across the hostfile — the ``ds_ssh`` utility
(reference ``bin/ds_ssh``: pdsh a command to every host in the hostfile).

    dstpu-ssh -f hostfile -- uptime
    dstpu-ssh -f hostfile -- pkill -f train.py

(Pass the command as separate tokens, not one quoted string — each token
is quoted for the remote shell verbatim.)

Uses pdsh when present (the reference's only mode); falls back to plain
ssh fan-out so the tool works on hosts without pdsh installed.
"""
import argparse
import shlex
import shutil
import subprocess
import sys
from typing import List, Optional

from .runner import parse_hostfile

DEFAULT_HOSTFILE = "/job/hostfile"  # reference default


def build_commands(hosts: List[str], command: str,
                   launcher: str) -> List[List[str]]:
    if launcher == "pdsh":
        return [["pdsh", "-w", ",".join(hosts), command]]
    return [["ssh", h, command] for h in hosts]


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="dstpu-ssh",
        description="run a command on every hostfile host "
                    "(reference bin/ds_ssh)")
    p.add_argument("-f", "--hostfile", default=DEFAULT_HOSTFILE)
    p.add_argument("--launcher", choices=("auto", "pdsh", "ssh"),
                   default="auto")
    p.add_argument("--dry_run", action="store_true")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run (prefix with -- to stop parsing)")
    args = p.parse_args(argv)
    cmd_tokens = list(args.command)
    if cmd_tokens[:1] == ["--"]:  # strip only the LEADING separator —
        del cmd_tokens[0]         # later '--' tokens belong to the command
    if not cmd_tokens:
        p.error("no command given")
    # quote per token: the remote shell must see the caller's tokens, not
    # re-split spaces or expand metacharacters
    command = " ".join(shlex.quote(t) for t in cmd_tokens)
    try:
        hosts = [h for h, _ in parse_hostfile(args.hostfile)]
    except (OSError, ValueError) as e:
        p.error(f"hostfile {args.hostfile}: {e}")
    launcher = args.launcher
    if launcher == "auto":
        launcher = "pdsh" if shutil.which("pdsh") else "ssh"
    cmds = build_commands(hosts, command, launcher)
    if args.dry_run:
        for c in cmds:
            print(" ".join(shlex.quote(t) for t in c))
        return 0
    rc = 0
    procs = [subprocess.Popen(c) for c in cmds]
    for pr in procs:
        rc = pr.wait() or rc
    return rc


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
