"""Logging utilities (analog of ``deepspeed/utils/logging.py``: ``logger`` +
rank-filtered ``log_dist``)."""
import logging
import os
import sys
from typing import Iterable, Optional

LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


def _create_logger(name: str = "dstpu", level: Optional[int] = None) -> logging.Logger:
    lg = logging.getLogger(name)
    if lg.handlers:
        return lg
    level = level if level is not None else _env_level()
    lg.setLevel(level)
    lg.propagate = False
    handler = logging.StreamHandler(stream=sys.stdout)
    handler.setFormatter(logging.Formatter(LOG_FORMAT))
    lg.addHandler(handler)
    return lg


def _env_level() -> int:
    return getattr(logging, os.environ.get("DSTPU_LOG_LEVEL", "INFO").upper(), logging.INFO)


logger = _create_logger()


def log_dist(message: str, ranks: Optional[Iterable[int]] = None,
             level: int = logging.INFO) -> None:
    """Log only on the given process ranks (reference: ``utils/logging.py`` log_dist).

    ``ranks=None`` or containing -1 logs everywhere; default logs on rank 0 only.
    """
    import jax

    my_rank = jax.process_index()
    ranks = list(ranks) if ranks is not None else [0]
    if -1 in ranks or my_rank in ranks:
        logger.log(level, "[Rank %d] %s", my_rank, message)


def should_log_le(max_log_level_str: str) -> bool:
    return logger.getEffectiveLevel() <= getattr(logging, max_log_level_str.upper())
