"""Wall-clock + throughput timers.

Analog of ``deepspeed/utils/timer.py``: ``SynchronizedWallClockTimer`` (``timer.py:43``,
device-event based) and ``ThroughputTimer`` (``timer.py:198``, samples/sec + TFLOPS).

On TPU there are no user-visible device events; synchronization means draining XLA's
async dispatch (``jax.block_until_ready``) before reading the host clock. That is what
the reference's ``synchronize()`` effectively does on its accelerators too.
"""
import time
from typing import Any, Dict, List, Optional

from .logging import logger

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self._start: Optional[float] = None
        self._elapsed = 0.0
        self._record: List[float] = []
        self.started = False

    def start(self, sync: bool = False):
        if sync:
            _sync()
        self._start = time.perf_counter()
        self.started = True

    def stop(self, sync: bool = False, record: bool = True):
        if not self.started:
            return
        if sync:
            _sync()
        delta = time.perf_counter() - self._start
        self._elapsed += delta
        if record:
            self._record.append(delta)
        self.started = False
        # re-pointed island: timer intervals land in the flight recorder ring
        # (when one is active) so the step timeline shows fwd/bwd/step spans
        from ..monitor.telemetry import get_active_recorder

        rec = get_active_recorder()
        if rec is not None:
            rec.record("span", f"timer/{self.name}", dur=delta)

    def reset(self):
        self._start = None
        self._elapsed = 0.0
        self.started = False

    def elapsed(self, reset: bool = True) -> float:
        now = time.perf_counter()
        value = self._elapsed
        if self.started:
            value += now - self._start
        if reset:
            self._elapsed = 0.0
            if self.started:
                # rebase a running timer: without this the interval just
                # reported would be re-added by the subsequent stop()
                self._start = now
        return value

    def mean(self) -> float:
        return sum(self._record) / len(self._record) if self._record else 0.0


def _sync():
    import jax

    jax.effects_barrier()


class SynchronizedWallClockTimer:
    """Named timer registry (reference: ``utils/timer.py:43``)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False) -> str:
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {ms:.2f}ms")
        msg = " | ".join(parts)
        logger.info("time: %s", msg)
        return msg

    @staticmethod
    def memory_usage() -> str:
        from ..accelerator import get_accelerator

        stats = get_accelerator().memory_stats()
        in_use = stats.get("bytes_in_use", 0) / 2**30
        peak = stats.get("peak_bytes_in_use", 0) / 2**30
        return f"mem_in_use={in_use:.2f}GB peak={peak:.2f}GB"


class ThroughputTimer:
    """Samples/sec + TFLOPS reporting (reference: ``utils/timer.py:198``)."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: int = 50, monitor_memory: bool = False,
                 logging_fn=None):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = max(1, steps_per_output)
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or logger.info
        self.global_step_count = 0
        self.local_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self._start_time = 0.0
        self.started = False

    def update_epoch_count(self):
        self.local_step_count = 0

    def start(self):
        self._start_time = time.perf_counter()
        self.started = True

    def stop(self, global_step: bool = True, report_speed: bool = True):
        if not self.started:
            return
        self.started = False
        self.global_step_count += int(global_step)
        self.local_step_count += 1
        duration = time.perf_counter() - self._start_time
        if self.global_step_count > self.start_step:
            self.total_elapsed_time += duration
            self.step_elapsed_time += duration
            if report_speed and self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"step={self.global_step_count}, "
                    f"samples/sec={self.avg_samples_per_sec():.2f}, "
                    f"batch_time={duration:.3f}s")
                self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            steps = self.global_step_count - self.start_step
            return self.batch_size / (self.total_elapsed_time / steps)
        return 0.0
