"""Deterministic fault injection for resilience testing.

TPU fleet reality (arXiv 2011.03641's failure-domain analysis): VMs get
maintenance-evicted mid-step, NFS/GCS writes fail transiently, and async
writers can be arbitrarily delayed — so every one of those failure modes
needs a *deterministic* lever the test suite can pull. A single process-wide
:class:`FaultInjector` exposes injection points that the checkpoint writers
(``checkpoint/engine.py``), the async engine
(``checkpoint/ckpt_engine.py::AsyncCheckpointEngine``) and the preemption
handler (``runtime/resilience.py``) consult. All state is counter-based —
no wall-clock or RNG — so a given spec replays identically.

Spec (programmatic dict or JSON in the ``DSTPU_FAULT_INJECTION`` env var):

``{"write_fail":  {"match": "state.bin", "count": 2},``
``  "truncate":   {"match": "state.bin", "keep_bytes": 64, "count": 1},``
``  "async_delay": 0.05,``
``  "preempt_at_step": 3,``
``  "hang_step":   {"rank": 1, "step": 4, "seconds": 600},``
``  "kill_step":   {"rank": 1, "step": 4, "rc": 1},``
``  "tear_pod":    {"rank": 0, "skip": 1, "drop": "commit", "count": 1}}``

* ``write_fail`` — the next ``count`` storage writes whose target path
  contains ``match`` raise a transient :class:`OSError` (``EIO``) before any
  bytes hit disk. Paired with :func:`retry_io` this exercises the
  self-healing path.
* ``truncate`` — after a matching file is durably written, chop it to
  ``keep_bytes`` (or ``keep_fraction`` of its size): a torn write, exactly
  what a preemption mid-``write(2)`` leaves behind.
* ``async_delay`` — seconds the async checkpoint worker sleeps before
  touching storage, widening the save/shutdown race window.
* ``preempt_at_step`` — deliver one simulated preemption request at the
  first step boundary where ``global_steps >= N`` (consumed by
  ``runtime/resilience.py``), standing in for a real SIGTERM.

Pod-scale (comm-layer) faults — rank-targeted and one-shot, so a chosen
rank misbehaves deterministically while its siblings stay healthy:

* ``hang_step`` — rank ``rank`` blocks for ``seconds`` (default forever,
  i.e. until killed) *before dispatching* step ``step``'s collectives: the
  rank never arrives at the all-reduce, every sibling spins inside it, and
  the collective watchdog (``comm/watchdog.py``) is what ends the pod.
  Consumed by the engine at the top of ``train_batch``.
* ``kill_step`` — rank ``rank`` dies with ``os._exit(rc)`` (default 1) at
  the step boundary *after* completing step ``step``: a hard crash with no
  emergency save, exercising the agent's prompt sibling teardown.
* ``tear_pod`` — tears the two-phase pod-commit record of a checkpoint
  after the save claims durability: ``drop: "commit"`` deletes
  ``dstpu_commit.json`` (phase 2 never happened), ``drop: "rank_manifest"``
  deletes rank ``drop_rank``'s phase-1 manifest. ``skip`` healthy saves
  pass through first; only the actor ``rank`` performs the teardown (the
  files are shared). Consumed by ``checkpoint/engine.py::save_tree``.

Serving-plane faults (one-shot, consumed by
``inference/v2/serving.ServingSession`` / the KV block allocator — the
deterministic levers behind the crash-replay and stuck-decode contracts in
``docs/serving.md``):

* ``decode_wedge`` — ``{"round": N, "seconds": S}``: the serving session
  blocks for ``seconds`` (default: until killed) inside scheduling round
  ``N``'s dispatch window, AFTER the stuck-decode watchdog armed — so the
  session's own watchdog converts the wedge into rc 219
  (``SERVE_HANG_EXIT_CODE``).
* ``serve_crash`` — ``{"round": N}`` or ``{"tokens": N}`` (+ optional
  ``rc``, default 1): the serving process dies with ``os._exit(rc)`` at
  the start of scheduling round ``N`` / once ``N`` total tokens have been
  emitted — a hard mid-decode crash with no cleanup, exercising the
  request journal + replica-supervisor replay path.

  Both serving faults accept an optional ``attempt`` key: fire only in
  supervisor incarnation ``DSTPU_ELASTIC_ATTEMPT == attempt`` (the env
  spec is re-read by every restarted process — without the gate a
  one-shot fault would re-arm each incarnation and recovery could never
  complete).
* ``kv_alloc_fail`` — ``{"count": N}``: the next ``N`` KV block-pool
  allocations behave as exhausted (``BlockedAllocator.try_allocate``
  returns None). Exercises the structured-backpressure contract: an
  allocation failure must queue/shed through the session, never raise out
  of the engine loop.

Numerical faults (consumed by the engine right before step dispatch; the
deterministic levers behind the training-health sentinel's ladder,
``runtime/sentinel.py`` / docs/resilience.md "numerical faults"). Each is
rank-targeted, fires for ``count`` consecutive steps starting at ``step``
(default 1), decrements as it fires — so a sentinel rollback that replays
the step window does NOT re-poison it — and honors the ``attempt`` gate:

* ``nan_step`` — ``{"rank": R, "step": N, "count": 1}``: every float leaf
  of step ``N``'s batch is multiplied by NaN, so the loss and every
  gradient go nonfinite. The in-graph health gate must discard the update.
* ``loss_spike`` — ``{"rank": R, "step": N, "factor": 1e3, "count": 1}``:
  float batch leaves are scaled by ``factor`` — a finite but wildly
  out-of-distribution loss, the spike the robust z-score detector names.
* ``bad_batch`` — ``{"rank": R, "step": N, "fill": 1e4, "count": 1}``:
  float batch leaves are REPLACED with the constant ``fill`` — garbage
  data (a corrupt shard read), not merely scaled data.
"""
import errno
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, TypeVar

from .logging import logger

ENV_SPEC = "DSTPU_FAULT_INJECTION"

T = TypeVar("T")


class InjectedOSError(OSError):
    """Marker subclass so logs/tests can tell injected faults from real ones."""


class FaultInjector:
    """Counter-based fault delivery; thread-safe (the async checkpoint worker
    and the training thread both consult it)."""

    def __init__(self, spec: Optional[Dict[str, Any]] = None):
        spec = dict(spec or {})
        self.write_fail = dict(spec.get("write_fail") or {})
        self.truncate = dict(spec.get("truncate") or {})
        self.async_delay = float(spec.get("async_delay") or 0.0)
        p = spec.get("preempt_at_step")
        self.preempt_at_step: Optional[int] = None if p is None else int(p)
        self.hang_step = dict(spec.get("hang_step") or {})
        self.kill_step = dict(spec.get("kill_step") or {})
        self.tear_pod = dict(spec.get("tear_pod") or {})
        self.decode_wedge = dict(spec.get("decode_wedge") or {})
        self.serve_crash = dict(spec.get("serve_crash") or {})
        # numerical faults (ISSUE 16): remaining-step counters, decremented
        # as they fire so a rollback replay never re-poisons the window
        self.nan_step = dict(spec.get("nan_step") or {})
        self.loss_spike = dict(spec.get("loss_spike") or {})
        self.bad_batch = dict(spec.get("bad_batch") or {})
        self._nan_steps_left = int(self.nan_step.get("count", 1)
                                   if self.nan_step else 0)
        self._spike_steps_left = int(self.loss_spike.get("count", 1)
                                     if self.loss_spike else 0)
        self._bad_batches_left = int(self.bad_batch.get("count", 1)
                                     if self.bad_batch else 0)
        self._kv_alloc_fails_left = int(
            (spec.get("kv_alloc_fail") or {}).get("count", 0))
        self._write_failures_left = int(self.write_fail.get("count", 0))
        self._truncates_left = int(self.truncate.get("count", 1)
                                   if self.truncate else 0)
        self._tears_left = int(self.tear_pod.get("count", 1)
                               if self.tear_pod else 0)
        self._tear_skips_left = int(self.tear_pod.get("skip", 0))
        self._preempted = False
        self._hung = False
        self._killed = False
        self._decode_wedged = False
        self._serve_crashed = False
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls) -> "FaultInjector":
        raw = os.environ.get(ENV_SPEC)
        if not raw:
            return cls()
        try:
            return cls(json.loads(raw))
        except ValueError as e:
            raise ValueError(f"{ENV_SPEC} is not valid JSON: {e}") from e

    @property
    def armed(self) -> bool:
        return bool(self.write_fail or self.truncate or self.async_delay
                    or self.preempt_at_step is not None
                    or self.hang_step or self.kill_step or self.tear_pod
                    or self.decode_wedge or self.serve_crash
                    or self.nan_step or self.loss_spike or self.bad_batch
                    or self._kv_alloc_fails_left)

    # ------------------------------------------------------- injection points
    @staticmethod
    def _matches(pattern: Optional[str], path: str) -> bool:
        return pattern is None or pattern in path

    def maybe_fail_write(self, path: str) -> None:
        """Raise a transient ``OSError`` for the next N matching writes."""
        with self._lock:
            if self._write_failures_left <= 0:
                return
            if not self._matches(self.write_fail.get("match"), path):
                return
            self._write_failures_left -= 1
        raise InjectedOSError(errno.EIO,
                              f"injected transient write failure for {path}")

    def maybe_truncate(self, path: str) -> bool:
        """Tear a durably-written file; returns True if it was truncated."""
        with self._lock:
            if self._truncates_left <= 0:
                return False
            if not self._matches(self.truncate.get("match"), path):
                return False
            self._truncates_left -= 1
        size = os.path.getsize(path)
        keep = self.truncate.get("keep_bytes")
        if keep is None:
            keep = int(size * float(self.truncate.get("keep_fraction", 0.5)))
        keep = max(0, min(int(keep), size))
        with open(path, "rb+") as f:
            f.truncate(keep)
        logger.warning("fault injection: tore %s to %d/%d bytes",
                       path, keep, size)
        return True

    def maybe_delay_async(self) -> None:
        if self.async_delay > 0:
            time.sleep(self.async_delay)

    def should_preempt(self, global_steps: int) -> bool:
        """One-shot simulated preemption at step boundary >= N."""
        with self._lock:
            if self._preempted or self.preempt_at_step is None:
                return False
            if global_steps < self.preempt_at_step:
                return False
            self._preempted = True
        return True

    # -------------------------------------------------- pod (comm-layer) faults
    def maybe_hang_step(self, rank: int, global_steps: int,
                        phase: str = "pre") -> bool:
        """One-shot rank-targeted stall in the step's collective window.

        ``phase: "pre"`` (spec default) stalls BEFORE the watchdog arms —
        the rank *never arrives* at the all-reduce; the siblings spin
        inside it and their watchdogs (or the agent's teardown) end the
        pod. ``phase: "in"`` stalls after arming — the rank arrived and
        then wedged, so its OWN watchdog fires. Blocks for ``seconds``
        (default: effectively forever; the process is expected to be
        killed first). Returns whether it hung."""
        with self._lock:
            if self._hung or not self.hang_step:
                return False
            if self.hang_step.get("phase", "pre") != phase:
                return False
            if int(self.hang_step.get("rank", 0)) != int(rank):
                return False
            if global_steps < int(self.hang_step.get("step", 0)):
                return False
            self._hung = True
        seconds = float(self.hang_step.get("seconds", 0) or 0)
        logger.warning("fault injection: rank %d hanging %s step %d's "
                       "collective window (%s)", rank,
                       "inside" if phase == "in" else "before",
                       global_steps,
                       f"{seconds:.0f}s" if seconds > 0 else "until killed")
        self._stall(seconds)
        return True

    @staticmethod
    def _stall(seconds: float) -> None:
        """Block for ``seconds`` (<= 0: effectively forever — the process
        is expected to be killed first). The sleep argument is clamped to
        >= 0: the deadline can elapse between the loop check and the
        argument computation, and a negative ``time.sleep`` raises."""
        deadline = (time.monotonic() + seconds) if seconds > 0 else None
        while deadline is None or time.monotonic() < deadline:
            time.sleep(max(0.0, min(1.0, (deadline - time.monotonic())
                                    if deadline else 1.0)))

    def should_kill(self, rank: int, global_steps: int) -> Optional[int]:
        """One-shot hard-death request for this rank at a step boundary:
        returns the exit code to die with (the caller ``os._exit``\\ s — no
        emergency save, no cleanup; this is a crash, not a preemption)."""
        with self._lock:
            if self._killed or not self.kill_step:
                return None
            if int(self.kill_step.get("rank", 0)) != int(rank):
                return None
            if global_steps < int(self.kill_step.get("step", 0)):
                return None
            self._killed = True
        return int(self.kill_step.get("rc", 1))

    def corrupt_batch(self, rank: int, global_steps: int, batch: Any,
                      skip_keys: tuple = ()) -> Any:
        """Numerical-fault seam (consumed by the engine right before step
        dispatch): poison the batch a chosen rank is about to train on.
        Fires for ``count`` consecutive steps starting at ``step`` and
        decrements as it fires, so a sentinel rollback that replays the
        window trains on clean data. Only floating-point leaves are
        touched; top-level dict keys in ``skip_keys`` (the engine's own
        riders: ``pld_theta``, the sentinel gate) pass through untouched."""
        mode = spec = None
        with self._lock:
            for name, left_attr, s in (
                    ("nan_step", "_nan_steps_left", self.nan_step),
                    ("bad_batch", "_bad_batches_left", self.bad_batch),
                    ("loss_spike", "_spike_steps_left", self.loss_spike)):
                left = getattr(self, left_attr)
                if left <= 0 or not s:
                    continue
                if int(s.get("rank", 0)) != int(rank):
                    continue
                if global_steps < int(s.get("step", 0)):
                    continue
                if not self._attempt_matches(s):
                    continue
                setattr(self, left_attr, left - 1)
                mode, spec = name, s
                break
        if mode is None:
            return batch
        import jax
        import numpy as np

        if mode == "nan_step":
            poison_leaf = lambda x: x * float("nan")  # noqa: E731
        elif mode == "loss_spike":
            factor = float(spec.get("factor", 1e3))
            poison_leaf = lambda x: x * factor  # noqa: E731
        else:  # bad_batch: replace with a constant, keep shape/dtype/placement
            fill = float(spec.get("fill", 1e4))
            poison_leaf = lambda x: x * 0 + fill  # noqa: E731

        def poison(x):
            dt = getattr(x, "dtype", None)
            if dt is None or not np.issubdtype(np.dtype(dt), np.floating):
                return x
            return poison_leaf(x)

        logger.warning("fault injection: rank %d %s poisoning the batch for "
                       "step %d", rank, mode, global_steps)
        if isinstance(batch, dict) and skip_keys:
            kept = {k: v for k, v in batch.items() if k in skip_keys}
            poisoned = jax.tree_util.tree_map(
                poison, {k: v for k, v in batch.items() if k not in kept})
            return {**poisoned, **kept}
        return jax.tree_util.tree_map(poison, batch)

    def maybe_tear_pod(self, path: str, rank: int) -> Optional[str]:
        """Tear a pod checkpoint's two-phase commit after the save claimed
        durability: delete the commit record (``drop: "commit"``) or one
        rank's phase-1 manifest (``drop: "rank_manifest"`` +
        ``drop_rank``). ``skip`` healthy saves pass first; only the actor
        ``rank`` tears (the files are shared across ranks). Returns the
        deleted path, or None."""
        with self._lock:
            if self._tears_left <= 0 or not self.tear_pod:
                return None
            if int(self.tear_pod.get("rank", 0)) != int(rank):
                return None
            if self._tear_skips_left > 0:
                self._tear_skips_left -= 1
                return None
            self._tears_left -= 1
        from ..checkpoint.engine import COMMIT_FILE, rank_manifest_name

        if self.tear_pod.get("drop", "commit") == "rank_manifest":
            victim = os.path.join(path, rank_manifest_name(
                int(self.tear_pod.get("drop_rank", 0))))
        else:
            victim = os.path.join(path, COMMIT_FILE)
        try:
            os.unlink(victim)
        except OSError as e:
            logger.warning("fault injection: could not tear pod commit "
                           "%s: %s", victim, e)
            return None
        logger.warning("fault injection: tore pod checkpoint %s (deleted "
                       "%s)", path, os.path.basename(victim))
        return victim

    # ----------------------------------------------------- serving-plane faults
    @staticmethod
    def _attempt_matches(spec: Dict[str, Any]) -> bool:
        """Optional ``attempt`` key: the fault fires only in the named
        supervisor incarnation (``DSTPU_ELASTIC_ATTEMPT``). The env spec is
        re-read by every restarted process, so without this gate a one-shot
        serving fault would re-arm in each incarnation and the recovery it
        exists to test could never complete."""
        a = spec.get("attempt")
        if a is None:
            return True
        return int(os.environ.get("DSTPU_ELASTIC_ATTEMPT", "0")) == int(a)

    def maybe_wedge_decode(self, round_no: int) -> bool:
        """One-shot stall inside the serving session's dispatch window
        (AFTER the stuck-decode watchdog armed, so rc 219 is the expected
        outcome). Blocks for ``seconds`` (default: effectively forever —
        the watchdog or the supervisor is expected to kill the process
        first). Returns whether it wedged."""
        with self._lock:
            if self._decode_wedged or not self.decode_wedge:
                return False
            if not self._attempt_matches(self.decode_wedge):
                return False
            if round_no < int(self.decode_wedge.get("round", 0)):
                return False
            self._decode_wedged = True
        seconds = float(self.decode_wedge.get("seconds", 0) or 0)
        logger.warning("fault injection: wedging serving round %d's decode "
                       "dispatch (%s)", round_no,
                       f"{seconds:.0f}s" if seconds > 0 else "until killed")
        self._stall(seconds)
        return True

    def should_serve_crash(self, round_no: int,
                           tokens_emitted: int) -> Optional[int]:
        """One-shot mid-decode hard-death request for the serving process:
        returns the exit code to die with (the caller ``os._exit``\\ s — no
        cleanup, no journal close; the request journal's per-record flush
        is what recovery rides). Triggers at scheduling round ``round`` or
        once ``tokens`` total tokens have been emitted."""
        with self._lock:
            if self._serve_crashed or not self.serve_crash:
                return None
            if not self._attempt_matches(self.serve_crash):
                return None
            at_round = self.serve_crash.get("round")
            at_tokens = self.serve_crash.get("tokens")
            hit = ((at_round is not None and round_no >= int(at_round))
                   or (at_tokens is not None
                       and tokens_emitted >= int(at_tokens)))
            if not hit:
                return None
            self._serve_crashed = True
        return int(self.serve_crash.get("rc", 1))

    def should_fail_kv_alloc(self) -> bool:
        """Consume one injected KV-pool allocation failure (the allocator
        reports exhaustion instead of handing out blocks)."""
        with self._lock:
            if self._kv_alloc_fails_left <= 0:
                return False
            self._kv_alloc_fails_left -= 1
        logger.warning("fault injection: KV block allocation reported as "
                       "exhausted")
        return True


# -------------------------------------------------------------- global access
_injector: Optional[FaultInjector] = None
_injector_lock = threading.Lock()


def get_fault_injector() -> FaultInjector:
    """Process-wide injector; built from ``DSTPU_FAULT_INJECTION`` on first use."""
    global _injector
    with _injector_lock:
        if _injector is None:
            _injector = FaultInjector.from_env()
        return _injector


def configure_fault_injection(spec: Optional[Dict[str, Any]]
                              ) -> Optional[FaultInjector]:
    """Install (or with ``None`` clear) the process-wide injector. After a
    clear the next :func:`get_fault_injector` re-reads the env var."""
    global _injector
    with _injector_lock:
        _injector = FaultInjector(spec) if spec is not None else None
        return _injector


# ------------------------------------------------------------------ retry I/O
def retry_io(fn: Callable[[], T], *, attempts: int = 3,
             base_delay: float = 0.01, max_delay: float = 0.5,
             what: str = "storage I/O",
             on_retry: Optional[Callable[[int, BaseException], None]] = None
             ) -> T:
    """Run ``fn`` retrying transient ``OSError`` with capped exponential
    backoff — GCS/NFS blips and injected faults self-heal instead of killing
    a multi-hour run. Each retry is recorded on the resilience counters
    (``monitor/monitor.py``) so operators see degradation, not silence."""
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn()
        except OSError as e:
            last = e
            if attempt == attempts - 1:
                break
            from ..monitor.monitor import resilience_counters

            resilience_counters.incr("io_retries")
            if on_retry is not None:
                on_retry(attempt + 1, e)
            delay = min(max_delay, base_delay * (2 ** attempt))
            logger.warning("%s failed (%s); retry %d/%d in %.3fs",
                           what, e, attempt + 1, attempts - 1, delay)
            time.sleep(delay)
    from ..monitor.monitor import resilience_counters

    resilience_counters.incr("io_giveups")
    assert last is not None
    raise last
