"""Jax cross-version compatibility shims (opt-in).

The codebase targets the current jax spelling of ``shard_map`` — a top-level
``jax.shard_map`` whose replication-checking knob is ``check_vma``. Older jax
(< 0.5, e.g. the 0.4.x baked into some images) only ships
``jax.experimental.shard_map.shard_map`` with the knob spelled ``check_rep``,
and lacks ``jax.lax.axis_size`` / ``jax.sharding.get_abstract_mesh``.

Set ``DSTPU_JAX_COMPAT=1`` (or call :func:`install` before building engines)
to graft the modern spellings onto an old jax at import time. Opt-in rather
than automatic: the shims mutate the global ``jax`` module, and the tier-1
suite budgets its wall-clock against the un-shimmed baseline — flipping the
default changes which tests execute real programs. :func:`uninstall` exists
so tests can exercise the shims without leaking them into the rest of the
process.
"""
import functools
import os
from typing import Any, List, Tuple

ENV_FLAG = "DSTPU_JAX_COMPAT"

_installed: List[Tuple[Any, str]] = []  # (owner, attr) we added


def enabled_by_env() -> bool:
    return os.environ.get(ENV_FLAG, "0").lower() in ("1", "true")


def install() -> List[str]:
    """Install whichever shims this jax is missing; idempotent. Returns the
    dotted names added (for logging/tests)."""
    import jax

    added: List[str] = []
    if _install_shard_map(jax):
        added.append("jax.shard_map")
    if _install_axis_size(jax):
        added.append("jax.lax.axis_size")
    if _install_get_abstract_mesh(jax):
        added.append("jax.sharding.get_abstract_mesh")
    return added


def uninstall() -> None:
    """Remove every attribute :func:`install` added (test hygiene)."""
    while _installed:
        owner, attr = _installed.pop()
        try:
            delattr(owner, attr)
        except AttributeError:  # pragma: no cover - already gone
            pass


def _install_shard_map(jax) -> bool:
    if hasattr(jax, "shard_map"):
        return False
    try:
        from jax.experimental.shard_map import shard_map as _legacy
    except ImportError:  # pragma: no cover - very old jax
        return False

    @functools.wraps(_legacy)
    def shard_map(f=None, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if "axis_names" in kwargs:
            # modern: axis_names = manually-mapped axes; legacy: auto = the
            # complement (axes left to the partitioner)
            manual = frozenset(kwargs.pop("axis_names"))
            mesh = kwargs.get("mesh")
            if mesh is not None:
                kwargs["auto"] = frozenset(mesh.axis_names) - manual
        if f is None:  # modern jax allows partial application
            return lambda g: _legacy(g, **kwargs)
        return _legacy(f, **kwargs)

    jax.shard_map = shard_map
    _installed.append((jax, "shard_map"))
    return True


def _install_axis_size(jax) -> bool:
    """``jax.lax.axis_size`` appeared after 0.4.x; the portable spelling on
    older jax is ``psum(1, axis)`` over the named axis."""
    if hasattr(jax.lax, "axis_size"):
        return False

    def axis_size(axis_name):
        # psum of the literal 1 folds to the (static) axis size at trace time
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = axis_size
    _installed.append((jax.lax, "axis_size"))
    return True


def _install_get_abstract_mesh(jax) -> bool:
    """``jax.sharding.get_abstract_mesh`` is public on newer jax; 0.4.x keeps
    it in ``jax._src.mesh``. Call sites only probe ``manual_axes`` with a
    default, so exposing the internal (whatever it returns) suffices."""
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return False
    try:
        from jax._src import mesh as _mesh

        jax.sharding.get_abstract_mesh = _mesh.get_abstract_mesh
    except (ImportError, AttributeError):  # pragma: no cover
        jax.sharding.get_abstract_mesh = lambda: None
    _installed.append((jax.sharding, "get_abstract_mesh"))
    return True
