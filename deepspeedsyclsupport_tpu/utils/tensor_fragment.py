"""Fragment-level parameter / optimizer-state access.

TPU-native analog of the reference's ``deepspeed/utils/tensor_fragment.py``
(+ ``mixed_precision_linkage.py``): debugging/introspection access to the
fp32 master value, optimizer moments, and last gradient of any single
parameter, regardless of which ZeRO stage / offload mode the engine runs —
there, per-param ``tensor_fragment`` records map flat-partition offsets back
to params; here, sharding is declarative (a leaf is one logical array with a
``jax.sharding`` layout), so a "fragment" is just the addressable view of
the leaf and the full value is ``jax.device_get`` of it.

API parity (reference names, engine-scoped because JAX params are pytree
leaves, not stateful tensors):

==============================================  ================================
reference (``utils/tensor_fragment.py``)          here
==============================================  ================================
``safe_get_full_fp32_param(p)``         :101      ``safe_get_full_fp32_param(engine, path)``
``safe_set_full_fp32_param(p, v)``      :117      ``safe_set_full_fp32_param(engine, path, v)``
``safe_get_full_optimizer_state(p, k)`` :133      ``safe_get_full_optimizer_state(engine, path, k)``
``safe_set_full_optimizer_state``       :150      ``safe_set_full_optimizer_state(engine, path, v, k)``
``safe_get_full_grad(p)``               :168      ``safe_get_full_grad(engine, path)``
``safe_get_local_fp32_param``           :204      ``safe_get_local_fp32_param(engine, path)``
``safe_get_local_optimizer_state``      :216      ``safe_get_local_optimizer_state(engine, path, k)``
==============================================  ================================

Optimizer-state keys use the reference's names (``exp_avg``/``exp_avg_sq``)
and map onto whatever optax state the engine built (``mu``/``nu`` for the
Adam family, ``mu`` for Lion/momentum, ``sum_of_squares`` for Adagrad);
the optax field names are accepted as aliases.
"""
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

__all__ = [
    "safe_get_full_fp32_param", "safe_set_full_fp32_param",
    "safe_get_full_optimizer_state", "safe_set_full_optimizer_state",
    "safe_get_full_grad", "safe_get_local_fp32_param",
    "safe_get_local_optimizer_state", "get_optimizer_state_keys",
    "resolve_param_path", "param_paths",
]

# reference key -> optax field candidates, in preference order
_KEY_ALIASES = {
    "exp_avg": ("mu",),
    "exp_avg_sq": ("nu",),
    "momentum": ("mu", "trace"),
    "sum": ("sum_of_squares",),
}


# ------------------------------------------------------------------ path utils
def _split(path) -> Tuple[Any, ...]:
    if isinstance(path, (tuple, list)):
        return tuple(path)
    return tuple(seg for seg in str(path).replace(".", "/").split("/") if seg)


def param_paths(tree: Any) -> List[str]:
    """All leaf paths of a params pytree as '/'-joined strings."""
    out = []
    for kp, _ in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out.append("/".join(_key_str(k) for k in kp))
    return out


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    if hasattr(k, "name"):
        return str(k.name)
    return str(k)


def resolve_param_path(tree: Any, path) -> Any:
    """Fetch the leaf at ``path`` ('/'- or '.'-separated, or a tuple)."""
    node = tree
    for seg in _split(path):
        if isinstance(node, (list, tuple)):
            node = node[int(seg)]
        elif isinstance(node, dict):
            if seg in node:
                node = node[seg]
            elif str(seg).isdigit() and int(seg) in node:
                node = node[int(seg)]
            else:
                raise KeyError(
                    f"path segment {seg!r} not found; available: "
                    f"{list(node)[:12]}")
        else:
            node = getattr(node, str(seg))
    return node


def _replace_leaf(tree: Any, path, value: Any) -> None:
    """In-place leaf replacement for dict/list pytrees (our params are plain
    dicts; engines own their trees, so in-place is safe here)."""
    segs = _split(path)
    parent = resolve_param_path(tree, segs[:-1]) if len(segs) > 1 else tree
    last = segs[-1]
    if isinstance(parent, dict):
        key = last if last in parent else int(last)
        parent[key] = value
    elif isinstance(parent, list):
        parent[int(last)] = value
    else:
        setattr(parent, str(last), value)


# ------------------------------------------------------- optimizer state walk
def _adam_like_states(opt_state) -> List[Any]:
    """Every element of the (possibly chained/nested) optax state that
    carries per-param moment trees."""
    found = []

    def walk(node):
        if node is None or isinstance(node, (int, float, np.ndarray,
                                             jax.Array)):
            return
        fields = getattr(node, "_fields", None)
        if fields:
            if any(f in ("mu", "nu", "trace", "sum_of_squares")
                   for f in fields):
                found.append(node)
            for f in fields:
                walk(getattr(node, f))
        elif isinstance(node, (tuple, list)):
            for sub in node:
                walk(sub)
        elif isinstance(node, dict):
            for sub in node.values():
                walk(sub)

    walk(opt_state)
    return found


def _moment_tree(engine, key: str) -> Tuple[Any, str]:
    """(tree-of-moments, resolved optax field) for a reference-style key."""
    opt_state = _materialized_opt_state(engine)
    candidates = _KEY_ALIASES.get(key, ()) + (key,)
    for st in _adam_like_states(opt_state):
        for cand in candidates:
            if cand in getattr(st, "_fields", ()):
                return getattr(st, cand), cand
    keys = get_optimizer_state_keys(engine)
    raise KeyError(f"optimizer state key {key!r} not found; available: "
                   f"{keys}")


def get_optimizer_state_keys(engine) -> List[str]:
    """Reference ``get_optim_state_keys``: the moment names this engine's
    optimizer actually carries (reference naming where one exists)."""
    rev = {"mu": "exp_avg", "nu": "exp_avg_sq", "sum_of_squares": "sum",
           "trace": "momentum"}
    if engine._mh_offload is not None:
        return ["exp_avg", "exp_avg_sq"]
    out = []
    for st in _adam_like_states(_materialized_opt_state(engine)):
        for f in st._fields:
            if f in rev and rev[f] not in out:
                out.append(rev[f])
    return out


def _materialized_opt_state(engine):
    """The optax state tree, swapping in from NVMe if it is parked there."""
    if engine.opt_state is None and engine._swapper is not None:
        engine._swap_in_opt_state()
    if engine.opt_state is None:
        raise RuntimeError(
            "engine has no materialized optimizer state (multi-host offload "
            "keeps per-host shards — use the safe_get_local_* variants)")
    return engine.opt_state


def _master_tree(engine):
    """Engine's fp32 authority tree: host master under offload, else the
    (fp32) device params."""
    if engine.master_params is not None:
        return engine.master_params
    return engine.params


# ------------------------------------------------------------------- full API
def _mh_single_controller(engine) -> bool:
    """Pipelined host-Adam offload where this process addresses EVERY
    shard — the full-value API works straight off the host shard store."""
    return engine._mh_offload is not None and jax.process_count() == 1


def safe_get_full_fp32_param(engine, path) -> np.ndarray:
    """Full fp32 master value of one parameter (reference
    ``safe_get_full_fp32_param``, ``utils/tensor_fragment.py:101``): gathered
    across shards (a ``device_get`` on a sharded array assembles it), fetched
    from the host master under ZeRO-Offload."""
    if engine._mh_offload is not None:
        if not _mh_single_controller(engine):
            raise RuntimeError(
                "full-value access under multi-host offload needs a "
                "cross-host gather — use safe_get_local_fp32_param on each "
                "controller")
        return np.asarray(engine._mh_offload.full_leaf_value(
            _mh_leaf_index(engine, path)), np.float32)
    leaf = resolve_param_path(_master_tree(engine), path)
    return np.asarray(jax.device_get(leaf), np.float32)


def safe_set_full_fp32_param(engine, path, value) -> None:
    """Write a full fp32 master value back (reference :117). The device
    working copy is refreshed so the next step sees the edit."""
    if engine._mh_offload is not None and not _mh_single_controller(engine):
        raise RuntimeError("setting params under multi-host offload is not "
                           "supported (each controller owns one shard)")
    if engine._mh_offload is not None:
        mh = engine._mh_offload
        li = _mh_leaf_index(engine, path)
        value = np.asarray(value)
        if tuple(value.shape) != tuple(mh._shapes[li]):
            raise ValueError(f"shape mismatch: param {tuple(mh._shapes[li])} "
                             f"vs value {value.shape}")
        mh.set_leaf_value(li, value)
        # refresh the device working copies from the edited master so the
        # next step trains FROM the edit (debug path — one full push)
        engine.params = engine._mh_push(mh.master_global_tree())
        return
    import jax.numpy as jnp

    tree = _master_tree(engine)
    old = resolve_param_path(tree, path)
    value = np.asarray(value)
    if value.shape != np.shape(old):
        raise ValueError(f"shape mismatch: param {np.shape(old)} vs value "
                         f"{value.shape}")
    if engine.master_params is not None:
        # host master is the authority; device params mirror in compute dtype
        new_master = jax.device_put(value.astype(np.float32),
                                    engine._cpu_device)
        _replace_leaf(engine.master_params, path, new_master)
        sh = resolve_param_path(engine.param_shardings, path)
        dev = jax.device_put(value.astype(engine.compute_dtype), sh)
        _replace_leaf(engine.params, path, dev)
    else:
        sh = resolve_param_path(engine.param_shardings, path)
        new = jax.device_put(value.astype(np.asarray(old).dtype), sh)
        _replace_leaf(engine.params, path, new)


_MH_MOMENT = {"exp_avg": "m", "mu": "m", "exp_avg_sq": "v", "nu": "v"}


def safe_get_full_optimizer_state(engine, path, key: str) -> np.ndarray:
    """Full value of one optimizer moment (reference :133); ``key`` is
    ``exp_avg`` / ``exp_avg_sq`` (or an optax field name)."""
    if engine._mh_offload is not None:
        if not _mh_single_controller(engine):
            raise RuntimeError(
                "full-value access under multi-host offload needs a "
                "cross-host gather — use safe_get_local_optimizer_state on "
                "each controller")
        which = _MH_MOMENT.get(key)
        if which is None:
            raise KeyError(f"host CPU Adam carries exp_avg/exp_avg_sq only; "
                           f"got {key!r}")
        return np.asarray(engine._mh_offload.full_moment_value(
            _mh_leaf_index(engine, path), which))
    tree, _ = _moment_tree(engine, key)
    return np.asarray(jax.device_get(resolve_param_path(tree, path)))


def safe_set_full_optimizer_state(engine, path, value, key: str) -> None:
    """Write one optimizer moment back (reference :150). The new value is
    placed with the old leaf's sharding/device, so stage placement is
    preserved; under NVMe offload the edited state is re-parked."""
    if engine._mh_offload is not None:
        if not _mh_single_controller(engine):
            raise RuntimeError("setting optimizer state under multi-host "
                               "offload is not supported")
        mh = engine._mh_offload
        which = _MH_MOMENT.get(key)
        if which is None:
            raise KeyError(f"host CPU Adam carries exp_avg/exp_avg_sq only; "
                           f"got {key!r}")
        li = _mh_leaf_index(engine, path)
        value = np.asarray(value, np.float32)
        if tuple(value.shape) != tuple(mh._shapes[li]):
            raise ValueError(f"shape mismatch: state {tuple(mh._shapes[li])} "
                             f"vs value {value.shape}")
        store = mh.m if which == "m" else mh.v
        from ..runtime.multihost_offload import _idx_key

        for idx in mh._dev_index[li].values():
            k = _idx_key(idx)
            if mh.swapper is not None and k in mh._swap_keys[li]:
                mh.swapper.swap_out(f"{which}/{li}/{k}",
                                    np.array(value[idx], np.float32))
            else:
                store[li][k] = np.array(value[idx], np.float32)
        return
    tree, _ = _moment_tree(engine, key)
    old = resolve_param_path(tree, path)
    value = np.asarray(value, np.asarray(old).dtype)
    if value.shape != np.shape(old):
        raise ValueError(f"shape mismatch: state {np.shape(old)} vs value "
                         f"{value.shape}")
    placement = getattr(old, "sharding", None) or getattr(
        engine, "_cpu_device", None)
    placed = jax.device_put(value, placement) if placement is not None \
        else value
    _replace_leaf(tree, path, placed)
    if engine._swapper is not None:
        engine._swap_out_opt_state()


def set_optimizer_step(engine, step: int) -> None:
    """Set every optax ``count`` leaf (Adam bias-correction step) to
    ``step`` — needed when optimizer moments are imported from an external
    checkpoint so the next update applies the right bias correction."""
    import jax.numpy as jnp

    opt_state = _materialized_opt_state(engine)

    def rebuild(node):
        if hasattr(node, "_fields"):
            vals = {}
            for f in node._fields:
                v = getattr(node, f)
                if f == "count":
                    vals[f] = jax.tree_util.tree_map(
                        lambda c: jnp.full_like(c, step), v)
                else:
                    vals[f] = rebuild(v)
            return type(node)(**vals)
        if isinstance(node, tuple):
            return tuple(rebuild(s) for s in node)
        if isinstance(node, list):
            return [rebuild(s) for s in node]
        if isinstance(node, dict):
            return {k: rebuild(v) for k, v in node.items()}
        return node

    engine.opt_state = rebuild(opt_state)
    if engine._swapper is not None:
        engine._swap_out_opt_state()


def safe_get_full_grad(engine, path) -> Optional[np.ndarray]:
    """Most recent accumulated fp32 gradient of a param (reference :168).
    Only the eager ``forward()/backward()`` loop retains gradients between
    calls; the fused ``train_batch()`` consumes them inside one jitted scan
    (they never materialize engine-side) — returns None there, like the
    reference returns None outside the grad-valid window."""
    acc = getattr(engine, "_accum_grads", None)
    if acc is None:
        return None
    return np.asarray(jax.device_get(resolve_param_path(acc, path)),
                      np.float32)


# ------------------------------------------------------------------ local API
def _local_shard(arr) -> np.ndarray:
    shards = getattr(arr, "addressable_shards", None)
    if not shards:
        return np.asarray(jax.device_get(arr))
    return np.asarray(shards[0].data)


def safe_get_local_fp32_param(engine, path) -> np.ndarray:
    """This controller's shard of the fp32 master (reference
    ``safe_get_local_fp32_param:204`` — the ZeRO-3 'local' view)."""
    if engine._mh_offload is not None:
        shards = engine._mh_offload.master[_mh_leaf_index(engine, path)]
        return np.asarray(next(iter(shards.values())), np.float32)
    return _local_shard(resolve_param_path(_master_tree(engine), path)) \
        .astype(np.float32)


def safe_get_local_optimizer_state(engine, path, key: str) -> np.ndarray:
    """This controller's shard of one optimizer moment (reference :216)."""
    if engine._mh_offload is not None:
        store = {"exp_avg": engine._mh_offload.m, "mu": engine._mh_offload.m,
                 "exp_avg_sq": engine._mh_offload.v,
                 "nu": engine._mh_offload.v}.get(key)
        if store is None:
            raise KeyError(f"multi-host CPU Adam carries exp_avg/exp_avg_sq "
                           f"only; got {key!r}")
        shards = store[_mh_leaf_index(engine, path)]
        return np.asarray(next(iter(shards.values())), np.float32)
    tree, _ = _moment_tree(engine, key)
    return _local_shard(resolve_param_path(tree, path))


def _mh_leaf_index(engine, path) -> int:
    """Flat leaf index of ``path`` (MultiHostCPUAdam stores per-leaf shard
    dicts in params tree_flatten order)."""
    leaves = jax.tree_util.tree_flatten_with_path(engine.params)[0]
    want = tuple(str(s) for s in _split(path))
    for i, (kp, _) in enumerate(leaves):
        if tuple(_key_str(k) for k in kp) == want:
            return i
    raise KeyError(f"param path {path!r} not found")
