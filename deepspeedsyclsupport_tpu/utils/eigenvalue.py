"""Hessian top-eigenvalue estimation by power iteration.

Analog of the reference's ``runtime/eigenvalue.py:Eigenvalue`` (used by the
compression scheduler to set layer-wise quantization/pruning ratios from
local curvature). The reference hand-rolls Hessian-vector products through
``torch.autograd.grad`` per block; here an HVP is one ``jax.jvp`` over
``jax.grad`` — the functional-transform composition TPU/XLA compiles into a
single fused program.

``compute_eigenvalue(loss_fn, params, batch)`` estimates the top eigenvalue
of the loss Hessian restricted to the parameter subtree selected by
``filter_fn`` (default: whole tree); per-block estimates (one per top-level
``layers`` entry, the reference's per-layer ratios) via ``block_prefixes``.
"""
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

__all__ = ["Eigenvalue"]


def _tree_dot(a, b):
    # accumulate in fp32: fp16/bf16 trees overflow/underflow their own dtype
    return sum(jnp.vdot(x.astype(jnp.float32), y.astype(jnp.float32))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


def _tree_norm(a):
    return jnp.sqrt(jnp.maximum(_tree_dot(a, a).real, 1e-30))


class Eigenvalue:
    def __init__(self, max_iter: int = 100, tol: float = 1e-2,
                 stability: float = 1e-6, verbose: bool = False, seed: int = 0):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.verbose = verbose
        self.seed = seed

    def compute_eigenvalue(self, loss_fn: Callable, params: Any, batch: Any,
                           filter_fn: Optional[Callable] = None) -> float:
        """Top Hessian eigenvalue of ``loss_fn(params, batch)`` w.r.t. the
        leaves where ``filter_fn(key_path) is True`` (all leaves by default).
        """
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        active = [filter_fn(kp) if filter_fn else True for kp, _ in flat]
        if not any(active):
            raise ValueError("filter_fn selected no parameters")

        def scalar_loss(p):
            out = loss_fn(p, batch)
            return out[0] if isinstance(out, tuple) else out

        grad_fn = jax.grad(scalar_loss)

        def hvp(v):
            return jax.jvp(grad_fn, (params,), (v,))[1]

        def mask(tree):
            leaves = jax.tree_util.tree_leaves(tree)
            return jax.tree_util.tree_unflatten(
                treedef, [l if a else jnp.zeros_like(l)
                          for l, a in zip(leaves, active)])

        rng = jax.random.PRNGKey(self.seed)
        ks = jax.random.split(rng, len(flat))
        # tangents must match the primal dtypes (bf16/fp16 models)
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, jnp.shape(p), jnp.result_type(p))
                      if a else jnp.zeros(jnp.shape(p), jnp.result_type(p))
                      for k, (_, p), a in zip(ks, flat, active)])
        nrm0 = _tree_norm(v)
        # divide in fp32, cast back: mixed-dtype trees must keep each
        # tangent leaf's dtype equal to its primal's
        v = jax.tree_util.tree_map(
            lambda x: (x.astype(jnp.float32) / nrm0).astype(x.dtype), v)

        hvp_j = jax.jit(lambda v: mask(hvp(v)))
        prev = 0.0
        eig = 0.0
        for it in range(self.max_iter):
            hv = hvp_j(v)
            eig = float(_tree_dot(v, hv).real)  # Rayleigh quotient
            nrm = _tree_norm(hv)
            v = jax.tree_util.tree_map(
                lambda x: (x.astype(jnp.float32)
                           / (nrm + self.stability)).astype(x.dtype), hv)
            if it > 0 and abs(eig) > 0 and \
                    abs(eig - prev) / abs(eig) < self.tol:
                break
            prev = eig
        return eig

    def compute_per_block(self, loss_fn: Callable, params: Any, batch: Any,
                          block_prefixes: List[str]) -> Dict[str, float]:
        """Per-block eigenvalues (the reference's layer-wise ratios): one
        power iteration per key-path prefix."""
        out = {}
        for prefix in block_prefixes:
            def fltr(kp, prefix=prefix):
                path = "/".join(str(getattr(k, "key", getattr(k, "name", k)))
                                for k in kp)
                # separator-aware: 'layers/1' must not match 'layers/10'
                return path == prefix or path.startswith(prefix + "/")

            out[prefix] = self.compute_eigenvalue(loss_fn, params, batch,
                                                  filter_fn=fltr)
        return out
