"""Safe persistent compilation cache: per-process staging + atomic publish.

PR 1 root-caused the seed suite's mid-run segfaults to jax's persistent
compilation cache: concurrent writers (or a writer killed mid-``write(2)``)
tear a cache entry in the shared directory, and every later process that
deserializes the torn executable corrupts its heap. The cache was therefore
turned OFF — but the ROADMAP says *fix rather than avoid*: the suite is
compile-bound, and a pod of rank processes compiling the same programs is
exactly the concurrent-writer shape that tears a naively shared directory.

The fix is the classic staging/publish split, the same discipline the
checkpoint layer already follows:

* each process points jax at a **private staging dir**
  (``<shared>/.proc-<pid>-<nonce>``) — no two writers ever share a file;
* the staging dir is **seeded** from the shared dir at enable time
  (hardlinks when possible, copies otherwise) so previously published
  entries still hit;
* new entries are **published** back by writing to a dotfile temp in the
  shared dir and ``os.replace``-ing onto the final name — readers see an
  entry either not at all or in full, never torn (rename atomicity on one
  filesystem);
* publish runs at interpreter exit (and on demand via
  :func:`publish_cache_entries`); dead processes' stale staging dirs are
  swept opportunistically.

Entry names never start with ``.`` (jax uses content hashes), so dotfiles
are safely ours: temps, staging dirs, and anything a killed publisher left
behind are invisible to seeding and to jax.
"""
import atexit
import os
import shutil
import uuid
from typing import Optional

from .logging import logger

__all__ = ["enable_safe_persistent_cache", "publish_cache_entries",
           "sweep_stale_staging"]

_STAGING_PREFIX = ".proc-"
_TMP_PREFIX = ".pub-"


def _is_entry(name: str) -> bool:
    """A real cache entry (jax content-hash filenames never start with a
    dot; everything dotted is our machinery or a torn temp)."""
    return bool(name) and not name.startswith(".")


def enable_safe_persistent_cache(shared_dir: str,
                                 min_compile_secs: float = 0.5,
                                 configure_jax: bool = True) -> str:
    """Arm the jax persistent compilation cache against ``shared_dir``
    safely, returning this process's private staging directory.

    ``configure_jax=False`` skips the ``jax.config`` mutation (unit tests
    exercise the seed/publish mechanics without retargeting the live
    process's cache)."""
    shared_dir = os.path.abspath(shared_dir)
    staging = os.path.join(shared_dir,
                           f"{_STAGING_PREFIX}{os.getpid()}-"
                           f"{uuid.uuid4().hex[:8]}")
    os.makedirs(staging, exist_ok=True)
    sweep_stale_staging(shared_dir)
    seeded = 0
    try:
        names = os.listdir(shared_dir)
    except OSError:
        names = []
    for name in names:
        if not _is_entry(name):
            continue
        src = os.path.join(shared_dir, name)
        dst = os.path.join(staging, name)
        if not os.path.isfile(src):
            continue
        try:
            os.link(src, dst)  # O(1); published entries are immutable
        except OSError:
            try:
                shutil.copy2(src, dst)  # cross-device / no-hardlink FS
            except OSError as e:
                logger.warning("compile cache: could not seed %s: %s",
                               name, e)
                continue
        seeded += 1
    if configure_jax:
        import jax

        jax.config.update("jax_compilation_cache_dir", staging)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
    atexit.register(publish_cache_entries, staging, shared_dir,
                    cleanup=True)
    logger.info("compile cache: staging %s over shared %s (%d entr%s "
                "seeded)", staging, shared_dir, seeded,
                "y" if seeded == 1 else "ies")
    return staging


def publish_cache_entries(staging: str, shared_dir: str,
                          cleanup: bool = False) -> int:
    """Atomically publish every entry in ``staging`` that the shared dir
    doesn't have yet: write the bytes to a dotted temp *in the shared dir*
    (same filesystem as the target — ``os.replace`` is only atomic there),
    fsync, rename. A concurrent publisher of the same entry is harmless:
    content is keyed by hash, so whoever renames last rewrites identical
    bytes. Returns the number published; with ``cleanup`` the staging dir
    is removed afterwards."""
    published = 0
    try:
        names = os.listdir(staging)
    except OSError:
        return 0
    for name in names:
        if not _is_entry(name):
            continue
        src = os.path.join(staging, name)
        dst = os.path.join(shared_dir, name)
        if not os.path.isfile(src) or os.path.exists(dst):
            continue
        tmp = os.path.join(shared_dir,
                           f"{_TMP_PREFIX}{os.getpid()}-{name}")
        try:
            with open(src, "rb") as fsrc, open(tmp, "wb") as fdst:
                shutil.copyfileobj(fsrc, fdst)
                fdst.flush()
                os.fsync(fdst.fileno())
            os.replace(tmp, dst)
            published += 1
        except OSError as e:
            logger.warning("compile cache: publish of %s failed: %s",
                           name, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
    if cleanup:
        shutil.rmtree(staging, ignore_errors=True)
    return published


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (OSError, PermissionError):  # pragma: no cover - exists, not ours
        return True
    return True


def sweep_stale_staging(shared_dir: str) -> int:
    """Remove staging dirs (and publish temps) left by dead processes — a
    crashed worker must not leak its private dir forever. Live processes'
    dirs are untouched (pid probe)."""
    removed = 0
    try:
        names = os.listdir(shared_dir)
    except OSError:
        return 0
    for name in names:
        p = os.path.join(shared_dir, name)
        pid: Optional[int] = None
        if name.startswith(_STAGING_PREFIX) or name.startswith(_TMP_PREFIX):
            tail = name.split("-", 2)
            if len(tail) >= 2 and tail[1].isdigit():
                pid = int(tail[1])
        if pid is None or _pid_alive(pid):
            continue
        try:
            if os.path.isdir(p):
                shutil.rmtree(p)
            else:
                os.unlink(p)
            removed += 1
        except OSError:  # pragma: no cover - racing another sweeper
            pass
    return removed
