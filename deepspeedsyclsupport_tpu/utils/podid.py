"""Pod identity: which rank am I, how many ranks share this run's storage.

Real multi-controller runs answer through ``jax.distributed``
(``jax.process_index``/``process_count``). But a pod can equally be N
*independent single-controller processes* sharing a checkpoint directory:
the fake-backend test harness shape (CPU jaxlib has no multiprocess
collectives), data-parallel replica fleets under an external launcher, and
the elastic agent's local pod mode (``elastic_agent.py --nprocs``) all look
like this. ``DSTPU_POD_RANKS`` declares such a pod's size; the standard
``RANK`` env names the member. The checkpoint commit protocol, telemetry
rank labeling and rank-targeted fault injection all resolve identity here,
so both pod shapes get the same contracts.
"""
import os
from typing import Tuple

ENV_POD_RANKS = "DSTPU_POD_RANKS"


def pod_identity() -> Tuple[int, int]:
    """``(rank, world)``. jax.distributed wins when initialized; otherwise
    an env-declared pod (``DSTPU_POD_RANKS`` + ``RANK``); otherwise the
    solo default ``(0, 1)``. Malformed env degrades to solo rather than
    crashing a training run over a bad launcher variable."""
    import jax

    if jax.process_count() > 1:
        return jax.process_index(), jax.process_count()
    try:
        world = int(os.environ.get(ENV_POD_RANKS, "1") or 1)
    except ValueError:
        world = 1
    if world > 1:
        try:
            rank = int(os.environ.get("RANK", "0") or 0)
        except ValueError:
            rank = 0
        return rank, world
    return 0, 1


def pod_rank() -> int:
    return pod_identity()[0]


def pod_world() -> int:
    return pod_identity()[1]
