from .logging import log_dist, logger
from .tensor_fragment import (get_optimizer_state_keys, param_paths,
                              resolve_param_path, safe_get_full_fp32_param,
                              safe_get_full_grad,
                              safe_get_full_optimizer_state,
                              safe_get_local_fp32_param,
                              safe_get_local_optimizer_state,
                              safe_set_full_fp32_param,
                              safe_set_full_optimizer_state)
from .timer import SynchronizedWallClockTimer, ThroughputTimer
