"""NUMA-aware process binding.

Analog of the reference's ``deepspeed/utils/numa.py`` (202 LoC) +
``--bind_cores_to_rank`` launcher flag: split the host's cores across local
ranks and generate a ``numactl`` prefix per rank (``-C`` cpu list, ``-m``
membind to the nodes those cores live on). On TPU hosts this is what keeps
the input pipeline and host-side steps (aio swapper, cpu-offloaded optimizer)
from bouncing across sockets.

Topology comes from ``/sys/devices/system/node`` (no numactl dependency for
discovery; ``numactl`` is only needed to RUN the generated prefix).
"""
import glob
import os
import re
import shutil
from typing import List, Optional, Tuple

__all__ = ["parse_range_list", "get_numa_cores", "check_for_numactl",
           "get_numactl_cmd"]


def parse_range_list(spec: str) -> List[int]:
    """``"0-3,8,10-11"`` → ``[0,1,2,3,8,10,11]`` (cpulist syntax)."""
    out: List[int] = []
    spec = spec.strip()
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        m = re.fullmatch(r"(\d+)-(\d+)", part)
        if m:
            lo, hi = int(m.group(1)), int(m.group(2))
            if hi < lo:
                raise ValueError(f"descending range {part!r}")
            out.extend(range(lo, hi + 1))
        elif re.fullmatch(r"\d+", part):
            out.append(int(part))
        else:
            raise ValueError(f"bad core-list element {part!r}")
    return sorted(set(out))


def get_numa_cores(sys_node_dir: str = "/sys/devices/system/node"
                   ) -> List[List[int]]:
    """Cores per NUMA node. Falls back to one node with all cpus when the
    sysfs topology is absent (containers, non-Linux)."""
    nodes = []
    for path in sorted(glob.glob(os.path.join(sys_node_dir, "node[0-9]*")),
                       key=lambda p: int(re.search(r"(\d+)$", p).group(1))):
        try:
            with open(os.path.join(path, "cpulist")) as f:
                nodes.append(parse_range_list(f.read()))
        except OSError:
            continue
    if not nodes:
        n = os.cpu_count() or 1
        nodes = [list(range(n))]
    return nodes


def check_for_numactl() -> bool:
    return shutil.which("numactl") is not None


def _compact(cores: List[int]) -> str:
    """[0,1,2,3,8] → "0-3,8" (inverse of :func:`parse_range_list`)."""
    parts: List[str] = []
    i = 0
    while i < len(cores):
        j = i
        while j + 1 < len(cores) and cores[j + 1] == cores[j] + 1:
            j += 1
        parts.append(str(cores[i]) if i == j else f"{cores[i]}-{cores[j]}")
        i = j + 1
    return ",".join(parts)


def get_numactl_cmd(bind_core_list: Optional[str], num_local_procs: int,
                    local_rank: int,
                    numa_nodes: Optional[List[List[int]]] = None
                    ) -> Tuple[List[str], List[int]]:
    """The reference's ``get_numactl_cmd``: carve this rank's core slice out
    of ``bind_core_list`` (default: all cores) and return the ``numactl``
    argv prefix plus the cores, membinding to the NUMA nodes that own them.
    """
    if num_local_procs < 1:
        raise ValueError("num_local_procs must be >= 1")
    numa_nodes = numa_nodes if numa_nodes is not None else get_numa_cores()
    all_cores = (parse_range_list(bind_core_list) if bind_core_list
                 else sorted(c for node in numa_nodes for c in node))
    if len(all_cores) < num_local_procs:
        raise ValueError(f"{len(all_cores)} cores cannot host "
                         f"{num_local_procs} ranks")
    per = len(all_cores) // num_local_procs
    lo = local_rank * per
    hi = len(all_cores) if local_rank == num_local_procs - 1 else lo + per
    cores = all_cores[lo:hi]
    mem_nodes = sorted({i for i, node in enumerate(numa_nodes)
                        if set(node) & set(cores)})
    cmd = ["numactl", "-C", _compact(cores)]
    if mem_nodes and len(mem_nodes) < len(numa_nodes):
        cmd += ["-m", ",".join(str(n) for n in mem_nodes)]
    return cmd, cores
