"""Meta-device model init.

Analog of ``OnDevice`` (``deepspeed/utils/init_on_device.py``): construct a
model "on meta" — shapes/dtypes only, no memory — then materialize onto real
devices later. The reference patches torch tensor constructors; under JAX this
is just ``jax.eval_shape`` (abstract tracing is native), and materialization
is a sharded init: each device initializes ONLY its shard, so a model larger
than any single host's memory can come up directly distributed — the job
``zero.Init`` (``partition_parameters.py:734``) does with constructor
monkey-patching.
"""
from typing import Any, Callable, Optional

import jax

from ..comm.topology import MeshTopology


def abstract_params(init_fn: Callable, *args, **kwargs) -> Any:
    """ShapeDtypeStruct tree of ``init_fn(*args)`` without allocating
    (the ``device='meta'`` construction path)."""
    return jax.eval_shape(init_fn, *args, **kwargs)


def materialize_sharded(init_fn: Callable, shardings: Any, *args,
                        **kwargs) -> Any:
    """Run the initializer SPMD: every device computes only its own shard
    (``zero.Init``'s partition-at-construction, minus the monkey-patching)."""
    return jax.jit(lambda: init_fn(*args, **kwargs),
                   out_shardings=shardings)()


class OnDevice:
    """Context-manager parity with the reference API::

        with OnDevice(dtype=jnp.bfloat16, device="meta"):
            shapes = model.init_params()        # abstract, if model supports it

    JAX needs no global patching, so this context only carries the
    configuration and offers :meth:`abstract` / :meth:`materialize`.
    """

    def __init__(self, dtype=None, device: str = "meta",
                 topology: Optional[MeshTopology] = None):
        self.dtype = dtype
        self.device = device
        self.topology = topology

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def abstract(self, init_fn: Callable, *args, **kwargs):
        return abstract_params(init_fn, *args, **kwargs)

    def materialize(self, init_fn: Callable, shardings, *args, **kwargs):
        return materialize_sharded(init_fn, shardings, *args, **kwargs)
