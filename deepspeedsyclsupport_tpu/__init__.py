"""deepspeedsyclsupport_tpu — a TPU-native distributed training + inference framework.

Brand-new JAX/XLA/Pallas/pjit design with the capabilities of the reference DeepSpeed
0.12.7 fork (delock/DeepSpeedSYCLSupport): one JSON-config engine composing DP / ZeRO-
style FSDP / TP / PP / Ulysses-SP / MoE-EP over a named TPU mesh, bf16/fp16 training,
sharded+universal checkpoints, a FastGen-class paged-KV serving engine, and the aux ring
(profiling, comm logging, monitoring, elasticity, autotuning).

Public API parity (reference ``deepspeed/__init__.py``):
  * :func:`initialize`        — ``deepspeed.initialize``        (``__init__.py:64``)
  * :func:`init_inference`    — ``deepspeed.init_inference``    (``__init__.py:269``)
  * :func:`init_distributed`  — ``deepspeed.init_distributed``
  * :mod:`comm`               — ``deepspeed.comm``
"""
from .version import __version__
from .utils import jax_compat as _jax_compat

if _jax_compat.enabled_by_env():
    # DSTPU_JAX_COMPAT=1: graft modern jax spellings (jax.shard_map with
    # check_vma/axis_names, lax.axis_size, sharding.get_abstract_mesh) onto
    # an older jax (utils/jax_compat.py). Opt-in — see the module docstring.
    _jax_compat.install()
from .accelerator import get_accelerator, set_accelerator
from .comm import init_distributed
from .comm.topology import MeshTopology, build_topology, get_world_topology

__all__ = [
    "__version__",
    "get_accelerator",
    "set_accelerator",
    "init_distributed",
    "MeshTopology",
    "build_topology",
    "get_world_topology",
    "initialize",
    "init_inference",
    "DeepSpeedTransformerLayer",
    "DeepSpeedTransformerConfig",
]


def __getattr__(name):
    # top-level aliases the reference exports from deepspeed/__init__.py,
    # resolved lazily so importing the package stays light
    if name in ("DeepSpeedTransformerLayer", "DeepSpeedTransformerConfig"):
        from .ops import transformer as _t

        return getattr(_t, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def initialize(*args, **kwargs):
    """Create a training :class:`~deepspeedsyclsupport_tpu.runtime.engine.Engine`
    (reference: ``deepspeed.initialize``, ``deepspeed/__init__.py:64``)."""
    from .runtime.engine import initialize as _impl

    return _impl(*args, **kwargs)


def init_inference(*args, **kwargs):
    """Create an inference engine (reference: ``deepspeed.init_inference``,
    ``deepspeed/__init__.py:269``)."""
    from .inference.engine import init_inference as _impl

    return _impl(*args, **kwargs)
