"""Import-path compat: ``deepspeed.pipe`` (reference ``deepspeed/pipe/``
re-exports ``PipelineModule``/schedules from ``runtime/pipe``). Ported
scripts keep their imports; the SPMD pipeline semantics live in
``parallel/pipeline.py``."""
from .parallel.pipeline import (InferenceSchedule,  # noqa: F401
                                PipelineModule, PipeSchedule,
                                TrainSchedule, partition_balanced,
                                partition_uniform, spmd_pipeline)
