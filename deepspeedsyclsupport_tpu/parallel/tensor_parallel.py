"""Tensor-parallel sharding helpers — the auto-TP analog.

The reference's inference auto-TP (``deepspeed/module_inject/auto_tp.py:483``
``AutoTP``) walks a torch module, pattern-detects Linears, and rewrites them into
``LinearLayer`` (column-split) / ``LinearAllreduce`` (row-split + allreduce).
On TPU the rewrite is unnecessary: TP is a *layout*, so auto-TP reduces to a rule
that maps parameter names/shapes → PartitionSpecs; XLA inserts the collectives
(the psum that ``LinearAllreduce`` hand-codes).

``auto_tp_rules`` is that rule for arbitrary user pytrees: column-parallel for
up-projections, row-parallel for down/output projections (recognized by the same
name conventions AutoTP keys on: ``o_proj/down_proj/out_proj/dense_4h_to_h/wo``…),
replicate everything else.
"""
from typing import Callable, Optional, Sequence, Tuple

# Output/down projections → row-parallel (shard input dim; XLA adds the psum).
# Mirrors AutoTP's allreduce-linear name list (auto_tp.py load policies).
ROW_PARALLEL_PATTERNS: Tuple[str, ...] = (
    "o_proj", "out_proj", "wo", "w_down", "down_proj", "dense_4h_to_h",
    "attention.dense", "fc2", "w2", "proj_out",
)
# Embedding-style tables → shard vocab dim
EMBEDDING_PATTERNS: Tuple[str, ...] = ("embed", "wte", "word_embeddings", "tok")


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "name", k))) for k in path).lower()


def auto_tp_rules(stacked_layer_key: Optional[str] = "layers",
                  row_patterns: Sequence[str] = ROW_PARALLEL_PATTERNS,
                  embed_patterns: Sequence[str] = EMBEDDING_PATTERNS
                  ) -> Callable:
    """Build an ``extra_rules(path, shape)`` callable for
    ``runtime/zero.tree_param_shardings`` from name heuristics."""

    def rules(path, shape):
        s = _path_str(path)
        ndim = len(shape)
        if ndim < 2:
            return None
        stacked = stacked_layer_key is not None and stacked_layer_key in s
        pre = (None,) if (stacked and ndim >= 3) else ()
        body = ndim - len(pre)
        if body < 2:
            return None
        if any(p in s for p in embed_patterns):
            return pre + ("model",) + (None,) * (body - 1)
        if any(p in s for p in row_patterns):
            # row-parallel: shard the (first body) input dim, fsdp the output dim
            return pre + ("model",) + ("fsdp",) + (None,) * (body - 2)
        # default column-parallel: output (last) dim over model, fsdp an input dim
        return pre + ("fsdp",) + (None,) * (body - 2) + ("model",)

    return rules


def column_parallel(*, stacked: bool = False) -> Tuple:
    """Spec for a [in, out] weight split on out (Megatron ColumnParallelLinear)."""
    return ((None,) if stacked else ()) + ("fsdp", "model")


def row_parallel(*, stacked: bool = False) -> Tuple:
    """Spec for a [in, out] weight split on in (Megatron RowParallelLinear)."""
    return ((None,) if stacked else ()) + ("model", "fsdp")


def vocab_parallel_embedding(table, input_ids):
    """Embedding lookup over a vocab-sharded table (Megatron
    VocabParallelEmbedding; reference analog: the sharded word-embedding
    containers in ``module_inject/``).

    A plain ``jnp.take`` on a table sharded ('model', 'fsdp') defeats the SPMD
    partitioner — it replicates the table then re-partitions ("involuntary full
    rematerialization"). This issues the Megatron pattern explicitly in a
    shard_map: each device looks up only ids inside its local vocab range,
    zero-fills the rest, and a psum over ``model`` combines; the hidden shards
    are all-gathered over ``fsdp``.

    table: [V, H] sharded ('model', 'fsdp'); input_ids: [B, S] sharded
    (('data','fsdp'), 'seq'). Returns [B, S, H] in the activation layout.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..comm import topology as topo_mod

    topo = topo_mod._WORLD_TOPOLOGY
    tp = topo.axis_sizes.get("model", 1) if topo is not None else 1
    # ANY manual axis (not just 'model') forbids the nested shard_map: the
    # ZeRO++ explicit step is manual over {data, fsdp} with 'model' auto, so
    # probing lax.axis_size('model') alone would miss it and this would
    # nest a shard_map over already-manual axes (trace error)
    in_manual_region = bool(
        set(getattr(jax.sharding.get_abstract_mesh(), "manual_axes",
                    ()) or ()))
    sizes = topo.axis_sizes if topo is not None else {}
    bdiv = sizes.get("data", 1) * sizes.get("fsdp", 1)
    divisible = (topo is not None
                 and input_ids.shape[0] % bdiv == 0
                 and input_ids.shape[1] % sizes.get("seq", 1) == 0
                 and table.shape[0] % tp == 0
                 and table.shape[1] % sizes.get("fsdp", 1) == 0)
    # fsdp > 1 alone (stage-3 tables with no TP: hidden sharded over fsdp,
    # e.g. the MiCS leg) also needs the explicit pattern — a plain take on
    # the fsdp-sharded table makes the cotangent reshard "involuntary full
    # rematerialization" in the partitioner
    if topo is None or (tp == 1 and sizes.get("fsdp", 1) == 1) \
            or in_manual_region or not divisible:
        return jnp.take(table, input_ids, axis=0)

    def body(tbl, ids):
        # tbl: [V/tp, H/fsdp]; ids: [B/(data·fsdp), S/sp]. The batch and the
        # hidden dim are BOTH fsdp-sharded, so assembling full-hidden rows
        # takes an all-to-all, not an all-gather: each rank looks up its
        # hidden slice for every row in its fsdp group, then the a2a sends
        # row-groups home while concatenating the hidden slices. (A plain
        # hidden all-gather would pair this rank's rows with OTHER ranks'
        # rows' hidden slices — corrupted embeddings.)
        vstart = lax.axis_index("model") * tbl.shape[0]
        ids_g = lax.all_gather(ids, "fsdp", axis=0, tiled=True)
        local = ids_g - vstart
        ok = jnp.logical_and(local >= 0, local < tbl.shape[0])
        x = jnp.take(tbl, jnp.where(ok, local, 0), axis=0)
        x = jnp.where(ok[..., None], x, jnp.zeros_like(x))
        x = lax.psum(x, "model")
        return lax.all_to_all(x, "fsdp", split_axis=0, concat_axis=2,
                              tiled=True)

    return jax.shard_map(
        body, mesh=topo.mesh,
        in_specs=(P("model", "fsdp"), P(("data", "fsdp"), "seq")),
        out_specs=P(("data", "fsdp"), "seq", None),
        check_vma=False)(table, input_ids)
