"""Tensor-parallel sharding helpers — the auto-TP analog.

The reference's inference auto-TP (``deepspeed/module_inject/auto_tp.py:483``
``AutoTP``) walks a torch module, pattern-detects Linears, and rewrites them into
``LinearLayer`` (column-split) / ``LinearAllreduce`` (row-split + allreduce).
On TPU the rewrite is unnecessary: TP is a *layout*, so auto-TP reduces to a rule
that maps parameter names/shapes → PartitionSpecs; XLA inserts the collectives
(the psum that ``LinearAllreduce`` hand-codes).

``auto_tp_rules`` is that rule for arbitrary user pytrees: column-parallel for
up-projections, row-parallel for down/output projections (recognized by the same
name conventions AutoTP keys on: ``o_proj/down_proj/out_proj/dense_4h_to_h/wo``…),
replicate everything else.
"""
from typing import Callable, Optional, Sequence, Tuple

# Output/down projections → row-parallel (shard input dim; XLA adds the psum).
# Mirrors AutoTP's allreduce-linear name list (auto_tp.py load policies).
ROW_PARALLEL_PATTERNS: Tuple[str, ...] = (
    "o_proj", "out_proj", "wo", "w_down", "down_proj", "dense_4h_to_h",
    "attention.dense", "fc2", "w2", "proj_out",
)
# Embedding-style tables → shard vocab dim
EMBEDDING_PATTERNS: Tuple[str, ...] = ("embed", "wte", "word_embeddings", "tok")


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "name", k))) for k in path).lower()


def auto_tp_rules(stacked_layer_key: Optional[str] = "layers",
                  row_patterns: Sequence[str] = ROW_PARALLEL_PATTERNS,
                  embed_patterns: Sequence[str] = EMBEDDING_PATTERNS
                  ) -> Callable:
    """Build an ``extra_rules(path, shape)`` callable for
    ``runtime/zero.tree_param_shardings`` from name heuristics."""

    def rules(path, shape):
        s = _path_str(path)
        ndim = len(shape)
        if ndim < 2:
            return None
        stacked = stacked_layer_key is not None and stacked_layer_key in s
        pre = (None,) if (stacked and ndim >= 3) else ()
        body = ndim - len(pre)
        if body < 2:
            return None
        if any(p in s for p in embed_patterns):
            return pre + ("model",) + (None,) * (body - 1)
        if any(p in s for p in row_patterns):
            # row-parallel: shard the (first body) input dim, fsdp the output dim
            return pre + ("model",) + ("fsdp",) + (None,) * (body - 2)
        # default column-parallel: output (last) dim over model, fsdp an input dim
        return pre + ("fsdp",) + (None,) * (body - 2) + ("model",)

    return rules


def column_parallel(*, stacked: bool = False) -> Tuple:
    """Spec for a [in, out] weight split on out (Megatron ColumnParallelLinear)."""
    return ((None,) if stacked else ()) + ("fsdp", "model")


def row_parallel(*, stacked: bool = False) -> Tuple:
    """Spec for a [in, out] weight split on in (Megatron RowParallelLinear)."""
    return ((None,) if stacked else ()) + ("model", "fsdp")
