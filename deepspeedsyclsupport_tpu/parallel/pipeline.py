"""Pipeline parallelism over the ``pipe`` mesh axis.

TPU-native analog of the reference pipeline stack (``deepspeed/runtime/pipe/``,
~3.1k LoC):

* ``PipelineModule`` + ``LayerSpec`` (``runtime/pipe/module.py:636``) — layer list
  partitioned onto stages by uniform/parameter balance.
* ``PipelineEngine._exec_schedule`` (``runtime/pipe/engine.py:1357``) — an
  instruction interpreter driven by generated schedules.
* ``TrainSchedule`` (1F1B) / ``InferenceSchedule`` (``runtime/pipe/schedule.py:189,
  135``) and the instruction classes (``schedule.py:327-489``).
* p2p activation/grad exchange (``runtime/pipe/p2p.py``).

Architecture shift (why this is ~10× smaller): the reference runs ONE PROCESS PER
STAGE and must hand-schedule sends/recvs and the 1F1B interleave, because eager
torch has no global program view. Under XLA SPMD the pipeline is a single jitted
program over the whole mesh: stage parameters are sharded over ``pipe`` on the
layer dim, microbatch activations rotate between neighbor stages with
``lax.ppermute`` (ICI neighbor hops — exactly the p2p the reference does over
NCCL), and a ``lax.scan`` over clock ticks drives the fill/steady/drain phases.
Because ``ppermute``/``scan`` are differentiable, the BACKWARD pipeline — reverse
ppermutes, reverse tick order, i.e. the other half of the reference's 1F1B
instruction stream — is derived by autodiff instead of hand-written
(``_exec_backward_pass`` / SendGrad / RecvGrad, ``pipe/engine.py:730,1008,1107``).
Activation memory is bounded with ``jax.checkpoint`` on the stage body, the analog
of the reference's activation-checkpointing integration (``pipe/engine.py:651``).

The instruction-schedule layer is still provided (host-level) for two reasons:
parity testing against the reference's schedule semantics, and driving a future
multi-controller host-loop executor where jit-per-stage is preferable (e.g. very
heterogeneous stages).
"""
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..comm.topology import MeshTopology

# ============================================================================
# Instruction schedule (parity layer with runtime/pipe/schedule.py)
# ============================================================================


class PipeInstruction:
    """Base instruction (reference ``schedule.py:327``)."""

    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    pass


class ForwardPass(PipeInstruction):
    pass


class BackwardPass(PipeInstruction):
    pass


class SendActivation(PipeInstruction):
    pass


class RecvActivation(PipeInstruction):
    pass


class SendGrad(PipeInstruction):
    pass


class RecvGrad(PipeInstruction):
    pass


class PipeSchedule:
    """Iterable of per-clock-tick instruction lists (reference ``schedule.py:12``)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self) -> int:
        return 2

    def steps(self):
        raise NotImplementedError

    def __iter__(self):
        return iter(self.steps())


class InferenceSchedule(PipeSchedule):
    """Forward-only fill/drain (reference ``schedule.py:135``)."""

    def steps(self):
        total = self.micro_batches + self.stages - 1
        out: List[List[PipeInstruction]] = []
        for t in range(total):
            cmds: List[PipeInstruction] = []
            mb = t - self.stage_id
            if 0 <= mb < self.micro_batches:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=mb % 2, micro_batch_id=mb))
                else:
                    cmds.append(RecvActivation(buffer_id=mb % 2, micro_batch_id=mb))
                cmds.append(ForwardPass(buffer_id=mb % 2, micro_batch_id=mb))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=mb % 2, micro_batch_id=mb))
            out.append(cmds)
        return out


class TrainSchedule(PipeSchedule):
    """1F1B: warmup forwards, steady one-forward-one-backward, drain backwards,
    then grad reduce + optimizer step (reference ``schedule.py:189``)."""

    def num_pipe_buffers(self) -> int:
        # in-flight activations on this stage (reference ``schedule.py:312``)
        return max(2, min(self.micro_batches, self.stages - self.stage_id))

    def steps(self):
        m, s, i = self.micro_batches, self.stages, self.stage_id
        warmup = min(s - i - 1, m)
        nbuf = self.num_pipe_buffers()
        out: List[List[PipeInstruction]] = []

        def fwd(mb):
            cmds: List[PipeInstruction] = []
            buf = mb % nbuf
            if self.is_first_stage:
                cmds.append(LoadMicroBatch(buffer_id=buf, micro_batch_id=mb))
            else:
                cmds.append(RecvActivation(buffer_id=buf, micro_batch_id=mb))
            cmds.append(ForwardPass(buffer_id=buf, micro_batch_id=mb))
            if not self.is_last_stage:
                cmds.append(SendActivation(buffer_id=buf, micro_batch_id=mb))
            return cmds

        def bwd(mb):
            cmds: List[PipeInstruction] = []
            buf = mb % nbuf
            if not self.is_last_stage:
                cmds.append(RecvGrad(buffer_id=buf, micro_batch_id=mb))
            cmds.append(BackwardPass(buffer_id=buf, micro_batch_id=mb))
            if not self.is_first_stage:
                cmds.append(SendGrad(buffer_id=buf, micro_batch_id=mb))
            return cmds

        f_next = 0  # next microbatch to forward
        b_next = 0  # next microbatch to backward
        for _ in range(warmup):
            out.append(fwd(f_next))
            f_next += 1
        # steady 1F1B
        while f_next < m:
            out.append(fwd(f_next))
            f_next += 1
            out.append(bwd(b_next))
            b_next += 1
        # drain
        while b_next < m:
            out.append(bwd(b_next))
            b_next += 1
        out.append([ReduceTiedGrads(), ReduceGrads(), OptimizerStep()])
        return out


# ============================================================================
# Stage partitioning (parity with runtime/pipe/module.py LayerSpec/partitioning)
# ============================================================================


def partition_balanced(weights: Sequence[float], num_parts: int) -> List[int]:
    """Split ``weights`` into ``num_parts`` contiguous chunks minimizing the max
    chunk sum (reference ``ds_utils.partition_balanced`` used by
    ``PipelineModule._partition_layers`` with ``partition_method='parameters'``).
    Returns part boundaries of length num_parts+1. DP over prefix sums, O(n²·p).
    """
    n = len(weights)
    if num_parts > n:
        raise ValueError(f"cannot split {n} layers into {num_parts} stages")
    prefix = np.concatenate([[0.0], np.cumsum(weights)])
    # cost[j][k] = best max-sum splitting first j items into k parts
    INF = float("inf")
    cost = np.full((n + 1, num_parts + 1), INF)
    back = np.zeros((n + 1, num_parts + 1), dtype=int)
    cost[0][0] = 0.0
    for k in range(1, num_parts + 1):
        for j in range(k, n + 1):
            for i in range(k - 1, j):
                c = max(cost[i][k - 1], prefix[j] - prefix[i])
                if c < cost[j][k]:
                    cost[j][k] = c
                    back[j][k] = i
    bounds = [n]
    j, k = n, num_parts
    while k > 0:
        j = back[j][k]
        bounds.append(j)
        k -= 1
    return list(reversed(bounds))


def partition_uniform(num_layers: int, num_parts: int) -> List[int]:
    """Uniform layer-count split (reference ``partition_method='uniform'``)."""
    return partition_balanced([1.0] * num_layers, num_parts)


# ============================================================================
# SPMD collective pipeline (the jitted TPU execution path)
# ============================================================================


def _spmd_pipeline_body(stage_fn: Callable, local_params: Any, x: jnp.ndarray,
                        extras: Any, axis: str
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """shard_map body: collective 1F1B-equivalent pipeline over ``axis``.

    ``x``: [n_micro, mb, ...] microbatched activations, replicated over ``axis``
    (only stage 0 reads them). ``local_params``: this stage's layer stack.
    ``extras``: pytree of [n_micro, ...] per-microbatch side inputs (positions,
    segment ids) that travel WITH each microbatch along the ring.
    ``stage_fn(local_params, h, extras_mb) -> (h, aux)``.
    Returns ([n_micro, mb, ...] outputs, [n_micro] aux sums), valid on the
    LAST stage (garbage elsewhere); callers broadcast via masked psum.

    Clock loop (reference ``_exec_schedule`` ``pipe/engine.py:1357``): at tick t,
    stage s computes microbatch (t - s) if in range; the carried state then
    rotates one hop along the ring (``ppermute`` = the p2p SendActivation/
    RecvActivation pair, ``pipe/p2p.py``), so activations reach stage s+1 at tick
    t+1. Total ticks = n_micro + n_stages - 1 (fill + steady + drain).
    """
    n_stages = jax.lax.psum(1, axis)
    stage = jax.lax.axis_index(axis)
    n_micro = x.shape[0]
    ticks = n_micro + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def mb_at(tree, t):
        return jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False), tree)

    def tick(carry, t):
        (h, aux, ex), outputs, aux_out = carry
        h_in = jnp.where(stage == 0, mb_at(x, t).astype(h.dtype), h)
        ex_in = jax.tree_util.tree_map(
            lambda fresh, rot: jnp.where(stage == 0, fresh, rot),
            mb_at(extras, t), ex)
        aux_in = jnp.where(stage == 0, 0.0, aux)
        out, aux_add = stage_fn(local_params, h_in, ex_in)
        aux_mb = aux_in + aux_add
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        valid = (stage == n_stages - 1) & (t >= n_stages - 1)
        cur = jax.lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(valid, out, cur), out_idx, 0)
        cur_a = jax.lax.dynamic_index_in_dim(aux_out, out_idx, 0,
                                             keepdims=False)
        aux_out = jax.lax.dynamic_update_index_in_dim(
            aux_out, jnp.where(valid, aux_mb, cur_a), out_idx, 0)
        h, aux, ex = jax.tree_util.tree_map(
            lambda a: jax.lax.ppermute(a, axis, perm), (out, aux_mb, ex_in))
        return ((h, aux, ex), outputs, aux_out), None

    state0 = (jnp.zeros_like(x[0]), jnp.zeros((), jnp.float32),
              jax.tree_util.tree_map(jnp.zeros_like, mb_at(extras, 0)))
    carry0 = (state0, jnp.zeros_like(x), jnp.zeros((n_micro,), jnp.float32))
    ((_, outputs, aux_out), _) = jax.lax.scan(tick, carry0, jnp.arange(ticks))
    return outputs, aux_out


def broadcast_from_last(y: jnp.ndarray, axis: str = "pipe") -> jnp.ndarray:
    """Replicate last-stage outputs to every pipe rank (the analog of the
    reference's final loss broadcast, ``pipe/engine.py`` train_batch tail)."""
    from ..comm import comm

    if y.dtype == jnp.bfloat16 and jax.default_backend() != "tpu":
        # XLA CPU's AllReducePromotion pass aborts cloning this bf16
        # all-reduce inside the partial-manual region (hlo_instruction.cc
        # "Invalid binary instruction opcode copy"); route around it off-TPU
        return broadcast_from_last(y.astype(jnp.float32),
                                   axis).astype(jnp.bfloat16)
    n_stages = jax.lax.psum(1, axis)
    return comm.broadcast(y, axis, src=n_stages - 1)


def spmd_pipeline(layer_fn: Callable,
                  stacked_params: Any,
                  x: jnp.ndarray,
                  topology: MeshTopology,
                  *,
                  n_microbatches: Optional[int] = None,
                  remat: bool = True,
                  batch_axes: Tuple[str, ...] = ("data", "fsdp"),
                  extras: Any = (),
                  with_aux: bool = False):
    """Run a stack of homogeneous layers as a pipeline over the ``pipe`` axis.

    ``layer_fn(layer_params, h) -> h`` — one layer, uniform activation shape
    (the transformer-trunk contract; embed/head run outside the pipeline).
    With ``with_aux=True`` the contract is ``layer_fn(layer_params, h,
    extras) -> (h, aux)`` where ``extras`` is a pytree of [batch, ...]
    per-sample side inputs (positions, segment ids) that is microbatched and
    travels with each microbatch, and ``aux`` is a scalar summed over layers
    and microbatches (MoE aux losses) — the return becomes ``(y, aux_sum)``.
    ``stacked_params``: pytree with leading layer dim L on every leaf (the
    scan-over-layers layout); sharded over ``pipe`` on that dim.
    ``x``: [batch, ...] activations; reshaped to [n_micro, mb, ...] internally.

    The shard_map is MANUAL over ``pipe`` only (``axis_names={'pipe'}``):
    fsdp/tp/expert shardings inside the stage body stay under GSPMD, so the
    pipeline composes with ZeRO-3 and tensor parallelism instead of
    gathering their shards (the reference composes PipelineEngine with ZeRO
    the same way — stage-local DP groups, ``runtime/pipe/engine.py:55``).

    Differentiable: ``jax.grad`` through this yields the reverse (backward)
    pipeline schedule automatically.
    """
    n_stages = topology.axis_sizes["pipe"]
    n_micro = n_microbatches or max(n_stages, 1)
    mesh = topology.mesh

    def scan_layers(local_params, h, ex):
        if with_aux:
            def body(carry, lp):
                hh, aux = carry
                hh, a = layer_fn(lp, hh, ex)
                return (hh, aux + a), None

            (h, aux), _ = jax.lax.scan(
                body, (h, jnp.zeros((), jnp.float32)), local_params)
            return h, aux

        def body(hh, lp):
            return layer_fn(lp, hh), None

        out, _ = jax.lax.scan(body, h, local_params)
        return out, jnp.zeros((), jnp.float32)

    stage_fn = jax.checkpoint(scan_layers) if remat else scan_layers

    if n_stages == 1:
        y, aux = stage_fn(stacked_params, x, extras)
        return (y, aux) if with_aux else y

    assert x.shape[0] % n_micro == 0, (
        f"batch {x.shape[0]} not divisible by n_microbatches {n_micro}")
    mb = x.shape[0] // n_micro

    # Keep the microbatch dim sharded over the largest prefix of batch_axes
    # that divides it (these axes stay AUTO — the constraint just guides
    # GSPMD; dropping an axis replicates the work across it — warn).
    kept: Tuple[str, ...] = batch_axes
    while kept and mb % int(np.prod([topology.axis_sizes[a] for a in kept])) != 0:
        kept = kept[:-1]
    if kept != batch_axes:
        from ..utils.logging import logger

        logger.warning(
            "pipeline microbatch size %d not divisible by %s sizes; sharding "
            "over %s only (rest replicated — consider fewer microbatches)",
            mb, batch_axes, kept or "nothing")

    def microbatch(a):
        # STRIDED split (microbatch m = rows {m, n_micro+m, ...}), not
        # contiguous: the batch arrives sharded over (data, fsdp) on dim 0,
        # and a contiguous [n_micro, mb, ...] reshape makes GSPMD shard the
        # *microbatch* dim over data (idle devices per scan step) and then
        # full-rematerialize against the mb-dim constraint. Splitting
        # [mb, n_micro, ...] then transposing keeps every device busy on
        # every microbatch with zero resharding — the reshape preserves the
        # device order of the batch dim and the transpose just permutes the
        # sharded dims. Constraints pin BOTH sides of the transpose so GSPMD
        # can't invent a third layout in between (it otherwise spreads the
        # mb dim over idle mesh axes and replicate-repartitions against the
        # pinned side). Row order is restored exactly on the way out.
        a2 = a.reshape((a.shape[0] // n_micro, n_micro) + a.shape[1:])
        if kept:
            a2 = jax.lax.with_sharding_constraint(
                a2, topology.sharding(kept))
        out = jnp.swapaxes(a2, 0, 1)
        if kept:
            out = jax.lax.with_sharding_constraint(
                out, topology.sharding(None, kept))
        return out

    xm = microbatch(x)
    exm = jax.tree_util.tree_map(microbatch, extras)

    # Specs constrain ONLY the manual axis ('pipe'): the stacked layer dim
    # splits into per-stage stacks; activations/extras replicate over pipe.
    param_specs = jax.tree_util.tree_map(lambda p: P("pipe"), stacked_params)
    ex_specs = jax.tree_util.tree_map(lambda e: P(), exm)

    # Off-TPU, bf16 values must not cross the manual-region boundary: the AD
    # transpose of the replicated-over-pipe input is a bf16 psum, which
    # XLA CPU's AllReducePromotion pass aborts on (see broadcast_from_last).
    compute_dtype = x.dtype
    boundary_cast = (compute_dtype == jnp.bfloat16
                     and jax.default_backend() != "tpu")
    if boundary_cast:
        xm = xm.astype(jnp.float32)

    # re-pin after the cast — a convert between constraint and boundary
    # gives GSPMD room to pick a different layout and full-rematerialize
    if kept:
        xm = jax.lax.with_sharding_constraint(
            xm, topology.sharding(None, kept))

    def body(local_params, xmb, ex):
        # Output lives on the last stage only; broadcast so every pipe rank
        # returns the same (replicated-over-pipe) value.
        out, aux = _spmd_pipeline_body(stage_fn, local_params,
                                       xmb.astype(compute_dtype), ex, "pipe")
        return (broadcast_from_last(out, "pipe"),
                broadcast_from_last(aux, "pipe"))

    # jit wrapper: the partial-manual (axis_names={'pipe'}) shard_map only
    # lowers under a jit trace; eager callers (tests, scripts) hit a
    # different impl path that rejects auto axes
    y, aux = jax.jit(jax.shard_map(
        body, mesh=mesh, axis_names={"pipe"},
        in_specs=(param_specs, P(), ex_specs),
        out_specs=(P(), P()), check_vma=False))(stacked_params, xm, exm)
    # invert the strided split, pinning both sides of the transpose like on
    # the way in (the AD transpose of this pair is the warned reshard site)
    if kept:
        y = jax.lax.with_sharding_constraint(y, topology.sharding(None, kept))
    y = jnp.swapaxes(y, 0, 1)
    if kept:
        y = jax.lax.with_sharding_constraint(y, topology.sharding(kept))
    y = y.reshape(x.shape)
    return (y, aux.sum()) if with_aux else y


# ============================================================================
# PipelineModule — layer-list façade (reference runtime/pipe/module.py)
# ============================================================================


class PipelineModule:
    """Partition a homogeneous layer stack onto pipe stages and expose a
    pipelined apply (reference ``PipelineModule``, ``runtime/pipe/module.py:636``).

    The reference walks arbitrary ``LayerSpec`` lists because torch modules are
    heterogeneous objects; the TPU-native contract is a single ``layer_fn`` over
    stacked params (the scan-over-layers layout every model in ``models/`` uses),
    with ``embed_fn``/``head_fn`` bracketing the pipelined trunk, mirroring how
    the reference keeps tied embeddings outside the schedule (TiedLayerSpec).
    """

    def __init__(self,
                 layer_fn: Callable,
                 num_layers: int,
                 topology: MeshTopology,
                 embed_fn: Optional[Callable] = None,
                 head_fn: Optional[Callable] = None,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "uniform",
                 remat: bool = True):
        if partition_method != "uniform":
            # partition_balanced() exists for a future host-driven executor; the
            # SPMD pipeline shards the stacked layer dim evenly by construction.
            raise NotImplementedError(
                "the SPMD pipeline only supports partition_method='uniform' "
                "(homogeneous stacked layers give equal stages by construction)")
        self.layer_fn = layer_fn
        self.num_layers = num_layers
        self.topology = topology
        self.embed_fn = embed_fn
        self.head_fn = head_fn
        self.loss_fn = loss_fn
        self.remat = remat
        stages = topology.axis_sizes["pipe"]
        if num_layers % max(stages, 1) != 0:
            raise ValueError(
                f"num_layers {num_layers} must divide evenly into {stages} pipe "
                f"stages for the SPMD pipeline (pad with identity layers to round "
                f"up, as the reference's uniform partitioner does implicitly)")
        self.parts = partition_uniform(num_layers, stages)

    def __call__(self, params: Any, x: jnp.ndarray, *,
                 n_microbatches: Optional[int] = None) -> jnp.ndarray:
        """params: {'embed': ..., 'layers': stacked, 'head': ...} (embed/head
        optional)."""
        if self.embed_fn is not None:
            x = self.embed_fn(params.get("embed"), x)
        y = spmd_pipeline(self.layer_fn, params["layers"], x, self.topology,
                          n_microbatches=n_microbatches, remat=self.remat)
        if self.head_fn is not None:
            y = self.head_fn(params.get("head"), y)
        return y

    def loss(self, params: Any, batch: Any, rng=None):
        if self.loss_fn is None:
            raise ValueError("PipelineModule needs loss_fn for training")
        return self.loss_fn(self, params, batch, rng)
