"""Ring attention — context parallelism over the ``seq`` ICI ring.

ABSENT in the reference snapshot (SURVEY.md §2.4: "no ring-attention/context-
parallel impl — worth adding natively; ring attention over the ICI ring is a TPU
sweet spot"). This is the TPU-native long-context story alongside Ulysses: K/V
blocks rotate around the ``seq`` mesh axis via ``ppermute`` while each device
accumulates attention for its resident Q block with a streaming (online-softmax)
update — memory O(S/n) per device, comm fully overlapped with the block matmuls.

Math per incoming block (flash-attention accumulation):
    s      = q·kᵀ/√d  (masked by absolute positions → causal across blocks)
    m'     = max(m, rowmax(s))
    acc    = acc·e^{m-m'} + e^{s-m'}·v
    l      = l·e^{m-m'} + rowsum(e^{s-m'})
    out    = acc / l    (after all n blocks)

Causal zigzag (load-balanced tile skip): a naive causal ring computes all n
block pairs per device — fully-masked future blocks still burn MXU, and the
last device does n live blocks while device 0 does one, so the lockstep ring
runs at worst-case occupancy. Here the sequence is re-laid out so device i
owns half-chunks (i, 2n-1-i) of 2n global half-chunks (one early + one late
— the llama-3-style "zigzag" split). Then at every rotation each device has
exactly TWO live half-chunk products (plus one extra on the diagonal step),
so causal attention does ~(2n+1)/(4n) ≈ half the matmul work of the full
ring, statically — visible in XLA cost analysis, not a runtime branch. The
re-layout is two ppermutes per tensor (a 2-regular bipartite multigraph
always 2-colors into perfect matchings), amortized over the n-step ring.

GQA runs repeat-free: grouped-query heads are batched against their shared
KV head via a 5-d einsum instead of materializing ``jnp.repeat``-ed K/V.
"""
from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


# --------------------------------------------------------------------- GQA
def _scores(qf, k_t, scale):
    """q [B,Cq,KVH,G,D] fp32 × k [B,Ck,KVH,D] → s [B,KVH,G,Cq,Ck]."""
    return jnp.einsum("bqhgd,bkhd->bhgqk", qf,
                      k_t.astype(jnp.float32)) * scale


def _apply_v(p, v_t):
    """p [B,KVH,G,Cq,Ck] × v [B,Ck,KVH,D] → [B,KVH,G,Cq,D]."""
    return jnp.einsum("bhgqk,bkhd->bhgqd", p, v_t.astype(jnp.float32))


def _update(acc, m, l, qf, q_pos, k_t, v_t, kv_pos, scale, causal):
    """One online-softmax accumulation of an incoming KV block."""
    s = _scores(qf, k_t, scale)
    if causal:
        mask = kv_pos[None, :] <= q_pos[:, None]           # [Cq, Ck]
        s = jnp.where(mask[None, None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))                 # [B,KVH,G,Cq]
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    acc = acc * corr[..., None] + _apply_v(p, v_t)
    l = l * corr + p.sum(axis=-1)
    return acc, m_new, l


def _group_q(q, kvh):
    """[B,C,H,D] → [B,C,KVH,G,D] matching jnp.repeat's head order
    (q head h ↔ kv head h // G)."""
    b, c, h, d = q.shape
    return q.reshape(b, c, kvh, h // kvh, d)


def _ungroup(x):
    """[B,KVH,G,C,D] → [B,C,H,D]."""
    b, kvh, g, c, d = x.shape
    return x.transpose(0, 3, 1, 2, 4).reshape(b, c, kvh * g, d)


# ----------------------------------------------------------- zigzag re-layout
@lru_cache(maxsize=None)
def _zigzag_plan(n: int):
    """Static transfer plan moving contiguous half-chunks to zigzag layout.

    Global half-chunks h ∈ [0, 2n): device h//2 holds h (front if even).
    Zigzag target: chunk h lands on device h (lo slot) if h < n, else on
    device 2n-1-h (hi slot). The 2n transfers form a 2-regular bipartite
    multigraph over devices; walking its alternating cycles 2-colors it into
    two perfect matchings → two ppermutes. Returns per color:
    (perm, send_front[src_dev], recv_is_lo[dst_dev]) plus the inverse plan
    for routing the output back (reversed edges, same coloring validity).
    """
    edges = []
    for h in range(2 * n):
        edges.append({"chunk": h, "src": h // 2, "front": h % 2 == 0,
                      "dst": h if h < n else 2 * n - 1 - h, "lo": h < n})
    by_src = {}
    by_dst = {}
    for i, e in enumerate(edges):
        by_src.setdefault(e["src"], []).append(i)
        by_dst.setdefault(e["dst"], []).append(i)

    def other(lst, i):
        return lst[0] if lst[1] == i else lst[1]

    color = [None] * len(edges)
    for start in range(len(edges)):
        if color[start] is not None:
            continue
        i, c = start, 0
        while color[i] is None:
            color[i] = c
            j = other(by_src[edges[i]["src"]], i)      # same src → flip
            if color[j] is not None:
                break
            color[j] = 1 - c
            i = other(by_dst[edges[j]["dst"]], j)      # same dst → flip back

    def pack(edge_list, src_key, dst_key, front_key, lo_key):
        out = []
        for c in (0, 1):
            es = [e for e, col in zip(edge_list, color) if col == c]
            assert len({e[src_key] for e in es}) == n, "bad matching"
            assert len({e[dst_key] for e in es}) == n, "bad matching"
            perm = tuple((e[src_key], e[dst_key]) for e in es)
            send_front = [True] * n
            recv_lo = [True] * n
            for e in es:
                send_front[e[src_key]] = e[front_key]
                recv_lo[e[dst_key]] = e[lo_key]
            out.append((perm, tuple(send_front), tuple(recv_lo)))
        return tuple(out)

    fwd = pack(edges, "src", "dst", "front", "lo")
    # inverse: chunk flows dst→src; "front" now describes the DESTINATION
    # slot (is the chunk the front half at home), "lo" the SOURCE slot
    inv = pack(edges, "dst", "src", "lo", "front")
    # inverse: sent half is selected by the zig slot (lo/hi), received half
    # placed by front/back — pack() keeps (send=3rd key, recv=4th key)
    return fwd, inv


def _route(front, back, plan_colors, axis_name, idx):
    """Send the two resident halves through the 2-matching plan; returns the
    pair (slot0, slot1) where slot0 is the 'lo'/'front' slot per the plan's
    recv flags."""
    recvs = []
    for perm, send_first, _recv_first in plan_colors:
        sel = jnp.asarray(send_first)[idx]
        sent = jnp.where(sel, front, back)
        recvs.append(lax.ppermute(sent, axis_name, list(perm)))
    # exactly one of the two received chunks belongs in the first slot
    c0_first = jnp.asarray(plan_colors[0][2])[idx]
    a = jnp.where(c0_first, recvs[0], recvs[1])
    b = jnp.where(c0_first, recvs[1], recvs[0])
    return a, b


# ------------------------------------------------------------------- bodies
def _ring_body_flash(q, k, v, axis_name: str, n: int, causal: bool):
    """Pallas-flash inner ring (the ``attn_impl`` wiring the ROADMAP names:
    ulysses dispatches its local attention to the flash kernel; this is the
    ring's equivalent). Each incoming KV block is ONE flash-kernel call with
    explicit absolute positions (cross-block causality lives in position
    space) returning ``(out, lse)``; blocks merge in lse space — the same
    streaming-softmax algebra as the inline path, with the inner O(C²) loop
    on the MXU instead of jnp.

    A fully-masked (future) block reports ``lse = -1e30`` per row; the
    guard zeroes its weight — without it ``exp(-1e30 − (-1e30)) == 1``
    would credit phantom mass. No zigzag variant: the static tile-skip
    re-layout is an inline-path optimization; here the kernel masks
    in-block and the A/B prices exactly that trade."""
    idx = lax.axis_index(axis_name)
    b, c, h, d = q.shape
    q_pos = jnp.broadcast_to((idx * c + jnp.arange(c))[None, :], (b, c))
    acc = jnp.zeros((b, c, h, d), jnp.float32)
    m = jnp.full((b, c, h), NEG_INF, jnp.float32)
    l = jnp.zeros((b, c, h), jnp.float32)
    k_t, v_t = k, v
    from ..ops.flash_attention import flash_attention

    for t in range(n):  # unrolled — same rationale as the inline bodies
        src_blk = (idx - t) % n
        kv_pos = jnp.broadcast_to((src_blk * c + jnp.arange(c))[None, :],
                                  (b, c))
        o_b, lse_b = flash_attention(
            q, k_t, v_t, causal=causal,
            q_positions=q_pos if causal else None,
            kv_positions=kv_pos if causal else None,
            return_lse=True)
        live = lse_b > NEG_INF / 2  # [B,C,H] per-row: block contributes
        m_new = jnp.where(live, jnp.maximum(m, lse_b), m)
        corr = jnp.exp(m - m_new)
        w = jnp.where(live, jnp.exp(lse_b - m_new), 0.0)
        acc = acc * corr[..., None] + o_b.astype(jnp.float32) * w[..., None]
        l = l * corr + w
        m = m_new
        if t < n - 1:
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_t = lax.ppermute(k_t, axis_name, perm)
            v_t = lax.ppermute(v_t, axis_name, perm)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def _ring_body_full(q, k, v, axis_name: str, causal: bool):
    """Naive n-block ring (non-causal, or causal fallback for odd chunks).
    shard_map body. q/k/v local: [B, C, H, D] (C = S / ring_size)."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, c, h, d = q.shape
    kvh = k.shape[2]
    scale = 1.0 / np.sqrt(d)
    qf = _group_q(q.astype(jnp.float32), kvh)
    q_pos = idx * c + jnp.arange(c)

    def step(t, carry):
        k_t, v_t, acc, m, l = carry
        src_blk = (idx - t) % n
        kv_pos = src_blk * c + jnp.arange(c)
        acc, m, l = _update(acc, m, l, qf, q_pos, k_t, v_t, kv_pos, scale,
                            causal)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_t = lax.ppermute(k_t, axis_name, perm)
        v_t = lax.ppermute(v_t, axis_name, perm)
        return k_t, v_t, acc, m, l

    g = h // kvh
    acc0 = jnp.zeros((b, kvh, g, c, d), jnp.float32)
    m0 = jnp.full((b, kvh, g, c), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, kvh, g, c), jnp.float32)
    carry = (k, v, acc0, m0, l0)
    for t in range(n):  # unrolled — see the zigzag body's note
        carry = step(t, carry)
    _, _, acc, m, l = carry
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return _ungroup(out).astype(q.dtype)


def _ring_body_zigzag(q, k, v, axis_name: str, n: int):
    """Load-balanced causal ring. q/k/v local: [B, C, H, D], C even."""
    idx = lax.axis_index(axis_name)
    b, c, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    c2 = c // 2
    scale = 1.0 / np.sqrt(d)
    fwd, inv = _zigzag_plan(n)

    def halves(x):
        return x[:, :c2], x[:, c2:]

    q_lo, q_hi = _route(*halves(q), fwd, axis_name, idx)
    k_lo, k_hi = _route(*halves(k), fwd, axis_name, idx)
    v_lo, v_hi = _route(*halves(v), fwd, axis_name, idx)
    qf_lo = _group_q(q_lo.astype(jnp.float32), kvh)
    qf_hi = _group_q(q_hi.astype(jnp.float32), kvh)
    ar = jnp.arange(c2)
    qpos_lo = idx * c2 + ar
    qpos_hi = (2 * n - 1 - idx) * c2 + ar

    def zeros():
        return (jnp.zeros((b, kvh, g, c2, d), jnp.float32),
                jnp.full((b, kvh, g, c2), NEG_INF, jnp.float32),
                jnp.zeros((b, kvh, g, c2), jnp.float32))

    acc_lo, m_lo, l_lo = zeros()
    acc_hi, m_hi, l_hi = zeros()

    # diagonal step (j == idx): both resident diagonals plus hi×lo
    kv_lo0 = idx * c2 + ar
    kv_hi0 = (2 * n - 1 - idx) * c2 + ar
    acc_lo, m_lo, l_lo = _update(acc_lo, m_lo, l_lo, qf_lo, qpos_lo,
                                 k_lo, v_lo, kv_lo0, scale, True)
    acc_hi, m_hi, l_hi = _update(acc_hi, m_hi, l_hi, qf_hi, qpos_hi,
                                 k_lo, v_lo, kv_lo0, scale, True)
    acc_hi, m_hi, l_hi = _update(acc_hi, m_hi, l_hi, qf_hi, qpos_hi,
                                 k_hi, v_hi, kv_hi0, scale, True)

    def step(t, carry):
        (k_lo, k_hi, v_lo, v_hi,
         acc_lo, m_lo, l_lo, acc_hi, m_hi, l_hi) = carry
        # rotate FIRST: the diagonal step above consumed the resident blocks,
        # so iteration t works on KV that has moved t hops (and the last
        # rotation isn't wasted)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_lo, k_hi, v_lo, v_hi = (lax.ppermute(x, axis_name, perm)
                                  for x in (k_lo, k_hi, v_lo, v_hi))
        j = (idx - t) % n  # t is a python int (ring unrolled); j is traced
        kv_lo_pos = j * c2 + ar
        kv_hi_pos = (2 * n - 1 - j) * c2 + ar
        # product A — always live for t >= 1: Q_hi attends K_lo(j) in full
        acc_hi, m_hi, l_hi = _update(acc_hi, m_hi, l_hi, qf_hi, qpos_hi,
                                     k_lo, v_lo, kv_lo_pos, scale, True)
        # product B — Q_lo×K_lo when j < idx (past block), else Q_hi×K_hi.
        # Gather the TARGET accumulator, run ONE update (one QK + one PV
        # matmul — selects are data movement, not flops), scatter back.
        early = j < idx
        qf_b = jnp.where(early, qf_lo, qf_hi)
        qpos_b = jnp.where(early, qpos_lo, qpos_hi)
        k_b = jnp.where(early, k_lo, k_hi)
        v_b = jnp.where(early, v_lo, v_hi)
        kv_b_pos = jnp.where(early, kv_lo_pos, kv_hi_pos)
        acc_t = jnp.where(early, acc_lo, acc_hi)
        m_t = jnp.where(early, m_lo, m_hi)
        l_t = jnp.where(early, l_lo, l_hi)
        acc_t, m_t, l_t = _update(acc_t, m_t, l_t, qf_b, qpos_b,
                                  k_b, v_b, kv_b_pos, scale, True)
        acc_lo = jnp.where(early, acc_t, acc_lo)
        m_lo = jnp.where(early, m_t, m_lo)
        l_lo = jnp.where(early, l_t, l_lo)
        acc_hi = jnp.where(early, acc_hi, acc_t)
        m_hi = jnp.where(early, m_hi, m_t)
        l_hi = jnp.where(early, l_hi, l_t)
        return (k_lo, k_hi, v_lo, v_hi,
                acc_lo, m_lo, l_lo, acc_hi, m_hi, l_hi)

    # UNROLLED over the ring (n is static and small): XLA overlaps each
    # rotation's ppermute with the previous step's matmuls, and the whole
    # schedule — including the per-step work — is visible to cost analysis
    # (a fori_loop body is costed once regardless of trip count)
    carry = (k_lo, k_hi, v_lo, v_hi,
             acc_lo, m_lo, l_lo, acc_hi, m_hi, l_hi)
    for t in range(1, n):
        carry = step(t, carry)
    (_, _, _, _, acc_lo, m_lo, l_lo, acc_hi, m_hi, l_hi) = carry

    out_lo = _ungroup(acc_lo / jnp.maximum(l_lo, 1e-30)[..., None])
    out_hi = _ungroup(acc_hi / jnp.maximum(l_hi, 1e-30)[..., None])
    front, back = _route(out_lo, out_hi, inv, axis_name, idx)
    return jnp.concatenate([front, back], axis=1).astype(q.dtype)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool = True,
                   axis_name: str = "seq",
                   topology=None,
                   inner: Optional[str] = None) -> jnp.ndarray:
    """q/k/v: [B, S, H|KVH, D] logically global, sequence-sharded over ``seq``.

    ``inner`` selects the per-block attention implementation — the
    ``attn_impl`` seam ulysses already has: ``"flash"`` runs each KV block
    through the Pallas kernel (lse-combined across blocks, exact),
    ``"xla"`` keeps the inline online-softmax bodies (zigzag-balanced when
    causal), ``None`` auto-selects flash on TPU. Reachable from model
    configs as ``attn_impl="ring:flash"`` / ``"ring:xla"``."""
    from ..comm.topology import get_world_topology

    topo = topology or get_world_topology()
    n = topo.axis_sizes.get(axis_name, 1) if topo is not None else 1
    if inner is None:
        inner = "flash" if jax.default_backend() == "tpu" else "xla"
    if inner not in ("flash", "xla"):
        raise ValueError(f"unknown ring inner impl {inner!r} (flash | xla)")
    if n <= 1:
        if inner == "flash":
            from ..ops.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal)
        from ..models.layers import reference_attention

        return reference_attention(q, k, v, causal=causal)

    c = q.shape[1] // n  # local chunk per device
    if inner == "flash":
        body = partial(_ring_body_flash, axis_name=axis_name, n=n,
                       causal=causal)
    elif causal and c % 2 == 0 and c >= 2:
        body = partial(_ring_body_zigzag, axis_name=axis_name, n=n)
    else:
        body = partial(_ring_body_full, axis_name=axis_name, causal=causal)

    spec = P(("data", "fsdp"), axis_name, "model", None)
    fn = jax.shard_map(
        body,
        mesh=topo.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
