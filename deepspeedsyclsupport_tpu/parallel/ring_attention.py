"""Ring attention — context parallelism over the ``seq`` ICI ring.

ABSENT in the reference snapshot (SURVEY.md §2.4: "no ring-attention/context-
parallel impl — worth adding natively; ring attention over the ICI ring is a TPU
sweet spot"). This is the TPU-native long-context story alongside Ulysses: K/V
blocks rotate around the ``seq`` mesh axis via ``ppermute`` while each device
accumulates attention for its resident Q block with a streaming (online-softmax)
update — memory O(S/n) per device, comm fully overlapped with the block matmuls.

Math per incoming block (flash-attention accumulation):
    s      = q·kᵀ/√d  (masked by absolute positions → causal across blocks)
    m'     = max(m, rowmax(s))
    acc    = acc·e^{m-m'} + e^{s-m'}·v
    l      = l·e^{m-m'} + rowsum(e^{s-m'})
    out    = acc / l    (after all n blocks)
"""
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _ring_body(q, k, v, axis_name: str, causal: bool):
    """shard_map body. q/k/v local: [B, C, H, D] (C = S / ring_size)."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, c, h, d = q.shape
    kvh = k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / np.sqrt(d)
    qf = q.astype(jnp.float32)
    q_pos = idx * c + jnp.arange(c)

    def step(t, carry):
        k_t, v_t, acc, m, l = carry
        # after t rotations device idx holds kv block (idx - t) mod n
        src_blk = (idx - t) % n
        kv_pos = src_blk * c + jnp.arange(c)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_t.astype(jnp.float32)) * scale
        if causal:
            mask = kv_pos[None, :] <= q_pos[:, None]          # [C, C]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))                 # [B, H, C]
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])                      # [B, H, C, C]
        acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_t.astype(jnp.float32))
        l = l * corr + p.sum(axis=-1)
        # rotate kv to the next device on the ring (send up, recv from below)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_t = jax.lax.ppermute(k_t, axis_name, perm)
        v_t = jax.lax.ppermute(v_t, axis_name, perm)
        return k_t, v_t, acc, m_new, l

    acc0 = jnp.zeros((b, h, c, d), jnp.float32)
    m0 = jnp.full((b, h, c), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, c), jnp.float32)
    _, _, acc, m, l = jax.lax.fori_loop(0, n, step, (k, v, acc0, m0, l0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]               # [B, H, C, D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)           # [B, C, H, D]


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool = True,
                   axis_name: str = "seq",
                   topology=None) -> jnp.ndarray:
    """q/k/v: [B, S, H|KVH, D] logically global, sequence-sharded over ``seq``."""
    from ..comm.topology import get_world_topology

    topo = topology or get_world_topology()
    if topo.axis_sizes.get(axis_name, 1) <= 1:
        from ..models.layers import reference_attention

        return reference_attention(q, k, v, causal=causal)

    spec = P(("data", "fsdp"), axis_name, "model", None)
    fn = jax.shard_map(
        partial(_ring_body, axis_name=axis_name, causal=causal),
        mesh=topo.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
