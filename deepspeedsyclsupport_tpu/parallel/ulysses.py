"""Ulysses sequence parallelism (DeepSpeed-Ulysses), TPU-native.

The reference's ``DistributedAttention`` (``deepspeed/sequence/layer.py:60``)
wraps any attention with two explicit all-to-alls over the sequence process
group: scatter heads / gather sequence before local attention
(``_SeqAllToAll:44``, ``single_all_to_all:15``), and the inverse after.

This implementation issues the same two explicit all-to-alls with
``jax.lax.all_to_all`` inside a ``shard_map`` over the ``seq`` mesh axis.
An earlier version *declared* the layout change with a pair of
``with_sharding_constraint`` calls and let the SPMD partitioner infer the
collective — correct, but the partitioner lowered it as replicate-then-
repartition ("involuntary full rematerialization"), throwing away exactly
the traffic saving Ulysses exists for. Explicit ``all_to_all`` lowers to the
single fused ICI collective, and the backward all-to-alls fall out of AD
(``lax.all_to_all`` is its own transpose up to axis swap, the role of the
reference's symmetric ``_SeqAllToAll.backward``).

Requirement (same as the reference's assert in ``sequence/layer.py``): query
and kv head counts must be divisible by sp·tp.
"""
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.layers import reference_attention


def _local_attention(q, k, v, causal, segment_ids, inner):
    """Per-device attention over the full sequence with a head slice."""
    if inner is None:
        inner = "flash" if jax.default_backend() == "tpu" else "xla"
    if inner not in ("flash", "xla"):
        raise ValueError(f"unknown ulysses inner impl {inner!r} "
                         f"(flash | xla)")
    if inner == "flash":
        from ..ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids)


def _seq_all_to_all_body(q, k, v, segment_ids, *, causal, inner):
    """shard_map body: shards arrive [B/b, S/sp, H/tp, D] (seg: [B/b, S/sp]).

    all-to-all #1 over ``seq`` scatters heads / gathers sequence
    (→ [B/b, S, H/(sp·tp), D]); local attention sees the full sequence so
    causality and segment masking are exact; all-to-all #2 inverts.
    """
    from .. import comm

    q = comm.all_to_all(q, "seq", split_axis=2, concat_axis=1)
    k = comm.all_to_all(k, "seq", split_axis=2, concat_axis=1)
    v = comm.all_to_all(v, "seq", split_axis=2, concat_axis=1)
    if segment_ids is not None:
        segment_ids = comm.all_gather(segment_ids, "seq", axis=1)
    out = _local_attention(q, k, v, causal, segment_ids, inner)
    return comm.all_to_all(out, "seq", split_axis=1, concat_axis=2)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      causal: bool = True,
                      segment_ids: Optional[jnp.ndarray] = None,
                      inner: Optional[str] = None) -> jnp.ndarray:
    """q: [B, S, H, D], k/v: [B, S, KVH, D] (logically global; physically
    sequence-sharded over ``seq`` and head-sharded over ``model``).

    head-scatter/seq-gather all-to-all → local attention (full sequence,
    head slice) → seq-scatter/head-gather all-to-all.
    """
    from ..comm import topology as topo_mod

    topo = topo_mod._WORLD_TOPOLOGY
    sp = topo.axis_sizes.get("seq", 1) if topo is not None else 1

    try:
        bound = lax.axis_size("seq") > 0  # inside an enclosing shard_map?
    except NameError:
        bound = False
    if bound:
        # already in a manual-sharding region that binds ``seq`` — the caller
        # holds per-device shards, so issue the collectives directly.
        return _seq_all_to_all_body(q, k, v, segment_ids, causal=causal,
                                    inner=inner)

    if topo is None or sp == 1:
        return _local_attention(q, k, v, causal, segment_ids, inner)

    tp = topo.axis_sizes.get("model", 1)
    g = sp * tp
    h, kvh = q.shape[2], k.shape[2]
    if h % g:
        raise ValueError(
            f"ulysses needs q heads ({h}) divisible by sp*tp ({sp}*{tp}) — "
            f"reference sequence/layer.py has the same constraint")
    if kvh % g:
        # GQA with fewer kv heads than sp·tp: replicate kv heads up to the lcm
        # so every device owns a whole head after the scatter. consecutive
        # repetition preserves the q→kv group mapping; costs (lcm/kvh)× extra
        # KV bytes on the wire, the unavoidable GQA-under-Ulysses trade.
        r = np.lcm(kvh, g) // kvh
        if (kvh * r) and h % (kvh * r) == 0:
            k = jnp.repeat(k, r, axis=2)
            v = jnp.repeat(v, r, axis=2)
            kvh *= r
        else:
            raise ValueError(
                f"ulysses cannot align kv heads ({k.shape[2]}) with sp*tp "
                f"({sp}*{tp}) for q heads {h}")

    from jax.sharding import PartitionSpec as P

    batch = ("data", "fsdp")
    qkv_spec = P(batch, "seq", "model", None)
    specs_in = [qkv_spec, qkv_spec, qkv_spec]
    args = [q, k, v]
    if segment_ids is not None:
        specs_in.append(P(batch, "seq"))
        args.append(segment_ids)
        body = partial(_seq_all_to_all_body, causal=causal, inner=inner)
    else:
        body = lambda a, b, c: _seq_all_to_all_body(a, b, c, None,
                                                    causal=causal, inner=inner)
    return jax.shard_map(body, mesh=topo.mesh, in_specs=tuple(specs_in),
                         out_specs=qkv_spec, check_vma=False)(*args)
