"""Ulysses sequence parallelism (DeepSpeed-Ulysses), TPU-native.

The reference's ``DistributedAttention`` (``deepspeed/sequence/layer.py:60``) wraps
any attention with two explicit all-to-alls over the sequence process group:
scatter heads / gather sequence before local attention (``_SeqAllToAll:44``,
``single_all_to_all:15``), and the inverse after. Here the same data movement is
*declared*: activations arrive sequence-sharded ``[B, S/sp, H, D]``; re-constraining
to head-sharded ``[B, S, H/(sp·tp), D]`` makes the SPMD partitioner emit exactly the
all-to-all over the ``seq`` ICI axis, fused and overlapped by XLA — no hand-rolled
autograd op, and the backward all-to-alls fall out of AD.

Requirement (same as the reference, ``sequence/layer.py`` assert): total heads must
be divisible by sp·tp.
"""
from typing import Optional

import jax.numpy as jnp

from ..models.layers import BATCH, constrain, reference_attention


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      causal: bool = True,
                      segment_ids: Optional[jnp.ndarray] = None,
                      inner: Optional[str] = None) -> jnp.ndarray:
    """q: [B, S, H, D] (logically global; physically sequence-sharded over 'seq').

    head-scatter/seq-gather → local attention (full sequence, head slice) →
    seq-scatter/head-gather.
    """
    # incoming layout: sequence split over 'seq', heads split over 'model'
    q = constrain(q, BATCH, "seq", "model", None)
    k = constrain(k, BATCH, "seq", "model", None)
    v = constrain(v, BATCH, "seq", "model", None)

    # all-to-all #1: gather sequence, scatter heads over (model, seq)
    q = constrain(q, BATCH, None, ("model", "seq"), None)
    k = constrain(k, BATCH, None, ("model", "seq"), None)
    v = constrain(v, BATCH, None, ("model", "seq"), None)

    if inner == "flash":
        from ..ops.flash_attention import flash_attention

        out = flash_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    else:
        out = reference_attention(q, k, v, causal=causal,
                                  segment_ids=segment_ids)

    # all-to-all #2: back to sequence-sharded, heads gathered
    out = constrain(out, BATCH, None, ("model", "seq"), None)
    return constrain(out, BATCH, "seq", "model", None)
