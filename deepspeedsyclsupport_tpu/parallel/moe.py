"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

TPU-native rebuild of ``deepspeed/moe/`` (SURVEY.md §2.4 EP row):

* gating — ``TopKGate`` / ``top1gating`` / ``top2gating``
  (``moe/sharded_moe.py:348,184,282``): router logits → top-k experts, capacity
  truncation, load-balance aux loss ``E * Σ_e (mean_prob_e × token_frac_e)``.
* dispatch — the reference routes tokens with an explicit ``_AllToAll`` autograd op
  (``moe/sharded_moe.py:95``) between expert-parallel ranks. Here dispatch/combine
  are einsums against a one-hot capacity layout; with experts sharded over the
  ``expert`` axis and tokens over (data, fsdp), XLA lowers those einsums to exactly
  the all-to-all pair over ICI — no hand-written comm.
* expert compute — vmapped GLU over the expert dim (the grouped-GEMM the reference
  gets from CUTLASS, ``inference/v2/.../cutlass_multi_gemm.py``; on TPU the batched
  einsum hits the MXU directly).

Shapes: T tokens, E experts, C capacity, D model, F ffn.
"""
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.layers import constrain


def topk_gating(logits: jnp.ndarray, k: int, capacity: int,
                rng: Optional[jax.Array] = None,
                jitter: float = 0.0) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k gating with capacity (reference ``top1gating``/``top2gating``,
    ``moe/sharded_moe.py:184,282``).

    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights, aux_loss).
    """
    t, e = logits.shape
    if jitter > 0.0 and rng is not None:
        logits = logits * jax.random.uniform(
            rng, logits.shape, logits.dtype, 1.0 - jitter, 1.0 + jitter)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [T, E]

    # top-k expert ids per token
    _, expert_idx = jax.lax.top_k(probs, k)                       # [T, k]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)     # [T, k, E]

    # Load-balance aux loss (top2gating: uses the top-1 assignment fraction).
    me = probs.mean(axis=0)                                       # [E]
    ce = onehot[:, 0, :].mean(axis=0)                             # [E]
    aux_loss = jnp.sum(me * ce) * e

    # Position of each (token, choice) within its expert's capacity buffer.
    # Flatten choices in priority order: all top-1 choices first (they win capacity
    # slots over top-2 spill), matching the reference's top-2 ordering.
    flat = onehot.transpose(1, 0, 2).reshape(k * t, e)            # [k*T, E]
    pos_in_expert = jnp.cumsum(flat, axis=0) - flat               # [k*T, E]
    within = (pos_in_expert < capacity)
    flat = flat * within
    pos = (pos_in_expert * flat).sum(axis=-1)                     # [k*T]
    keep = flat.sum(axis=-1)                                      # [k*T] 0/1

    gate_w = jnp.take_along_axis(probs, expert_idx, axis=1)       # [T, k]
    gate_w = gate_w / jnp.maximum(gate_w.sum(axis=-1, keepdims=True), 1e-9)
    gate_flat = gate_w.transpose(1, 0).reshape(k * t) * keep      # [k*T]

    cap_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=jnp.float32)               # [k*T, C]
    # [k*T, E, C] → sum over choices → [T, E, C]
    dc = flat[:, :, None] * cap_onehot[:, None, :]
    dispatch = dc.reshape(k, t, e, capacity).sum(axis=0)
    combine = (gate_flat[:, None, None] * dc).reshape(
        k, t, e, capacity).sum(axis=0)
    return dispatch, combine, aux_loss


def moe_mlp(p: Dict[str, Any], x: jnp.ndarray, cfg,
            rng: Optional[jax.Array] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE GLU block (reference ``MOELayer.forward``, ``moe/sharded_moe.py:425``).

    x: [B, S, D] → (out [B, S, D], aux_loss scalar).
    """
    b, s, d = x.shape
    t = b * s
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    capacity = int(np.ceil(t * cfg.capacity_factor * k / e))
    capacity = max(capacity, k)

    xt = x.reshape(t, d)
    logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                        p["router"].astype(jnp.float32))
    dispatch, combine, aux = topk_gating(logits, k, capacity, rng,
                                         cfg.router_jitter)

    # dispatch → [E, C, D]; sharded over the expert axis so the einsum below is
    # the all-to-all the reference implements by hand (_AllToAll, sharded_moe.py:95)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), xt)
    expert_in = constrain(expert_in, "expert", None, None)

    act = jax.nn.silu if cfg.activation == "silu" else jax.nn.gelu

    def one_expert(w, h):  # h: [C, D]
        gate = jnp.einsum("cd,df->cf", h, w["w_gate"])
        up = jnp.einsum("cd,df->cf", h, w["w_up"])
        return jnp.einsum("cf,fd->cd", act(gate) * up, w["w_down"])

    expert_out = jax.vmap(one_expert)(
        {"w_gate": p["w_gate"], "w_up": p["w_up"], "w_down": p["w_down"]},
        expert_in)                                               # [E, C, D]
    expert_out = constrain(expert_out, "expert", None, None)

    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return out.reshape(b, s, d), aux.astype(jnp.float32)


def moe_mlp_nodrop(p: Dict[str, Any], x: jnp.ndarray, cfg) -> jnp.ndarray:
    """Exact top-k MoE for flat token streams (the serving path).

    The reference serves MoE through ``moe_scatter`` → CUTLASS grouped GEMM →
    ``moe_gather`` (``inference/v2/kernels/ragged_ops/``,
    ``modules/implementations/moe/cutlass_multi_gemm.py``). TPU-native
    equivalent: sort (token, choice) rows by expert and run the three expert
    GEMMs as ``jax.lax.ragged_dot`` grouped matmuls. No capacity truncation —
    inference must never drop a routed token (unlike the training path's
    capacity buffers, :func:`moe_mlp`).

    x: [T, D] flat tokens → [T, D].
    """
    t, d = x.shape
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        p["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    gate_w, expert_idx = jax.lax.top_k(probs, k)              # [T, k]
    gate_w = gate_w / jnp.maximum(gate_w.sum(-1, keepdims=True), 1e-9)

    flat_expert = expert_idx.reshape(t * k)
    flat_tok = jnp.repeat(jnp.arange(t), k)
    order = jnp.argsort(flat_expert, stable=True)             # moe_scatter
    sorted_tok = flat_tok[order]
    xs = x[sorted_tok]                                        # [T*k, D]
    group_sizes = jnp.bincount(flat_expert, length=e).astype(jnp.int32)

    act = jax.nn.silu if cfg.activation == "silu" else jax.nn.gelu
    wg = p["w_gate"].astype(x.dtype)
    wu = p["w_up"].astype(x.dtype)
    wd = p["w_down"].astype(x.dtype)
    gate = jax.lax.ragged_dot(xs, wg, group_sizes)
    up = jax.lax.ragged_dot(xs, wu, group_sizes)
    ys = jax.lax.ragged_dot(act(gate) * up, wd, group_sizes)  # [T*k, D]

    w_flat = gate_w.reshape(t * k)[order].astype(x.dtype)
    out = jnp.zeros((t, d), x.dtype).at[sorted_tok].add(      # moe_gather
        ys * w_flat[:, None])
    return out
