"""Parallelism strategies over the named mesh.

TPU-native analogs of the reference's strategy layer (SURVEY.md §2.4):

* :mod:`.moe` — expert parallel MoE (``deepspeed/moe/sharded_moe.py``)
* :mod:`.ulysses` — Ulysses sequence parallel (``deepspeed/sequence/layer.py``)
* :mod:`.ring_attention` — ring-attention context parallel (absent upstream; the
  TPU-native long-context addition, SURVEY.md §2.4 CP row)
* :mod:`.pipeline` — pipeline parallel 1F1B (``deepspeed/runtime/pipe/``)
* :mod:`.tensor_parallel` — TP sharding-rule helpers (``module_inject/auto_tp.py``)
"""
from .moe import moe_mlp, moe_mlp_nodrop, topk_gating  # noqa: F401
from .pipeline import (InferenceSchedule, PipelineModule,  # noqa: F401
                       TrainSchedule, partition_balanced, partition_uniform,
                       spmd_pipeline)
from .ring_attention import ring_attention  # noqa: F401
from .tensor_parallel import (auto_tp_rules, column_parallel,  # noqa: F401
                              row_parallel, vocab_parallel_embedding)
from .ulysses import ulysses_attention  # noqa: F401
