from .elasticity import (ElasticityConfigError, ElasticityError,  # noqa: F401
                         compute_elastic_config, get_compatible_gpus)
