from .elastic_agent import DSElasticAgent  # noqa: F401
from .elasticity import (ElasticityConfigError, ElasticityError,  # noqa: F401
                         compute_elastic_config, get_compatible_gpus)
