"""Elastic batch-size configuration.

Analog of ``deepspeed/elasticity/elasticity.py`` (774 LoC): given a maximum
acceptable global batch size and a set of candidate micro-batch sizes, find the
global batch size that stays valid across a whole RANGE of chip counts, so a job
can lose or gain hardware and resume without changing its effective batch (the
contract ``compute_elastic_config`` at ``elasticity/elasticity.py:233`` serves
for torchelastic; here the restart path is jax.distributed re-init + the
resharding checkpoint load, which needs no conversion).

The math is topology-independent and ports as pure functions. v0.2 semantics:
``model_parallel_size`` divides chips into model replicas first.
"""
from dataclasses import dataclass
from functools import reduce
from typing import Dict, List, Sequence, Tuple


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


def _lcm(a: int, b: int) -> int:
    from math import gcd

    return a * b // gcd(a, b)


def get_valid_gpus(batch_size: int, micro_batches: Sequence[int],
                   min_valid_gpus: int, max_valid_gpus: int) -> List[int]:
    """Chip counts that evenly factor ``batch_size = micro × gas × gpus`` for
    some micro in ``micro_batches`` (reference ``_get_valid_gpus``)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        max_gpus = batch_size // mb
        for g in range(1, max_gpus + 1):
            if max_gpus % g == 0 and min_valid_gpus <= g <= max_valid_gpus:
                valid.add(g)
    return sorted(valid)


def get_best_candidates(max_acceptable_batch_size: int,
                        micro_batches: Sequence[int],
                        min_gpus: int, max_gpus: int,
                        prefer_larger: bool
                        ) -> Tuple[int, List[int], Dict[int, List[int]]]:
    """Search candidate batch sizes (multiples of lcm(micro_batches) and power-
    of-two scalings, reference ``_get_compatible_candidate_batch_sizes``): pick
    the one covering the most chip counts, tie-broken by batch size."""
    base = reduce(_lcm, micro_batches)
    candidates = set()
    b = base
    while b <= max_acceptable_batch_size:
        candidates.add(b)
        b *= 2
    for mb in micro_batches:
        b = mb
        while b <= max_acceptable_batch_size:
            candidates.add(b)
            b *= 2
    best: Tuple[int, List[int]] = (0, [])
    table: Dict[int, List[int]] = {}
    for c in sorted(candidates, reverse=prefer_larger):
        gpus = get_valid_gpus(c, micro_batches, min_gpus, max_gpus)
        table[c] = gpus
        if len(gpus) > len(best[1]):
            best = (c, gpus)
    if not best[1]:
        raise ElasticityError(
            f"no batch size ≤ {max_acceptable_batch_size} compatible with "
            f"micro batches {list(micro_batches)} on {min_gpus}..{max_gpus} chips")
    return best[0], best[1], table


def get_compatible_gpus(max_acceptable_batch_size: int,
                        micro_batches: Sequence[int],
                        min_gpus: int = 1, max_gpus: int = 10000,
                        prefer_larger: bool = True) -> Tuple[int, List[int]]:
    b, gpus, _ = get_best_candidates(max_acceptable_batch_size, micro_batches,
                                     min_gpus, max_gpus, prefer_larger)
    return b, gpus


@dataclass
class ElasticResult:
    final_batch_size: int
    valid_gpus: List[int]
    micro_batch_per_gpu: int
    gradient_accumulation_steps: int


def compute_elastic_config(ds_config: dict, target_deployment_size: int = None,
                           return_microbatch: bool = True) -> ElasticResult:
    """Reference ``compute_elastic_config`` (``elasticity/elasticity.py:233``):
    resolve the elastic section against a concrete chip count.

    ``return_microbatch=False`` skips micro-batch/GAS resolution (the fields
    come back 0), matching the reference's two return shapes — use it when the
    deployment only needs the batch size and valid-chip-count set.
    """
    e = dict(ds_config.get("elasticity", {}))
    if not e.get("enabled", False):
        raise ElasticityConfigError("elasticity section missing or disabled")
    max_batch = int(e.get("max_train_batch_size", 0))
    micro_batches = [int(m) for m in e.get("micro_batch_sizes", [])]
    if max_batch < 1 or not micro_batches:
        raise ElasticityConfigError(
            "elasticity needs max_train_batch_size and micro_batch_sizes")
    min_gpus = int(e.get("min_gpus", 1))
    max_gpus = int(e.get("max_gpus", 10000))
    prefer_larger = bool(e.get("prefer_larger_batch", True))
    mp = int(e.get("model_parallel_size", 1))

    batch, gpus = get_compatible_gpus(max_batch, micro_batches, min_gpus,
                                      max_gpus, prefer_larger)
    if target_deployment_size is None:
        return ElasticResult(batch, gpus, 0, 0)
    if target_deployment_size % mp:
        raise ElasticityError(
            f"deployment of {target_deployment_size} chips does not divide by "
            f"model_parallel_size {mp} — {target_deployment_size % mp} chips "
            f"would be stranded")
    dp = target_deployment_size // mp
    if dp < 1 or dp not in gpus:
        raise ElasticityError(
            f"deployment of {target_deployment_size} chips (dp={dp} at "
            f"mp={mp}) is not in the valid set {gpus} for batch {batch}")
    if not return_microbatch:
        return ElasticResult(batch, gpus, 0, 0)
    # choose the largest compatible micro batch (fewest accumulation steps)
    per_gpu = batch // dp
    micro = max((m for m in micro_batches if per_gpu % m == 0), default=None)
    if micro is None:
        raise ElasticityError(
            f"no micro batch in {micro_batches} divides per-chip batch {per_gpu}")
    return ElasticResult(batch, gpus, micro, per_gpu // micro)
