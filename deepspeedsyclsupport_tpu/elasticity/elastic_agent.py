"""Elastic training agent — fault-tolerant restart supervision.

Analog of ``DSElasticAgent`` (reference ``elasticity/elastic_agent.py:28``, a
torchelastic ``LocalElasticAgent`` subclass): monitor workers, and on failure
re-admit the (possibly changed) membership and restart. The torchelastic
rendezvous is replaced by plain re-discovery at restart time — JAX's
coordinator-based ``jax.distributed`` has no dynamic membership, so an
elastic event is a process-tree restart with a recomputed world:

1. discover the current deployment size (env / hostfile),
2. resolve the elastic batch config for it (``compute_elastic_config`` —
   the same math the reference uses, ``elasticity/elasticity.py:233``),
3. export it to the workers (``DSTPU_ELASTIC_*`` env), spawn the command,
4. on a non-zero exit, loop — membership is re-discovered, the batch
   config re-resolved, and the restarted run resumes from its latest
   checkpoint (the engine's resharding-on-load makes topology-changing
   resume work; reference needs universal checkpoints for this).
"""
import os
import random
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .elasticity import ElasticityError, compute_elastic_config
from ..comm.watchdog import COMM_HANG_EXIT_CODE, SERVE_HANG_EXIT_CODE
from ..runtime.resilience import PREEMPTION_EXIT_CODE
# imported from sentinel.py directly (not via runtime.resilience) to keep the
# supervisor's import graph jax-free: sentinel's top level is stdlib+numpy
from ..runtime.sentinel import DIVERGENCE_EXIT_CODE
from ..utils.logging import logger


class DSElasticAgent:
    """Supervise an elastic training command (reference ``DSElasticAgent``).

    Restart accounting distinguishes three exit classes:

    * ``PREEMPTION_EXIT_CODE`` (217) — the worker caught SIGTERM, wrote an
      emergency checkpoint and exited cleanly. The restart is *free* (a
      preempted VM is fleet weather, not a crash loop) and relaunch is
      paced at the base backoff.
    * ``COMM_HANG_EXIT_CODE`` (218) — the collective watchdog
      (``comm/watchdog.py``) declared a hung all-reduce and aborted with
      stacks + flight recorder on disk. Counted separately
      (``comm_hang_restarts``, bounded by ``comm_hang_limit``) and backed
      off exponentially — a broken link would hot-loop — but never billed
      against ``restart_limit``: the code didn't crash, the fabric (or one
      host) did.
    * ``SERVE_HANG_EXIT_CODE`` (219) — the serving plane's stuck-decode
      watchdog (``inference/v2/serving.py``) declared a wedged decode
      dispatch: same treatment as 218 (own streak counter
      ``serve_hang_restarts``, bounded by ``serve_hang_limit``,
      exponential backoff, never billed to ``restart_limit``) — the
      restarted replica replays its request journal
      (``inference/v2/supervisor.py``).
    * ``DIVERGENCE_EXIT_CODE`` (220) — the training-health sentinel
      (``runtime/sentinel.py``) exhausted its skip/rollback ladder against
      a numerical fault (NaN'd state, runaway loss). Own streak counter
      (``divergence_restarts``, bounded by ``divergence_limit``),
      exponential backoff, never billed to ``restart_limit``: the restart
      resumes from the promoted *last-good* checkpoint and replays the
      health journal's skip decisions — but a model that diverges
      repeatedly from its best known state needs an operator, so the
      streak limit matters more here than for the hang classes.
    * any other non-zero rc — a real failure: counted against
      ``restart_limit`` and backed off exponentially
      (``backoff_seconds * 2^failures`` + jitter, capped at
      ``backoff_ceiling``) so a hard crash loop cannot hammer the cluster
      scheduler or a shared filesystem.

    With ``nprocs`` set the agent supervises a local POD: it spawns one
    process per rank (``RANK``/``LOCAL_RANK`` exported) and, the moment any
    rank exits non-zero, terminates the siblings immediately — they are
    wedged in a collective their dead peer will never join, and waiting for
    them to cascade into their own timeouts wastes the whole recovery
    budget. ``storm_limit`` caps TOTAL relaunches of any cause so no
    combination of free-restart classes can loop forever.
    """

    def __init__(self, cmd: Sequence[str], ds_config: Dict[str, Any],
                 min_nodes: int = 1, max_nodes: int = -1,
                 restart_limit: int = 3,
                 backoff_seconds: float = 0.0,
                 backoff_ceiling: float = 60.0,
                 backoff_jitter: float = 0.25,
                 backoff_seed: Optional[int] = None,
                 preemption_limit: Optional[int] = None,
                 comm_hang_limit: Optional[int] = None,
                 serve_hang_limit: Optional[int] = None,
                 divergence_limit: Optional[int] = None,
                 storm_limit: Optional[int] = None,
                 nprocs: Optional[int] = None,
                 teardown_grace: float = 5.0,
                 env: Optional[Dict[str, str]] = None,
                 hostfile: Optional[str] = None,
                 sleep_fn: Optional[Callable[[float], None]] = None,
                 heartbeat_file: Optional[str] = None,
                 heartbeat_timeout: Optional[float] = None,
                 heartbeat_poll: float = 1.0,
                 hang_grace: float = 5.0):
        self.cmd = list(cmd)
        self.ds_config = ds_config
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.restart_limit = restart_limit
        self.backoff_seconds = backoff_seconds
        self.backoff_ceiling = backoff_ceiling
        self.backoff_jitter = backoff_jitter
        # consecutive preemptions before the agent gives up and returns the
        # preemption rc (None = unbounded): a fleet-wide drain that SIGTERMs
        # every relaunch would otherwise loop forever
        self.preemption_limit = preemption_limit
        # consecutive watchdog comm-hang exits (rc 218) before giving up —
        # a persistently broken interconnect is not self-healing
        self.comm_hang_limit = comm_hang_limit
        # consecutive stuck-decode exits (rc 219, the serving-plane
        # watchdog) before giving up — same reasoning as comm hangs
        self.serve_hang_limit = serve_hang_limit
        # consecutive divergence exits (rc 220, the training-health
        # sentinel) before giving up — a run that keeps diverging from its
        # last-good checkpoint needs a human, not a restart loop
        self.divergence_limit = divergence_limit
        # restart-storm cap: TOTAL relaunches of ANY cause (failure,
        # preemption, comm hang). The per-class limits each bound their own
        # streak; this bounds their sum, so alternating causes can't dodge
        # every limit (None = unbounded).
        self.storm_limit = storm_limit
        # pod supervision: spawn nprocs rank processes per launch and tear
        # the survivors down promptly when any rank dies
        self.nprocs = nprocs
        self.teardown_grace = teardown_grace
        # seedable jitter so the fault-injection suite replays identically
        self._rng = random.Random(backoff_seed)
        self._sleep = sleep_fn or time.sleep
        self.extra_env = dict(env or {})
        self.hostfile = hostfile
        # Heartbeat watch (telemetry's per-rank freshness file,
        # ``monitor/telemetry.py::Heartbeat``): when the worker's heartbeat
        # goes stale past ``heartbeat_timeout`` the step is HUNG, not slow —
        # demand a faulthandler stack dump (SIGUSR1, registered by the
        # worker's telemetry), give it ``hang_grace`` seconds, then kill and
        # restart. None disables the watch.
        self.heartbeat_file = heartbeat_file
        self.heartbeat_timeout = heartbeat_timeout
        self.heartbeat_poll = heartbeat_poll
        self.hang_grace = hang_grace
        self.hang_count = 0
        self.restart_count = 0  # failures only — preemptions are free
        self.preemption_count = 0
        self.comm_hang_count = 0
        self.serve_hang_count = 0
        self.divergence_count = 0
        self.teardown_count = 0
        self.launch_history: List[Dict[str, Any]] = []
        # set by serving-mode subclasses (ReplicaSupervisor's drain path):
        # stop supervising after the current launch instead of relaunching
        self._stop_requested = False

    def next_backoff(self, consecutive_failures: int) -> float:
        """Capped exponential backoff + jitter for the Nth consecutive
        failure (1-based). Jitter is multiplicative in
        ``[1, 1 + backoff_jitter]`` — always *added* so the cap stays a true
        ceiling on the base and concurrent agents still de-synchronize."""
        if self.backoff_seconds <= 0:
            return 0.0
        base = min(self.backoff_ceiling,
                   self.backoff_seconds * (2 ** max(0, consecutive_failures - 1)))
        return base * (1.0 + self.backoff_jitter * self._rng.random())

    # ------------------------------------------------------------ membership
    def discover_world_size(self) -> int:
        """Chips in the current deployment: WORLD_SIZE env, hostfile slots,
        or the local device count."""
        if "WORLD_SIZE" in os.environ:
            return int(os.environ["WORLD_SIZE"])
        if self.hostfile and os.path.exists(self.hostfile):
            from ..launcher.runner import parse_hostfile

            return sum(slots for _, slots in parse_hostfile(self.hostfile))
        import jax

        return jax.device_count()

    def _resolve(self, world: int) -> Dict[str, str]:
        e = dict(self.ds_config.get("elasticity", {}))
        if not e.get("enabled", False):
            return {}
        r = compute_elastic_config(self.ds_config,
                                   target_deployment_size=world)
        return {
            "DSTPU_ELASTIC_BATCH": str(r.final_batch_size),
            "DSTPU_ELASTIC_MICRO_BATCH": str(r.micro_batch_per_gpu),
            "DSTPU_ELASTIC_GAS": str(r.gradient_accumulation_steps),
        }

    # ------------------------------------------------------------- heartbeat
    def _heartbeat_files(self) -> List[str]:
        """``heartbeat_file`` may be a glob (``heartbeat_rank*.json``) so a
        multi-rank local job is watched pod-wide — telemetry writes one
        freshness file PER RANK, and under SPMD one hung rank hangs every
        rank at the next collective."""
        import glob

        if self.heartbeat_file and glob.has_magic(self.heartbeat_file):
            return sorted(glob.glob(self.heartbeat_file))
        return [self.heartbeat_file] if self.heartbeat_file else []

    def _heartbeat_stale(self, launched_at: float) -> bool:
        from ..monitor.telemetry import Heartbeat

        ages = [Heartbeat.age(p) for p in self._heartbeat_files()]
        ages = [a for a in ages if a is not None]
        if not ages:
            # no beat yet: a worker that hangs in init (distributed setup,
            # first compile) never writes one — count staleness from launch.
            # Enabling the watch therefore REQUIRES worker telemetry
            # heartbeats; size the timeout to cover startup + first compile.
            # launched_at is monotonic: an NTP step during init must not
            # spuriously declare (or mask) a hang.
            ages = [time.monotonic() - launched_at]
        # the STALEST rank decides: one hung rank is a hung pod
        return max(ages) > self.heartbeat_timeout

    def _launch(self, env: Dict[str, str]) -> int:
        """Run one worker attempt. Without a heartbeat watch this is a plain
        blocking wait; with one, poll the freshness file and escalate on
        staleness: SIGUSR1 (worker faulthandler dumps all stacks) → grace →
        SIGTERM → SIGKILL. A hang-killed worker returns a negative rc and is
        counted as a failure by :meth:`run`."""
        if self.nprocs is not None:
            return self._launch_pod(env)
        if self.heartbeat_file is None or self.heartbeat_timeout is None:
            return subprocess.run(self.cmd, env=env).returncode
        import signal

        # a leftover heartbeat from the previous incarnation is stale by
        # definition — without this every relaunch would be declared hung
        # (and killed) before the fresh worker reaches its first beat
        for path in self._heartbeat_files():
            try:
                os.unlink(path)
            except OSError:
                pass
        launched_at = time.monotonic()
        proc = subprocess.Popen(self.cmd, env=env)
        while True:
            rc = proc.poll()
            if rc is not None:
                return rc
            if self._heartbeat_stale(launched_at):
                break
            self._sleep(self.heartbeat_poll)
        from ..monitor.monitor import resilience_counters

        self.hang_count += 1
        resilience_counters.incr("hang_restarts")
        logger.error("elastic agent: heartbeat %s stale > %.1fs — worker "
                     "hung; requesting stack dump then killing pid %d",
                     self.heartbeat_file, self.heartbeat_timeout, proc.pid)
        if hasattr(signal, "SIGUSR1"):
            try:  # worker telemetry registered faulthandler on SIGUSR1
                proc.send_signal(signal.SIGUSR1)
            except OSError:  # pragma: no cover - it died under us
                pass
            self._sleep(self.hang_grace)
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=self.hang_grace)
            except subprocess.TimeoutExpired:  # pragma: no cover
                proc.kill()
        return proc.wait()

    # ------------------------------------------------------------- pod mode
    def _launch_pod(self, env: Dict[str, str]) -> int:
        """Spawn ``nprocs`` rank processes and supervise them as ONE pod.

        The moment any rank self-exits non-zero the siblings are terminated
        immediately (SIGTERM → ``teardown_grace`` → SIGKILL): under SPMD
        they are wedged inside a collective their dead peer will never
        join, and letting each discover that through its own timeout
        multiplies the recovery latency by the world size. The pod rc is
        the most *specific* self-exit cause observed — rc 218 (comm hang)
        over rc 217 (preemption) over the first plain failure — so the
        restart accounting in :meth:`run` classifies the pod by its root
        cause, not by whichever sibling our SIGTERM reaped first."""
        import signal

        for path in self._heartbeat_files():
            try:  # a leftover beat from the last incarnation is stale
                os.unlink(path)
            except OSError:
                pass
        procs: List[subprocess.Popen] = []
        for r in range(self.nprocs):
            penv = dict(env)
            penv["RANK"] = str(r)
            penv.setdefault("LOCAL_RANK", str(r))
            # declare the pod to the workers (utils/podid.py): the
            # checkpoint commit protocol and telemetry rank labels need
            # identity even when jax.distributed isn't in play. Force-set
            # like RANK above — a stale DSTPU_POD_RANKS inherited from the
            # shell would make rank 0 wait for manifests from ranks this
            # pod doesn't have, leaving every save torn.
            penv["DSTPU_POD_RANKS"] = str(self.nprocs)
            procs.append(subprocess.Popen(self.cmd, env=penv))
        launched_at = time.monotonic()
        rcs: Dict[int, Optional[int]] = {}
        killed: set = set()
        tore_down = False
        while len(rcs) < len(procs):
            for i, p in enumerate(procs):
                if i not in rcs:
                    rc = p.poll()
                    if rc is not None:
                        rcs[i] = rc
            # a clean preemption (rc 217) does NOT trigger teardown: the
            # scheduler SIGTERMed every rank, and the siblings are busy
            # writing their own emergency checkpoints — killing them after
            # teardown_grace would tear exactly the saves the rc-217
            # free-restart contract exists to preserve. They exit 217 on
            # their own; crashes and watchdog aborts (218) tear down NOW.
            self_failed = {i: rc for i, rc in rcs.items()
                           if rc not in (0, PREEMPTION_EXIT_CODE)
                           and i not in killed}
            if self_failed and not tore_down and len(rcs) < len(procs):
                tore_down = True
                self._teardown_siblings(procs, rcs, killed, self_failed)
                continue  # collect the terminated siblings' rcs
            if len(rcs) == len(procs):
                break
            if self.heartbeat_file is not None \
                    and self.heartbeat_timeout is not None \
                    and self._heartbeat_stale(launched_at):
                from ..monitor.monitor import resilience_counters

                self.hang_count += 1
                resilience_counters.incr("hang_restarts")
                logger.error("elastic agent: pod heartbeat stale > %.1fs — "
                             "stack-dumping and killing all ranks",
                             self.heartbeat_timeout)
                if hasattr(signal, "SIGUSR1"):
                    for i, p in enumerate(procs):
                        if i not in rcs:
                            try:
                                p.send_signal(signal.SIGUSR1)
                            except OSError:
                                pass
                    self._sleep(self.hang_grace)
                for i, p in enumerate(procs):
                    if i not in rcs:
                        killed.add(i)
                        try:
                            p.terminate()
                        except OSError:  # pragma: no cover
                            pass
                self._kill_procs(procs, rcs)
                break
            self._sleep(self.heartbeat_poll)
        for i, p in enumerate(procs):
            if i not in rcs:
                rcs[i] = p.wait()
        self_exits = {i: rc for i, rc in rcs.items()
                      if i not in killed and rc is not None}
        return self._pod_rc(rcs, self_exits)

    def _teardown_siblings(self, procs, rcs, killed, self_failed) -> None:
        """Prompt pod teardown: a rank died, so end the survivors NOW."""
        from ..monitor.monitor import resilience_counters

        self.teardown_count += 1
        resilience_counters.incr("pod_teardowns")
        logger.error("elastic agent: rank(s) %s exited %s — tearing down "
                     "%d sibling rank(s) immediately (no cascade wait)",
                     sorted(self_failed), sorted(self_failed.values()),
                     sum(1 for i in range(len(procs)) if i not in rcs))
        for i, p in enumerate(procs):
            if i in rcs:
                continue
            rc = p.poll()
            if rc is not None:
                # it self-exited in the window since the last poll round:
                # record the real rc instead of writing it off as our kill
                # (a sibling's own rc 218 must keep its cause attribution)
                rcs[i] = rc
                continue
            killed.add(i)
            try:
                p.terminate()
            except OSError:  # pragma: no cover - died under us
                pass
        self._kill_procs(procs, rcs)

    def _kill_procs(self, procs, rcs) -> None:
        """Grace-bounded reap: SIGTERM was sent; escalate to SIGKILL."""
        deadline = time.monotonic() + self.teardown_grace
        for i, p in enumerate(procs):
            if i in rcs:
                continue
            timeout = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()

    def _pod_rc(self, rcs: Dict[int, int], self_exits: Dict[int, int]) -> int:
        """Aggregate a pod's exit: most specific self-exit cause wins."""
        fails = {i: rc for i, rc in self_exits.items() if rc != 0}
        if not fails:
            # every rank either succeeded or only died by our hand
            # (heartbeat-hang kills land here: negative rc, counted by run)
            non_zero = [rc for rc in rcs.values() if rc != 0]
            return 0 if not non_zero else non_zero[0]
        for cause in (COMM_HANG_EXIT_CODE, SERVE_HANG_EXIT_CODE,
                      DIVERGENCE_EXIT_CODE, PREEMPTION_EXIT_CODE):
            if cause in fails.values():
                return cause
        return fails[min(fails)]

    # ------------------------------------------------------------------ run
    def run(self) -> int:
        """Launch; restart on failure up to ``restart_limit`` times. A
        ``PREEMPTION_EXIT_CODE`` exit restarts for free (the worker saved an
        emergency checkpoint on SIGTERM — see ``runtime/resilience.py``) and
        resets the failure backoff; any other non-zero rc counts against the
        limit and backs off exponentially. Returns the final exit code
        (0 on success)."""
        from ..monitor.monitor import resilience_counters

        consecutive_failures = 0
        consecutive_preemptions = 0
        consecutive_comm_hangs = 0
        consecutive_serve_hangs = 0
        consecutive_divergences = 0
        while True:
            world = self.discover_world_size()
            if world < self.min_nodes:
                raise ElasticityError(
                    f"deployment of {world} below min_nodes {self.min_nodes}")
            if 0 < self.max_nodes < world:
                world = self.max_nodes
            attempt = (self.restart_count + self.preemption_count
                       + self.comm_hang_count + self.serve_hang_count
                       + self.divergence_count)
            env = dict(os.environ)
            env.update(self.extra_env)
            env.update(self._resolve(world))
            env["DSTPU_ELASTIC_RESTART_COUNT"] = str(self.restart_count)
            env["DSTPU_ELASTIC_PREEMPTION_COUNT"] = str(self.preemption_count)
            env["DSTPU_ELASTIC_COMM_HANG_COUNT"] = str(self.comm_hang_count)
            env["DSTPU_ELASTIC_SERVE_HANG_COUNT"] = str(self.serve_hang_count)
            env["DSTPU_ELASTIC_DIVERGENCE_COUNT"] = str(self.divergence_count)
            # total prior relaunches of any cause: workers use it to rotate
            # rendezvous ports / name per-incarnation artifacts
            env["DSTPU_ELASTIC_ATTEMPT"] = str(attempt)
            env["DSTPU_ELASTIC_WORLD_SIZE"] = str(world)
            logger.info("elastic agent: launching (attempt %d, world=%d)",
                        attempt + 1, world)
            rc = self._launch(env)
            self.launch_history.append(
                {"world": world, "rc": rc,
                 "restart": self.restart_count,
                 "preempted": rc == PREEMPTION_EXIT_CODE,
                 "comm_hang": rc == COMM_HANG_EXIT_CODE,
                 "serve_hang": rc == SERVE_HANG_EXIT_CODE,
                 "divergence": rc == DIVERGENCE_EXIT_CODE})
            if rc == 0:
                return 0
            if self._stop_requested:
                # a drain was requested mid-launch (ReplicaSupervisor's
                # SIGTERM forwarding): supervision ends with this rc —
                # relaunching a replica the operator asked to stop would
                # fight the deployment controller
                logger.info("elastic agent: stop requested — not "
                            "relaunching (rc=%d)", rc)
                return rc
            resilience_counters.incr("restarts")
            total_relaunches = (self.restart_count + self.preemption_count
                                + self.comm_hang_count
                                + self.serve_hang_count
                                + self.divergence_count)
            if self.storm_limit is not None \
                    and total_relaunches >= self.storm_limit:
                logger.error("elastic agent: restart storm — %d total "
                             "relaunches reached storm_limit %d (last "
                             "rc=%d); giving up",
                             total_relaunches, self.storm_limit, rc)
                return rc
            if rc in (COMM_HANG_EXIT_CODE, SERVE_HANG_EXIT_CODE,
                      DIVERGENCE_EXIT_CODE):
                # a watchdog/sentinel abort — collective hang (218),
                # serving decode hang (219) or training divergence (220):
                # stacks, flight recorder, request/health journals are on
                # disk; the restart recovers from the last pod-complete
                # (for 220: last *promoted* last-good) checkpoint and
                # replays journaled streams. Not billed against
                # restart_limit (the code didn't crash), but backed off
                # exponentially — a severed link, a wedging dispatch or a
                # persistently diverging model would otherwise hot-loop —
                # and bounded by its own per-cause consecutive limit.
                consecutive_failures = 0
                consecutive_preemptions = 0
                if rc == SERVE_HANG_EXIT_CODE:
                    consecutive_comm_hangs = 0
                    consecutive_divergences = 0
                    consecutive_serve_hangs += 1
                    self.serve_hang_count += 1
                    streak, limit = (consecutive_serve_hangs,
                                     self.serve_hang_limit)
                    what, counter = "serve hang", "serve_hang_restarts"
                    resume = ("restarting; the replica will replay its "
                              "request journal")
                    msg_what = "stuck-decode hang"
                    nth = self.serve_hang_count
                elif rc == DIVERGENCE_EXIT_CODE:
                    consecutive_comm_hangs = 0
                    consecutive_serve_hangs = 0
                    consecutive_divergences += 1
                    self.divergence_count += 1
                    streak, limit = (consecutive_divergences,
                                     self.divergence_limit)
                    what, counter = "divergence", "divergence_restarts"
                    resume = ("restarting from the promoted last-good "
                              "checkpoint; the health journal's skip "
                              "decisions replay deterministically")
                    msg_what = "training divergence"
                    nth = self.divergence_count
                else:
                    consecutive_serve_hangs = 0
                    consecutive_divergences = 0
                    consecutive_comm_hangs += 1
                    self.comm_hang_count += 1
                    streak, limit = (consecutive_comm_hangs,
                                     self.comm_hang_limit)
                    what, counter = "comm hang", "comm_hang_restarts"
                    resume = ("restarting from the newest pod-complete "
                              "checkpoint")
                    msg_what = "pod comm hang"
                    nth = self.comm_hang_count
                resilience_counters.incr(counter)
                if limit is not None and streak > limit:
                    logger.error("elastic agent: %d consecutive %s exits "
                                 "exceeds limit %d — giving up",
                                 streak, what, limit)
                    return rc
                delay = self.next_backoff(streak)
                logger.warning("elastic agent: %s (rc=%d, #%d) — "
                               "%s in %.2fs", msg_what, rc, nth, resume,
                               delay)
                if delay > 0:
                    self._sleep(delay)
                continue
            if rc == PREEMPTION_EXIT_CODE:
                # clean preemption: durable emergency checkpoint exists, the
                # eviction wasn't the worker's fault — the restart is free,
                # but not a hot loop: a fleet-wide drain SIGTERMs every
                # relaunch seconds after startup, so pace relaunches at the
                # jittered base backoff and bound the streak
                self.preemption_count += 1
                consecutive_preemptions += 1
                consecutive_failures = 0
                consecutive_comm_hangs = 0
                consecutive_serve_hangs = 0
                consecutive_divergences = 0
                if self.preemption_limit is not None \
                        and consecutive_preemptions > self.preemption_limit:
                    logger.error("elastic agent: %d consecutive preemptions "
                                 "exceeds limit %d — giving up",
                                 consecutive_preemptions,
                                 self.preemption_limit)
                    return rc
                logger.warning("elastic agent: worker preempted (rc=%d, "
                               "preemption #%d) — restarting without "
                               "consuming restart budget",
                               rc, self.preemption_count)
                delay = self.next_backoff(1)  # base only: no failure streak
                if delay > 0:
                    self._sleep(delay)
                continue
            self.restart_count += 1
            consecutive_failures += 1
            consecutive_preemptions = 0
            consecutive_comm_hangs = 0
            consecutive_serve_hangs = 0
            consecutive_divergences = 0
            if self.restart_count > self.restart_limit:
                logger.error("elastic agent: restart limit %d exhausted "
                             "(last rc=%d)", self.restart_limit,
                             rc)
                return rc
            delay = self.next_backoff(consecutive_failures)
            logger.warning("elastic agent: worker failed rc=%d — "
                           "re-discovering membership and restarting "
                           "in %.2fs", rc, delay)
            if delay > 0:
                self._sleep(delay)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """CLI: ``python -m deepspeedsyclsupport_tpu.elasticity.elastic_agent
    --config ds_config.json [--restart-limit N] -- cmd args...``"""
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--config", required=True)
    ap.add_argument("--restart-limit", type=int, default=3)
    ap.add_argument("--min-nodes", type=int, default=1)
    ap.add_argument("--max-nodes", type=int, default=-1)
    ap.add_argument("--backoff-seconds", type=float, default=1.0,
                    help="base delay after a failure; doubles per consecutive "
                         "failure up to --backoff-ceiling, plus jitter")
    ap.add_argument("--backoff-ceiling", type=float, default=60.0)
    ap.add_argument("--preemption-limit", type=int, default=None,
                    help="consecutive preemption exits before the agent "
                         "gives up (default: unbounded)")
    ap.add_argument("--comm-hang-limit", type=int, default=None,
                    help="consecutive collective-watchdog exits (rc 218) "
                         "before the agent gives up (default: unbounded)")
    ap.add_argument("--serve-hang-limit", type=int, default=None,
                    help="consecutive stuck-decode-watchdog exits (rc 219, "
                         "the serving plane) before the agent gives up "
                         "(default: unbounded)")
    ap.add_argument("--divergence-limit", type=int, default=None,
                    help="consecutive training-divergence exits (rc 220, "
                         "the health sentinel's abort) before the agent "
                         "gives up (default: unbounded)")
    ap.add_argument("--storm-limit", type=int, default=None,
                    help="TOTAL relaunches of any cause before the agent "
                         "gives up — the restart-storm cap (default: "
                         "unbounded)")
    ap.add_argument("--nprocs", type=int, default=None,
                    help="supervise a local pod of N rank processes "
                         "(RANK/LOCAL_RANK exported per rank); when any "
                         "rank dies its siblings are torn down immediately")
    ap.add_argument("--teardown-grace", type=float, default=5.0,
                    help="seconds between SIGTERM and SIGKILL during a pod "
                         "teardown")
    ap.add_argument("--heartbeat-file", default=None,
                    help="telemetry heartbeat file to watch (the worker's "
                         "telemetry_logs/heartbeat_rank0.json); a glob like "
                         "'telemetry_logs/heartbeat_rank*.json' watches every "
                         "rank — the stalest one decides (one hung rank is a "
                         "hung pod)")
    ap.add_argument("--heartbeat-timeout", type=float, default=None,
                    help="seconds of heartbeat staleness before the worker "
                         "is declared hung (stack-dumped via SIGUSR1, then "
                         "killed and restarted)")
    ap.add_argument("--hostfile", default=None)
    ap.add_argument("cmd", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    with open(args.config) as f:
        ds_config = json.load(f)
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    agent = DSElasticAgent(cmd, ds_config, min_nodes=args.min_nodes,
                           max_nodes=args.max_nodes,
                           restart_limit=args.restart_limit,
                           backoff_seconds=args.backoff_seconds,
                           backoff_ceiling=args.backoff_ceiling,
                           preemption_limit=args.preemption_limit,
                           comm_hang_limit=args.comm_hang_limit,
                           serve_hang_limit=args.serve_hang_limit,
                           divergence_limit=args.divergence_limit,
                           storm_limit=args.storm_limit,
                           nprocs=args.nprocs,
                           teardown_grace=args.teardown_grace,
                           heartbeat_file=args.heartbeat_file,
                           heartbeat_timeout=args.heartbeat_timeout,
                           hostfile=args.hostfile)
    return agent.run()


if __name__ == "__main__":
    sys.exit(main())
