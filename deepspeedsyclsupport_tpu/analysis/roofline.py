"""Roofline partition of a compiled step: per-region FLOPs/bytes vs peaks.

The jaxpr half of the MFU ledger (``monitor/mfu.py`` holds the stdlib
trace/join half): walk the step's closed jaxpr (``jaxpr_walk`` — scan
bodies multiply by trip count, so the layer stack costs L×), attribute
every equation to the ``mfu.<region>`` named-scope label recorded in its
``source_info.name_stack`` (forward AND backward: transpose/jvp wrappers
preserve the scope — ``transpose(jvp(mfu.attn))`` still names ``attn``),
and price each region against a device peak-spec:

* analytic FLOPs per region (``profiling/flops_profiler.eqn_flops`` — the
  same rules the engine's FLOPS profiler counts with);
* HBM bytes per region — a perfect-fusion FLOOR: matmuls/convolutions/
  reductions/data movement count operand + result bytes (those arrays must
  stream through memory), elementwise ops count result bytes only (XLA
  fuses their inputs into the producer). Optimistic by construction, which
  is what "roofline-achievable" must be — real traffic sits between this
  floor and the unfused sum.
* comm bytes per region — in-jaxpr collective payloads (shard_map bodies:
  ring/ulysses/zeropp). Partitioner-INSERTED collectives never appear in a
  jaxpr; their bytes come from the HLO census (``analysis/collectives.py``)
  and land in the derived ``collective`` region via ``census_bytes``.

Each region's roofline-achievable time is ``max(flops/peak, bytes/hbm_bw,
comm/ici_bw)`` and the max's argument is the bound-by verdict — the
"name where the step time goes" instrument the ROADMAP's MFU item needs
before any real-TPU run can be interpreted.
"""
import math
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from ..monitor.mfu import REGIONS, region_of  # stdlib-only module

#: in-jaxpr collective primitives (explicit shard_map bodies); payload =
#: result bytes. The partitioner's own collectives are censused from HLO.
COLLECTIVE_PRIMS = frozenset({
    "psum", "all_gather", "all_to_all", "ppermute", "psum_scatter",
    "pmax", "pmin", "reduce_scatter",
})


@dataclass(frozen=True)
class DeviceSpec:
    """Per-chip peaks. ``peak_flops`` is the dense bf16 (or fp32 for the
    CPU sim) matmul peak; ``hbm_gbps`` main-memory bandwidth; ``ici_gbps``
    per-chip interconnect bandwidth (one direction, all links)."""
    name: str
    peak_flops: float
    hbm_gbps: float
    ici_gbps: float

    def as_dict(self) -> Dict[str, float]:
        return {"name": self.name, "peak_flops": self.peak_flops,
                "hbm_gbps": self.hbm_gbps, "ici_gbps": self.ici_gbps}


#: Peak-spec registry. TPU numbers are the published per-chip peaks
#: (bf16 dense / HBM BW / aggregate ICI per chip); add a device by adding a
#: row here and (if its ``device_kind`` string is new) a match in
#: :func:`device_spec` — docs/observability.md documents the procedure.
DEVICE_SPECS: Dict[str, DeviceSpec] = {
    "tpu-v4": DeviceSpec("tpu-v4", 275e12, 1228.0, 300.0),
    "tpu-v5e": DeviceSpec("tpu-v5e", 197e12, 819.0, 200.0),
    "tpu-v5p": DeviceSpec("tpu-v5p", 459e12, 2765.0, 600.0),
    "tpu-v6e": DeviceSpec("tpu-v6e", 918e12, 1640.0, 400.0),
    # CPU-sim entry: replaced by a measured calibration (see
    # calibrate_cpu_spec) the first time it is asked for, so CPU-sim MFU
    # numbers mean "fraction of what THIS host's XLA actually peaks at",
    # not fraction of an arbitrary constant.
    "cpu-sim": DeviceSpec("cpu-sim", 5e10, 10.0, 1.0),
}

_cpu_calibrated: Optional[DeviceSpec] = None


def calibrate_cpu_spec(force: bool = False) -> DeviceSpec:
    """Measured CPU-sim peaks (cached process-wide): a 512³ f32 matmul
    chain prices ``peak_flops``, a large copy prices ``hbm_gbps``. Coarse
    (one shape, one dtype) but honest — the roofline verdicts on the CPU
    sim then compare against what this host can actually do."""
    global _cpu_calibrated
    if _cpu_calibrated is not None and not force:
        return _cpu_calibrated
    import time

    import jax
    import jax.numpy as jnp

    n, iters = 512, 8

    @jax.jit
    def chain(x):
        for _ in range(iters):
            x = x @ x
        return x

    x = jnp.ones((n, n), jnp.float32)
    chain(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    chain(x).block_until_ready()
    dt = max(time.perf_counter() - t0, 1e-9)
    peak = 2.0 * n ** 3 * iters / dt

    m = 1 << 22  # 4M f32 = 16 MiB through the copy

    @jax.jit
    def copy(x):
        return x + 1.0

    y = jnp.ones((m,), jnp.float32)
    copy(y).block_until_ready()
    t0 = time.perf_counter()
    copy(y).block_until_ready()
    dt = max(time.perf_counter() - t0, 1e-9)
    bw = 2.0 * m * 4 / dt / 1e9  # read + write
    _cpu_calibrated = DeviceSpec("cpu-sim", peak, bw,
                                 DEVICE_SPECS["cpu-sim"].ici_gbps)
    return _cpu_calibrated


def device_spec(device: Any = None,
                calibrate_cpu: bool = True) -> DeviceSpec:
    """Spec for a jax device (default: ``jax.devices()[0]``), matched on
    ``device_kind``/platform. Unknown TPU generations fall back to the
    newest known entry (with its name kept honest); CPU returns the
    calibrated CPU-sim entry."""
    import jax

    device = device if device is not None else jax.devices()[0]
    if device.platform != "tpu":
        return (calibrate_cpu_spec() if calibrate_cpu
                else DEVICE_SPECS["cpu-sim"])
    kind = (getattr(device, "device_kind", "") or "").lower()
    for tag, key in (("v6", "tpu-v6e"), ("v5p", "tpu-v5p"),
                     ("v5", "tpu-v5e"), ("v4", "tpu-v4")):
        if tag in kind:
            return DEVICE_SPECS[key]
    # unknown generation: borrow the newest known peaks but SAY SO in the
    # spec name — every ledger/artifact then carries the guess visibly
    # instead of silently claiming the chip is a v6e
    base = DEVICE_SPECS["tpu-v6e"]
    return DeviceSpec(f"tpu-unknown({kind or '?'})~tpu-v6e",
                      base.peak_flops, base.hbm_gbps, base.ici_gbps)


# ----------------------------------------------------------- region costing
def _aval_bytes(aval) -> float:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0.0
    try:
        itemsize = np.dtype(getattr(aval, "dtype", np.float32)).itemsize
    except TypeError:
        # extended dtypes (PRNG keys) have no numpy equivalent; 4 bytes per
        # element is close enough for arrays this small
        itemsize = 4
    return float(math.prod(shape)) * itemsize if shape else float(itemsize)


def _eqn_region(eqn) -> Optional[str]:
    # ONE extraction rule for both halves of the ledger: the jaxpr name
    # stack and the HLO op_name metadata are the same path syntax, so the
    # analytic and measured views must share monitor/mfu.region_of — a
    # local re-implementation could silently drift and mis-join regions
    return region_of(str(getattr(eqn.source_info, "name_stack", "") or ""))


def region_costs(closed_jaxpr) -> Dict[str, Dict[str, float]]:
    """Per-region analytic cost table ``{region: {"flops", "hbm_bytes",
    "comm_bytes", "n_eqns"}}`` over the recursive equation stream. Scoped
    regions come from the name stack; in-jaxpr collectives override to
    ``collective``; everything else is ``other``."""
    from ..profiling.flops_profiler import _CHEAP, eqn_flops
    from .jaxpr_walk import iter_eqns

    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    out: Dict[str, Dict[str, float]] = {
        r: {"flops": 0.0, "hbm_bytes": 0.0, "comm_bytes": 0.0, "n_eqns": 0}
        for r in REGIONS if r != "host"}
    for eqn, mult in iter_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim in COLLECTIVE_PRIMS:
            region = "collective"
            comm = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        else:
            region = _eqn_region(eqn) or "other"
            comm = 0.0
        row = out[region]
        f = eqn_flops(eqn)
        if f is not None:
            row["flops"] += f * mult
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        if prim in _CHEAP:
            # elementwise: inputs fuse into their producer — result only
            nbytes = out_bytes
        else:
            nbytes = out_bytes + sum(_aval_bytes(v.aval) for v in eqn.invars
                                     if hasattr(v, "aval"))
        row["hbm_bytes"] += mult * nbytes
        row["comm_bytes"] += comm * mult
        row["n_eqns"] += 1
    return out


def roofline_table(costs: Dict[str, Dict[str, float]],
                   spec: DeviceSpec,
                   census_bytes: Optional[int] = None) -> Dict[str, Any]:
    """Evaluate per-region costs against a device spec: each region's
    achievable time is the max of its compute, memory and comm terms and
    ``bound_by`` names the binding one. ``census_bytes`` (the HLO
    collective census total, ``analysis/collectives.py``) is added to the
    ``collective`` region — partitioner-inserted traffic the jaxpr can't
    see. Serializes to the ``monitor/mfu.ledger`` roofline contract."""
    regions: Dict[str, Dict[str, Any]] = {}
    total_flops = total_achievable = 0.0
    costs = {k: dict(v) for k, v in costs.items()}
    if census_bytes:
        col = costs.setdefault(
            "collective",
            {"flops": 0.0, "hbm_bytes": 0.0, "comm_bytes": 0.0, "n_eqns": 0})
        col["comm_bytes"] += float(census_bytes)
    for name, c in costs.items():
        t_compute = c["flops"] / spec.peak_flops if spec.peak_flops else 0.0
        t_memory = c["hbm_bytes"] / (spec.hbm_gbps * 1e9) \
            if spec.hbm_gbps else 0.0
        t_comm = c["comm_bytes"] / (spec.ici_gbps * 1e9) \
            if spec.ici_gbps else 0.0
        terms = {"compute": t_compute, "memory": t_memory, "comm": t_comm}
        bound = max(terms, key=terms.get)
        achievable = terms[bound]
        regions[name] = {
            "flops": c["flops"], "hbm_bytes": c["hbm_bytes"],
            "comm_bytes": c["comm_bytes"],
            "t_compute": t_compute, "t_memory": t_memory, "t_comm": t_comm,
            "achievable_s": achievable,
            "bound_by": bound if achievable > 0 else None,
        }
        total_flops += c["flops"]
        total_achievable += achievable
    return {
        "device": spec.name,
        "spec": spec.as_dict(),
        "regions": regions,
        "total_flops": total_flops,
        "total_achievable_s": total_achievable,
    }
