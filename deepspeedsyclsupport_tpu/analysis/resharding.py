"""Resharding detector: device-to-device copies from mismatched shardings.

Two ``NamedSharding``s that disagree about a value's layout cost a
collective every step — XLA silently inserts all-to-all / collective-permute
(or gather+slice) to move the data, and the step "works", just slower.
Detection is two-sided:

* **boundary**: the shardings the compiled executable *wants* for its
  inputs vs the shardings the caller's arrays *have*. A mismatch means jax
  copies that argument at every dispatch (host-visible resharding).
* **internal**: collective traffic the census could not attribute to the
  canonical classes (param-gather / grad-sync / scalar) — all-to-all and
  collective-permute entries are the partitioner's resharding spellings,
  plus unattributed gathers over activation-shaped payloads.

The internal side shares classification with ``collectives.py``: run the
census check first and hand its ``other`` class here.
"""
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from .collectives import classify_collectives, collective_census

RESHARD_OPS = ("all-to-all", "collective-permute")


@dataclass
class ReshardingReport:
    ok: bool
    boundary_mismatches: List[Dict[str, Any]] = field(default_factory=list)
    internal_suspects: List[Dict[str, Any]] = field(default_factory=list)
    suspect_bytes: int = 0

    def report(self) -> str:
        lines = [f"resharding audit: {'OK' if self.ok else 'FAIL'} "
                 f"({len(self.boundary_mismatches)} boundary, "
                 f"{len(self.internal_suspects)} internal, "
                 f"{self.suspect_bytes} B/step)"]
        for b in self.boundary_mismatches:
            lines.append(f"  BOUNDARY arg {b['index']}: given {b['given']} "
                         f"!= compiled {b['wanted']}")
        for s in self.internal_suspects:
            lines.append(f"  INTERNAL {s['op']} {s['shape']} "
                         f"({s['bytes']} B)")
        return "\n".join(lines)


def resharding_audit(compiled: Any,
                     params: Any = None,
                     param_shardings: Any = None,
                     given_in_shardings: Optional[Sequence[Any]] = None,
                     census: Optional[Sequence[Dict[str, Any]]] = None,
                     ) -> ReshardingReport:
    """Audit one compiled step for resharding traffic.

    ``params``/``param_shardings`` feed the census classifier so canonical
    param/grad traffic is not blamed; without them every collective in a
    reshard-spelling opcode is a suspect. ``given_in_shardings`` is the flat
    list of shardings the caller's arrays actually carry (``None`` entries
    skip the comparison).
    """
    census = list(census if census is not None
                  else collective_census(compiled))
    if params is not None:
        other = classify_collectives(census, params, param_shardings).other
    else:
        other = [r for r in census if r["op"] in RESHARD_OPS]
    suspects = [r for r in other
                if r["op"] in RESHARD_OPS or r["op"] == "all-gather"]

    boundary: List[Dict[str, Any]] = []
    if given_in_shardings is not None:
        wanted = _flat_input_shardings(compiled)
        for i, (giv, want) in enumerate(zip(given_in_shardings, wanted)):
            if giv is None or want is None:
                continue
            if not _shardings_equal(giv, want):
                boundary.append({"index": i, "given": _spec_str(giv),
                                 "wanted": _spec_str(want)})
    return ReshardingReport(
        ok=not boundary and not suspects,
        boundary_mismatches=boundary, internal_suspects=suspects,
        suspect_bytes=sum(s["bytes"] for s in suspects))


def _flat_input_shardings(compiled: Any) -> List[Any]:
    try:
        args_sh, kw_sh = compiled.input_shardings
        flat = list(args_sh) + list(kw_sh.values())
        return flat
    except Exception:  # backend/version dependent
        return []


def _spec_str(s: Any) -> str:
    spec = getattr(s, "spec", None)
    return str(spec) if spec is not None else str(s)


def _shardings_equal(a: Any, b: Any) -> bool:
    sa, sb = getattr(a, "spec", None), getattr(b, "spec", None)
    if sa is None or sb is None:
        return str(a) == str(b)

    def norm(spec):
        t = [tuple(e) if isinstance(e, tuple) else e for e in spec]
        while t and t[-1] is None:  # trailing Nones are implicit
            t.pop()
        return tuple(t)

    return norm(sa) == norm(sb)
