"""Baseline workflow: existing lint debt is visible, only NEW debt fails.

The baseline file (``tools/dslint_baseline.json``) maps violation keys to
occurrence counts. Keys are line-number independent
(``rule|path|stripped-source-line``), so moving code doesn't churn the
baseline while editing a violating line makes it new — the edit is the
moment to fix it.

* ``--check``: fail (exit 1) on violations whose key is absent from the
  baseline or whose count grew. Baselined entries that no longer fire are
  reported as stale (fix them by regenerating) but do not fail.
* ``--update-baseline``: rewrite the file from the current tree.
"""
import json
import os
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Sequence

from .codelint import Violation

BASELINE_VERSION = 1
DEFAULT_BASELINE = os.path.join("tools", "dslint_baseline.json")


def load_baseline(path: str) -> Dict[str, int]:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return {}
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"baseline {path} has version "
                         f"{data.get('version')!r}, expected "
                         f"{BASELINE_VERSION}; regenerate with "
                         f"--update-baseline")
    return {k: int(v) for k, v in data.get("violations", {}).items()}


def save_baseline(path: str, violations: Sequence[Violation]) -> Dict[str, int]:
    counts = dict(sorted(Counter(v.key for v in violations).items()))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"version": BASELINE_VERSION,
                   "comment": "dslint debt baseline — regenerate with "
                              "`python tools/dslint.py --update-baseline`; "
                              "keys are rule|path|source-line",
                   "violations": counts}, f, indent=1, sort_keys=False)
        f.write("\n")
    os.replace(tmp, path)
    return counts


@dataclass
class BaselineCheck:
    new: List[Violation] = field(default_factory=list)
    baselined: List[Violation] = field(default_factory=list)
    stale_keys: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.new


def check_against_baseline(violations: Sequence[Violation],
                           baseline: Dict[str, int]) -> BaselineCheck:
    res = BaselineCheck()
    seen: Counter = Counter()
    for v in violations:
        seen[v.key] += 1
        if seen[v.key] <= baseline.get(v.key, 0):
            res.baselined.append(v)
        else:
            res.new.append(v)
    res.stale_keys = sorted(k for k, n in baseline.items()
                            if seen.get(k, 0) < n)
    return res
