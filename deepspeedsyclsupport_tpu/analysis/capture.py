"""Shared capture of a step's abstract args (avals + shardings).

One definition of "what does this step program take" serves three callers:
the engine's comms logging (HLO re-lowering without holding donated
arrays), the post-hoc ``Engine.graph_report`` analyzers, and tests that
lower a step at exactly the shapes a real run used. Previously this lived
as an ``aval()`` closure inside ``runtime/engine.py`` — deduplicated here.
"""
from typing import Any

import jax
import jax.numpy as jnp


def abstract_leaf(x: Any) -> jax.ShapeDtypeStruct:
    """Abstract aval of one array-like leaf, keeping its mesh-wide sharding.

    Only mesh-wide ``NamedSharding``s transfer to abstract avals;
    single-device-committed leaves (host scaler pieces) must stay
    unconstrained or lowering sees a device clash.
    """
    from jax.sharding import NamedSharding

    s = getattr(x, "sharding", None)
    s = s if isinstance(s, NamedSharding) else None
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x), sharding=s)


def abstract_step_args(tree: Any) -> Any:
    """ShapeDtypeStruct pytree mirroring ``tree`` — enough to re-lower the
    step program (a compile-cache hit) without pinning the real buffers."""
    return jax.tree_util.tree_map(abstract_leaf, tree)
