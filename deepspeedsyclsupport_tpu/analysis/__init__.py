"""Static analysis of compiled JAX step programs + codebase invariants.

Two halves, one motivation: every property this package checks used to be
enforced only by runtime telemetry or reviewer memory, and each of the
roadmap's perf directions (quantized ZeRO++ collectives, Pallas MFU work,
the shard_map-native refactor) needs the *compiled program's* behavior —
bytes on the wire, buffers donated, dtypes kept, layouts stable — proven
before and after the change.

Graph lint (``collectives``, ``donation``, ``dtype_audit``, ``resharding``):
analyzers over a lowered/compiled train or infer step. Under JAX these are
exact static analyses — the program is a closed jaxpr/HLO module, the same
property ``profiling/flops_profiler.py`` exploits for FLOPs.

Codebase lint (``codelint`` + ``baseline``): an AST rule engine encoding the
invariants PRs 1-2 paid for in debugging (async-signal-safe handlers,
declared monitor event names, monotonic step timing, no stray host syncs in
hot loops), reported against a checked-in baseline so existing debt is
visible but only NEW violations fail. CLI: ``tools/dslint.py``.
"""
from .capture import abstract_step_args
from .collectives import (CollectiveClasses, CollectiveExpectation,
                          check_collectives, classify_collectives,
                          collective_census, expected_train_collectives)
from .donation import DonationReport, donation_audit
from .dtype_audit import DtypeReport, dtype_audit
from .resharding import ReshardingReport, resharding_audit
from .roofline import (DEVICE_SPECS, DeviceSpec, device_spec, region_costs,
                       roofline_table)

__all__ = [
    "abstract_step_args",
    "collective_census", "classify_collectives", "expected_train_collectives",
    "check_collectives", "CollectiveExpectation", "CollectiveClasses",
    "donation_audit", "DonationReport",
    "dtype_audit", "DtypeReport",
    "resharding_audit", "ReshardingReport",
    "DeviceSpec", "DEVICE_SPECS", "device_spec", "region_costs",
    "roofline_table",
]
