"""Collective census vs analytic expectation for a compiled train step.

EQuARX (PAPERS.md) frames collective-byte accounting as the metric that
decides compute-bound vs interconnect-bound at pod scale; ZeRO-Infinity's
bandwidth-centric design likewise lives on statically knowable transfer
volumes. Under JAX both are exact static analyses: the compiled step is one
HLO module, and every partitioner-inserted collective is a line in it
(``comm/hlo_comms.py`` does the parsing).

What can be *exactly* predicted and what can't:

* **param-gather** traffic (ZeRO-3 all-gather of fsdp-sharded params) is
  canonical — one full-bytes all-gather per sharded param per use (XLA CSEs
  the fwd/bwd pair when the gathered value stays live; remat re-gathers).
* **grad-sync** traffic is semantically fixed (every grad leaf must be
  summed across the batch-splitting axes) but its *lowering* is XLA's
  choice: all-reduce, reduce-scatter, or all-to-all + local reduce are all
  legal spellings of the same data movement. The census therefore CLASSIFIES
  observed collectives into traffic classes and checks class totals, not
  opcode-exact lists.
* anything unclassified is a **reshard suspect** — the resharding analyzer's
  input (``resharding.py``).
"""
import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..comm.hlo_comms import parse_collectives

#: collectives ≤ this payload are scalar control sync (loss means, overflow
#: flags, grad-norm reductions) — never param/grad traffic
SCALAR_BYTES = 64


def _as_text(compiled_or_text: Any) -> str:
    if isinstance(compiled_or_text, str):
        return compiled_or_text
    return compiled_or_text.as_text()


def collective_census(compiled_or_text: Any) -> List[Dict[str, Any]]:
    """Every data-moving collective of a compiled step program:
    ``[{op, bytes, shape, group_size}]`` (see ``hlo_comms.parse_collectives``)."""
    return parse_collectives(_as_text(compiled_or_text))


# ---------------------------------------------------------------- expectation
@dataclass
class CollectiveExpectation:
    """Analytic per-step expectation derived from the parallelism config.

    Byte counts are HLO payload bytes (full logical result), matching the
    census; wire bytes per device are ``(N-1)/N`` of that for ring
    implementations — a constant factor that cancels in expected-vs-observed
    comparison.
    """
    param_gather_count: int          # sharded params × gathers_per_param
    param_gather_bytes: int          # Σ full bytes of fsdp-sharded params
    grad_sync_count: int             # grad leaves needing cross-batch sum
    grad_sync_bytes: int             # Σ full bytes of those grads
    group_size: int                  # devices in the batch-splitting group
    scalar_sync_max_bytes: int = 16 * SCALAR_BYTES
    notes: Dict[str, Any] = field(default_factory=dict)

    @property
    def total_bytes(self) -> int:
        return self.param_gather_bytes + self.grad_sync_bytes


def _leaf_entries(tree: Any, shardings: Any = None,
                  itemsize: int = None) -> List[Tuple[int, bool]]:
    """[(full_bytes, fsdp_sharded)] per array leaf of ``tree``.
    ``itemsize`` overrides each leaf's dtype width — ``itemsize=1`` yields
    the int8-transport byte signature of every leaf (the quantized
    collectives' payload size, ``comm/quantized.py``)."""
    import jax
    from jax.sharding import NamedSharding

    leaves = jax.tree_util.tree_leaves(tree)
    s_leaves = (jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, NamedSharding))
        if shardings is not None else [None] * len(leaves))
    out = []
    for leaf, s in zip(leaves, s_leaves):
        shape = np.shape(leaf)
        if not shape:
            continue  # scalars sync in the scalar class
        dt = np.dtype(getattr(leaf, "dtype", np.float32))
        nbytes = int(math.prod(shape)) * (itemsize or dt.itemsize)
        s = s if s is not None else getattr(leaf, "sharding", None)
        spec = getattr(s, "spec", None) or ()
        axes = {a for e in spec for a in
                ((e,) if not isinstance(e, tuple) else e) if a}
        out.append((nbytes, "fsdp" in axes))
    return out


def expected_train_collectives(params: Any, topo: Any, stage: int,
                               param_shardings: Any = None,
                               grad_shardings: Any = None,
                               gathers_per_param: int = 1,
                               ) -> CollectiveExpectation:
    """Canonical per-step expectation for the engine's fused train step.

    * stage 3: each fsdp-sharded param is all-gathered ``gathers_per_param``
      times (1 when XLA keeps the gathered value live across fwd/bwd, 2
      under remat); every grad leaf is summed across (data, fsdp).
    * stage 0-2: params replicated (no gather class); every grad leaf is
      summed across the batch-splitting axes.

    ``gradient_accumulation_steps`` does not multiply anything: the scan
    accumulates *locally* and the engine syncs once per optimizer step.
    """
    entries = _leaf_entries(params, param_shardings)
    grad_entries = (_leaf_entries(params, grad_shardings)
                    if grad_shardings is not None else entries)
    sharded = [(b, s) for b, s in entries if s] if stage >= 3 else []
    axes = topo.axis_sizes
    group = axes.get("data", 1) * axes.get("fsdp", 1)
    # a group of 1 moves no bytes: XLA emits no collective for a
    # single-member axis, so the expectation must be zero or the
    # conservation check flags a correct single-device program
    if axes.get("fsdp", 1) == 1:
        sharded = []
    if group == 1:
        grad_entries = []
    return CollectiveExpectation(
        param_gather_count=len(sharded) * gathers_per_param,
        param_gather_bytes=sum(b for b, _ in sharded) * gathers_per_param,
        grad_sync_count=len(grad_entries),
        grad_sync_bytes=sum(b for b, _ in grad_entries),
        group_size=group,
        notes={"stage": stage, "gathers_per_param": gathers_per_param,
               "n_param_leaves": len(entries),
               "n_sharded_params": len(sharded)})


# ------------------------------------------------------------- classification
@dataclass
class CollectiveClasses:
    """Observed census split into traffic classes."""
    param_gather: List[Dict[str, Any]] = field(default_factory=list)
    grad_sync: List[Dict[str, Any]] = field(default_factory=list)
    scalar_sync: List[Dict[str, Any]] = field(default_factory=list)
    other: List[Dict[str, Any]] = field(default_factory=list)

    def bytes_of(self, cls: str) -> int:
        return sum(e["bytes"] for e in getattr(self, cls))

    def counts(self) -> Dict[str, int]:
        return {c: len(getattr(self, c)) for c in
                ("param_gather", "grad_sync", "scalar_sync", "other")}

    def summary(self) -> Dict[str, Dict[str, int]]:
        return {c: {"count": len(getattr(self, c)),
                    "total_bytes": self.bytes_of(c)}
                for c in ("param_gather", "grad_sync", "scalar_sync", "other")}


GRAD_SYNC_OPS = ("all-reduce", "reduce-scatter")


def classify_collectives(census: Sequence[Dict[str, Any]],
                         params: Any,
                         param_shardings: Any = None,
                         ) -> CollectiveClasses:
    """Attribute each observed collective to a traffic class by byte-matching
    against the param tree:

    * ``param_gather`` — an all-gather whose payload equals a sharded
      param's full bytes;
    * ``grad_sync`` — an all-reduce/reduce-scatter whose payload equals any
      param leaf's full bytes (grads are param-shaped);
    * ``scalar_sync`` — payload ≤ ``SCALAR_BYTES`` (loss/overflow/norm);
    * ``other`` — everything else: quantization scale sidecars, exotic
      grad-sync lowerings and genuine resharding traffic. A canonical
      layout leaves this class empty; growth here is the resharding signal.

    Quantized transports (ZeRO++ qwZ int8 all-gather / qgZ int8
    all-to-all quant-reduce, ``comm/quantized.py``) are recognized by the
    ONE-byte-per-element signature: an all-gather moving exactly a sharded
    param's element count is that param's quantized gather, an
    all-reduce/reduce-scatter/all-to-all moving a grad leaf's element
    count is its quantized sync. The fp32 block scales ride separate small
    collectives and land in ``other``/``scalar_sync`` — honest: they are
    overhead the quantization pays, not param/grad payload. (A same-dtype
    leaf whose byte size collides with another leaf's element count is
    caught by the full-dtype clauses first.)
    """
    entries = _leaf_entries(params, param_shardings)
    param_sizes = {b for b, _ in entries}
    sharded_sizes = {b for b, s in entries if s}
    q_entries = _leaf_entries(params, param_shardings, itemsize=1)
    q_param_sizes = {b for b, _ in q_entries}
    q_sharded_sizes = {b for b, s in q_entries if s}
    out = CollectiveClasses()
    for rec in census:
        if rec["bytes"] <= SCALAR_BYTES:
            out.scalar_sync.append(rec)
        elif rec["op"] == "all-gather" and rec["bytes"] in sharded_sizes:
            out.param_gather.append(rec)
        elif rec["op"] == "all-gather" and rec["bytes"] in q_sharded_sizes:
            out.param_gather.append(rec)
        elif rec["op"] in GRAD_SYNC_OPS and rec["bytes"] in param_sizes:
            out.grad_sync.append(rec)
        elif rec["op"] in GRAD_SYNC_OPS + ("all-to-all",) \
                and rec["bytes"] in q_param_sizes:
            out.grad_sync.append(rec)
        else:
            out.other.append(rec)
    return out


# -------------------------------------------------------------------- checker
@dataclass
class CollectiveCheck:
    ok: bool
    classes: CollectiveClasses
    expectation: CollectiveExpectation
    problems: List[str] = field(default_factory=list)

    def report(self) -> str:
        lines = [f"collective census check: {'OK' if self.ok else 'FAIL'}"]
        exp = self.expectation
        s = self.classes.summary()
        lines.append(f"  param_gather: observed {s['param_gather']['count']} "
                     f"ops / {s['param_gather']['total_bytes']} B, expected "
                     f"{exp.param_gather_count} / {exp.param_gather_bytes} B")
        lines.append(f"  grad_sync:    observed {s['grad_sync']['count']} "
                     f"ops / {s['grad_sync']['total_bytes']} B, expected "
                     f"{exp.grad_sync_count} / {exp.grad_sync_bytes} B")
        lines.append(f"  scalar_sync:  {s['scalar_sync']['count']} ops / "
                     f"{s['scalar_sync']['total_bytes']} B")
        lines.append(f"  other:        {s['other']['count']} ops / "
                     f"{s['other']['total_bytes']} B")
        lines.extend(f"  PROBLEM: {p}" for p in self.problems)
        return "\n".join(lines)


def check_collectives(census: Sequence[Dict[str, Any]],
                      expectation: CollectiveExpectation,
                      params: Any,
                      param_shardings: Any = None,
                      exact: bool = True,
                      other_budget_bytes: int = 0) -> CollectiveCheck:
    """Compare an observed census against the analytic expectation.

    ``exact=True`` (canonical layouts) demands class totals equal the
    expectation and the ``other`` class stay within ``other_budget_bytes``.
    ``exact=False`` only enforces the conservation law — total observed
    param+grad class bytes never *exceeds* the expectation (more traffic
    than the analytic model means an unintended gather/sync) and grad sync
    is not silently missing when the expectation requires it.
    """
    classes = classify_collectives(census, params, param_shardings)
    problems: List[str] = []
    pg_bytes, gs_bytes = classes.bytes_of("param_gather"), classes.bytes_of("grad_sync")
    if exact:
        if len(classes.param_gather) != expectation.param_gather_count:
            problems.append(
                f"param_gather count {len(classes.param_gather)} != expected "
                f"{expectation.param_gather_count}")
        if pg_bytes != expectation.param_gather_bytes:
            problems.append(f"param_gather bytes {pg_bytes} != expected "
                            f"{expectation.param_gather_bytes}")
        if gs_bytes != expectation.grad_sync_bytes:
            problems.append(f"grad_sync bytes {gs_bytes} != expected "
                            f"{expectation.grad_sync_bytes}")
        if classes.bytes_of("other") > other_budget_bytes:
            problems.append(
                f"unclassified collective traffic {classes.bytes_of('other')} B "
                f"exceeds budget {other_budget_bytes} B (resharding suspect — "
                f"see resharding_audit)")
    else:
        if pg_bytes > expectation.param_gather_bytes:
            problems.append(f"param_gather bytes {pg_bytes} exceed analytic "
                            f"budget {expectation.param_gather_bytes}")
        if expectation.grad_sync_bytes and not (
                gs_bytes or classes.other):
            problems.append("no grad-sync traffic observed but the config "
                            "requires cross-batch gradient summation")
    scalar = classes.bytes_of("scalar_sync")
    if scalar > expectation.scalar_sync_max_bytes:
        problems.append(f"scalar sync {scalar} B exceeds "
                        f"{expectation.scalar_sync_max_bytes} B — a tensor is "
                        f"hiding in the scalar class or control sync grew")
    groups = {e.get("group_size") for e in census if e.get("group_size")}
    bad_groups = groups - {expectation.group_size, None}
    if bad_groups and exact:
        problems.append(f"collectives over unexpected group sizes "
                        f"{sorted(bad_groups)} (expected "
                        f"{expectation.group_size})")
    return CollectiveCheck(ok=not problems, classes=classes,
                           expectation=expectation, problems=problems)
