"""AST rule engine for codebase invariants the runtime can't cheaply check.

Each rule encodes a contract an earlier PR paid for in debugging:

* ``signal-handler-safety`` — ``runtime/resilience.py`` contract: a signal
  handler runs between bytecodes of the frame it interrupted, so any lock
  acquisition (Event.set, logging, counters), allocation-heavy call or IO
  inside one can deadlock the process at the worst possible moment. Handler
  bodies may only do attribute stores on pre-existing objects.
* ``undeclared-event-name`` — every monitor event name in a declared group
  (``Train/``, ``Goodput/``, …) must resolve against
  ``monitor/telemetry.py``'s ``EVENT_NAMES``/``EVENT_PREFIXES`` registry.
  This makes ``DSTPU_STRICT_EVENTS`` a static check: the typo'd metric
  fails lint at commit time, not at runtime in strict mode.
* ``wall-clock-in-step-path`` — ``time.time()`` is wall clock; NTP steps it
  backwards/forwards under running jobs, corrupting durations. Step-path
  modules must measure with ``time.perf_counter()``/``monotonic()`` (or the
  ``utils/timer.py`` timers, which do). Wall timestamps meant for humans
  are fine — suppress those lines explicitly.
* ``host-sync-in-step-path`` — ``jax.block_until_ready``/``jax.device_get``
  in a hot loop serializes host dispatch against device compute (the
  overlap ``Engine._post_step`` documents). Syncs belong at print
  boundaries, checkpoint sites and opt-in telemetry paths.

Suppression: append ``# dslint: allow(<rule-name>)`` to the offending line
(with a reason in a nearby comment). Baseline workflow: ``baseline.py``.
"""
import ast
import os
import re
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set

# --------------------------------------------------------------------- model


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str          # repo-relative
    line: int
    message: str
    snippet: str       # stripped source line — the stable part of the key

    @property
    def key(self) -> str:
        """Line-number-independent identity used by the baseline: a moved
        violation is the same debt, an edited one is new."""
        return f"{self.rule}|{self.path}|{self.snippet}"

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


_ALLOW_RE = re.compile(r"#\s*dslint:\s*allow\(([\w\-, ]+)\)")


def _suppressed(source_lines: Sequence[str], lineno: int, rule: str) -> bool:
    if not 1 <= lineno <= len(source_lines):
        return False
    m = _ALLOW_RE.search(source_lines[lineno - 1])
    return bool(m) and rule in [r.strip() for r in m.group(1).split(",")]


# ------------------------------------------------------------- module scopes

#: modules on the training/inference step path: wall-clock durations and
#: host syncs here execute once per step (or per token)
STEP_PATH_MODULES = (
    "runtime/engine.py", "runtime/zero.py", "runtime/zeropp.py",
    "runtime/onebit.py", "runtime/loss_scaler.py", "runtime/sentinel.py",
    "runtime/multihost_offload.py", "runtime/offload_pipeline.py",
    "comm/comm.py", "comm/comms_logging.py",
    "parallel/", "inference/v2/", "moe/",
    "utils/timer.py", "monitor/telemetry.py",
    "elasticity/elastic_agent.py",
)

#: functions sanctioned to host-sync: print boundaries, checkpoint/telemetry
#: sites, offline accessors, and the offload pipeline's single designated
#: wait points (every other pull must ride the async-issue/delayed-wait
#: seam). module-relative "ClassName.method" or "func".
HOST_SYNC_SANCTIONED = {
    "runtime/engine.py": {
        "Engine._post_step", "Engine._flush_monitor", "Engine.get_lr",
        "Engine.get_loss_scale", "Engine.skipped_steps",
        "Engine.stop_profile", "Engine.save_checkpoint",
        "Engine.load_checkpoint", "Engine._offload_train_batch",
        "Engine.xla_comms_summary", "Engine.state_dict", "Engine.eval_batch",
        "Engine.save_16bit_model",
    },
    # the offload seam: init/restore materialization (once per run) and
    # the pipeline's designated delayed-wait points — a bare
    # np.asarray(shard.data) anywhere else in the step path is exactly the
    # serial pull the bucketed pipeline replaced
    "runtime/multihost_offload.py": {
        "MultiHostCPUAdam.__init__", "MultiHostCPUAdam.load_state.pull",
    },
    "runtime/offload_pipeline.py": {"ShardPull.wait"},
    # the sentinel's ONE designated pull: lag-deferred device_get of step
    # scalars whose step already retired (and its rollback/abort paths,
    # which by definition end the overlapped steady state anyway)
    "runtime/sentinel.py": {
        "TrainingSentinel._process", "TrainingSentinel._rollback",
        "TrainingSentinel._abort",
    },
    "comm/comm.py": {"barrier"},
    "elasticity/elastic_agent.py": set(),
}


def _in_step_path(relpath: str) -> bool:
    return any(relpath.endswith(m) or (m.endswith("/") and f"/{m}" in
               f"/{relpath}") for m in STEP_PATH_MODULES)


# --------------------------------------------------------------------- rules


class Rule:
    name = ""
    description = ""

    def check(self, relpath: str, tree: ast.AST,
              source_lines: Sequence[str]) -> Iterable[Violation]:
        raise NotImplementedError


def _qualname(stack: Sequence[ast.AST]) -> str:
    parts = [n.name for n in stack
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))]
    return ".".join(parts) or "<module>"


class _ScopedVisitor(ast.NodeVisitor):
    """Tracks the class/function nesting stack while visiting."""

    def __init__(self):
        self.stack: List[ast.AST] = []

    def visit_scope(self, node):
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = visit_AsyncFunctionDef = visit_ClassDef = visit_scope


def _call_name(node: ast.Call) -> str:
    """Dotted name of a call target ('time.time', 'jax.device_get', ...)."""
    parts: List[str] = []
    t = node.func
    while isinstance(t, ast.Attribute):
        parts.append(t.attr)
        t = t.value
    if isinstance(t, ast.Name):
        parts.append(t.id)
    return ".".join(reversed(parts))


class SignalHandlerSafety(Rule):
    name = "signal-handler-safety"
    description = ("signal handlers may only store attributes — no calls, "
                   "locks, allocs or IO (runtime/resilience.py contract)")

    def check(self, relpath, tree, source_lines):
        handlers: List[ast.FunctionDef] = []
        defs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
                if node.name == "_on_signal":
                    handlers.append(node)
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call)
                    and _call_name(node).endswith("signal.signal")
                    and len(node.args) >= 2):
                h = node.args[1]
                hname = (h.attr if isinstance(h, ast.Attribute)
                         else h.id if isinstance(h, ast.Name) else None)
                if hname in defs:
                    handlers.append(defs[hname])
        seen: Set[int] = set()
        for fn in handlers:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            for sub in ast.walk(fn):
                bad: Optional[str] = None
                if isinstance(sub, ast.Call):
                    bad = f"call to {_call_name(sub) or 'expression'}()"
                elif isinstance(sub, (ast.With, ast.AsyncWith)):
                    bad = "with-block (lock acquisition)"
                elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                    bad = "import (allocates, takes the import lock)"
                elif isinstance(sub, ast.Raise):
                    bad = "raise (unwinds the interrupted frame)"
                if bad is None:
                    continue
                line = getattr(sub, "lineno", fn.lineno)
                if _suppressed(source_lines, line, self.name):
                    continue
                snippet = source_lines[line - 1].strip() \
                    if line <= len(source_lines) else ""
                yield Violation(
                    self.name, relpath, line,
                    f"signal handler {fn.name!r} does {bad}; handlers must "
                    f"be async-signal-safe (attribute stores only)", snippet)


class UndeclaredEventName(Rule):
    name = "undeclared-event-name"
    description = ("monitor event-name literals in declared groups must "
                   "resolve against telemetry's EVENT_NAMES/EVENT_PREFIXES")

    def __init__(self):
        from ..monitor import telemetry as T

        self._is_declared = T.is_declared
        groups = {n.split("/", 1)[0] for n in T.EVENT_NAMES}
        groups |= {p.rstrip("/") for p in T.EVENT_PREFIXES}
        self._groups = groups

    def check(self, relpath, tree, source_lines):
        if relpath.startswith(("tests/", "docs/")):
            return
        docstrings = _docstring_linenos(tree)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            s = node.value
            if "/" not in s or "\n" in s:
                continue
            first = s.split("/", 1)[0]
            if first not in self._groups:
                continue
            if node.lineno in docstrings:
                continue
            if self._is_declared(s) or self._is_declared(s + "/x"):
                # exact name, family member, or a group prefix being used
                # to BUILD a name (f-string / concat base like "Comm/")
                continue
            if s.rstrip("/") in self._groups:
                continue
            if _suppressed(source_lines, node.lineno, self.name):
                continue
            snippet = source_lines[node.lineno - 1].strip() \
                if node.lineno <= len(source_lines) else ""
            yield Violation(
                self.name, relpath, node.lineno,
                f"event name {s!r} is in declared group {first!r} but does "
                f"not resolve against the telemetry registry (typo, or add "
                f"it to EVENT_NAMES / declare_events)", snippet)


class UndeclaredRegionName(Rule):
    name = "undeclared-region"
    description = ("MFU region labels (region_scope(...) / 'mfu.<name>' "
                   "scope literals) must resolve against monitor/mfu.py's "
                   "SCOPE_REGIONS registry — a typo'd label silently "
                   "orphans its region's time in the step-time ledger")

    def __init__(self):
        from ..monitor.mfu import SCOPE_PREFIX, SCOPE_REGIONS

        self._regions = set(SCOPE_REGIONS)
        self._prefix = SCOPE_PREFIX

    def _bad(self, label: str) -> bool:
        return label not in self._regions

    def check(self, relpath, tree, source_lines):
        if relpath.startswith(("tests/", "docs/")):
            return
        docstrings = _docstring_linenos(tree)
        # region_scope("<literal>") calls with an undeclared region
        region_call_args: Set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _call_name(node).split(".")[-1] not in ("region_scope",
                                                       "named_scope"):
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            region_call_args.add(id(arg))
            s = arg.value
            label = (s[len(self._prefix):]
                     if s.startswith(self._prefix) else s)
            is_scope_helper = _call_name(node).endswith("region_scope")
            if not is_scope_helper and not s.startswith(self._prefix):
                continue  # unrelated named_scope — not an MFU region
            if self._bad(label) and not _suppressed(
                    source_lines, node.lineno, self.name):
                snippet = source_lines[node.lineno - 1].strip() \
                    if node.lineno <= len(source_lines) else ""
                yield Violation(
                    self.name, relpath, node.lineno,
                    f"MFU region {label!r} is not declared in "
                    f"monitor/mfu.py SCOPE_REGIONS (typo, or add the "
                    f"region there + to the MFU/region.* event family)",
                    snippet)
        # bare "mfu.<name>" literals anywhere else (building a label by
        # hand bypasses region_scope's runtime check)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            if id(node) in region_call_args or node.lineno in docstrings:
                continue
            s = node.value
            if not s.startswith(self._prefix) or "\n" in s or "/" in s:
                continue
            if s.endswith((".py", ".json", ".gz", ".txt", ".md")):
                continue  # a filename (mfu.py, mfu_opmap.json), not a label
            label = s[len(self._prefix):]
            if not label or not label.replace("_", "").isalnum():
                continue  # "mfu." prefix itself / regex fragments
            if self._bad(label) and not _suppressed(
                    source_lines, node.lineno, self.name):
                snippet = source_lines[node.lineno - 1].strip() \
                    if node.lineno <= len(source_lines) else ""
                yield Violation(
                    self.name, relpath, node.lineno,
                    f"string {s!r} names MFU region {label!r} which is "
                    f"not in monitor/mfu.py SCOPE_REGIONS", snippet)


class UndeclaredStageName(Rule):
    name = "undeclared-stage-name"
    description = ("request-lifecycle stage literals (ServingSession._stage /"
                   " RequestJournal.stage / note_stage calls and "
                   "{'stage': ...} record payloads) must resolve against "
                   "monitor/reqtrace.py's stage registries — a typo'd stage "
                   "silently orphans its interval as 'unattributed' in every "
                   "request waterfall")

    STAGE_CALLS = ("stage", "_stage", "note_stage")

    def __init__(self):
        from ..monitor.reqtrace import FLEET_STAGES, SERVE_STAGES

        self._stages = set(SERVE_STAGES) | set(FLEET_STAGES)

    def _literals(self, node):
        """String constants reachable from a stage argument (plain literal
        or the branches of a conditional expression)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node.lineno
        elif isinstance(node, ast.IfExp):
            yield from self._literals(node.body)
            yield from self._literals(node.orelse)

    def check(self, relpath, tree, source_lines):
        if relpath.startswith(("tests/", "docs/")):
            return
        docstrings = _docstring_linenos(tree)

        def _flag(value, lineno, where):
            if value in self._stages or lineno in docstrings:
                return None
            if _suppressed(source_lines, lineno, self.name):
                return None
            snippet = source_lines[lineno - 1].strip() \
                if lineno <= len(source_lines) else ""
            return Violation(
                self.name, relpath, lineno,
                f"stage {value!r} ({where}) is not declared in "
                f"monitor/reqtrace.py SERVE_STAGES/FLEET_STAGES — the "
                f"join would bucket its time as 'unattributed' (typo, or "
                f"declare the stage + its Serve/stage.* event)", snippet)

        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and len(node.args) >= 2 and \
                    _call_name(node).split(".")[-1] in self.STAGE_CALLS:
                for value, lineno in self._literals(node.args[1]):
                    v = _flag(value, lineno, "stage call")
                    if v is not None:
                        yield v
            elif isinstance(node, ast.Dict):
                for k, val in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and k.value == "stage":
                        for value, lineno in self._literals(val):
                            v = _flag(value, lineno, "record payload")
                            if v is not None:
                                yield v


def _docstring_linenos(tree: ast.AST) -> Set[int]:
    """Line ranges of every docstring (multi-line strings included)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                end = getattr(body[0], "end_lineno", body[0].lineno)
                out.update(range(body[0].lineno, end + 1))
    return out


class WallClockInStepPath(Rule):
    name = "wall-clock-in-step-path"
    description = ("time.time() in step-path modules — wall clock jumps "
                   "under NTP; use time.perf_counter()/monotonic() (or the "
                   "utils/timer.py timers)")

    def check(self, relpath, tree, source_lines):
        if not _in_step_path(relpath):
            return
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _call_name(node) == "time.time":
                if _suppressed(source_lines, node.lineno, self.name):
                    continue
                snippet = source_lines[node.lineno - 1].strip() \
                    if node.lineno <= len(source_lines) else ""
                yield Violation(
                    self.name, relpath, node.lineno,
                    "time.time() measures wall clock; step-path durations "
                    "must use time.perf_counter() (NTP steps corrupt "
                    "wall-clock deltas)", snippet)


class HostSyncInStepPath(Rule):
    name = "host-sync-in-step-path"
    description = ("block_until_ready/device_get — and blocking per-shard "
                   "np.asarray(shard.data) pulls — outside sanctioned "
                   "checkpoint/telemetry/offload-seam sites stall the "
                   "dispatch pipeline")

    SYNC_CALLS = ("block_until_ready", "device_get")
    #: np.asarray / np.array over a ``<expr>.data`` attribute is the
    #: blocking per-shard D2H pull (``shard.data`` is a single-device jax
    #: array; materializing it synchronously serializes host dispatch
    #: against the transfer). The sanctioned spelling is an async
    #: ``jax.device_put`` to the host backend with a delayed wait —
    #: ``runtime/offload_pipeline.py ShardPull``.
    PULL_FNS = ("asarray", "array")

    def _is_shard_pull(self, node: ast.Call) -> bool:
        name = _call_name(node)
        if name.split(".")[-1] not in self.PULL_FNS:
            return False
        return bool(node.args) and isinstance(node.args[0], ast.Attribute) \
            and node.args[0].attr == "data"

    def check(self, relpath, tree, source_lines):
        if not _in_step_path(relpath):
            return
        sanctioned = HOST_SYNC_SANCTIONED.get(
            next((m for m in HOST_SYNC_SANCTIONED if relpath.endswith(m)),
                 relpath), set())

        violations: List[Violation] = []
        rule = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                name = _call_name(node)
                is_sync = any(name.endswith(c) for c in rule.SYNC_CALLS)
                is_pull = not is_sync and rule._is_shard_pull(node)
                if is_sync or is_pull:
                    qn = _qualname(self.stack)
                    if qn not in sanctioned and not _suppressed(
                            source_lines, node.lineno, rule.name):
                        snippet = source_lines[node.lineno - 1].strip() \
                            if node.lineno <= len(source_lines) else ""
                        msg = (f"host sync {name}() in step-path function "
                               f"{qn!r}; move it to a print boundary / "
                               f"checkpoint site or suppress with a reason"
                               if is_sync else
                               f"blocking per-shard pull {name}(….data) in "
                               f"step-path function {qn!r}; issue an async "
                               f"jax.device_put to the host backend with a "
                               f"delayed wait (offload_pipeline.ShardPull) "
                               f"or suppress with a reason")
                        violations.append(Violation(
                            rule.name, relpath, node.lineno, msg, snippet))
                self.generic_visit(node)

        V().visit(tree)
        yield from violations


ALL_RULES: Sequence[Callable[[], Rule]] = (
    SignalHandlerSafety, UndeclaredEventName, UndeclaredRegionName,
    UndeclaredStageName, WallClockInStepPath, HostSyncInStepPath)


# -------------------------------------------------------------------- runner

def lint_paths(root: str, relpaths: Optional[Iterable[str]] = None,
               rules: Optional[Sequence[Rule]] = None) -> List[Violation]:
    """Run every rule over the package tree under ``root`` (repo root).
    ``relpaths`` limits the scan; default walks ``deepspeedsyclsupport_tpu``
    and ``tools``."""
    if rules is None:
        rules = [cls() for cls in ALL_RULES]
    if relpaths is None:
        relpaths = []
        for base in ("deepspeedsyclsupport_tpu", "tools"):
            for dirpath, dirnames, files in os.walk(os.path.join(root, base)):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for f in sorted(files):
                    if f.endswith(".py"):
                        relpaths.append(os.path.relpath(
                            os.path.join(dirpath, f), root))
    out: List[Violation] = []
    for rel in sorted(relpaths):
        path = os.path.join(root, rel)
        try:
            source = open(path, encoding="utf-8").read()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            continue
        lines = source.splitlines()
        rel_posix = rel.replace(os.sep, "/")
        for rule in rules:
            out.extend(rule.check(rel_posix, tree, lines))
    return out
