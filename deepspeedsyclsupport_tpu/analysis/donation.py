"""Donation audit: donatable buffers that a compiled step failed to alias.

``jax.jit(..., donate_argnums=...)`` is a *request*; XLA only aliases an
input to an output when shapes/layouts line up and the value is provably
dead. A donated-but-unaliased param or optimizer-state buffer silently
doubles its HBM footprint every step — invisible at runtime until the OOM.
The compiled module states the truth in its header::

    input_output_alias={ {0}: (0, {}, may-alias), ... }

so the audit is exact: flatten the donatable arg subtree, map flat indices
to tree paths, and flag every leaf whose parameter index never appears on
the right-hand side of the alias map.
"""
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

_ALIAS_ENTRY_RE = re.compile(r"\{[0-9,\s]*\}:\s*\((\d+)")


def parse_aliased_params(hlo_text: str) -> List[int]:
    """Entry-parameter indices the compiled module aliases to outputs."""
    start = hlo_text.find("input_output_alias={")
    if start < 0:
        return []
    i = start + len("input_output_alias=")
    depth, j = 0, i
    while j < len(hlo_text):  # brace-matched block (entries nest {} inside)
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
        j += 1
    block = hlo_text[i:j + 1]
    return sorted({int(g) for g in _ALIAS_ENTRY_RE.findall(block)})


@dataclass
class DonationReport:
    ok: bool
    donated: List[str] = field(default_factory=list)       # tree paths
    not_donated: List[Dict[str, Any]] = field(default_factory=list)
    wasted_bytes: int = 0          # HBM doubled by missed donations
    unmapped: bool = False         # flat index mapping could not be trusted

    def report(self) -> str:
        lines = [f"donation audit: {'OK' if self.ok else 'FAIL'} "
                 f"({len(self.donated)} donated, "
                 f"{len(self.not_donated)} missed, "
                 f"{self.wasted_bytes} B doubled)"]
        for miss in self.not_donated:
            lines.append(f"  NOT DONATED: {miss['path']} "
                         f"{miss['shape']}:{miss['dtype']} "
                         f"({miss['bytes']} B)")
        if self.unmapped:
            lines.append("  (flat arg mapping unverified: entry parameter "
                         "count != argument leaf count)")
        return "\n".join(lines)


def donation_audit(compiled: Any, args: Sequence[Any],
                   donate_argnums: Tuple[int, ...]) -> DonationReport:
    """Audit one compiled step.

    ``args`` are the call arguments (arrays or ShapeDtypeStructs — only
    tree structure/shape/dtype are read); ``donate_argnums`` the argnums the
    call site requested donation for. Flat entry-parameter order is the
    flattened order of ``args`` — verified against the module's parameter
    count before any leaf is blamed.
    """
    import jax

    text = compiled.as_text() if not isinstance(compiled, str) else compiled
    aliased_entry = set(parse_aliased_params(text))
    n_params_re = re.search(r"entry_computation_layout=\{\((.*?)\)->", text,
                            re.S)
    n_entry = (len(_split_top(n_params_re.group(1))) if n_params_re else -1)

    flat: List[Tuple[str, Any]] = []
    donatable: List[int] = []
    idx = 0
    for argnum, arg in enumerate(args):
        for kp, leaf in jax.tree_util.tree_flatten_with_path(arg)[0]:
            flat.append((f"arg{argnum}{jax.tree_util.keystr(kp)}", leaf))
            if argnum in donate_argnums:
                donatable.append(idx)
            idx += 1

    # entry parameter j is flat leaf kept[j]: jit prunes unused leaves
    # (an unused rng, a dead config scalar) from the entry computation
    kept = getattr(getattr(compiled, "_executable", None),
                   "_kept_var_idx", None)
    kept = sorted(kept) if kept is not None else list(range(len(flat)))
    unmapped = n_entry >= 0 and n_entry != len(kept)
    aliased = {kept[j] for j in aliased_entry if j < len(kept)}
    pruned = set(range(len(flat))) - set(kept)

    donated, missed, wasted = [], [], 0
    for i in donatable:
        path, leaf = flat[i]
        if i in aliased or i in pruned:
            # pruned: the program never consumes this leaf, so there is no
            # buffer to double — donation is moot, not missed
            donated.append(path)
            continue
        shape = tuple(np.shape(leaf))
        if not shape:
            # scalar leaves (step counters, hyperparams) cost nothing;
            # report only tensors whose doubling matters
            continue
        dt = np.dtype(getattr(leaf, "dtype", np.float32))
        nbytes = int(np.prod(shape)) * dt.itemsize
        missed.append({"path": path, "shape": shape, "dtype": str(dt),
                       "bytes": nbytes, "flat_index": i})
        wasted += nbytes
    return DonationReport(ok=not missed and not unmapped, donated=donated,
                          not_donated=missed, wasted_bytes=wasted,
                          unmapped=unmapped)


def _split_top(s: str) -> List[str]:
    """Split an entry-layout tuple body on top-level commas (shapes may
    contain ``{...}`` layout braces and ``/*index=N*/`` comments)."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "({[":
            depth += 1
        elif ch in ")}]":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur and "".join(cur).strip():
        parts.append("".join(cur))
    return [p for p in parts if p.strip()]
