"""Recursive jaxpr traversal shared by the static analyzers.

Generalized from the walker in ``profiling/flops_profiler.py`` (which now
uses it): yields every equation with its static trip multiplier, descending
into call/scan/while/cond sub-jaxprs. ``scan`` bodies multiply by the
static ``length``; ``cond`` descends into EVERY branch (branch order in
``eqn.params['branches']`` is lowering-defined — for ``lax.cond`` index 0
is the FALSE branch — so picking one positionally audits the wrong code;
walking all over-approximates, which is the safe direction for audits and
for FLOPs of the skip-vs-run pattern, where the skip branch is ~empty).
"""
from typing import Any, Iterator, List, Tuple

#: eqn.params keys that hold sub-jaxprs (possibly lists of them)
SUBJAXPR_PARAMS = ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr",
                   "branches")


def subjaxprs(eqn) -> List[Any]:
    """The sub-jaxprs of one equation, unwrapped from ClosedJaxpr."""
    subs: List[Any] = []
    for p in SUBJAXPR_PARAMS:
        v = eqn.params.get(p)
        if v is None:
            continue
        vs = v if isinstance(v, (list, tuple)) else [v]
        subs.extend(getattr(s, "jaxpr", s) for s in vs)
    return subs


def iter_eqns(jaxpr, mult: float = 1.0) -> Iterator[Tuple[Any, float]]:
    """Yield ``(eqn, trip_multiplier)`` for every *leaf* equation reachable
    from ``jaxpr``. Equations that only wrap a sub-jaxpr are descended into,
    not yielded."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        sub_mult = mult
        if name == "scan":
            sub_mult = mult * eqn.params.get("length", 1)
        subs = subjaxprs(eqn)
        if subs:
            for s in subs:
                yield from iter_eqns(s, sub_mult)
            continue
        yield eqn, mult
