"""Dtype-promotion audit: f32 upcasts hiding inside a declared-bf16 graph.

Mixed precision dies by a thousand silent promotions: one stray f32
constant or ``astype`` and a whole activation chain runs at double width —
2× the HBM traffic and none of the MXU rate the bf16 config promised. In a
closed jaxpr every promotion is a ``convert_element_type`` equation, so the
audit is exact.

Sanctioned promotions (the master-weight pattern) are excluded by shape:
gradients/master params are *param-shaped*, and upcasting them to f32 for
the optimizer is the point of mixed precision. What gets flagged are
*activation-shaped* upcasts above a size floor — the ones that ride the
batch through the matmuls.
"""
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .jaxpr_walk import iter_eqns

LOW_DTYPES = ("bfloat16", "float16")
#: upcasts below this element count are noise (loss terms, norms, indices)
DEFAULT_MIN_ELEMENTS = 4096


@dataclass
class DtypeReport:
    ok: bool
    upcasts: List[Dict[str, Any]] = field(default_factory=list)
    total_upcast_bytes: int = 0      # extra bytes materialized at f32
    sanctioned: int = 0              # param-shaped (master-weight) upcasts

    def report(self) -> str:
        lines = [f"dtype audit: {'OK' if self.ok else 'FAIL'} "
                 f"({len(self.upcasts)} activation upcasts, "
                 f"{self.total_upcast_bytes} B widened, "
                 f"{self.sanctioned} param-shaped upcasts sanctioned)"]
        for u in self.upcasts:
            lines.append(f"  UPCAST {u['from']} -> {u['to']} at shape "
                         f"{u['shape']} x{u['mult']:g} ({u['bytes']} B)")
        return "\n".join(lines)


def dtype_audit(fn_or_jaxpr: Any, *args: Any,
                allowed_shapes: Optional[Sequence[Tuple[int, ...]]] = None,
                min_elements: int = DEFAULT_MIN_ELEMENTS,
                **kwargs: Any) -> DtypeReport:
    """Walk a jaxpr (or trace ``fn(*args)``) for low→f32 promotions.

    ``allowed_shapes``: shapes whose upcast is the sanctioned master-weight
    pattern (pass the param leaf shapes of the step). Scan bodies multiply
    reported bytes by their trip count.
    """
    import jax

    jaxpr = fn_or_jaxpr
    if callable(fn_or_jaxpr) and not hasattr(fn_or_jaxpr, "eqns"):
        jaxpr = jax.make_jaxpr(fn_or_jaxpr)(*args, **kwargs)
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    allowed: Set[Tuple[int, ...]] = {tuple(s) for s in (allowed_shapes or ())}

    upcasts: List[Dict[str, Any]] = []
    sanctioned = 0
    total = 0
    for eqn, mult in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = eqn.invars[0].aval
        dst = eqn.outvars[0].aval
        if str(src.dtype) not in LOW_DTYPES or str(dst.dtype) != "float32":
            continue
        shape = tuple(src.shape)
        n = int(np.prod(shape)) if shape else 1
        if n < min_elements:
            continue
        if shape in allowed or (
                len(shape) > 2 and shape[1:] in allowed):
            # param-shaped (incl. a scanned/stacked leading dim): the
            # master-weight grad upcast — sanctioned by construction.
            # The leading-dim rule requires the trailing shape to be a
            # MATRIX param (len > 2): a 1-D allowed shape (a bias) must not
            # excuse (batch, bias_dim) activation upcasts — exactly the
            # promotion this audit exists to catch
            sanctioned += 1
            continue
        nbytes = int(n * 2 * mult)   # extra bytes: f32 copy minus bf16 source
        upcasts.append({"from": str(src.dtype), "to": "float32",
                        "shape": shape, "mult": mult, "bytes": nbytes})
        total += nbytes
    return DtypeReport(ok=not upcasts, upcasts=upcasts,
                       total_upcast_bytes=total, sanctioned=sanctioned)
