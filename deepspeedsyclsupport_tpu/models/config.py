"""Model configuration for the built-in transformer families.

The reference ships per-architecture *policies* that map external (HF) modules onto
its fused containers (``deepspeed/module_inject/containers/*.py``, 19 families) and a
v2 model zoo (``deepspeed/inference/v2/model_implementations/``: llama_v2, mistral,
mixtral, opt, falcon, phi). Here the framework owns the model definition outright —
one config dataclass covers the dense Llama/GPT family and the Mixtral-style MoE
family; per-family presets live in :data:`PRESETS`.
"""
from dataclasses import dataclass, field, replace
from typing import Optional, Tuple


@dataclass
class ModelConfig:
    # Core dimensions
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # None => MHA; < num_heads => GQA
    head_dim: Optional[int] = None      # None => hidden_size // num_heads
    max_seq_len: int = 4096

    # Architecture knobs. Together these cover the reference's per-arch policy
    # zoo (deepspeed/module_inject/containers/*.py — llama, gpt2, opt, bloom,
    # falcon, gptneox, gptj, phi, ...) as config axes on ONE model definition
    # instead of 19 module-surgery policies.
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    attn_impl: str = "auto"  # auto | xla | flash | ring | ulysses
    activation: str = "silu"   # silu | gelu | gelu_exact | relu
    use_bias: bool = False     # biases on attention/MLP projections
    qkv_bias: Optional[bool] = None  # override bias for q/k/v only (Qwen-style)
    attn_out_bias: Optional[bool] = None  # override bias for attn out proj (gptj)
    lm_head_bias: bool = False      # bias on the unembedding (gptj/phi)
    norm_type: str = "rmsnorm"      # rmsnorm | layernorm (learned bias)
    pos_embed: str = "rope"         # rope | learned | alibi | none
    alibi_scale: float = 1.0        # falcon-rw divides alibi by sqrt(head_dim)
    pos_embed_offset: int = 0       # OPT stores positions at offset 2
    rotary_pct: float = 1.0         # partial rotary (gpt-neox 0.25, phi 0.4)
    mlp_type: str = "glu"           # glu (gated, 3 mats) | mlp (fc1/fc2)
    parallel_block: bool = False    # attn+mlp both from norms of x (gptj/neox/falcon/phi)
    shared_block_norm: bool = False  # parallel block with ONE norm (gptj/falcon-7b/phi)
    embed_norm: bool = False        # layernorm right after embedding (bloom)
    sliding_window: Optional[int] = None  # Mistral-style local attention window
    # non-standard attention logit scale (None => 1/sqrt(head_dim); GPT-Neo
    # uses 1.0 — folded into q so every backend inherits it)
    attn_scale: Optional[float] = None
    # per-layer sliding windows (GPT-Neo alternating global/local pattern;
    # None entries = global). Heterogeneous layers, so requires
    # scan_layers=False (enforced in __post_init__).
    attn_windows: Optional[Tuple[Optional[int], ...]] = None

    # MoE (Mixtral-family; reference: deepspeed/moe/sharded_moe.py)
    num_experts: int = 0            # 0 => dense MLP
    num_experts_per_tok: int = 2    # top-k routing
    moe_layer_freq: int = 1         # every Nth layer is MoE
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    router_jitter: float = 0.0

    # Training-time behavior
    remat: bool = False             # jax.checkpoint each layer (activation ckpt)
    remat_policy: Optional[str] = None  # jax.checkpoint_policies name
    scan_layers: bool = True        # lax.scan over stacked layer params
    # pipeline microbatches per forward when the topology has pipe>1
    # (None => number of stages); config key pipeline.micro_batches
    pipe_microbatches: Optional[int] = None
    # pipe-stage count the trunk is built for. The engine sets this from its
    # topology at init so the pipelined trunk is an EXPLICIT config property
    # (visible to jit retracing), not a hidden global read; None falls back
    # to the world topology's pipe axis for direct model use.
    pipe_stages: Optional[int] = None
    dropout: float = 0.0
    dtype: str = "bfloat16"         # compute dtype hint (engine may override)
    # Random layerwise token dropping (reference csrc/random_ltd/ +
    # data_pipeline/data_routing): middle layers process only
    # random_ltd_current randomly kept tokens (engine schedules the value)
    random_ltd: bool = False
    random_ltd_current: Optional[int] = None

    # Initializer
    initializer_range: float = 0.02

    def __post_init__(self):
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_heads
        if self.qkv_bias is None:
            self.qkv_bias = self.use_bias
        if self.attn_out_bias is None:
            self.attn_out_bias = self.use_bias
        if self.norm_type not in ("rmsnorm", "layernorm"):
            raise ValueError(f"unknown norm_type {self.norm_type!r}")
        if self.pos_embed not in ("rope", "learned", "alibi", "none"):
            raise ValueError(f"unknown pos_embed {self.pos_embed!r}")
        if self.mlp_type not in ("glu", "mlp"):
            raise ValueError(f"unknown mlp_type {self.mlp_type!r}")
        if self.shared_block_norm and not self.parallel_block:
            raise ValueError("shared_block_norm requires parallel_block")
        if self.attn_windows is not None:
            self.attn_windows = tuple(self.attn_windows)
            if len(self.attn_windows) != self.num_layers:
                raise ValueError(
                    f"attn_windows has {len(self.attn_windows)} entries for "
                    f"{self.num_layers} layers")
            if self.scan_layers:
                # per-layer windows make layers heterogeneous — the stacked
                # lax.scan trunk requires identical layer programs
                self.scan_layers = False

    @property
    def rotary_dim(self) -> int:
        """Rotated prefix of head_dim (the rest passes through un-rotated)."""
        rd = int(self.head_dim * self.rotary_pct)
        return rd - rd % 2

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def is_moe_layer(self, layer_idx: int) -> bool:
        return self.num_experts > 0 and (layer_idx % self.moe_layer_freq == 0)

    @property
    def any_moe(self) -> bool:
        return self.num_experts > 0

    def param_count(self) -> int:
        """Approximate parameter count (embeddings + layers)."""
        d, f, v = self.hidden_size, self.intermediate_size, self.vocab_size
        attn = d * self.q_dim + 2 * d * self.kv_dim + self.q_dim * d
        mlp = (3 if self.mlp_type == "glu" else 2) * d * f
        if self.num_experts > 0:
            mlp = mlp * self.num_experts + d * self.num_experts
        per_layer = attn + mlp + 2 * d
        total = per_layer * self.num_layers + v * d + d
        if not self.tie_embeddings:
            total += d * v
        return total


def _p(**kw) -> ModelConfig:
    return ModelConfig(**kw)


PRESETS = {
    # Test-scale configs (CI / CPU-mesh friendly)
    "tiny": _p(vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
               num_heads=4, num_kv_heads=2, max_seq_len=256),
    "tiny-moe": _p(vocab_size=512, hidden_size=64, intermediate_size=128, num_layers=2,
                   num_heads=4, num_kv_heads=2, max_seq_len=256, num_experts=4,
                   num_experts_per_tok=2),
    "small": _p(vocab_size=8192, hidden_size=512, intermediate_size=1408,
                num_layers=8, num_heads=8, num_kv_heads=8, max_seq_len=2048),
    # GPT-2/BERT-era scale (BASELINE config #1 family)
    # NOTE: 50257 matches real HF GPT-2 checkpoints for ingestion parity; pad
    # vocab (e.g. 50304) via overrides when running vocab-TP at degree > 1
    "gpt2-small": _p(vocab_size=50257, hidden_size=768, intermediate_size=3072,
                     num_layers=12, num_heads=12, max_seq_len=1024,
                     tie_embeddings=True, norm_type="layernorm",
                     pos_embed="learned", mlp_type="mlp", activation="gelu",
                     use_bias=True),
    "gpt2-xl": _p(vocab_size=50257, hidden_size=1600, intermediate_size=6400,
                  num_layers=48, num_heads=25, max_seq_len=1024,
                  tie_embeddings=True, norm_type="layernorm",
                  pos_embed="learned", mlp_type="mlp", activation="gelu",
                  use_bias=True),
    "bert-large-like": _p(vocab_size=30592, hidden_size=1024, intermediate_size=4096,
                          num_layers=24, num_heads=16, max_seq_len=512,
                          norm_type="layernorm", pos_embed="learned",
                          mlp_type="mlp", activation="gelu_exact",
                          use_bias=True),
    # The wider module_inject policy zoo (containers/{opt,bloom,gptneox,gptj}.py
    # + v2 model_implementations/{opt,falcon,phi}) as config presets:
    "opt-1.3b": _p(vocab_size=50272, hidden_size=2048, intermediate_size=8192,
                   num_layers=24, num_heads=32, max_seq_len=2048,
                   tie_embeddings=True, norm_type="layernorm",
                   pos_embed="learned", pos_embed_offset=2, mlp_type="mlp",
                   activation="relu", use_bias=True),
    "bloom-7b1": _p(vocab_size=250880, hidden_size=4096, intermediate_size=16384,
                    num_layers=30, num_heads=32, max_seq_len=2048,
                    tie_embeddings=True, norm_type="layernorm",
                    pos_embed="alibi", mlp_type="mlp", activation="gelu",
                    use_bias=True, embed_norm=True),
    "falcon-7b": _p(vocab_size=65024, hidden_size=4544, intermediate_size=18176,
                    num_layers=32, num_heads=71, num_kv_heads=1,
                    max_seq_len=2048, tie_embeddings=True,
                    norm_type="layernorm", mlp_type="mlp",
                    activation="gelu_exact",  # HF falcon uses erf gelu
                    parallel_block=True, shared_block_norm=True),
    "phi-2": _p(vocab_size=51200, hidden_size=2560, intermediate_size=10240,
                num_layers=32, num_heads=32, max_seq_len=2048,
                norm_type="layernorm", mlp_type="mlp", activation="gelu",
                use_bias=True, rotary_pct=0.4, parallel_block=True,
                shared_block_norm=True, lm_head_bias=True),
    "gpt-neox-20b": _p(vocab_size=50432, hidden_size=6144, intermediate_size=24576,
                       num_layers=44, num_heads=64, max_seq_len=2048,
                       norm_type="layernorm", mlp_type="mlp",
                       activation="gelu_exact",  # HF hidden_act="gelu" = erf
                       use_bias=True, rotary_pct=0.25, parallel_block=True),
    "gptj-6b": _p(vocab_size=50400, hidden_size=4096, intermediate_size=16384,
                  num_layers=28, num_heads=16, max_seq_len=2048,
                  norm_type="layernorm", mlp_type="mlp", activation="gelu",
                  use_bias=True, qkv_bias=False, attn_out_bias=False,
                  rotary_pct=0.25, parallel_block=True, shared_block_norm=True,
                  lm_head_bias=True),
    # Llama-2 family (FastGen/ZeRO baselines; blogs/deepspeed-fastgen/README.md:135)
    # llama-650m: single-v5e bench size — fp32 master + Adam moments + grads
    # (16 bytes/param peak) fit a 16GB chip with headroom, unlike the 1b
    "llama-650m": _p(vocab_size=32000, hidden_size=1792, intermediate_size=4864,
                     num_layers=14, num_heads=14, num_kv_heads=14,
                     max_seq_len=4096),
    "llama2-1b": _p(vocab_size=32000, hidden_size=2048, intermediate_size=5504,
                    num_layers=16, num_heads=16, num_kv_heads=16, max_seq_len=4096),
    "llama2-7b": _p(vocab_size=32000, hidden_size=4096, intermediate_size=11008,
                    num_layers=32, num_heads=32, num_kv_heads=32, max_seq_len=4096),
    "llama2-13b": _p(vocab_size=32000, hidden_size=5120, intermediate_size=13824,
                     num_layers=40, num_heads=40, num_kv_heads=40, max_seq_len=4096),
    "llama2-70b": _p(vocab_size=32000, hidden_size=8192, intermediate_size=28672,
                     num_layers=80, num_heads=64, num_kv_heads=8, max_seq_len=4096),
    "mistral-7b": _p(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                     num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
                     sliding_window=4096),
    "mixtral-8x7b": _p(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                       num_layers=32, num_heads=32, num_kv_heads=8, max_seq_len=8192,
                       num_experts=8, num_experts_per_tok=2),
}


def get_config(name: str, **overrides) -> ModelConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown model preset {name!r}; have {sorted(PRESETS)}")
    return replace(PRESETS[name], **overrides) if overrides else PRESETS[name]
