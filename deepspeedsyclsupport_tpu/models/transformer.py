"""Flagship causal-LM transformer (Llama/Mistral/Mixtral family), TPU-first.

This replaces the reference's model-integration machinery — policy-driven module
surgery (``deepspeed/module_inject/replace_module.py:182``), per-arch containers
(``module_inject/containers/*``), and the inference-v2 model zoo
(``inference/v2/model_implementations/``) — with a framework-owned functional model:

* params are a plain pytree (stacked per-layer leaves, leading dim = layer) so the
  whole depth compiles as ONE ``lax.scan`` step — constant compile time in depth,
  and ZeRO/TP placement is just sharding rules over the stacked leaves.
* the same ``_forward`` serves training (no cache) and decode (KV cache carried
  through the scan) — the train/generate weight-sharing the reference needs a
  whole Hybrid Engine for (``runtime/hybrid_engine.py:32``).
* tensor-parallel layout is declared, not rewritten: :meth:`sharding_rules` gives
  Megatron-style specs (the auto-TP analog of ``module_inject/auto_tp.py:483``)
  that ``runtime/zero.py`` composes with FSDP placement.
"""
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig, get_config
from .layers import BATCH, attention_block, constrain, mlp_block, norm

Params = Dict[str, Any]


class KVCache(NamedTuple):
    """Per-model decode cache: stacked [L, B, max_len, kv_heads, head_dim]."""
    k: jnp.ndarray
    v: jnp.ndarray
    write_pos: jnp.ndarray  # scalar int32: next slot to fill


class CausalLM:
    """Decoder-only LM implementing the engine protocol:
    ``init_params() -> pytree``, ``loss(params, batch, rng) -> (loss, metrics)``,
    ``sharding_rules(path, shape) -> PartitionSpec prefix``.
    """

    def __init__(self, config: ModelConfig, seed: int = 0):
        self.config = config
        self.seed = seed

    # ------------------------------------------------------------------ init
    def init_params(self, rng: Optional[jax.Array] = None) -> Params:
        cfg = self.config
        rng = rng if rng is not None else jax.random.PRNGKey(self.seed)
        std = cfg.initializer_range
        keys = iter(jax.random.split(rng, 64))

        def dense(shape, key, scale=std):
            return (jax.random.normal(key, shape, jnp.float32) * scale)

        def norm_params() -> Params:
            p = {"scale": jnp.ones((cfg.hidden_size,), jnp.float32)}
            if cfg.norm_type == "layernorm":
                p["bias"] = jnp.zeros((cfg.hidden_size,), jnp.float32)
            return p

        def layer_params(key) -> Params:
            ks = iter(jax.random.split(key, 16))
            d, q, kv, f = (cfg.hidden_size, cfg.q_dim, cfg.kv_dim,
                           cfg.intermediate_size)
            attn: Params = {
                "wq": dense((d, q), next(ks)),
                "wk": dense((d, kv), next(ks)),
                "wv": dense((d, kv), next(ks)),
                "wo": dense((q, d), next(ks),
                            scale=std / np.sqrt(2 * cfg.num_layers)),
            }
            if cfg.qkv_bias:
                attn.update(bq=jnp.zeros((q,), jnp.float32),
                            bk=jnp.zeros((kv,), jnp.float32),
                            bv=jnp.zeros((kv,), jnp.float32))
            if cfg.attn_out_bias:
                attn["bo"] = jnp.zeros((d,), jnp.float32)
            p: Params = {"attn_norm": norm_params(), "attn": attn}
            if not cfg.shared_block_norm:
                p["mlp_norm"] = norm_params()
            if cfg.any_moe:
                e = cfg.num_experts
                p["moe"] = {
                    "router": dense((d, e), next(ks)),
                    "w_gate": dense((e, d, f), next(ks)),
                    "w_up": dense((e, d, f), next(ks)),
                    "w_down": dense((e, f, d), next(ks),
                                    scale=std / np.sqrt(2 * cfg.num_layers)),
                }
            elif cfg.mlp_type == "mlp":
                p["mlp"] = {
                    "fc1": dense((d, f), next(ks)),
                    "fc2": dense((f, d), next(ks),
                                 scale=std / np.sqrt(2 * cfg.num_layers)),
                }
                if cfg.use_bias:
                    p["mlp"].update(b1=jnp.zeros((f,), jnp.float32),
                                    b2=jnp.zeros((d,), jnp.float32))
            else:
                p["mlp"] = {
                    "w_gate": dense((d, f), next(ks)),
                    "w_up": dense((d, f), next(ks)),
                    "w_down": dense((f, d), next(ks),
                                    scale=std / np.sqrt(2 * cfg.num_layers)),
                }
            return p

        if cfg.scan_layers:
            lkeys = jax.random.split(next(keys), cfg.num_layers)
            layers = jax.vmap(layer_params)(lkeys)  # stacked leaves [L, ...]
        else:
            layers = [layer_params(k)
                      for k in jax.random.split(next(keys), cfg.num_layers)]
        params: Params = {
            "embed": {"embedding": dense((cfg.vocab_size, cfg.hidden_size),
                                         next(keys))},
            "layers": layers,
            "final_norm": norm_params(),
        }
        if cfg.pos_embed == "learned":
            # OPT-style tables carry pos_embed_offset extra rows and are
            # indexed at position + offset (HF OPTLearnedPositionalEmbedding)
            params["pos_embed"] = {"embedding": dense(
                (cfg.max_seq_len + cfg.pos_embed_offset, cfg.hidden_size),
                next(keys))}
        if cfg.embed_norm:
            params["embed_norm"] = norm_params()
        if not cfg.tie_embeddings:
            params["lm_head"] = {
                "kernel": dense((cfg.hidden_size, cfg.vocab_size), next(keys))}
            if cfg.lm_head_bias:
                params["lm_head"]["bias"] = jnp.zeros((cfg.vocab_size,),
                                                      jnp.float32)
        return params

    # ------------------------------------------------------------------ forward
    def _layer(self, p: Params, x: jnp.ndarray, positions, segment_ids,
               cache_slice, rng, kv_mask=None, kv_positions=None,
               layer_idx: Optional[int] = None
               ) -> Tuple[jnp.ndarray, Any, jnp.ndarray]:
        cfg = self.config
        # ZeRO-Inference: int8 QuantTensor leaves dequantize here, inside the
        # layer scan — at most one layer's weights are fp at a time
        from ..compression.quantize import dequantize_tree

        p = dequantize_tree(p, jnp.dtype(cfg.dtype))
        dtype = x.dtype  # pin activation dtype: fp32 params must not promote bf16

        def run_mlp(y):
            if cfg.any_moe:
                from ..monitor.mfu import region_scope
                from ..parallel.moe import moe_mlp

                with region_scope("mlp"):  # MoE is the mlp MFU region too
                    return moe_mlp(p["moe"], y, cfg, rng)
            return mlp_block(p["mlp"], y, cfg), jnp.zeros((), jnp.float32)

        from .layers import _WINDOW_FROM_CFG

        window = (cfg.attn_windows[layer_idx]
                  if cfg.attn_windows is not None and layer_idx is not None
                  else _WINDOW_FROM_CFG)
        x_norm = norm(x, p["attn_norm"], cfg)
        h, new_cache = attention_block(
            p["attn"], x_norm, cfg, positions, segment_ids, cache_slice,
            kv_mask=kv_mask, kv_positions=kv_positions,
            window_override=window)
        if cfg.parallel_block:
            # GPT-J/NeoX/Falcon/Phi residual form: x + attn(norm(x)) + mlp(·),
            # with the MLP reading either the same norm (shared_block_norm)
            # or its own norm of the SAME input x (NeoX two-norm form)
            y = x_norm if cfg.shared_block_norm else norm(x, p["mlp_norm"], cfg)
            m, aux = run_mlp(y)
            return (x + h + m).astype(dtype), new_cache, aux
        x = (x + h).astype(dtype)
        h, aux = run_mlp(norm(x, p["mlp_norm"], cfg))
        return (x + h).astype(dtype), new_cache, aux

    def _forward(self, params: Params, input_ids: jnp.ndarray,
                 positions: Optional[jnp.ndarray] = None,
                 segment_ids: Optional[jnp.ndarray] = None,
                 cache: Optional[KVCache] = None,
                 rng: Optional[jax.Array] = None,
                 kv_mask: Optional[jnp.ndarray] = None,
                 kv_positions: Optional[jnp.ndarray] = None,
                 pld_theta: Optional[jnp.ndarray] = None,
                 train: bool = True
                 ) -> Tuple[jnp.ndarray, Optional[KVCache], jnp.ndarray]:
        """Returns (logits [B,S,V] fp32, new_cache, total_aux_loss)."""
        cfg = self.config
        b, s = input_ids.shape
        if positions is None:
            base = cache.write_pos if cache is not None else 0
            positions = jnp.arange(s)[None, :] + base
            positions = jnp.broadcast_to(positions, (b, s))
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        from ..monitor.mfu import region_scope
        from ..parallel.tensor_parallel import vocab_parallel_embedding

        with region_scope("embed"):  # MFU-region label (monitor/mfu.py)
            x = vocab_parallel_embedding(params["embed"]["embedding"],
                                         input_ids)
            if cfg.pos_embed == "learned":
                # same Megatron masked-lookup+psum pattern as the vocab
                # table — a plain take on a row-sharded table makes SPMD
                # full-remat
                table = params["pos_embed"]["embedding"]
                pos = jnp.clip(positions + cfg.pos_embed_offset, 0,
                               table.shape[0] - 1)
                x = x + vocab_parallel_embedding(table, pos).astype(x.dtype)
            x = x.astype(jnp.dtype(cfg.dtype))
            if cfg.embed_norm:
                x = norm(x, params["embed_norm"], cfg)
            x = constrain(x, BATCH, "seq", None)

        def layer_fn(x, p, ck, cv, rng_l, layer_idx=None):
            cache_slice = None
            if cache is not None:
                cache_slice = (ck, cv, cache.write_pos)
            x, new_c, aux = self._layer(p, x, positions, segment_ids,
                                        cache_slice, rng_l, kv_mask=kv_mask,
                                        kv_positions=kv_positions,
                                        layer_idx=layer_idx)
            nck, ncv = (new_c[0], new_c[1]) if new_c is not None else (ck, cv)
            return x, nck, ncv, aux

        if cfg.remat:
            policy = None
            if cfg.remat_policy == "offload_dots_to_host":
                # activation offload (reference cpu_checkpointing): saved
                # dots land in pinned host memory instead of HBM
                policy = jax.checkpoint_policies.offload_dot_with_no_batch_dims(
                    "device", "pinned_host")
            elif cfg.remat_policy and cfg.remat_policy != "nothing_saveable":
                policy = getattr(jax.checkpoint_policies, cfg.remat_policy)
            # layer_idx is a STATIC python arg (per-layer window selection)
            layer_fn = jax.checkpoint(layer_fn, policy=policy,
                                      static_argnums=(5,))

        new_cache = None
        rltd_keep = cfg.random_ltd_current
        use_rltd = (cfg.random_ltd and train and cache is None
                    and cfg.scan_layers and rltd_keep is not None
                    and rltd_keep < s and cfg.num_layers >= 3)
        from ..comm import topology as topo_mod

        wtopo = topo_mod._WORLD_TOPOLOGY
        # cfg.pipe_stages (set by the engine from its topology) decides the
        # trunk explicitly; the world-topology read is the fallback for
        # direct model.loss() use. NOTE: the fallback is read at TRACE time —
        # a jitted callable keeps the topology live at its first trace.
        if cfg.pipe_stages is not None:
            pipe_n = cfg.pipe_stages
        else:
            pipe_n = wtopo.axis_sizes.get("pipe", 1) if wtopo is not None else 1
        if pipe_n > 1:
            # Pipeline-parallel trunk (reference ``runtime/pipe/module.py:636``
            # PipelineModule semantics, reachable from ``{"pipeline":
            # {"stages": N}}``): embed/head stay outside the pipeline (the
            # TiedLayerSpec pattern), the stacked layers run through the
            # SPMD 1F1B ring over the ``pipe`` axis, composed with fsdp/tp
            # via partial-manual shard_map.
            if cache is not None:
                raise NotImplementedError(
                    "KV-cache decode through the pipeline is not supported; "
                    "serve with a pipe=1 topology (the inference engines "
                    "shard with TP instead)")
            if use_rltd or pld_theta is not None:
                raise ValueError(
                    "pipeline parallelism is incompatible with random-LTD / "
                    "progressive layer dropping (they restructure the stack)")
            if kv_mask is not None or kv_positions is not None:
                raise NotImplementedError(
                    "kv_mask/kv_positions are not supported through the "
                    "pipelined trunk (they are decode-path arguments; train "
                    "packing uses segment_ids, which IS supported)")
            if not cfg.scan_layers:
                raise ValueError("pipeline parallelism requires "
                                 "scan_layers=True (stacked layer params)")
            from ..parallel.pipeline import spmd_pipeline

            lrngs = jax.random.split(rng, cfg.num_layers)
            stacked = {"w": params["layers"], "rng": lrngs}

            def pp_layer(lp, h, ex):
                pos, seg = ex
                h2, _, aux = self._layer(lp["w"], h, pos, seg, None,
                                         lp["rng"])
                return h2, aux

            x, aux_total = spmd_pipeline(
                pp_layer, stacked, x, wtopo,
                n_microbatches=cfg.pipe_microbatches,
                remat=cfg.remat, extras=(positions, segment_ids),
                with_aux=True)
        elif use_rltd:
            # Random layerwise token dropping (reference csrc/random_ltd/
            # token_sort/gather_scatter kernels + data_routing/basic_layer):
            # first and last layers see every token; the middle stack runs on
            # a random per-row subset of rltd_keep tokens (kept in causal
            # order), and dropped tokens skip those layers via the residual.
            lp = params["layers"]
            first = jax.tree_util.tree_map(lambda t: t[0], lp)
            mid = jax.tree_util.tree_map(lambda t: t[1:-1], lp)
            last = jax.tree_util.tree_map(lambda t: t[-1], lp)
            rngs = jax.random.split(rng, cfg.num_layers + 1)
            x, _, aux0 = self._layer(first, x, positions, segment_ids, None,
                                     rngs[0])

            def sample_idx(r):
                return jnp.sort(jax.random.permutation(r, s)[:rltd_keep])

            idx = jax.vmap(sample_idx)(jax.random.split(rngs[-1], b))
            x_sub = jnp.take_along_axis(x, idx[..., None], axis=1)
            pos_sub = jnp.take_along_axis(positions, idx, axis=1)
            seg_sub = (jnp.take_along_axis(segment_ids, idx, axis=1)
                       if segment_ids is not None else None)

            def mid_fn(xc, p, rng_l):
                xc, _, aux = self._layer(p, xc, pos_sub, seg_sub, None, rng_l)
                return xc, aux

            if cfg.remat:
                mid_fn = jax.checkpoint(mid_fn)

            def mid_body(xc, inp):
                p, rng_l = inp
                xc, aux = mid_fn(xc, p, rng_l)
                return xc, aux

            x_sub, auxes = jax.lax.scan(
                mid_body, x_sub, (mid, rngs[1:cfg.num_layers - 1]))
            x = x.at[jnp.arange(b)[:, None], idx].set(x_sub.astype(x.dtype))
            x, _, auxl = self._layer(last, x, positions, segment_ids, None,
                                     rngs[cfg.num_layers - 1])
            aux_total = aux0 + auxes.sum() + auxl
        elif cfg.scan_layers:
            dummy = jnp.zeros((cfg.num_layers, 0)) if cache is None else None
            ks = jax.random.split(rng, cfg.num_layers)
            # Progressive Layer Dropping (reference
            # runtime/progressive_layer_drop.py, arXiv:2010.13369): per-layer
            # keep prob p_l = 1 − (l+1)/L·(1−θ(t)); dropped layers skip via
            # lax.cond so they cost neither FLOPs nor activation memory.
            # Recorded decision: kept layers are NOT rescaled by 1/p_l
            # (stochastic-depth style), matching the paper and the
            # reference, which argue PreLN identity paths tolerate the
            # train(θ<1)/eval(all-layers) expectation gap; rescaling would
            # also change parity with reference-trained checkpoints.
            use_pld = (pld_theta is not None and train and cache is None)

            def body(x, inp):
                p, ck, cv, rng_l, li = inp
                if not use_pld:
                    x, nck, ncv, aux = layer_fn(x, p, ck, cv, rng_l, None)
                    return x, ((nck, ncv), aux)
                keep_p = 1.0 - (li + 1).astype(jnp.float32) / cfg.num_layers \
                    * (1.0 - pld_theta)
                keep = jax.random.bernoulli(jax.random.fold_in(rng_l, 17),
                                            keep_p)

                def run(_):
                    return layer_fn(x, p, ck, cv, rng_l, None)

                def skip(_):
                    return x, ck, cv, jnp.zeros((), jnp.float32)

                x, nck, ncv, aux = jax.lax.cond(keep, run, skip, None)
                return x, ((nck, ncv), aux)

            xs = (params["layers"],
                  cache.k if cache is not None else dummy,
                  cache.v if cache is not None else dummy,
                  ks, jnp.arange(cfg.num_layers))
            x, ((nk, nv), auxes) = jax.lax.scan(body, x, xs)
            aux_total = auxes.sum()
            if cache is not None:
                new_cache = KVCache(nk, nv, cache.write_pos + s)
        else:
            aux_total = jnp.zeros((), jnp.float32)
            nks, nvs = [], []
            use_pld = (pld_theta is not None and train and cache is None)
            for i, p in enumerate(params["layers"]):
                ck = cache.k[i] if cache is not None else None
                cv = cache.v[i] if cache is not None else None
                rng_l = jax.random.fold_in(rng, i)
                if use_pld:
                    keep_p = 1.0 - (i + 1) / cfg.num_layers \
                        * (1.0 - pld_theta)
                    keep = jax.random.bernoulli(
                        jax.random.fold_in(rng_l, 17), keep_p)
                    x, nck, ncv, aux = jax.lax.cond(
                        keep,
                        lambda _: layer_fn(x, p, ck, cv, rng_l, i),
                        lambda _: (x, ck, cv, jnp.zeros((), jnp.float32)),
                        None)
                else:
                    x, nck, ncv, aux = layer_fn(x, p, ck, cv, rng_l, i)
                aux_total = aux_total + aux
                if cache is not None:
                    nks.append(nck)
                    nvs.append(ncv)
            if cache is not None:
                new_cache = KVCache(jnp.stack(nks), jnp.stack(nvs),
                                    cache.write_pos + s)

        with region_scope("head"):  # final norm + LM head projection
            x = norm(x, params["final_norm"], cfg)
            if cfg.tie_embeddings:
                logits = jnp.einsum(
                    "bsd,vd->bsv", x,
                    params["embed"]["embedding"].astype(x.dtype))
            else:
                logits = jnp.einsum(
                    "bsd,dv->bsv", x,
                    params["lm_head"]["kernel"].astype(x.dtype))
                if cfg.lm_head_bias:
                    logits = logits + params["lm_head"]["bias"].astype(
                        logits.dtype)
        return logits.astype(jnp.float32), new_cache, aux_total

    def apply(self, params: Params, input_ids: jnp.ndarray, **kw) -> jnp.ndarray:
        return self._forward(params, input_ids, **kw)[0]

    # ------------------------------------------------------------------ loss
    def loss(self, params: Params, batch: Dict[str, jnp.ndarray],
             rng: Optional[jax.Array] = None, train: bool = True):
        """Next-token cross-entropy with optional ``labels``/``loss_mask``;
        the engine's ``loss_fn`` protocol. ``train=False`` disables
        train-only stochastic behavior (random-LTD token dropping)."""
        input_ids = batch["input_ids"]
        logits, _, aux = self._forward(
            params, input_ids,
            positions=batch.get("positions"),
            segment_ids=batch.get("segment_ids"), rng=rng,
            pld_theta=batch.get("pld_theta"), train=train)
        from ..monitor.mfu import region_scope

        with region_scope("loss"):  # softmax-xent MFU region
            if "labels" in batch:
                labels = batch["labels"]
                mask = batch.get("loss_mask",
                                 (labels >= 0).astype(jnp.float32))
                labels = jnp.maximum(labels, 0)
            else:
                labels = jnp.concatenate(
                    [input_ids[:, 1:], jnp.zeros_like(input_ids[:, :1])],
                    axis=1)
                mask = jnp.concatenate(
                    [jnp.ones_like(input_ids[:, 1:], jnp.float32),
                     jnp.zeros_like(input_ids[:, :1], jnp.float32)], axis=1)
                if "loss_mask" in batch:
                    mask = mask * batch["loss_mask"]
            logz = jax.scipy.special.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, labels[..., None],
                                       axis=-1)[..., 0]
            nll = (logz - gold) * mask
            denom = jnp.maximum(mask.sum(), 1.0)
            lm_loss = nll.sum() / denom
            total = lm_loss + self.config.aux_loss_coef * aux
        metrics = {"lm_loss": lm_loss}
        if self.config.any_moe:
            metrics["moe_aux_loss"] = aux
        return total, metrics

    # ------------------------------------------------------------------ decode
    def init_kv_cache(self, batch_size: int, max_len: int,
                      dtype=jnp.bfloat16) -> KVCache:
        cfg = self.config
        shape = (cfg.num_layers, batch_size, max_len, cfg.num_kv_heads,
                 cfg.head_dim)
        return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype),
                       jnp.zeros((), jnp.int32))

    def decode_step(self, params: Params, cache: KVCache,
                    tokens: jnp.ndarray,
                    positions: Optional[jnp.ndarray] = None,
                    kv_mask: Optional[jnp.ndarray] = None,
                    kv_positions: Optional[jnp.ndarray] = None
                    ) -> Tuple[jnp.ndarray, KVCache]:
        """One incremental step over ``tokens`` [B, S] (S=1 for pure decode,
        larger for prefill/chunked-prefill). Returns (logits [B, S, V], cache).
        ``positions``/``kv_mask`` support ragged right-padded batches (see
        ``inference/engine.py``)."""
        logits, new_cache, _ = self._forward(params, tokens, positions=positions,
                                             cache=cache, kv_mask=kv_mask,
                                             kv_positions=kv_positions)
        return logits, new_cache

    # ------------------------------------------------------------------ sharding
    def sharding_rules(self, path, shape) -> Optional[Tuple]:
        """Megatron-style TP + explicit FSDP dims, composed by ``runtime/zero.py``
        (which strips ``fsdp`` below stage 3). Stacked layer leaves lead with the
        layer dim, which must never shard (scan iterates it)."""
        names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
        s = "/".join(str(n) for n in names)
        stacked = "layers" in names and self.config.scan_layers
        if stacked:
            # under pipeline parallelism the stacked layer dim shards over
            # ``pipe`` (each stage owns its contiguous layer block — the
            # PipelineModule partitioning); otherwise it must never shard
            # (scan iterates it). cfg.pipe_stages (engine-set) decides;
            # world topology is the direct-use fallback.
            if self.config.pipe_stages is not None:
                pipe = self.config.pipe_stages > 1
            else:
                from ..comm import topology as topo_mod

                t = topo_mod._WORLD_TOPOLOGY
                pipe = (t is not None and t.axis_sizes.get("pipe", 1) > 1)
            pre: Tuple = ("pipe",) if pipe else (None,)
        else:
            pre = ()

        if s.endswith("embed/embedding"):
            return ("model", "fsdp")
        if s.endswith("lm_head/kernel"):
            return ("fsdp", "model")
        if "attn/" in s or s.endswith(("wq", "wk", "wv", "wo")):
            if s.endswith(("wq", "wk", "wv")):
                return pre + ("fsdp", "model")
            if s.endswith("wo"):
                return pre + ("model", "fsdp")
        if s.endswith(("mlp/w_gate", "mlp/w_up", "mlp/fc1")):
            return pre + ("fsdp", "model")
        if s.endswith(("mlp/w_down", "mlp/fc2")):
            return pre + ("model", "fsdp")
        if s.endswith("pos_embed/embedding"):
            return ("model", "fsdp")  # looked up via vocab_parallel_embedding
        if s.endswith("moe/router"):
            return pre + (None, None)
        if s.endswith(("moe/w_gate", "moe/w_up")):
            return pre + ("expert", "fsdp", "model")
        if s.endswith("moe/w_down"):
            return pre + ("expert", "model", "fsdp")
        if s.endswith("scale"):
            return pre or None  # norm scales replicate (per pipe stage)
        return pre or None


def build_model(name_or_config, **overrides) -> CausalLM:
    """Model factory (registry analog of ``inference/v2/engine_factory.py:123``)."""
    if isinstance(name_or_config, ModelConfig):
        cfg = name_or_config
    else:
        cfg = get_config(name_or_config, **overrides)
    return CausalLM(cfg)
