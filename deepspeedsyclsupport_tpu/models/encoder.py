"""Encoder architectures: BERT-family masked-LM models and CLIP dual towers.

Reference analog: the encoder half of ``module_inject``'s policy zoo —
``deepspeed/module_inject/containers/bert.py``, ``distil_bert.py``,
``clip.py`` — which rewrites HF modules with fused kernels. Here the same
architectures are framework-owned functional models (the decoder-only
counterpart is ``models/transformer.py``): stacked per-layer leaves scanned
with ``lax.scan``, TP/FSDP placement declared via ``sharding_rules``, and
attention routed through the same ``models/layers.attention`` seam (flash
kernel on TPU, XLA oracle elsewhere).

The vision tower's patchify is the conv-as-matmul formulation — a stride-p
conv over non-overlapping patches IS a reshape+matmul, which XLA tiles onto
the MXU far better than a tiny-window conv.
"""
import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .layers import attention, layer_norm

Params = Dict[str, Any]


def _act(name: str):
    if name == "quick_gelu":            # CLIP: x * sigmoid(1.702 x)
        return lambda x: x * jax.nn.sigmoid(1.702 * x)
    if name == "gelu_exact":
        return lambda x: jax.nn.gelu(x, approximate=False)
    if name == "gelu":
        return jax.nn.gelu
    if name == "relu":
        return jax.nn.relu
    raise ValueError(f"unsupported encoder activation {name!r}")


@dataclasses.dataclass
class EncoderConfig:
    """Config for one transformer tower (text or vision)."""
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    type_vocab_size: int = 2          # 0 => no token-type embeddings
    layer_norm_eps: float = 1e-12
    activation: str = "gelu_exact"    # HF bert "gelu" is the erf form
    norm_position: str = "post"       # bert/distilbert: post-LN; clip: pre-LN
    causal: bool = False              # clip text tower attends causally
    dtype: str = "float32"
    # training-time dropout (applied by tower_forward when train=True and
    # an rng is supplied). attn_dropout is applied to the ATTENTION OUTPUT
    # (probs-dropout would defeat the flash kernel) — a documented
    # approximation of the reference kernel's prob-space dropout.
    hidden_dropout: float = 0.0
    attn_dropout: float = 0.0
    # vision tower (0 => text tower)
    image_size: int = 0
    patch_size: int = 0

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


# ======================================================================
# shared tower
# ======================================================================
def _dense(rng, shape, std=0.02):
    return jax.random.normal(rng, shape, jnp.float32) * std


def _ln_params(d):
    return {"scale": jnp.ones((d,), jnp.float32),
            "bias": jnp.zeros((d,), jnp.float32)}


def tower_layer_params(cfg: EncoderConfig, rng,
                       std: float = 0.02) -> Params:
    d, f = cfg.hidden_size, cfg.intermediate_size
    ks = iter(jax.random.split(rng, 8))
    return {
        "attn": {"wq": _dense(next(ks), (d, d), std), "bq": jnp.zeros((d,)),
                 "wk": _dense(next(ks), (d, d), std), "bk": jnp.zeros((d,)),
                 "wv": _dense(next(ks), (d, d), std), "bv": jnp.zeros((d,)),
                 "wo": _dense(next(ks), (d, d), std), "bo": jnp.zeros((d,))},
        "attn_norm": _ln_params(d),
        "mlp": {"fc1": _dense(next(ks), (d, f), std), "b1": jnp.zeros((f,)),
                "fc2": _dense(next(ks), (f, d), std), "b2": jnp.zeros((d,))},
        "mlp_norm": _ln_params(d),
    }


def tower_forward(cfg: EncoderConfig, layers: Params, x: jnp.ndarray,
                  mask: Optional[jnp.ndarray],
                  rng: Optional[jax.Array] = None,
                  train: bool = False) -> jnp.ndarray:
    """Scan the stacked encoder layers over ``x [B,S,D]``.

    ``mask [B,S]``: 1 for valid tokens. Padding isolation rides the flash
    kernel's segment-id masking (pads form their own segment, so valid
    tokens never attend to them); outputs at pad rows are garbage the
    caller must ignore — exactly the HF contract. ``train=True`` with an
    ``rng`` enables the config's dropout (BERT placement: inside each
    sublayer, before the residual).
    """
    act = _act(cfg.activation)
    eps = cfg.layer_norm_eps
    seg = mask.astype(jnp.int32) if mask is not None else None
    b, s, d = x.shape
    use_drop = bool(train and rng is not None
                    and (cfg.hidden_dropout > 0 or cfg.attn_dropout > 0))

    def drop(h, rate, key):
        if not use_drop or rate <= 0:
            return h
        keep = jax.random.bernoulli(key, 1.0 - rate, h.shape)
        return jnp.where(keep, h / (1.0 - rate), 0.0).astype(h.dtype)

    def attn_sub(p, h, key=None):
        q = (jnp.einsum("bsd,dq->bsq", h, p["wq"])
             + p["bq"].astype(h.dtype))
        k = (jnp.einsum("bsd,dk->bsk", h, p["wk"])
             + p["bk"].astype(h.dtype))
        v = (jnp.einsum("bsd,dk->bsk", h, p["wv"])
             + p["bv"].astype(h.dtype))
        q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.num_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.num_heads, cfg.head_dim)
        o = attention(q, k, v, causal=cfg.causal, segment_ids=seg)
        if key is not None:
            o = drop(o, cfg.attn_dropout, jax.random.fold_in(key, 1))
        o = o.reshape(b, s, d)
        o = jnp.einsum("bsq,qd->bsd", o, p["wo"]) + p["bo"].astype(h.dtype)
        if key is not None:
            o = drop(o, cfg.hidden_dropout, jax.random.fold_in(key, 2))
        return o

    def mlp_sub(p, h, key=None):
        h = act(jnp.einsum("bsd,df->bsf", h, p["fc1"])
                + p["b1"].astype(h.dtype))
        h = jnp.einsum("bsf,fd->bsd", h, p["fc2"]) + p["b2"].astype(h.dtype)
        if key is not None:
            h = drop(h, cfg.hidden_dropout, jax.random.fold_in(key, 3))
        return h

    def ln(h, p):
        return layer_norm(h, p["scale"], p["bias"], eps)

    def layer(h, inp):
        p, key = inp
        if cfg.norm_position == "post":       # bert: LN(x + sub(x))
            h = ln(h + attn_sub(p["attn"], h, key), p["attn_norm"])
            h = ln(h + mlp_sub(p["mlp"], h, key), p["mlp_norm"])
        else:                                  # clip/vit: x + sub(LN(x))
            h = h + attn_sub(p["attn"], ln(h, p["attn_norm"]), key)
            h = h + mlp_sub(p["mlp"], ln(h, p["mlp_norm"]), key)
        return h, None

    n_layers = jax.tree_util.tree_leaves(layers)[0].shape[0]
    keys = (jax.random.split(rng, n_layers) if use_drop
            else jnp.zeros((n_layers, 2), jnp.uint32))
    if not use_drop:
        keys = None
    x, _ = jax.lax.scan(layer, x, (layers, keys))
    return x


def _tower_sharding(names, s: str, pre: Tuple) -> Optional[Tuple]:
    if s.endswith(("wq", "wk", "wv", "fc1")):
        return pre + ("fsdp", "model")
    if s.endswith(("wo", "fc2")):
        return pre + ("model", "fsdp")
    return (pre or None) if pre else None


# ======================================================================
# BERT family
# ======================================================================
class BertModel:
    """BERT / DistilBERT masked-LM model (engine protocol: ``init_params``,
    ``loss``, ``sharding_rules``; serving surface: :meth:`apply`).

    Reference parity targets: ``module_inject/containers/bert.py`` (layer
    rewrite) and ``distil_bert.py``; ingestion + logits parity live in
    ``checkpoint/hf.load_hf_encoder_checkpoint``.
    """

    def __init__(self, config: EncoderConfig, seed: int = 0,
                 tie_mlm_decoder: bool = True):
        self.config = config
        self.seed = seed
        self.tie_mlm_decoder = tie_mlm_decoder

    def init_params(self, rng: Optional[jax.Array] = None) -> Params:
        cfg = self.config
        rng = rng if rng is not None else jax.random.PRNGKey(self.seed)
        ks = iter(jax.random.split(rng, 16))
        d = cfg.hidden_size
        params: Params = {
            "embed": {"word": _dense(next(ks), (cfg.vocab_size, d)),
                      "pos": _dense(next(ks), (cfg.max_seq_len, d))},
            "embed_norm": _ln_params(d),
            "layers": jax.vmap(lambda k: tower_layer_params(cfg, k))(
                jax.random.split(next(ks), cfg.num_layers)),
            "mlm": {"dense": _dense(next(ks), (d, d)),
                    "bias_d": jnp.zeros((d,)),
                    "norm": _ln_params(d),
                    "decoder_bias": jnp.zeros((cfg.vocab_size,))},
            "pooler": {"w": _dense(next(ks), (d, d)), "b": jnp.zeros((d,))},
        }
        if cfg.type_vocab_size > 0:
            params["embed"]["type"] = _dense(next(ks),
                                             (cfg.type_vocab_size, d))
        if not self.tie_mlm_decoder:
            params["mlm"]["decoder"] = _dense(next(ks), (d, cfg.vocab_size))
        return params

    # ---------------------------------------------------------------- forward
    def encode(self, params: Params, input_ids: jnp.ndarray,
               attention_mask: Optional[jnp.ndarray] = None,
               token_type_ids: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        cfg = self.config
        b, s = input_ids.shape
        x = params["embed"]["word"][input_ids]
        x = x + params["embed"]["pos"][jnp.arange(s)][None]
        if cfg.type_vocab_size > 0:
            tt = (token_type_ids if token_type_ids is not None
                  else jnp.zeros((b, s), jnp.int32))
            x = x + params["embed"]["type"][tt]
        x = layer_norm(x, params["embed_norm"]["scale"],
                       params["embed_norm"]["bias"], cfg.layer_norm_eps)
        x = x.astype(jnp.dtype(cfg.dtype))
        return tower_forward(cfg, params["layers"], x, attention_mask)

    def apply(self, params: Params, input_ids: jnp.ndarray,
              attention_mask: Optional[jnp.ndarray] = None,
              token_type_ids: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """Masked-LM logits [B,S,V]."""
        cfg = self.config
        h = self.encode(params, input_ids, attention_mask, token_type_ids)
        m = params["mlm"]
        h = jnp.einsum("bsd,de->bse", h, m["dense"]) + m["bias_d"]
        h = _act(cfg.activation)(h)
        h = layer_norm(h, m["norm"]["scale"], m["norm"]["bias"],
                       cfg.layer_norm_eps)
        dec = (params["embed"]["word"].T if self.tie_mlm_decoder
               else m["decoder"])
        return (jnp.einsum("bsd,dv->bsv", h, dec.astype(h.dtype))
                + m["decoder_bias"]).astype(jnp.float32)

    def pooled(self, params: Params, input_ids: jnp.ndarray,
               attention_mask: Optional[jnp.ndarray] = None,
               token_type_ids: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        """[CLS] pooler output [B,D] (the classification head input)."""
        h = self.encode(params, input_ids, attention_mask, token_type_ids)
        p = params["pooler"]
        return jnp.tanh(h[:, 0] @ p["w"] + p["b"])

    # ------------------------------------------------------------------ loss
    def loss(self, params: Params, batch: Dict[str, jnp.ndarray],
             rng: Optional[jax.Array] = None, train: bool = True):
        """Masked-LM cross-entropy: ``labels`` with -100 (HF) or any
        negative value marking unmasked positions."""
        logits = self.apply(params, batch["input_ids"],
                            batch.get("attention_mask"),
                            batch.get("token_type_ids"))
        labels = batch["labels"]
        mask = (labels >= 0).astype(jnp.float32)
        labels = jnp.maximum(labels, 0)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        nll = (logz - gold) * mask
        loss = nll.sum() / jnp.maximum(mask.sum(), 1.0)
        return loss, {"mlm_loss": loss}

    # -------------------------------------------------------------- sharding
    def sharding_rules(self, path, shape) -> Optional[Tuple]:
        names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
        s = "/".join(str(n) for n in names)
        pre = (None,) if "layers" in names else ()
        if s.endswith(("embed/word", "mlm/decoder")):
            return ("model", "fsdp") if s.endswith("word") else ("fsdp",
                                                                 "model")
        return _tower_sharding(names, s, pre)


# ======================================================================
# CLIP
# ======================================================================
@dataclasses.dataclass
class CLIPConfig:
    text: EncoderConfig = dataclasses.field(default_factory=lambda:
                                            EncoderConfig(
                                                vocab_size=49408,
                                                hidden_size=512,
                                                intermediate_size=2048,
                                                num_layers=12, num_heads=8,
                                                max_seq_len=77,
                                                type_vocab_size=0,
                                                layer_norm_eps=1e-5,
                                                activation="quick_gelu",
                                                norm_position="pre",
                                                causal=True))
    vision: EncoderConfig = dataclasses.field(default_factory=lambda:
                                              EncoderConfig(
                                                  vocab_size=0,
                                                  hidden_size=768,
                                                  intermediate_size=3072,
                                                  num_layers=12,
                                                  num_heads=12,
                                                  type_vocab_size=0,
                                                  layer_norm_eps=1e-5,
                                                  activation="quick_gelu",
                                                  norm_position="pre",
                                                  image_size=224,
                                                  patch_size=32))
    projection_dim: int = 512
    eos_token_id: int = 49407
    logit_scale_init: float = 2.6592


class CLIPModel:
    """CLIP dual-tower model (reference ``module_inject/containers/clip.py``
    rewrites the HF towers; here both towers are native).

    ``apply_text`` / ``apply_image`` give the projected, L2-normalized
    embeddings; :meth:`loss` is the symmetric contrastive objective.
    """

    def __init__(self, config: Optional[CLIPConfig] = None, seed: int = 0):
        self.config = config or CLIPConfig()
        self.seed = seed

    def init_params(self, rng: Optional[jax.Array] = None) -> Params:
        cfg = self.config
        rng = rng if rng is not None else jax.random.PRNGKey(self.seed)
        ks = iter(jax.random.split(rng, 16))
        t, v = cfg.text, cfg.vision
        patch_in = 3 * v.patch_size * v.patch_size
        return {
            "text": {
                "embed": {"word": _dense(next(ks), (t.vocab_size,
                                                    t.hidden_size)),
                          "pos": _dense(next(ks), (t.max_seq_len,
                                                   t.hidden_size))},
                "layers": jax.vmap(lambda k: tower_layer_params(t, k))(
                    jax.random.split(next(ks), t.num_layers)),
                "final_norm": _ln_params(t.hidden_size),
            },
            "vision": {
                "class_embed": _dense(next(ks), (v.hidden_size,)),
                "patch_embed": _dense(next(ks), (patch_in, v.hidden_size)),
                "pos_embed": _dense(next(ks), (v.num_patches + 1,
                                               v.hidden_size)),
                "pre_norm": _ln_params(v.hidden_size),
                "layers": jax.vmap(lambda k: tower_layer_params(v, k))(
                    jax.random.split(next(ks), v.num_layers)),
                "post_norm": _ln_params(v.hidden_size),
            },
            "text_projection": _dense(next(ks), (t.hidden_size,
                                                 cfg.projection_dim)),
            "visual_projection": _dense(next(ks), (v.hidden_size,
                                                   cfg.projection_dim)),
            "logit_scale": jnp.asarray(cfg.logit_scale_init, jnp.float32),
        }

    # ---------------------------------------------------------------- towers
    def apply_text(self, params: Params, input_ids: jnp.ndarray
                   ) -> jnp.ndarray:
        """Projected text embeddings [B, proj] (NOT normalized — HF
        get_text_features contract)."""
        cfg = self.config.text
        p = params["text"]
        b, s = input_ids.shape
        x = p["embed"]["word"][input_ids] + p["embed"]["pos"][
            jnp.arange(s)][None]
        x = tower_forward(cfg, p["layers"], x, None)
        x = layer_norm(x, p["final_norm"]["scale"], p["final_norm"]["bias"],
                       cfg.layer_norm_eps)
        # pool at the (first) EOS token position
        is_eos = (input_ids == self.config.eos_token_id)
        eos_pos = jnp.argmax(is_eos, axis=1)
        # prompts without an explicit eos fall back to the last token
        eos_pos = jnp.where(is_eos.any(axis=1), eos_pos, s - 1)
        pooled = jnp.take_along_axis(x, eos_pos[:, None, None], axis=1)[:, 0]
        return pooled @ params["text_projection"]

    def apply_image(self, params: Params, pixel_values: jnp.ndarray
                    ) -> jnp.ndarray:
        """Projected image embeddings [B, proj]. ``pixel_values``:
        [B, 3, H, W] (the HF processor layout)."""
        cfg = self.config.vision
        p = params["vision"]
        b = pixel_values.shape[0]
        ps, d = cfg.patch_size, cfg.hidden_size
        hp = cfg.image_size // ps
        # conv-as-matmul patchify: [B,3,H,W] → [B, N, p·p·3] @ [p·p·3, D]
        x = jnp.transpose(pixel_values, (0, 2, 3, 1))        # B,H,W,C
        x = x.reshape(b, hp, ps, hp, ps, 3)
        x = jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(b, hp * hp, -1)
        x = x @ p["patch_embed"]
        cls = jnp.broadcast_to(p["class_embed"], (b, 1, d))
        x = jnp.concatenate([cls, x], axis=1) + p["pos_embed"][None]
        x = layer_norm(x, p["pre_norm"]["scale"], p["pre_norm"]["bias"],
                       cfg.layer_norm_eps)
        x = tower_forward(cfg, p["layers"], x, None)
        pooled = layer_norm(x[:, 0], p["post_norm"]["scale"],
                            p["post_norm"]["bias"], cfg.layer_norm_eps)
        return pooled @ params["visual_projection"]

    def apply(self, params: Params, input_ids: jnp.ndarray,
              pixel_values: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(logits_per_text [Bt,Bi], logits_per_image [Bi,Bt])."""
        te = self.apply_text(params, input_ids)
        ie = self.apply_image(params, pixel_values)
        te = te / jnp.linalg.norm(te, axis=-1, keepdims=True)
        ie = ie / jnp.linalg.norm(ie, axis=-1, keepdims=True)
        scale = jnp.exp(params["logit_scale"])
        lt = scale * te @ ie.T
        return lt, lt.T

    # ------------------------------------------------------------------ loss
    def loss(self, params: Params, batch: Dict[str, jnp.ndarray],
             rng: Optional[jax.Array] = None, train: bool = True):
        """Symmetric InfoNCE over in-batch pairs (the CLIP objective)."""
        lt, li = self.apply(params, batch["input_ids"],
                            batch["pixel_values"])
        n = lt.shape[0]
        labels = jnp.arange(n)

        def xent(logits):
            logz = jax.scipy.special.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, labels[:, None],
                                       axis=-1)[:, 0]
            return (logz - gold).mean()

        loss = 0.5 * (xent(lt) + xent(li))
        return loss, {"clip_loss": loss}

    # -------------------------------------------------------------- sharding
    def sharding_rules(self, path, shape) -> Optional[Tuple]:
        names = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
        s = "/".join(str(n) for n in names)
        pre = (None,) if "layers" in names else ()
        if s.endswith("embed/word"):
            return ("model", "fsdp")
        if s.endswith(("text_projection", "visual_projection")):
            return ("fsdp", "model")
        return _tower_sharding(names, s, pre)
