"""Diffusion model family: SD-style VAE (AutoencoderKL) and conditional UNet.

Reference analog: the diffusion half of ``module_inject`` —
``deepspeed/module_inject/containers/unet.py`` / ``vae.py`` replace the HF
diffusers modules' attention and bias-adds with fused kernels
(``csrc/spatial/csrc/opt_bias_add.cu``, diffusers attention in
``ops/transformer/inference/diffusers_attention.py``). Here the
architectures are framework-owned functional models, with the spatial
bias-add family (``ops/spatial.py``) on the conv paths and attention routed
through the shared :func:`models.layers.attention` seam.

TPU notes: convs run NHWC (XLA's preferred TPU layout); spatial attention
flattens H·W into a sequence so the flash kernel applies; GroupNorm runs in
fp32 like the other norms.
"""
import dataclasses
import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .layers import attention
from ..ops.spatial import nhwc_bias_add

Params = Dict[str, Any]


# ======================================================================
# primitives
# ======================================================================
def conv2d(x: jnp.ndarray, w: jnp.ndarray, b: Optional[jnp.ndarray] = None,
           stride: int = 1, padding: int = 1) -> jnp.ndarray:
    """NHWC conv with HWIO kernel (XLA tiles this onto the MXU)."""
    y = jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if b is not None:
        y = nhwc_bias_add(y, b)
    return y


def group_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               groups: int = 32, eps: float = 1e-6) -> jnp.ndarray:
    """GroupNorm over NHWC (diffusers convention), fp32 accumulation."""
    b, h, w, c = x.shape
    g = min(groups, c)
    dtype = x.dtype
    xf = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * jax.lax.rsqrt(var + eps)
    xf = xf.reshape(b, h, w, c)
    return (xf * scale.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(dtype)


def timestep_embedding(t: jnp.ndarray, dim: int,
                       max_period: float = 10000.0) -> jnp.ndarray:
    """Sinusoidal timestep embedding [B, dim] (DDPM/diffusers convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    ang = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)


def _conv_p(rng, kh, kw, cin, cout, std=None):
    std = std if std is not None else 1.0 / np.sqrt(kh * kw * cin)
    return {"w": jax.random.normal(rng, (kh, kw, cin, cout),
                                   jnp.float32) * std,
            "b": jnp.zeros((cout,), jnp.float32)}


def _lin_p(rng, cin, cout, std=None):
    std = std if std is not None else 1.0 / np.sqrt(cin)
    return {"w": jax.random.normal(rng, (cin, cout), jnp.float32) * std,
            "b": jnp.zeros((cout,), jnp.float32)}


def _gn_p(c):
    return {"scale": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32)}


def _lin(p, x):
    return x @ p["w"] + p["b"].astype(x.dtype)


# ======================================================================
# blocks
# ======================================================================
def resnet_block_params(rng, cin, cout, temb_dim: int = 0) -> Params:
    ks = iter(jax.random.split(rng, 4))
    p = {"norm1": _gn_p(cin), "conv1": _conv_p(next(ks), 3, 3, cin, cout),
         "norm2": _gn_p(cout), "conv2": _conv_p(next(ks), 3, 3, cout, cout)}
    if temb_dim:
        p["temb"] = _lin_p(next(ks), temb_dim, cout)
    if cin != cout:
        p["shortcut"] = _conv_p(next(ks), 1, 1, cin, cout)
    return p


def resnet_block(p: Params, x: jnp.ndarray,
                 temb: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    h = jax.nn.silu(group_norm(x, **p["norm1"]))
    h = conv2d(h, p["conv1"]["w"], p["conv1"]["b"])
    if temb is not None and "temb" in p:
        h = h + _lin(p["temb"], jax.nn.silu(temb))[:, None, None, :]
    h = jax.nn.silu(group_norm(h, **p["norm2"]))
    h = conv2d(h, p["conv2"]["w"], p["conv2"]["b"])
    if "shortcut" in p:
        x = conv2d(x, p["shortcut"]["w"], p["shortcut"]["b"], padding=0)
    return x + h


def attn_block_params(rng, c, ctx_dim: int = 0) -> Params:
    ks = iter(jax.random.split(rng, 5))
    kv_in = ctx_dim or c
    return {"norm": _gn_p(c),
            "q": _lin_p(next(ks), c, c), "k": _lin_p(next(ks), kv_in, c),
            "v": _lin_p(next(ks), kv_in, c), "o": _lin_p(next(ks), c, c)}


def spatial_attention(p: Params, x: jnp.ndarray,
                      context: Optional[jnp.ndarray] = None,
                      heads: int = 1) -> jnp.ndarray:
    """Self- (or cross-) attention over flattened H·W positions — the role
    of the reference's fused diffusers attention
    (``ops/transformer/inference/diffusers_attention.py``). ``heads`` is
    model config, NOT a param leaf (int leaves would break jax.grad)."""
    b, hh, ww, c = x.shape
    hd = c // heads
    seq = group_norm(x, **p["norm"]).reshape(b, hh * ww, c)
    ctx = seq if context is None else context.astype(seq.dtype)
    q = _lin(p["q"], seq).reshape(b, hh * ww, heads, hd)
    k = _lin(p["k"], ctx).reshape(b, ctx.shape[1], heads, hd)
    v = _lin(p["v"], ctx).reshape(b, ctx.shape[1], heads, hd)
    o = attention(q, k, v, causal=False).reshape(b, hh * ww, c)
    return x + _lin(p["o"], o).reshape(b, hh, ww, c)


# ======================================================================
# VAE (AutoencoderKL)
# ======================================================================
@dataclasses.dataclass
class VAEConfig:
    in_channels: int = 3
    latent_channels: int = 4
    base_channels: int = 32
    channel_mults: Tuple[int, ...] = (1, 2, 4)
    layers_per_block: int = 1
    scaling_factor: float = 0.18215   # SD latent scale
    dtype: str = "float32"


class AutoencoderKL:
    """SD-style KL VAE (reference serving surface:
    ``module_inject/containers/vae.py`` policy over diffusers
    ``AutoencoderKL``). Engine protocol: ``init_params`` / ``loss``."""

    def __init__(self, config: Optional[VAEConfig] = None, seed: int = 0):
        self.config = config or VAEConfig()
        self.seed = seed

    def init_params(self, rng: Optional[jax.Array] = None) -> Params:
        cfg = self.config
        rng = rng if rng is not None else jax.random.PRNGKey(self.seed)
        ks = iter(jax.random.split(rng, 64))
        chans = [cfg.base_channels * m for m in cfg.channel_mults]
        enc: Params = {"conv_in": _conv_p(next(ks), 3, 3, cfg.in_channels,
                                          chans[0]),
                       "down": []}
        c = chans[0]
        for i, co in enumerate(chans):
            blk = {"res": [resnet_block_params(next(ks), c if j == 0 else co,
                                               co)
                           for j in range(cfg.layers_per_block)]}
            if i < len(chans) - 1:
                blk["down"] = _conv_p(next(ks), 3, 3, co, co)
            enc["down"].append(blk)
            c = co
        enc["mid"] = {"res1": resnet_block_params(next(ks), c, c),
                      "attn": attn_block_params(next(ks), c),
                      "res2": resnet_block_params(next(ks), c, c)}
        enc["norm_out"] = _gn_p(c)
        enc["conv_out"] = _conv_p(next(ks), 3, 3, c,
                                  2 * cfg.latent_channels)
        dec: Params = {"conv_in": _conv_p(next(ks), 3, 3,
                                          cfg.latent_channels, c),
                       "mid": {"res1": resnet_block_params(next(ks), c, c),
                               "attn": attn_block_params(next(ks), c),
                               "res2": resnet_block_params(next(ks), c, c)},
                       "up": []}
        for i, co in enumerate(reversed(chans)):
            blk = {"res": [resnet_block_params(next(ks), c if j == 0 else co,
                                               co)
                           for j in range(cfg.layers_per_block + 1)]}
            if i < len(chans) - 1:
                blk["up"] = _conv_p(next(ks), 3, 3, co, co)
            dec["up"].append(blk)
            c = co
        dec["norm_out"] = _gn_p(c)
        dec["conv_out"] = _conv_p(next(ks), 3, 3, c, cfg.in_channels)
        return {"encoder": enc, "decoder": dec,
                "quant_conv": _conv_p(next(ks), 1, 1,
                                      2 * cfg.latent_channels,
                                      2 * cfg.latent_channels),
                "post_quant_conv": _conv_p(next(ks), 1, 1,
                                           cfg.latent_channels,
                                           cfg.latent_channels)}

    # ---------------------------------------------------------------- encode
    def encode(self, params: Params, x: jnp.ndarray
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """[B,H,W,3] → (mean, logvar) latents [B,H/2^d,W/2^d,Cl]."""
        p = params["encoder"]
        h = conv2d(x, p["conv_in"]["w"], p["conv_in"]["b"])
        for blk in p["down"]:
            for r in blk["res"]:
                h = resnet_block(r, h)
            if "down" in blk:
                h = conv2d(h, blk["down"]["w"], blk["down"]["b"], stride=2)
        m = p["mid"]
        h = resnet_block(m["res1"], h)
        h = spatial_attention(m["attn"], h)
        h = resnet_block(m["res2"], h)
        h = jax.nn.silu(group_norm(h, **p["norm_out"]))
        h = conv2d(h, p["conv_out"]["w"], p["conv_out"]["b"])
        h = conv2d(h, params["quant_conv"]["w"], params["quant_conv"]["b"],
                   padding=0)
        mean, logvar = jnp.split(h, 2, axis=-1)
        return mean, jnp.clip(logvar, -30.0, 20.0)

    def decode(self, params: Params, z: jnp.ndarray) -> jnp.ndarray:
        p = params["decoder"]
        h = conv2d(z, params["post_quant_conv"]["w"],
                   params["post_quant_conv"]["b"], padding=0)
        h = conv2d(h, p["conv_in"]["w"], p["conv_in"]["b"])
        m = p["mid"]
        h = resnet_block(m["res1"], h)
        h = spatial_attention(m["attn"], h)
        h = resnet_block(m["res2"], h)
        for blk in p["up"]:
            for r in blk["res"]:
                h = resnet_block(r, h)
            if "up" in blk:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = conv2d(h, blk["up"]["w"], blk["up"]["b"])
        h = jax.nn.silu(group_norm(h, **p["norm_out"]))
        return conv2d(h, p["conv_out"]["w"], p["conv_out"]["b"])

    def loss(self, params: Params, batch: Dict[str, jnp.ndarray],
             rng: Optional[jax.Array] = None, train: bool = True):
        """Reconstruction + KL (beta from batch or 1e-6, the SD-VAE
        regime)."""
        x = batch["pixel_values"]
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        mean, logvar = self.encode(params, x)
        z = mean + jnp.exp(0.5 * logvar) * jax.random.normal(
            rng, mean.shape, mean.dtype)
        rec = self.decode(params, z)
        rec_loss = jnp.mean((rec - x) ** 2)
        kl = 0.5 * jnp.mean(mean ** 2 + jnp.exp(logvar) - 1.0 - logvar)
        beta = float(batch.get("kl_weight", 1e-6))
        loss = rec_loss + beta * kl
        return loss, {"rec_loss": rec_loss, "kl": kl}

    def sharding_rules(self, path, shape):
        return None  # conv kernels are small; replicate (DP/fsdp via engine)


# ======================================================================
# Conditional UNet (UNet2DConditionModel-style)
# ======================================================================
@dataclasses.dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    base_channels: int = 32
    channel_mults: Tuple[int, ...] = (1, 2, 4)
    layers_per_block: int = 1
    attn_levels: Tuple[int, ...] = (1, 2)  # levels with transformer blocks
    num_heads: int = 4
    cross_attention_dim: int = 64
    dtype: str = "float32"

    @property
    def temb_dim(self) -> int:
        return self.base_channels * 4


class UNet2DCondition:
    """Conditional UNet (reference serving surface:
    ``module_inject/containers/unet.py`` policy over diffusers
    ``UNet2DConditionModel``): timestep-embedded resnet trunks, self+cross
    attention at the configured levels, skip connections down→up.

    Training protocol (engine ``loss``): DDPM epsilon-prediction MSE with
    uniformly sampled timesteps, the standard diffusion objective.
    """

    def __init__(self, config: Optional[UNetConfig] = None, seed: int = 0):
        self.config = config or UNetConfig()
        self.seed = seed

    # ---------------------------------------------------------------- params
    def _attn_pair(self, rng, c) -> Params:
        cfg = self.config
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        return {"self": attn_block_params(k1, c),
                "cross": attn_block_params(k2, c,
                                           ctx_dim=cfg.cross_attention_dim),
                "ff1": _lin_p(k3, c, 4 * c), "ff2": _lin_p(k4, 4 * c, c),
                "ff_norm": _gn_p(c)}

    def init_params(self, rng: Optional[jax.Array] = None) -> Params:
        cfg = self.config
        rng = rng if rng is not None else jax.random.PRNGKey(self.seed)
        ks = iter(jax.random.split(rng, 128))
        chans = [cfg.base_channels * m for m in cfg.channel_mults]
        td = cfg.temb_dim
        p: Params = {
            "time_mlp": {"fc1": _lin_p(next(ks), cfg.base_channels, td),
                         "fc2": _lin_p(next(ks), td, td)},
            "conv_in": _conv_p(next(ks), 3, 3, cfg.in_channels, chans[0]),
            "down": [], "up": [],
        }
        c = chans[0]
        for lvl, co in enumerate(chans):
            blk = {"res": [], "attn": []}
            for j in range(cfg.layers_per_block):
                blk["res"].append(resnet_block_params(
                    next(ks), c if j == 0 else co, co, temb_dim=td))
                if lvl in cfg.attn_levels:
                    blk["attn"].append(self._attn_pair(next(ks), co))
            if lvl < len(chans) - 1:
                blk["down"] = _conv_p(next(ks), 3, 3, co, co)
            p["down"].append(blk)
            c = co
        p["mid"] = {"res1": resnet_block_params(next(ks), c, c, temb_dim=td),
                    "attn": self._attn_pair(next(ks), c),
                    "res2": resnet_block_params(next(ks), c, c, temb_dim=td)}
        # up path consumes skips: channel bookkeeping mirrors diffusers
        skip_chans = [chans[0]]
        for lvl, co in enumerate(chans):
            skip_chans += [co] * cfg.layers_per_block
            if lvl < len(chans) - 1:
                skip_chans.append(co)
        for lvl in reversed(range(len(chans))):
            co = chans[lvl]
            blk = {"res": [], "attn": []}
            for j in range(cfg.layers_per_block + 1):
                cin = c + skip_chans.pop()
                blk["res"].append(resnet_block_params(next(ks), cin, co,
                                                      temb_dim=td))
                if lvl in cfg.attn_levels:
                    blk["attn"].append(self._attn_pair(next(ks), co))
                c = co
            if lvl > 0:
                blk["up"] = _conv_p(next(ks), 3, 3, co, co)
            p["up"].append(blk)
        p["norm_out"] = _gn_p(c)
        p["conv_out"] = _conv_p(next(ks), 3, 3, c, cfg.out_channels)
        return p

    # --------------------------------------------------------------- forward
    def _transformer(self, tp: Params, h: jnp.ndarray,
                     context: jnp.ndarray) -> jnp.ndarray:
        heads = self.config.num_heads
        h = spatial_attention(tp["self"], h, heads=heads)
        h = spatial_attention(tp["cross"], h, context=context, heads=heads)
        b, hh, ww, c = h.shape
        y = group_norm(h, **tp["ff_norm"]).reshape(b, hh * ww, c)
        y = _lin(tp["ff2"], jax.nn.gelu(_lin(tp["ff1"], y)))
        return h + y.reshape(b, hh, ww, c)

    def apply(self, params: Params, sample: jnp.ndarray,
              timesteps: jnp.ndarray,
              encoder_hidden_states: jnp.ndarray) -> jnp.ndarray:
        """``sample`` [B,H,W,Cin], ``timesteps`` [B], context [B,S,ctx] →
        predicted noise [B,H,W,Cout]."""
        cfg = self.config
        temb = timestep_embedding(timesteps, cfg.base_channels)
        temb = _lin(params["time_mlp"]["fc2"],
                    jax.nn.silu(_lin(params["time_mlp"]["fc1"], temb)))
        h = conv2d(sample, params["conv_in"]["w"], params["conv_in"]["b"])
        skips = [h]
        for blk in params["down"]:
            for j, r in enumerate(blk["res"]):
                h = resnet_block(r, h, temb)
                if blk["attn"]:
                    h = self._transformer(blk["attn"][j], h,
                                          encoder_hidden_states)
                skips.append(h)
            if "down" in blk:
                h = conv2d(h, blk["down"]["w"], blk["down"]["b"], stride=2)
                skips.append(h)
        m = params["mid"]
        h = resnet_block(m["res1"], h, temb)
        h = self._transformer(m["attn"], h, encoder_hidden_states)
        h = resnet_block(m["res2"], h, temb)
        for blk in params["up"]:
            for j, r in enumerate(blk["res"]):
                h = jnp.concatenate([h, skips.pop()], axis=-1)
                h = resnet_block(r, h, temb)
                if blk["attn"]:
                    h = self._transformer(blk["attn"][j], h,
                                          encoder_hidden_states)
            if "up" in blk:
                b, hh, ww, c = h.shape
                h = jax.image.resize(h, (b, hh * 2, ww * 2, c), "nearest")
                h = conv2d(h, blk["up"]["w"], blk["up"]["b"])
        h = jax.nn.silu(group_norm(h, **params["norm_out"]))
        return conv2d(h, params["conv_out"]["w"], params["conv_out"]["b"])

    # ------------------------------------------------------------------ loss
    def loss(self, params: Params, batch: Dict[str, jnp.ndarray],
             rng: Optional[jax.Array] = None, train: bool = True):
        """DDPM epsilon-prediction: noise latents at a random timestep,
        predict the noise (the SD training objective)."""
        x = batch["latents"]
        ctx = batch["encoder_hidden_states"]
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        kt, kn = jax.random.split(rng)
        b = x.shape[0]
        t = jax.random.randint(kt, (b,), 0, 1000)
        # cosine-ish ᾱ schedule, enough for the training objective
        abar = jnp.cos((t.astype(jnp.float32) / 1000.0 + 0.008) / 1.008
                       * jnp.pi / 2) ** 2
        noise = jax.random.normal(kn, x.shape, x.dtype)
        srt = jnp.sqrt(abar)[:, None, None, None]
        srt1 = jnp.sqrt(1.0 - abar)[:, None, None, None]
        noisy = srt * x + srt1 * noise
        pred = self.apply(params, noisy, t, ctx)
        loss = jnp.mean((pred - noise) ** 2)
        return loss, {"eps_mse": loss}

    def sharding_rules(self, path, shape):
        names = [getattr(k, "key", getattr(k, "name", str(k)))
                 for k in path]
        s = "/".join(str(n) for n in names)
        # the big matmuls (attention projections, FFN) shard over model
        if s.endswith(("q/w", "k/w", "v/w", "ff1/w")):
            return (None, "model")
        if s.endswith(("o/w", "ff2/w")):
            return ("model", None)
        return None
