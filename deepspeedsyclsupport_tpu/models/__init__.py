"""Built-in model families (framework-owned; see transformer.py docstring for how
this replaces the reference's module_inject/model_implementations machinery)."""
from .config import ModelConfig, PRESETS, get_config  # noqa: F401
from .diffusion import (AutoencoderKL, UNet2DCondition,  # noqa: F401
                        UNetConfig, VAEConfig)
from .encoder import (BertModel, CLIPConfig, CLIPModel,  # noqa: F401
                      EncoderConfig)
from .transformer import CausalLM, KVCache, build_model  # noqa: F401
