"""Transformer building blocks, written TPU-first.

Functional (params-in, activations-out) equivalents of the reference's fused modules
(``deepspeed/ops/transformer/inference/ds_attention.py``, ``ds_mlp.py``,
``csrc/transformer/*``): on TPU the elementwise/norm fusion those CUDA kernels provide
comes from XLA, so these are plain jnp compositions; the genuinely kernel-worthy op
(attention over long sequences) dispatches through :func:`attention` to a Pallas flash
kernel when on TPU (``ops/flash_attention.py``) and to an exact jnp reference elsewhere.

Sharding: activations are annotated with logical axes via :func:`constrain` so the
SPMD partitioner keeps batch over (data, fsdp), sequence over seq, and heads/ffn over
model — the activation-layout contract TP/SP rest on.
"""
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

Params = Dict[str, Any]


# --------------------------------------------------------------------------- sharding
def constrain(x: jnp.ndarray, *spec) -> jnp.ndarray:
    """Best-effort ``with_sharding_constraint`` against the world topology.

    No-op when no topology is installed (pure single-device use) or when the spec
    doesn't apply (axis missing from the mesh). Model code stays mesh-agnostic.
    """
    from ..comm import topology as topo_mod

    topo = topo_mod._WORLD_TOPOLOGY
    if topo is None:
        return x
    # inside a shard_map manual region (ZeRO++ explicit step, pipeline ring)
    # a constraint naming manual axes is rejected at lowering — and the data
    # is already placed per-shard there, so the constraint is meaningless.
    # get_abstract_mesh is a modern spelling (shimmed by utils/jax_compat);
    # without it — old jax, shims off — there is no manual-region tracking
    # to consult, so fall through to the constraint attempt.
    _gam = getattr(jax.sharding, "get_abstract_mesh", None)
    manual = set(getattr(_gam(), "manual_axes", ()) or ()) if _gam else set()
    if manual:
        used = {a for s in spec
                for a in (s if isinstance(s, (tuple, list)) else (s,)) if a}
        if used & manual:
            return x
    try:
        return jax.lax.with_sharding_constraint(x, topo.sharding(*spec))
    except (ValueError, TypeError):
        return x


BATCH = ("data", "fsdp")  # input batch dim is split over both DP-ish axes


# --------------------------------------------------------------------------- norm
def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float) -> jnp.ndarray:
    """RMSNorm (reference kernel: ``csrc/transformer/inference/csrc/rms_norm.cu``;
    XLA fuses the reduction+rescale chain on TPU)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(dtype)


def layer_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
               eps: float) -> jnp.ndarray:
    """LayerNorm with learned bias (reference ``csrc/transformer/inference/csrc/
    layer_norm.cu``) — the GPT-2/OPT/BLOOM/Falcon-era norm."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def norm(x: jnp.ndarray, p: Params, cfg: ModelConfig) -> jnp.ndarray:
    """Norm dispatch on ``cfg.norm_type`` over a ``{"scale"[, "bias"]}`` leaf dict."""
    if cfg.norm_type == "layernorm":
        return layer_norm(x, p["scale"], p["bias"], cfg.rms_norm_eps)
    return rms_norm(x, p["scale"], cfg.rms_norm_eps)


# --------------------------------------------------------------------------- rope
def rope_frequencies(head_dim: int, theta: float) -> np.ndarray:
    return 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float,
               rotary_dim: Optional[int] = None) -> jnp.ndarray:
    """Rotary embedding (reference kernel: ``csrc/transformer/inference/csrc/
    apply_rotary_pos_emb.cu``). x: [B, S, H, D]; positions: [B, S] or [S].
    ``rotary_dim < D`` rotates only the leading dims (GPT-NeoX/GPT-J/Phi
    partial rotary; ingestion converts interleaved layouts to this split-half
    convention by permuting q/k weight columns)."""
    head_dim = x.shape[-1]
    rd = head_dim if rotary_dim is None else rotary_dim
    x_rot, x_pass = (x, None) if rd == head_dim else (x[..., :rd], x[..., rd:])
    freqs = jnp.asarray(rope_frequencies(rd, theta))
    if positions.ndim == 1:
        positions = positions[None, :]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, rd/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, S, 1, rd/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x_rot.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    out = out.astype(x.dtype)
    return out if x_pass is None else jnp.concatenate([out, x_pass], axis=-1)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (reference builds these in
    ``module_inject/containers/bloom.py``-served models via HF; standard
    geometric schedule from the ALiBi paper, non-power-of-2 interpolation)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return start * (start ** np.arange(n))

    n = 2 ** int(np.floor(np.log2(num_heads)))
    slopes = pow2_slopes(n)
    if n < num_heads:
        extra = pow2_slopes(2 * n)[0::2][: num_heads - n]
        slopes = np.concatenate([slopes, extra])
    return slopes.astype(np.float32)


# --------------------------------------------------------------------------- attention
def reference_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        causal: bool = True,
                        segment_ids: Optional[jnp.ndarray] = None,
                        kv_positions_below: Optional[jnp.ndarray] = None,
                        kv_mask: Optional[jnp.ndarray] = None,
                        alibi: Optional[jnp.ndarray] = None,
                        window: Optional[int] = None,
                        q_positions: Optional[jnp.ndarray] = None,
                        kv_positions: Optional[jnp.ndarray] = None
                        ) -> jnp.ndarray:
    """Exact softmax attention in jnp — the parity reference for the Pallas kernels
    (the role torch plays for the reference's kernel tests, SURVEY.md §4).

    q: [B, Sq, H, D], k/v: [B, Skv, KVH, D]. GQA handled by head repetition.
    ``kv_positions_below``: decode-mode masking — attend only to kv slots < this
    per-query position (used with a prefilled KV cache where Sq << Skv).
    ``kv_mask``: [B, Skv] explicit slot-validity mask, ANDed in — needed when
    cache slot index ≠ token position (right-padded ragged batches, where pad
    slots sit between each prompt's end and the shared decode region).
    ``alibi``: per-head slopes [H] — adds ``slope·(k_pos − q_pos)`` to logits
    (BLOOM-family positional scheme). ``window``: sliding-window local
    attention — queries see only the last ``window`` positions (Mistral).
    """
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    skv = k.shape[1]
    explicit_pos = q_positions is not None and kv_positions is not None
    if explicit_pos:
        # true logical positions (ragged decode: slot index ≠ position)
        q_pos = q_positions.astype(jnp.int32)[:, None, :, None]
        k_pos = kv_positions.astype(jnp.int32)[:, None, None, :]
    elif kv_positions_below is not None:
        q_pos = (kv_positions_below - 1).astype(jnp.int32)[:, None, :, None]
        k_pos = jnp.broadcast_to(jnp.arange(skv, dtype=jnp.int32)[None, None,
                                                                  None, :],
                                 (b, 1, sq, skv))
    else:
        q_pos = (jnp.arange(sq, dtype=jnp.int32)
                 + (skv - sq))[None, None, :, None]
        k_pos = jnp.arange(skv, dtype=jnp.int32)[None, None, None, :]
    if alibi is not None:
        logits = logits + alibi.astype(jnp.float32)[None, :, None, None] * (
            k_pos - q_pos).astype(jnp.float32)
    mask = None
    if explicit_pos:
        if causal:
            mask = k_pos <= q_pos  # position-space causality
    elif kv_positions_below is not None:
        kv_idx = jnp.arange(skv)[None, None, :]
        mask = kv_idx < kv_positions_below[:, :, None]  # [B, Sq, Skv]
        mask = mask[:, None, :, :]
    elif causal:
        qi = jnp.arange(sq)[:, None]
        ki = jnp.arange(skv)[None, :]
        mask = (ki <= qi + (skv - sq))[None, None, :, :]
    if window is not None:
        wmask = (q_pos - k_pos) < window
        mask = wmask if mask is None else jnp.logical_and(mask, wmask)
    if segment_ids is not None:
        seg = (segment_ids[:, None, :, None] == segment_ids[:, None, None, :]) \
            if segment_ids.shape[1] == sq and sq == skv else None
        if seg is not None:
            mask = seg if mask is None else jnp.logical_and(mask, seg)
    if kv_mask is not None:
        m = kv_mask[:, None, None, :]  # [B, 1, 1, Skv]
        mask = m if mask is None else jnp.logical_and(mask, m)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _cached_flash_attention(q, k, v, causal, kv_positions_below, kv_mask,
                            alibi=None, window=None, q_positions=None,
                            kv_positions=None, interpret=None):
    """KV-cache attention through the flash kernel (the v1 engine's prefill
    and decode steps). Slot-space masks map onto the kernel's ragged mode:
    ``kv_positions_below`` becomes explicit q positions (query i sees slots
    < below[i] ⇔ slot index <= below[i]-1; kv positions default to slot
    indices), and ``kv_mask`` becomes a kv segment id (-1 = invalid slot,
    matching no query). ``segment_ids`` are deliberately NOT consumed here,
    matching :func:`reference_attention`, which ignores them whenever
    Sq != Skv (the cached case)."""
    from ..ops.flash_attention import flash_attention

    b, sq = q.shape[:2]
    skv = k.shape[1]
    use_causal = causal
    if q_positions is not None and kv_positions is not None:
        # true logical positions (ragged: slot ≠ position) — position-space
        # causality, and alibi/window distances come out right
        q_pos, kv_pos = (q_positions.astype(jnp.int32),
                         kv_positions.astype(jnp.int32))
        use_causal = True
    elif kv_positions_below is not None:
        q_pos = kv_positions_below.astype(jnp.int32) - 1     # [B, Sq]
        kv_pos = None
        use_causal = True
    else:
        q_pos = kv_pos = None
    seg_q = seg_k = None
    if kv_mask is not None:
        seg_q = jnp.zeros((b, sq), jnp.int32)
        seg_k = jnp.where(kv_mask, 0, -1).astype(jnp.int32)
    return flash_attention(q, k, v, causal=use_causal,
                           segment_ids=seg_q, kv_segment_ids=seg_k,
                           q_positions=q_pos, kv_positions=kv_pos,
                           alibi=alibi, window=window,
                           interpret=interpret)


def attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              impl: str = "auto",
              causal: bool = True,
              segment_ids: Optional[jnp.ndarray] = None,
              kv_positions_below: Optional[jnp.ndarray] = None,
              kv_mask: Optional[jnp.ndarray] = None,
              alibi: Optional[jnp.ndarray] = None,
              window: Optional[int] = None,
              q_positions: Optional[jnp.ndarray] = None,
              kv_positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Attention dispatch — the seam where Pallas/SP implementations plug in
    (reference analog: the op-binding indirection of
    ``ops/transformer/inference/op_binding/``).

    Sequence-parallel impls take an inner (per-shard) implementation after
    a colon — ``"ring:flash"`` / ``"ring:xla"`` / ``"ulysses:flash"`` /
    ``"ulysses:xla"`` — the ``attn_impl`` spelling the bench's ring A/B
    arms use; bare ``"ring"``/``"ulysses"`` auto-select (flash on TPU).
    """
    inner = None
    if impl and ":" in impl:
        impl, inner = impl.split(":", 1)
        if impl not in ("ring", "ulysses"):
            raise ValueError(
                f"attn_impl {impl + ':' + inner!r}: only the "
                f"sequence-parallel impls take an inner "
                f"('ring:...'/'ulysses:...')")
        if inner not in ("flash", "xla"):
            # a typo'd inner silently falling back would make an A/B
            # compare an arm against itself and report a bogus no-diff
            raise ValueError(f"unknown inner attention impl {inner!r} "
                             f"(flash | xla)")
    if (window is not None and not causal
            and kv_positions_below is None and kv_positions is None):
        # the window bound is one-sided (how far BACK a query sees) on every
        # backend; with no other causality mechanism in play (cached decode
        # supplies kv_positions_below/kv_positions instead of the flag),
        # rejecting here keeps flash and xla behavior identical instead of
        # raising on one platform and silently attending to unbounded
        # future keys on the other
        raise ValueError("window requires causal=True (the sliding window "
                         "only bounds attention to the past)")
    if impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "xla"
    if (kv_positions_below is not None or kv_mask is not None
            or kv_positions is not None):
        # cached-decode masking (slot validity + slot- or position-space
        # causality). The flash kernel handles it via explicit position
        # arrays + kv segment ids; ring/ulysses are training patterns and
        # fall back to xla.
        if impl == "flash":
            return _cached_flash_attention(q, k, v, causal,
                                           kv_positions_below, kv_mask,
                                           alibi=alibi, window=window,
                                           q_positions=q_positions,
                                           kv_positions=kv_positions)
        impl = "xla"
    if impl == "flash":
        from ..ops.flash_attention import flash_attention

        try:
            return flash_attention(q, k, v, causal=causal,
                                   segment_ids=segment_ids, alibi=alibi,
                                   window=window)
        except NotImplementedError:
            impl = "xla"
    if impl in ("ring", "ulysses") and (alibi is not None
                                        or window is not None):
        # silently materializing O(S²) logits would defeat the point of SP
        raise NotImplementedError(
            f"attn_impl={impl!r} does not support alibi/sliding-window yet; "
            f"use attn_impl='flash' or 'xla'")
    if impl == "ring":
        from ..parallel.ring_attention import ring_attention

        return ring_attention(q, k, v, causal=causal, inner=inner)
    if impl == "ulysses":
        from ..parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, causal=causal,
                                 segment_ids=segment_ids, inner=inner)
    return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                               kv_positions_below=kv_positions_below,
                               kv_mask=kv_mask, alibi=alibi, window=window,
                               q_positions=q_positions,
                               kv_positions=kv_positions)


# --------------------------------------------------------------------------- blocks
def _kv_memory_shardings():
    """(host, device) shardings for a per-layer cache slice [B, len, KVH,
    hd] under the world topology — TP keeps kv heads on the model axis in
    BOTH memory spaces."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..comm.topology import get_world_topology

    topo = get_world_topology()
    spec = P(None, None, "model", None)
    return (NamedSharding(topo.mesh, spec, memory_kind="pinned_host"),
            NamedSharding(topo.mesh, spec, memory_kind="device"))


_WINDOW_FROM_CFG = object()  # sentinel: "use cfg.sliding_window"


def attention_block(p: Params, x: jnp.ndarray, cfg: ModelConfig,
                    positions: jnp.ndarray,
                    segment_ids: Optional[jnp.ndarray] = None,
                    kv_cache: Optional[Tuple] = None,
                    impl: Optional[str] = None,
                    kv_mask: Optional[jnp.ndarray] = None,
                    kv_positions: Optional[jnp.ndarray] = None,
                    window_override=_WINDOW_FROM_CFG):
    """Self-attention sublayer: qkv proj → RoPE → attention → out proj.

    With ``kv_cache=(k_cache, v_cache, write_pos)`` runs in decode mode: appends
    current k/v at ``write_pos`` and attends over the cache (the role of the
    reference's ``linear_blocked_kv_rotary`` + ``blocked_flash`` kernels,
    ``inference/v2/kernels/ragged_ops/``). Returns (out, new_kv_cache).

    The whole sublayer traces under the ``attn`` MFU region scope
    (``monitor/mfu.py``): XLA stamps the label into every lowered op's
    metadata (backward included — the transpose wrapper preserves it), so
    the step-time attribution ledger can name attention's share of a
    measured step.
    """
    from ..monitor.mfu import region_scope

    with region_scope("attn"):
        return _attention_block_impl(p, x, cfg, positions, segment_ids,
                                     kv_cache, impl, kv_mask, kv_positions,
                                     window_override)


def _attention_block_impl(p, x, cfg, positions, segment_ids, kv_cache, impl,
                          kv_mask, kv_positions, window_override):
    b, s, d = x.shape
    q = jnp.einsum("bsd,dq->bsq", x, p["wq"])
    k = jnp.einsum("bsd,dk->bsk", x, p["wk"])
    v = jnp.einsum("bsd,dk->bsk", x, p["wv"])
    if cfg.qkv_bias:
        q = q + p["bq"].astype(q.dtype)
        k = k + p["bk"].astype(k.dtype)
        v = v + p["bv"].astype(v.dtype)
    q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    q = constrain(q, BATCH, "seq", "model", None)
    k = constrain(k, BATCH, "seq", "model", None)
    if cfg.pos_embed == "rope":
        q = apply_rope(q, positions, cfg.rope_theta, cfg.rotary_dim)
        k = apply_rope(k, positions, cfg.rope_theta, cfg.rotary_dim)
    alibi = (jnp.asarray(alibi_slopes(cfg.num_heads) * cfg.alibi_scale)
             if cfg.pos_embed == "alibi" else None)
    window = (cfg.sliding_window if window_override is _WINDOW_FROM_CFG
              else window_override)
    if cfg.attn_scale is not None:
        # non-standard logit scale (GPT-Neo uses 1.0, not 1/√d): fold the
        # correction into q so every attention backend (flash kernel, xla
        # oracle, ring/ulysses) inherits it without a kernel knob
        q = q * jnp.asarray(cfg.attn_scale * np.sqrt(cfg.head_dim),
                            q.dtype)

    new_cache = None
    if kv_cache is not None:
        k_cache, v_cache, write_pos = kv_cache
        # ZeRO-Inference KV offload: a host-resident cache (detected from
        # the traced memory space) is updated IN host space — the new
        # token's k/v hop to host, the single-token write stays there —
        # and the full per-layer slice streams to device for attention.
        # HBM holds one layer's cache at a time instead of all of them.
        cache_space = getattr(k_cache.aval, "memory_space", None)
        offloaded = (cache_space is not None
                     and cache_space != getattr(k.aval, "memory_space",
                                                cache_space))
        if offloaded:
            host_s, dev_s = _kv_memory_shardings()
            k = jax.device_put(k, host_s)
            v = jax.device_put(v, host_s)
        k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k, write_pos, axis=1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v, write_pos, axis=1)
        new_cache = (k_cache, v_cache, write_pos + s)
        if offloaded:
            k_cache = jax.device_put(k_cache, dev_s)
            v_cache = jax.device_put(v_cache, dev_s)
        if kv_positions is not None:
            # ragged with true per-slot positions supplied (engine knows
            # slot→position): position-space causality, and alibi/window
            # distances are computed on logical positions, not cache slots
            out = attention(q, k_cache, v_cache, impl=impl or cfg.attn_impl,
                            causal=True, kv_mask=kv_mask, alibi=alibi,
                            window=window, q_positions=positions,
                            kv_positions=kv_positions)
        else:
            if kv_mask is not None:
                # ragged right-padded batches without per-slot positions:
                # causality must be slot-space — query i of this chunk
                # (written at write_pos+i) sees slots <= write_pos+i;
                # kv_mask supplies validity of the rest
                kv_below = write_pos + jnp.arange(s)[None, :] + 1
                if cfg.pos_embed == "alibi" or window is not None:
                    # the EFFECTIVE window (cfg.sliding_window or the
                    # per-layer override) — slot-space distances would be
                    # silently wrong either way
                    raise ValueError(
                        "alibi/sliding-window ragged decode needs kv_positions"
                        " (slot index ≠ logical position would skew distances)")
            else:
                kv_below = positions + 1  # slot == position: own pos or before
            out = attention(q, k_cache, v_cache, impl=impl or cfg.attn_impl,
                            causal=False, kv_positions_below=kv_below,
                            kv_mask=kv_mask, alibi=alibi, window=window)
    else:
        out = attention(q, k, v, impl=impl or cfg.attn_impl, causal=True,
                        segment_ids=segment_ids, alibi=alibi, window=window)
    out = out.reshape(b, s, cfg.q_dim)
    out = jnp.einsum("bsq,qd->bsd", out, p["wo"])
    if cfg.attn_out_bias:
        out = out + p["bo"].astype(out.dtype)
    return constrain(out, BATCH, "seq", None), new_cache


def _activation(name: str):
    return {"silu": jax.nn.silu, "gelu": jax.nn.gelu,
            "gelu_exact": partial(jax.nn.gelu, approximate=False),
            "relu": jax.nn.relu}[name]


def glu_mlp(p: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Gated-linear-unit MLP (SwiGLU/GeGLU). Reference fuses bias+activation in
    ``csrc/transformer/inference/csrc/gelu.cu`` / v2 ``gated_activations``; XLA
    fuses the same chain into the matmul epilogue on TPU."""
    act = _activation(cfg.activation)
    gate = jnp.einsum("bsd,df->bsf", x, p["w_gate"])
    up = jnp.einsum("bsd,df->bsf", x, p["w_up"])
    h = act(gate) * up
    h = constrain(h, BATCH, "seq", "model")
    return jnp.einsum("bsf,fd->bsd", h, p["w_down"])


def std_mlp(p: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Two-matrix MLP (fc1 → act → fc2), the GPT-2/OPT/BLOOM/Falcon/Phi shape
    (reference fused path: ``csrc/transformer/inference/csrc/gelu.cu``
    fused_bias_gelu)."""
    act = _activation(cfg.activation)
    h = jnp.einsum("bsd,df->bsf", x, p["fc1"])
    if cfg.use_bias:
        h = h + p["b1"].astype(h.dtype)
    h = act(h)
    h = constrain(h, BATCH, "seq", "model")
    out = jnp.einsum("bsf,fd->bsd", h, p["fc2"])
    if cfg.use_bias:
        out = out + p["b2"].astype(out.dtype)
    return out


def mlp_block(p: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    from ..monitor.mfu import region_scope

    with region_scope("mlp"):  # MFU-region label (see attention_block)
        return (std_mlp(p, x, cfg) if cfg.mlp_type == "mlp"
                else glu_mlp(p, x, cfg))
