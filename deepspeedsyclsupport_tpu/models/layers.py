"""Transformer building blocks, written TPU-first.

Functional (params-in, activations-out) equivalents of the reference's fused modules
(``deepspeed/ops/transformer/inference/ds_attention.py``, ``ds_mlp.py``,
``csrc/transformer/*``): on TPU the elementwise/norm fusion those CUDA kernels provide
comes from XLA, so these are plain jnp compositions; the genuinely kernel-worthy op
(attention over long sequences) dispatches through :func:`attention` to a Pallas flash
kernel when on TPU (``ops/flash_attention.py``) and to an exact jnp reference elsewhere.

Sharding: activations are annotated with logical axes via :func:`constrain` so the
SPMD partitioner keeps batch over (data, fsdp), sequence over seq, and heads/ffn over
model — the activation-layout contract TP/SP rest on.
"""
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

Params = Dict[str, Any]


# --------------------------------------------------------------------------- sharding
def constrain(x: jnp.ndarray, *spec) -> jnp.ndarray:
    """Best-effort ``with_sharding_constraint`` against the world topology.

    No-op when no topology is installed (pure single-device use) or when the spec
    doesn't apply (axis missing from the mesh). Model code stays mesh-agnostic.
    """
    from ..comm import topology as topo_mod

    topo = topo_mod._WORLD_TOPOLOGY
    if topo is None:
        return x
    try:
        return jax.lax.with_sharding_constraint(x, topo.sharding(*spec))
    except (ValueError, TypeError):
        return x


BATCH = ("data", "fsdp")  # input batch dim is split over both DP-ish axes


# --------------------------------------------------------------------------- norm
def rms_norm(x: jnp.ndarray, scale: jnp.ndarray, eps: float) -> jnp.ndarray:
    """RMSNorm (reference kernel: ``csrc/transformer/inference/csrc/rms_norm.cu``;
    XLA fuses the reduction+rescale chain on TPU)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(dtype)


# --------------------------------------------------------------------------- rope
def rope_frequencies(head_dim: int, theta: float) -> np.ndarray:
    return 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary embedding (reference kernel: ``csrc/transformer/inference/csrc/
    apply_rotary_pos_emb.cu``). x: [B, S, H, D]; positions: [B, S] or [S]."""
    head_dim = x.shape[-1]
    freqs = jnp.asarray(rope_frequencies(head_dim, theta))
    if positions.ndim == 1:
        positions = positions[None, :]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, S, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


# --------------------------------------------------------------------------- attention
def reference_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        causal: bool = True,
                        segment_ids: Optional[jnp.ndarray] = None,
                        kv_positions_below: Optional[jnp.ndarray] = None,
                        kv_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Exact softmax attention in jnp — the parity reference for the Pallas kernels
    (the role torch plays for the reference's kernel tests, SURVEY.md §4).

    q: [B, Sq, H, D], k/v: [B, Skv, KVH, D]. GQA handled by head repetition.
    ``kv_positions_below``: decode-mode masking — attend only to kv slots < this
    per-query position (used with a prefilled KV cache where Sq << Skv).
    ``kv_mask``: [B, Skv] explicit slot-validity mask, ANDed in — needed when
    cache slot index ≠ token position (right-padded ragged batches, where pad
    slots sit between each prompt's end and the shared decode region).
    """
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    skv = k.shape[1]
    mask = None
    if kv_positions_below is not None:
        kv_idx = jnp.arange(skv)[None, None, :]
        mask = kv_idx < kv_positions_below[:, :, None]  # [B, Sq, Skv]
        mask = mask[:, None, :, :]
    elif causal:
        qi = jnp.arange(sq)[:, None]
        ki = jnp.arange(skv)[None, :]
        mask = (ki <= qi + (skv - sq))[None, None, :, :]
    if segment_ids is not None:
        seg = (segment_ids[:, None, :, None] == segment_ids[:, None, None, :]) \
            if segment_ids.shape[1] == sq and sq == skv else None
        if seg is not None:
            mask = seg if mask is None else jnp.logical_and(mask, seg)
    if kv_mask is not None:
        m = kv_mask[:, None, None, :]  # [B, 1, 1, Skv]
        mask = m if mask is None else jnp.logical_and(mask, m)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _cached_flash_attention(q, k, v, causal, kv_positions_below, kv_mask,
                            interpret=None):
    """KV-cache attention through the flash kernel (the v1 engine's prefill
    and decode steps). Slot-space masks map onto the kernel's ragged mode:
    ``kv_positions_below`` becomes explicit q positions (query i sees slots
    < below[i] ⇔ slot index <= below[i]-1; kv positions default to slot
    indices), and ``kv_mask`` becomes a kv segment id (-1 = invalid slot,
    matching no query). ``segment_ids`` are deliberately NOT consumed here,
    matching :func:`reference_attention`, which ignores them whenever
    Sq != Skv (the cached case)."""
    from ..ops.flash_attention import flash_attention

    b, sq = q.shape[:2]
    skv = k.shape[1]
    q_pos = None
    use_causal = causal
    if kv_positions_below is not None:
        q_pos = kv_positions_below.astype(jnp.int32) - 1     # [B, Sq]
        use_causal = True
    seg_q = seg_k = None
    if kv_mask is not None:
        seg_q = jnp.zeros((b, sq), jnp.int32)
        seg_k = jnp.where(kv_mask, 0, -1).astype(jnp.int32)
    return flash_attention(q, k, v, causal=use_causal,
                           segment_ids=seg_q, kv_segment_ids=seg_k,
                           q_positions=q_pos, interpret=interpret)


def attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              impl: str = "auto",
              causal: bool = True,
              segment_ids: Optional[jnp.ndarray] = None,
              kv_positions_below: Optional[jnp.ndarray] = None,
              kv_mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Attention dispatch — the seam where Pallas/SP implementations plug in
    (reference analog: the op-binding indirection of
    ``ops/transformer/inference/op_binding/``)."""
    if impl == "auto":
        impl = "flash" if jax.default_backend() == "tpu" else "xla"
    if kv_positions_below is not None or kv_mask is not None:
        # cached-decode masking (slot-space causality + slot validity). The
        # flash kernel handles it via explicit position arrays + kv segment
        # ids; ring/ulysses are training patterns and fall back to xla.
        if impl == "flash":
            return _cached_flash_attention(q, k, v, causal,
                                           kv_positions_below, kv_mask)
        impl = "xla"
    if impl == "flash":
        from ..ops.flash_attention import flash_attention

        try:
            return flash_attention(q, k, v, causal=causal, segment_ids=segment_ids)
        except NotImplementedError:
            impl = "xla"
    if impl == "ring":
        from ..parallel.ring_attention import ring_attention

        return ring_attention(q, k, v, causal=causal)
    if impl == "ulysses":
        from ..parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, causal=causal,
                                 segment_ids=segment_ids)
    return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                               kv_positions_below=kv_positions_below,
                               kv_mask=kv_mask)


# --------------------------------------------------------------------------- blocks
def attention_block(p: Params, x: jnp.ndarray, cfg: ModelConfig,
                    positions: jnp.ndarray,
                    segment_ids: Optional[jnp.ndarray] = None,
                    kv_cache: Optional[Tuple] = None,
                    impl: Optional[str] = None,
                    kv_mask: Optional[jnp.ndarray] = None):
    """Self-attention sublayer: qkv proj → RoPE → attention → out proj.

    With ``kv_cache=(k_cache, v_cache, write_pos)`` runs in decode mode: appends
    current k/v at ``write_pos`` and attends over the cache (the role of the
    reference's ``linear_blocked_kv_rotary`` + ``blocked_flash`` kernels,
    ``inference/v2/kernels/ragged_ops/``). Returns (out, new_kv_cache).
    """
    b, s, d = x.shape
    q = jnp.einsum("bsd,dq->bsq", x, p["wq"]).reshape(
        b, s, cfg.num_heads, cfg.head_dim)
    k = jnp.einsum("bsd,dk->bsk", x, p["wk"]).reshape(
        b, s, cfg.num_kv_heads, cfg.head_dim)
    v = jnp.einsum("bsd,dk->bsk", x, p["wv"]).reshape(
        b, s, cfg.num_kv_heads, cfg.head_dim)
    q = constrain(q, BATCH, "seq", "model", None)
    k = constrain(k, BATCH, "seq", "model", None)
    q = apply_rope(q, positions, cfg.rope_theta)
    k = apply_rope(k, positions, cfg.rope_theta)

    new_cache = None
    if kv_cache is not None:
        k_cache, v_cache, write_pos = kv_cache
        k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k, write_pos, axis=1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v, write_pos, axis=1)
        new_cache = (k_cache, v_cache, write_pos + s)
        if kv_mask is not None:
            # ragged right-padded batches: slot != position, so causality must
            # be slot-space — query i of this chunk (written at write_pos+i)
            # sees slots <= write_pos+i; kv_mask supplies validity of the rest
            kv_below = write_pos + jnp.arange(s)[None, :] + 1
        else:
            kv_below = positions + 1  # slot == position: at-or-before own pos
        out = attention(q, k_cache, v_cache, impl=impl or cfg.attn_impl,
                        causal=False, kv_positions_below=kv_below,
                        kv_mask=kv_mask)
    else:
        out = attention(q, k, v, impl=impl or cfg.attn_impl, causal=True,
                        segment_ids=segment_ids)
    out = out.reshape(b, s, cfg.q_dim)
    out = jnp.einsum("bsq,qd->bsd", out, p["wo"])
    return constrain(out, BATCH, "seq", None), new_cache


def glu_mlp(p: Params, x: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """Gated-linear-unit MLP (SwiGLU/GeGLU). Reference fuses bias+activation in
    ``csrc/transformer/inference/csrc/gelu.cu`` / v2 ``gated_activations``; XLA
    fuses the same chain into the matmul epilogue on TPU."""
    act = jax.nn.silu if cfg.activation == "silu" else jax.nn.gelu
    gate = jnp.einsum("bsd,df->bsf", x, p["w_gate"])
    up = jnp.einsum("bsd,df->bsf", x, p["w_up"])
    h = act(gate) * up
    h = constrain(h, BATCH, "seq", "model")
    return jnp.einsum("bsf,fd->bsd", h, p["w_down"])
