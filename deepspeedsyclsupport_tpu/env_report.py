"""Environment report — the ``ds_report`` CLI analog (reference
``deepspeed/env_report.py``: torch/cuda/nccl versions + op build status table).

Run: ``python -m deepspeedsyclsupport_tpu.env_report``.
"""
import sys


def get_report_lines():
    import jax
    import jaxlib

    from .accelerator import get_accelerator
    from .ops.op_builder import ALL_OPS
    from .version import __version__

    lines = ["-" * 62,
             "deepspeedsyclsupport_tpu environment report (ds_report analog)",
             "-" * 62]
    lines.append(f"dstpu version ........ {__version__}")
    lines.append(f"jax version .......... {jax.__version__}")
    lines.append(f"jaxlib version ....... {jaxlib.__version__}")
    lines.append(f"python ............... {sys.version.split()[0]}")
    acc = get_accelerator()
    lines.append(f"accelerator .......... {acc.name()}")
    try:
        devs = acc.devices()
        lines.append(f"devices .............. {len(devs)} × "
                     f"{getattr(devs[0], 'device_kind', devs[0].platform)}")
    except Exception as e:  # device probe can fail off-hardware
        lines.append(f"devices .............. unavailable ({e})")
    try:
        import flax

        lines.append(f"flax version ......... {flax.__version__}")
    except ImportError:
        pass
    try:
        import optax

        lines.append(f"optax version ........ {optax.__version__}")
    except ImportError:
        pass
    lines.append("-" * 62)
    lines.append("native ops (op_builder):")
    for name, builder in ALL_OPS.items():
        import os

        so = builder.so_path()  # None when sources are unreadable
        compatible = so is not None and builder.is_compatible()
        built = compatible and os.path.exists(so)
        lines.append(f"  {name:<12} compatible: {str(compatible):<5} "
                     f"built: {built}")
    lines.append("-" * 62)
    return lines


def main():
    print("\n".join(get_report_lines()))


if __name__ == "__main__":  # pragma: no cover
    main()
