from .ds_import import (DeepSpeedCheckpoint,  # noqa: F401
                        load_deepspeed_checkpoint)
from .engine import load_tree, save_tree  # noqa: F401
from .hf import (HFCheckpointSource, config_from_hf,  # noqa: F401
                 load_hf_checkpoint)
from .universal import DSTpuCheckpoint, load_state_dict  # noqa: F401
from .zero_to_fp32 import (  # noqa: F401
    convert_zero_checkpoint_to_fp32_state_dict,
    get_fp32_state_dict_from_zero_checkpoint)
