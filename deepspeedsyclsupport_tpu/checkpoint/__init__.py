from .engine import load_tree, save_tree
