"""Pluggable checkpoint engines.

Analog of the reference's ``CheckpointEngine`` ABC
(``runtime/checkpoint_engine/checkpoint_engine.py:30``) and its two
implementations — ``TorchCheckpointEngine`` (synchronous) and
``NebulaCheckpointEngine`` (``nebula_checkpoint_engine.py``: Azure Nebula's
async tiered persistence, where ``save`` returns immediately and durability
is reached in the background, with ``commit`` sealing a tag).

TPU-native shape: the synchronous engine wraps the placement-aware
``save_tree``/``load_tree`` writers; the async engine snapshots device
arrays to host **before** returning (the train step donates its buffers, so
background threads must never hold live device references) and streams the
write from a worker thread. Durability protocol: the tree is written into a
``.staging-<tag>`` directory and atomically renamed onto the final tag path
when complete, and the ``latest`` pointer is only updated after the rename —
a crash mid-save can never leave ``latest`` pointing at a torn checkpoint
(Nebula's tier-commit semantic).
"""
import os
import shutil
import threading
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ..utils.fault_injection import get_fault_injector
from ..utils.logging import log_dist, logger

__all__ = ["CheckpointEngine", "NativeCheckpointEngine",
           "AsyncCheckpointEngine", "build_checkpoint_engine",
           "sweep_staging_dirs"]


class CheckpointEngine(ABC):
    """save/load/commit surface (reference ``checkpoint_engine.py:30``)."""

    name = "base"

    @abstractmethod
    def save(self, path: str, state: Any, meta: Dict[str, Any],
             latest_file: Optional[str] = None, tag: str = "",
             post_commit: Optional[Callable[[], None]] = None) -> None:
        """Persist ``state``+``meta`` under ``path``. When ``latest_file`` is
        given, point it at ``tag`` once the checkpoint is durable.
        ``post_commit`` runs after durability is reached (for the async
        engine: on the worker thread) — the rotation hook, which must only
        ever observe the new tag fully on disk."""

    @abstractmethod
    def load(self, path: str, template: Any) -> Tuple[Any, Dict[str, Any]]:
        ...

    def commit(self, tag: str = "") -> bool:
        """Seal a tag: returns True once every pending write for it is
        durable (reference ``nebula_checkpoint_engine.py commit``)."""
        self.wait()
        return True

    def wait(self) -> None:
        """Block until all in-flight saves are durable."""


def _write_latest(latest_file: Optional[str], tag: str) -> None:
    """Atomically repoint ``latest``: temp file + fsync + ``os.replace``.
    An in-place ``write()`` can be torn by a crash, leaving a pointer that
    names no tag — after which every restart fails to resume. Pod rank 0
    only (env-declared pods included — two replicas repointing the same
    file would race)."""
    from ..utils.podid import pod_rank

    if latest_file and pod_rank() == 0:
        from .engine import _durable_write

        _durable_write(latest_file + ".tmp", tag,
                       what=f"latest-pointer update {latest_file}",
                       rename_to=latest_file)


def _run_post_commit(post_commit: Optional[Callable[[], None]]) -> None:
    if post_commit is None:
        return
    try:
        post_commit()
    except Exception as e:  # GC must never fail a durable save
        logger.warning("checkpoint post-commit hook failed: %s", e)


def sweep_staging_dirs(directory: str, keep: Optional[str] = None,
                       deep: bool = True) -> int:
    """Clean up orphaned ``.staging-*`` dirs (a worker killed between
    ``save_tree`` and ``os.replace`` leaves one behind). An orphan that
    verifies complete and whose target tag is absent is *promoted* (the
    interrupted rename is finished) — it can be the only copy of the newest
    checkpoint when the old tag dir was already deleted to make way for it.
    Everything else is removed. Returns the number handled.

    Torn-POD tags are also quarantined here: a preemption that landed
    between the commit protocol's phases (rank manifests written, no pod
    commit record — see ``checkpoint/engine.py::pod_commit``) leaves a tag
    no rank must ever resolve. The sweep runs at resume time, when no save
    can be in flight, so a commit-less tag here is conclusively torn rather
    than merely in progress.

    ``deep=False`` verifies by structure + size only (no crc re-read) — for
    callers on the training thread, where re-streaming a multi-GB orphan
    would stall the step; same-size bit rot in a promoted tag is still
    caught at load time and quarantined."""
    from .engine import (_QUARANTINE_RE, is_torn_pod, quarantine_tag,
                         verify_tree)

    handled = 0
    promoted = 0
    quarantined = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        p = os.path.join(directory, name)
        if name.startswith(".staging") or _QUARANTINE_RE.search(name) \
                or not os.path.isdir(p) or p == keep:
            continue
        if is_torn_pod(p):
            try:
                dst = quarantine_tag(p)
            except OSError as e:  # leave it; the verify gate still skips it
                logger.warning("could not quarantine torn-pod tag %s: %s",
                               p, e)
                continue
            logger.warning("quarantined torn-pod checkpoint %s -> %s (rank "
                           "manifests without a matching pod commit)", p, dst)
            quarantined += 1
    if quarantined:
        from ..monitor.monitor import resilience_counters

        resilience_counters.incr("torn_pod_quarantined", quarantined)
    for name in names:
        p = os.path.join(directory, name)
        if not (name.startswith(".staging") and os.path.isdir(p)
                and p != keep):
            continue
        target = os.path.join(directory, name[len(".staging-"):])
        promotable = (name.startswith(".staging-") and name != ".staging-"
                      and verify_tree(p, deep=deep)[0])
        if promotable and os.path.exists(target) \
                and not verify_tree(target, deep=deep)[0]:
            # the target tag exists but is torn (a failed rmtree-then-replace
            # left it partially deleted) while the staging copy is complete:
            # the staging tree is the real checkpoint — move the wreck aside
            try:
                quarantine_tag(target)
            except OSError as e:
                # can't clear the way: leave the staging tree untouched (it
                # may be the only intact copy) for a later sweep to retry
                logger.warning("could not quarantine torn tag %s; keeping "
                               "%s for a later sweep: %s", target, p, e)
                continue
        if promotable and not os.path.exists(target):
            try:
                os.replace(p, target)
                logger.warning("promoted complete checkpoint staging dir "
                               "%s -> %s", p, target)
                handled += 1
                promoted += 1
                continue
            except OSError as e:
                logger.warning("could not promote staging dir %s: %s", p, e)
        shutil.rmtree(p, ignore_errors=True)
        logger.warning("swept orphaned checkpoint staging dir %s", p)
        handled += 1
    if handled:
        from ..monitor.monitor import resilience_counters

        resilience_counters.incr("staging_sweeps", handled - promoted)
        if promoted:
            resilience_counters.incr("staging_promotions", promoted)
    return handled + quarantined


class NativeCheckpointEngine(CheckpointEngine):
    """Synchronous engine over ``save_tree``/``load_tree`` (the
    ``TorchCheckpointEngine`` analog — durable when ``save`` returns)."""

    name = "native"

    def save(self, path, state, meta, latest_file=None, tag="",
             post_commit=None):
        from .engine import save_tree

        save_tree(path, state, meta)
        _write_latest(latest_file, tag)
        _run_post_commit(post_commit)

    def load(self, path, template):
        from .engine import load_tree

        return load_tree(path, template)


class AsyncCheckpointEngine(CheckpointEngine):
    """Background-thread engine (the Nebula analog): ``save`` returns after
    the device→host snapshot; serialization + fsync happen off the training
    thread. Single in-flight save (a new save waits for the previous)."""

    name = "async"

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, path, state, meta, latest_file=None, tag="",
             post_commit=None):
        from .engine import save_tree

        from ..utils.podid import pod_world

        if jax.process_count() > 1 or pod_world() > 1:
            # multi-controller writes are collective (orbax), and an
            # env-declared pod's commit protocol barriers on sibling
            # manifests in the FINAL tag dir — neither belongs on a
            # background thread staging under a colliding path: degrade to
            # sync
            logger.warning("async checkpoint engine degrades to synchronous "
                           "saves under multi-rank execution")
            save_tree(path, state, meta)
            _write_latest(latest_file, tag)
            _run_post_commit(post_commit)
            return
        self.wait()  # one in-flight save; surfaces prior failures
        # a worker killed mid-save last run (or a failed save this run) left
        # a .staging-* orphan: sweep before staging the new one. Shallow
        # verify — this runs on the training thread, and deep-crc'ing a
        # multi-GB orphan here would stall the step the async engine exists
        # to protect.
        sweep_staging_dirs(os.path.dirname(os.path.abspath(path)),
                           deep=False)
        # snapshot NOW, with a forced copy: the jitted train step donates
        # params/opt_state, and on the CPU backend (or host-offloaded state)
        # device_get can return a zero-copy VIEW of the donated buffer — the
        # background writer must never alias memory the next step reuses
        import numpy as _np

        host_state = jax.tree_util.tree_map(
            lambda a: (_np.array(jax.device_get(a))
                       if hasattr(a, "devices") else a),
            state)
        staging = os.path.join(os.path.dirname(path),
                               f".staging-{os.path.basename(path)}")

        def work():
            try:
                get_fault_injector().maybe_delay_async()
                if os.path.isdir(staging):
                    shutil.rmtree(staging)
                save_tree(staging, host_state, meta)
                if os.path.isdir(path):
                    shutil.rmtree(path)
                os.replace(staging, path)
                _write_latest(latest_file, tag)
                _run_post_commit(post_commit)
                log_dist(f"async checkpoint {path} durable")
            except BaseException as e:  # surfaced on next wait()
                self._error = e
                from .engine import verify_tree

                # `path` can be a *partially deleted* old tag dir (rmtree
                # failed midway), so "a directory exists there" is not "a
                # checkpoint exists there" — only a verified target makes
                # the staging copy redundant
                target_ok = os.path.isdir(path) and verify_tree(path)[0]
                if os.path.isdir(staging) and not target_ok \
                        and verify_tree(staging)[0]:
                    # rmtree/os.replace (or later) failed after a complete
                    # write and no healthy copy exists at the target: this
                    # staging tree is the only copy of the checkpoint. Leave
                    # it for the next sweep to promote instead of destroying
                    # data.
                    logger.warning("async save of %s failed after a complete "
                                   "staging write; keeping %s for promotion",
                                   path, staging)
                else:
                    # torn staging tree: a failed save cleans up after itself
                    shutil.rmtree(staging, ignore_errors=True)

        self._thread = threading.Thread(target=work, daemon=True,
                                        name="dstpu-ckpt-writer")
        self._thread.start()

    def load(self, path, template):
        from .engine import load_tree

        self.wait()  # never read a tag that is still being written
        return load_tree(path, template)

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint save failed") from err


def build_checkpoint_engine(kind: str) -> CheckpointEngine:
    engines = {"native": NativeCheckpointEngine, "async": AsyncCheckpointEngine}
    if kind not in engines:
        raise ValueError(f"unknown checkpoint engine {kind!r} "
                         f"(have {sorted(engines)})")
    return engines[kind]()
