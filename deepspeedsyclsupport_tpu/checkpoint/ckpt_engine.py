"""Pluggable checkpoint engines.

Analog of the reference's ``CheckpointEngine`` ABC
(``runtime/checkpoint_engine/checkpoint_engine.py:30``) and its two
implementations — ``TorchCheckpointEngine`` (synchronous) and
``NebulaCheckpointEngine`` (``nebula_checkpoint_engine.py``: Azure Nebula's
async tiered persistence, where ``save`` returns immediately and durability
is reached in the background, with ``commit`` sealing a tag).

TPU-native shape: the synchronous engine wraps the placement-aware
``save_tree``/``load_tree`` writers; the async engine snapshots device
arrays to host **before** returning (the train step donates its buffers, so
background threads must never hold live device references) and streams the
write from a worker thread. Durability protocol: the tree is written into a
``.staging-<tag>`` directory and atomically renamed onto the final tag path
when complete, and the ``latest`` pointer is only updated after the rename —
a crash mid-save can never leave ``latest`` pointing at a torn checkpoint
(Nebula's tier-commit semantic).
"""
import os
import shutil
import threading
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from ..utils.logging import log_dist, logger

__all__ = ["CheckpointEngine", "NativeCheckpointEngine",
           "AsyncCheckpointEngine", "build_checkpoint_engine"]


class CheckpointEngine(ABC):
    """save/load/commit surface (reference ``checkpoint_engine.py:30``)."""

    name = "base"

    @abstractmethod
    def save(self, path: str, state: Any, meta: Dict[str, Any],
             latest_file: Optional[str] = None, tag: str = "") -> None:
        """Persist ``state``+``meta`` under ``path``. When ``latest_file`` is
        given, point it at ``tag`` once the checkpoint is durable."""

    @abstractmethod
    def load(self, path: str, template: Any) -> Tuple[Any, Dict[str, Any]]:
        ...

    def commit(self, tag: str = "") -> bool:
        """Seal a tag: returns True once every pending write for it is
        durable (reference ``nebula_checkpoint_engine.py commit``)."""
        self.wait()
        return True

    def wait(self) -> None:
        """Block until all in-flight saves are durable."""


def _write_latest(latest_file: Optional[str], tag: str) -> None:
    if latest_file and jax.process_index() == 0:
        with open(latest_file, "w") as f:
            f.write(tag)


class NativeCheckpointEngine(CheckpointEngine):
    """Synchronous engine over ``save_tree``/``load_tree`` (the
    ``TorchCheckpointEngine`` analog — durable when ``save`` returns)."""

    name = "native"

    def save(self, path, state, meta, latest_file=None, tag=""):
        from .engine import save_tree

        save_tree(path, state, meta)
        _write_latest(latest_file, tag)

    def load(self, path, template):
        from .engine import load_tree

        return load_tree(path, template)


class AsyncCheckpointEngine(CheckpointEngine):
    """Background-thread engine (the Nebula analog): ``save`` returns after
    the device→host snapshot; serialization + fsync happen off the training
    thread. Single in-flight save (a new save waits for the previous)."""

    name = "async"

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def save(self, path, state, meta, latest_file=None, tag=""):
        from .engine import save_tree

        if jax.process_count() > 1:
            # multi-controller writes are collective (orbax) — degrade to
            # sync rather than running collectives off-thread
            logger.warning("async checkpoint engine degrades to synchronous "
                           "saves under multi-controller execution")
            save_tree(path, state, meta)
            _write_latest(latest_file, tag)
            return
        self.wait()  # one in-flight save; surfaces prior failures
        # snapshot NOW, with a forced copy: the jitted train step donates
        # params/opt_state, and on the CPU backend (or host-offloaded state)
        # device_get can return a zero-copy VIEW of the donated buffer — the
        # background writer must never alias memory the next step reuses
        import numpy as _np

        host_state = jax.tree_util.tree_map(
            lambda a: (_np.array(jax.device_get(a))
                       if hasattr(a, "devices") else a),
            state)
        staging = os.path.join(os.path.dirname(path),
                               f".staging-{os.path.basename(path)}")

        def work():
            try:
                if os.path.isdir(staging):
                    shutil.rmtree(staging)
                save_tree(staging, host_state, meta)
                if os.path.isdir(path):
                    shutil.rmtree(path)
                os.replace(staging, path)
                _write_latest(latest_file, tag)
                log_dist(f"async checkpoint {path} durable")
            except BaseException as e:  # surfaced on next wait()
                self._error = e

        self._thread = threading.Thread(target=work, daemon=True,
                                        name="dstpu-ckpt-writer")
        self._thread.start()

    def load(self, path, template):
        from .engine import load_tree

        self.wait()  # never read a tag that is still being written
        return load_tree(path, template)

    def wait(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("async checkpoint save failed") from err


def build_checkpoint_engine(kind: str) -> CheckpointEngine:
    engines = {"native": NativeCheckpointEngine, "async": AsyncCheckpointEngine}
    if kind not in engines:
        raise ValueError(f"unknown checkpoint engine {kind!r} "
                         f"(have {sorted(engines)})")
    return engines[kind]()
