"""Export checkpoint weights as an fp32 state dict (torch-compatible).

Analog of ``deepspeed/utils/zero_to_fp32.py`` (592 LoC): the reference walks
per-DP-rank ZeRO shard files, reassembles flat partitions, and emits a
``pytorch_model.bin``. Our shards reassemble at save time (the native format
stores whole logical arrays), so export is: read leaves → upcast fp32 →
``torch.save`` (torch-cpu is a baked-in dependency; falls back to ``.npz``
without it).

CLI parity: ``python -m deepspeedsyclsupport_tpu.checkpoint.zero_to_fp32
<checkpoint_dir> <output_file>``.
"""
import argparse
import os
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from .universal import load_state_dict


def get_fp32_state_dict_from_zero_checkpoint(
        ckpt_dir: str, tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Reference ``get_fp32_state_dict_from_zero_checkpoint``: flat
    {param-path: fp32 array}."""
    sd = load_state_dict(ckpt_dir, tag, prefix="params")
    out = {}
    for name, arr in sd.items():
        key = name[len("params/"):] if name.startswith("params/") else name
        # jnp.issubdtype, not np: ml_dtypes bfloat16 is not np.floating
        if jnp.issubdtype(arr.dtype, jnp.floating):
            arr = arr.astype(np.float32)
        out[key] = arr
    return out


def convert_zero_checkpoint_to_fp32_state_dict(ckpt_dir: str, output_file: str,
                                               tag: Optional[str] = None
                                               ) -> str:
    """Reference ``convert_zero_checkpoint_to_fp32_state_dict``: write a
    consolidated fp32 state dict file."""
    sd = get_fp32_state_dict_from_zero_checkpoint(ckpt_dir, tag)
    os.makedirs(os.path.dirname(os.path.abspath(output_file)), exist_ok=True)
    try:
        import torch

        torch.save({k: torch.from_numpy(np.ascontiguousarray(v))
                    for k, v in sd.items()}, output_file)
    except ImportError:  # pragma: no cover - torch is baked into the image
        np.savez(output_file, **sd)
    return output_file


def main():  # pragma: no cover - thin CLI
    p = argparse.ArgumentParser(
        description="Consolidate a dstpu checkpoint into an fp32 state dict")
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("-t", "--tag", default=None)
    a = p.parse_args()
    path = convert_zero_checkpoint_to_fp32_state_dict(
        a.checkpoint_dir, a.output_file, a.tag)
    print(f"wrote {path}")


if __name__ == "__main__":  # pragma: no cover
    main()
