"""Checkpoint engine.

Analog of the reference's pluggable ``CheckpointEngine``
(``runtime/checkpoint_engine/checkpoint_engine.py:30``: Torch + Nebula tiered
backends) and the save/load plumbing in ``engine.py:3050,2688``.

Two backends behind one ``save_tree``/``load_tree`` surface:

* **native** (single controller): leaves are pulled to host and streamed into one
  raw binary file with a JSON index (offset/dtype/shape per leaf). No pickle — the
  format is language-neutral so the C++ async-IO layer (csrc/ analog of the
  reference's ``csrc/aio``) can produce/consume it. Restore is placement-aware:
  every leaf is ``device_put`` against the *caller's current* sharding, giving
  topology-changing resume ("universal checkpoint", reference
  ``checkpoint/ds_to_universal.py``) with no offline conversion.
* **orbax** (multi-host): every host writes its addressable shards in parallel.
  Selected automatically when ``jax.process_count() > 1``.
"""
import itertools
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

META_FILE = "dstpu_meta.json"
INDEX_FILE = "state_index.json"
DATA_FILE = "state.bin"
STATE_DIR = "state"  # orbax subdir


def _key_str(k) -> str:
    """Human-stable path segment: dict key / index / attr name, no brackets."""
    if isinstance(k, jax.tree_util.DictKey):
        return str(k.key)
    if isinstance(k, jax.tree_util.SequenceKey):
        return str(k.idx)
    if isinstance(k, jax.tree_util.GetAttrKey):
        return k.name
    if isinstance(k, jax.tree_util.FlattenedIndexKey):
        return str(k.key)
    return str(k)


def _leaf_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return ["/".join(_key_str(k) for k in path) for path, _ in flat]


def _legacy_names(name: str):
    """Clean name → the bracketed reprs older checkpoints may have stored
    (``str(DictKey)`` = ``['key']``, ``str(SequenceKey)`` = ``[idx]``). A
    numeric segment is ambiguous — a dict key that is the *string* "0" was
    stored as ``['0']``, a list index as ``[0]`` — so yield every combination."""
    options = [([f"[{s}]", f"['{s}']"] if s.isdigit() else [f"['{s}']"])
               for s in name.split("/")]
    for combo in itertools.product(*options):
        yield "/".join(combo)


def save_tree(path: str, state: Dict[str, Any], meta: Dict[str, Any]) -> None:
    """Write a sharded state tree + JSON metadata under ``path``."""
    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    if jax.process_count() > 1:  # pragma: no cover - needs real pod
        _save_orbax(path, state)
    else:
        _save_native(path, state)
    if jax.process_index() == 0:
        with open(os.path.join(path, META_FILE), "w") as f:
            json.dump(_jsonable(meta), f, indent=2)


def load_tree(path: str, template: Dict[str, Tuple[Any, Any]]
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Restore into the caller's current shardings.

    ``template`` maps top-level key → (example_tree, sharding_tree). The example
    supplies structure/shape/dtype; the shardings direct placement of every
    restored leaf — the resharding-on-load path.
    """
    path = os.path.abspath(path)
    example = {k: ex for k, (ex, _) in template.items()}
    shardings = {k: sh for k, (_, sh) in template.items()}
    if os.path.exists(os.path.join(path, INDEX_FILE)):
        state = _load_native(path, example, shardings)
    else:  # pragma: no cover - needs real pod
        state = _load_orbax(path, example, shardings)
    meta_path = os.path.join(path, META_FILE)
    meta: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    return state, meta


# ---------------------------------------------------------------- native backend
def _save_native(path: str, state) -> None:
    leaves = jax.tree_util.tree_leaves(state)
    names = _leaf_paths(state)
    index = []
    offset = 0
    with open(os.path.join(path, DATA_FILE), "wb") as f:
        for name, leaf in zip(names, leaves):
            arr = np.asarray(jax.device_get(leaf))
            data = arr.tobytes()
            index.append({"name": name, "offset": offset, "nbytes": len(data),
                          "dtype": str(arr.dtype), "shape": list(arr.shape)})
            f.write(data)
            offset += len(data)
    with open(os.path.join(path, INDEX_FILE), "w") as f:
        json.dump(index, f)


def _load_native(path: str, example, shardings):
    with open(os.path.join(path, INDEX_FILE)) as f:
        index = json.load(f)
    by_name = {e["name"]: e for e in index}
    names = _leaf_paths(example)
    treedef = jax.tree_util.tree_structure(example)
    sh_leaves = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
    ex_leaves = jax.tree_util.tree_leaves(example)
    if len(sh_leaves) != len(ex_leaves):
        raise ValueError("sharding tree does not match example tree")
    out = []
    with open(os.path.join(path, DATA_FILE), "rb") as f:
        for name, ex, sh in zip(names, ex_leaves, sh_leaves):
            if name not in by_name:
                # pre-_key_str bracketed formats
                legacy = next((c for c in _legacy_names(name) if c in by_name),
                              None)
                if legacy is not None:
                    name = legacy
                else:
                    raise KeyError(f"checkpoint missing leaf {name!r}")
            e = by_name[name]
            f.seek(e["offset"])
            arr = np.frombuffer(f.read(e["nbytes"]),
                                dtype=jnp.dtype(e["dtype"])).reshape(e["shape"])
            if tuple(arr.shape) != tuple(np.shape(ex)):
                raise ValueError(
                    f"shape mismatch for {name!r}: checkpoint {arr.shape} vs "
                    f"model {np.shape(ex)}")
            ex_dtype = getattr(ex, "dtype", None)
            if ex_dtype is not None and arr.dtype != ex_dtype:
                # dtype-changing resume (e.g. an x64-written counter into an i32
                # engine): cast at the boundary so the already-compiled train step
                # sees its expected dtypes instead of recompiling or failing later.
                arr = arr.astype(ex_dtype)
            out.append(jax.device_put(arr, sh))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------- orbax backend
def _globalize(state):
    """Host-local leaves (uncommitted scalars like loss-scale state, or numpy)
    → fully-replicated global arrays. Orbax refuses host-local jax.Arrays in a
    multi-controller save; every process holds the same value for these, so
    declaring them replicated over the world mesh is exact."""
    from jax.experimental import multihost_utils

    from ..comm.topology import get_world_topology

    mesh = get_world_topology().mesh
    if jax.process_count() == 1:
        return state  # single-controller saves take the native backend anyway

    def fix(x):
        if not hasattr(x, "dtype"):
            return x
        sh = getattr(x, "sharding", None)
        if sh is not None and len(sh.device_set) > 1:
            return x  # already a global (mesh-sharded/replicated) array
        return multihost_utils.host_local_array_to_global_array(
            np.asarray(x), mesh, jax.sharding.PartitionSpec())

    return jax.tree_util.tree_map(fix, state)


def _save_orbax(path: str, state) -> None:
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    try:
        ckptr.save(os.path.join(path, STATE_DIR), _globalize(state),
                   force=True)
    finally:
        ckptr.close()


def _load_orbax(path: str, example, shardings):  # pragma: no cover
    import orbax.checkpoint as ocp

    item = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=s)
        if hasattr(x, "dtype") else x, example, shardings)
    ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    try:
        return ckptr.restore(os.path.join(path, STATE_DIR),
                             args=ocp.args.PyTreeRestore(item=item))
    finally:
        ckptr.close()


def _jsonable(obj):
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()
    return obj
