"""Checkpoint engine.

Analog of the reference's pluggable ``CheckpointEngine``
(``runtime/checkpoint_engine/checkpoint_engine.py:30``: Torch + Nebula tiered
backends) and the save/load plumbing in ``engine.py:3050,2688``.

Two backends behind one ``save_tree``/``load_tree`` surface:

* **native** (single controller): leaves are pulled to host and streamed into one
  raw binary file with a JSON index (offset/dtype/shape per leaf). No pickle — the
  format is language-neutral so the C++ async-IO layer (csrc/ analog of the
  reference's ``csrc/aio``) can produce/consume it. Restore is placement-aware:
  every leaf is ``device_put`` against the *caller's current* sharding, giving
  topology-changing resume ("universal checkpoint", reference
  ``checkpoint/ds_to_universal.py``) with no offline conversion.
* **orbax** (multi-host): every host writes its addressable shards in parallel.
  Selected automatically when ``jax.process_count() > 1``.
"""
import itertools
import json
import os
import re
import shutil
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.fault_injection import get_fault_injector, retry_io
from ..utils.logging import logger

META_FILE = "dstpu_meta.json"
INDEX_FILE = "state_index.json"
DATA_FILE = "state.bin"
STATE_DIR = "state"  # orbax subdir
LATEST_FILE = "latest"  # tag-pointer file (kept in sync with runtime/engine.py)
# Health-gated tag pointer (runtime/sentinel.py): names the newest tag the
# training sentinel PROMOTED — observed K healthy steps beyond it — so a
# divergence rollback never resumes from a checkpoint that may already carry
# the poisoned state `latest` happily points at.
LAST_GOOD_FILE = "last_good"
INTEGRITY_KEY = "__integrity__"  # manifest section inside META_FILE
# Two-phase pod commit (all-ranks checkpoint consistency): phase 1 = every
# rank durably writes its own rank manifest after its shard payload; phase 2
# = rank 0 writes the pod commit record (expected rank set + per-rank
# manifest digests) only after a cross-process barrier proved every rank's
# phase 1 done. A tag with rank manifests but no (or mismatched) commit
# record is a TORN POD — a preemption landed between the phases — and is
# never resolved by any rank (quarantined by the resume-time staging sweep).
COMMIT_FILE = "dstpu_commit.json"
_RANK_MANIFEST_RE = re.compile(r"^dstpu_rank_(\d+)\.json$")


def rank_manifest_name(rank: int) -> str:
    """Phase-1 per-rank manifest filename inside a tag directory."""
    return f"dstpu_rank_{int(rank)}.json"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint failed integrity verification (torn write / bit rot)."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


def _counters():
    from ..monitor.monitor import resilience_counters

    return resilience_counters


def _key_str(k) -> str:
    """Human-stable path segment: dict key / index / attr name, no brackets."""
    if isinstance(k, jax.tree_util.DictKey):
        return str(k.key)
    if isinstance(k, jax.tree_util.SequenceKey):
        return str(k.idx)
    if isinstance(k, jax.tree_util.GetAttrKey):
        return k.name
    if isinstance(k, jax.tree_util.FlattenedIndexKey):
        return str(k.key)
    return str(k)


def _leaf_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return ["/".join(_key_str(k) for k in path) for path, _ in flat]


def _legacy_names(name: str):
    """Clean name → the bracketed reprs older checkpoints may have stored
    (``str(DictKey)`` = ``['key']``, ``str(SequenceKey)`` = ``[idx]``). A
    numeric segment is ambiguous — a dict key that is the *string* "0" was
    stored as ``['0']``, a list index as ``[0]`` — so yield every combination."""
    options = [([f"[{s}]", f"['{s}']"] if s.isdigit() else [f"['{s}']"])
               for s in name.split("/")]
    for combo in itertools.product(*options):
        yield "/".join(combo)


def save_tree(path: str, state: Dict[str, Any], meta: Dict[str, Any]) -> None:
    """Write a sharded state tree + JSON metadata under ``path``.

    Durability details: every file write is fsynced and wrapped in
    :func:`~..utils.fault_injection.retry_io` so transient storage errors
    self-heal; the meta file carries an integrity manifest (per-file size +
    crc32, per-leaf crc32 in the index) that :func:`verify_tree` and
    :func:`load_tree` check so a torn or bit-rotted checkpoint is detected
    at load time instead of poisoning a resumed run."""
    from ..utils.podid import pod_identity

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    rank, _world = pod_identity()
    if jax.process_count() > 1:  # pragma: no cover - needs real pod
        _save_orbax(path, state)
    elif rank == 0:
        # env-declared pod of single-controller replicas (utils/podid.py):
        # every member holds the same full state, so rank 0 owns the
        # payload and the siblings only participate in the commit protocol
        # below — two replicas streaming the same bytes into one file
        # would race each other
        _save_native(path, state)
    if rank == 0:
        meta = dict(meta)
        meta[INTEGRITY_KEY] = _build_manifest(path)
        meta_path = os.path.join(path, META_FILE)
        _durable_write(meta_path, json.dumps(_jsonable(meta), indent=2),
                       what=f"checkpoint meta write {meta_path}")
    pod_commit(path, meta)
    # torn-write simulation happens after the save claims durability: the
    # failure mode under test is "save completed, file is still short"
    fi = get_fault_injector()
    for fname in (DATA_FILE, INDEX_FILE, META_FILE):
        p = os.path.join(path, fname)
        if os.path.exists(p):
            fi.maybe_truncate(p)
    fi.maybe_tear_pod(path, rank)


def _durable_write(path: str, text: str, what: str,
                   rename_to: Optional[str] = None) -> None:
    """One retry unit for a small durable text file: fault-injection hook,
    write, fsync, optional atomic rename — shared by the meta/index writers
    and the ``latest`` pointer so their durability semantics can't drift."""

    def write():
        get_fault_injector().maybe_fail_write(rename_to or path)
        with open(path, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        if rename_to is not None:
            os.replace(path, rename_to)

    retry_io(write, what=what)


def _file_digest(path: str) -> Dict[str, int]:
    crc = 0
    nbytes = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            nbytes += len(chunk)
    return {"nbytes": nbytes, "crc32": crc}


def _build_manifest(path: str) -> Dict[str, Any]:
    """File-level manifest for the native layout (orbax dirs carry their own
    per-array checksums); recorded in META_FILE, checked by verify_tree."""
    files = {}
    for fname in (DATA_FILE, INDEX_FILE):
        p = os.path.join(path, fname)
        if os.path.exists(p):
            files[fname] = _file_digest(p)
    return {"version": 1, "files": files}


# ------------------------------------------------------------ pod commit
#: rank 0 waits at most this long (env-overridable) for sibling phase-1
#: manifests before leaving the tag uncommitted (= torn, never resolved)
POD_COMMIT_TIMEOUT_ENV = "DSTPU_POD_COMMIT_TIMEOUT_S"
_POD_COMMIT_TIMEOUT_S = 120.0
_POD_COMMIT_POLL_S = 0.05


def pod_commit(path: str, meta: Dict[str, Any],
               timeout_s: Optional[float] = None) -> bool:
    """Two-phase all-ranks commit of one tag directory.

    Phase 1 (every rank): atomically publish this rank's manifest — the
    durable record that *this rank* finished its part of the save at this
    ``global_steps``. Phase 2 (rank 0 only): once every expected rank's
    manifest is present — via the ``jax.distributed`` barrier when one
    exists, else by polling the shared directory (the env-declared pod of
    independent controllers, ``utils/podid.py``) — write the pod commit
    record naming the expected rank set and each rank's manifest digest.

    A death between the phases leaves rank manifests without a commit
    record — a torn pod, detected by :func:`pod_complete`, skipped by
    every resolution walk and quarantined by the resume-time sweep. If the
    siblings never show up within the timeout, rank 0 *leaves the tag
    uncommitted* (the correct verdict: the pod did not complete this save)
    rather than failing the caller. Single-process saves run the identical
    protocol with ``world_size=1`` so the format stays uniform. Returns
    whether this rank considers the tag committed."""
    import time

    from ..utils.podid import pod_identity

    t0 = time.perf_counter()
    rank, world = pod_identity()
    rm = {"version": 1, "rank": int(rank), "world_size": int(world),
          "global_steps": meta.get("global_steps")}
    rm_path = os.path.join(path, rank_manifest_name(rank))
    # atomic publish (tmp + rename): a polling rank 0 must never read a
    # half-written sibling manifest as evidence
    _durable_write(rm_path + f".tmp{os.getpid()}",
                   json.dumps(_jsonable(rm), sort_keys=True),
                   what=f"rank manifest write {rm_path}",
                   rename_to=rm_path)
    committed = True
    if world > 1 and jax.process_count() > 1:  # pragma: no cover - real pod
        from jax.experimental import multihost_utils

        # the barrier IS the phase-1→2 hand-off: no rank passes it before
        # its manifest is durable, so rank 0 reads complete evidence below
        multihost_utils.sync_global_devices(
            f"dstpu_pod_commit_{os.path.basename(path)}_"
            f"{meta.get('global_steps')}")
    if rank == 0:
        committed = _commit_as_rank0(path, meta, world, timeout_s)
    from ..monitor.telemetry import metrics_registry

    metrics_registry.histogram("ckpt_pod_commit_s").observe(
        time.perf_counter() - t0)
    return committed


def _commit_as_rank0(path: str, meta: Dict[str, Any], world: int,
                     timeout_s: Optional[float]) -> bool:
    """Rank 0's phase 2: gather every rank's manifest (polling covers pods
    with no collective backend), cross-check the step, write the commit."""
    import time

    if timeout_s is None:
        try:
            timeout_s = float(os.environ.get(POD_COMMIT_TIMEOUT_ENV,
                                             _POD_COMMIT_TIMEOUT_S))
        except ValueError:
            timeout_s = _POD_COMMIT_TIMEOUT_S
    want_steps = meta.get("global_steps")
    deadline = time.monotonic() + timeout_s
    digests: Dict[int, int] = {}
    while True:
        for r in range(world):
            if r in digests:
                continue
            p = os.path.join(path, rank_manifest_name(r))
            try:
                with open(p, "rb") as f:
                    raw = f.read()
                rm = json.loads(raw.decode())
            except (OSError, ValueError):
                continue  # not there yet (publish is atomic, so no partials)
            if want_steps is not None and \
                    rm.get("global_steps") != want_steps:
                continue  # a stale manifest from an older save of this tag
            digests[r] = zlib.crc32(raw)
        if len(digests) == world:
            break
        if time.monotonic() >= deadline:
            logger.error(
                "pod commit of %s: only %d/%d rank manifest(s) appeared "
                "within %.0fs — leaving the tag UNCOMMITTED (torn pod; no "
                "rank will resolve it)", path, len(digests), world,
                timeout_s)
            return False
        time.sleep(_POD_COMMIT_POLL_S)
    commit = {"version": 1, "world_size": int(world),
              "global_steps": want_steps,
              "ranks": {str(r): d for r, d in sorted(digests.items())}}
    commit_path = os.path.join(path, COMMIT_FILE)
    _durable_write(commit_path + f".tmp{os.getpid()}",
                   json.dumps(_jsonable(commit), indent=2, sort_keys=True),
                   what=f"pod commit write {commit_path}",
                   rename_to=commit_path)
    _counters().incr("pod_commits")
    return True


def pod_complete(path: str) -> Tuple[bool, str]:
    """Is this tag a *pod-complete* checkpoint — every rank of the saving
    pod committed? Returns ``(ok, reason)``. A tag with no commit record
    and no rank manifests predates the protocol and is treated as complete
    (its per-file integrity manifest still gates it); a tag with phase-1
    manifests but a missing/mismatched commit record is a torn pod."""
    try:
        names = os.listdir(path)
    except OSError as e:
        return False, f"unreadable tag dir: {e}"
    manifests = {int(m.group(1)): n for n in names
                 for m in [_RANK_MANIFEST_RE.match(n)] if m}
    commit_path = os.path.join(path, COMMIT_FILE)
    if not os.path.exists(commit_path):
        if manifests:
            return False, (f"torn pod: {len(manifests)} rank manifest(s) "
                           f"but no {COMMIT_FILE} (commit phase never ran)")
        return True, "ok (pre-pod-commit tag)"
    try:
        with open(commit_path) as f:
            commit = json.load(f)
        ranks = {int(r): int(d) for r, d in commit["ranks"].items()}
        world = int(commit["world_size"])
    except (OSError, ValueError, KeyError, TypeError) as e:
        return False, f"unreadable {COMMIT_FILE}: {e!r}"
    if sorted(ranks) != list(range(world)):
        return False, (f"torn pod: commit names ranks {sorted(ranks)} but "
                       f"world_size is {world}")
    for r, want in sorted(ranks.items()):
        p = os.path.join(path, rank_manifest_name(r))
        try:
            with open(p, "rb") as f:
                got = zlib.crc32(f.read())
        except OSError:
            return False, f"torn pod: rank {r} manifest missing"
        if got != want:
            return False, (f"torn pod: rank {r} manifest digest {got} != "
                           f"committed {want}")
    return True, "ok"


def is_torn_pod(path: str) -> bool:
    """True when the tag carries pod-commit protocol files that do NOT add
    up to a complete pod — the quarantine predicate for the resume-time
    sweep (a protocol-less legacy tag is not torn, just old)."""
    try:
        names = os.listdir(path)
    except OSError:
        return False
    has_protocol = (COMMIT_FILE in names
                    or any(_RANK_MANIFEST_RE.match(n) for n in names))
    return has_protocol and not pod_complete(path)[0]


def load_tree(path: str, template: Dict[str, Tuple[Any, Any]]
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Restore into the caller's current shardings.

    ``template`` maps top-level key → (example_tree, sharding_tree). The example
    supplies structure/shape/dtype; the shardings direct placement of every
    restored leaf — the resharding-on-load path.
    """
    path = os.path.abspath(path)
    example = {k: ex for k, (ex, _) in template.items()}
    shardings = {k: sh for k, (_, sh) in template.items()}
    if os.path.exists(os.path.join(path, INDEX_FILE)):
        state = _load_native(path, example, shardings)
    else:  # pragma: no cover - needs real pod
        state = _load_orbax(path, example, shardings)
    meta_path = os.path.join(path, META_FILE)
    meta: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    return state, meta


# ---------------------------------------------------------------- native backend
def _save_native(path: str, state) -> None:
    leaves = jax.tree_util.tree_leaves(state)
    names = _leaf_paths(state)
    data_path = os.path.join(path, DATA_FILE)
    index_path = os.path.join(path, INDEX_FILE)
    index: List[Dict[str, Any]] = []

    def write_data():
        # the whole file is one retry unit: "wb" re-truncates, so a retry
        # after a partial write starts from a clean slate
        index.clear()
        get_fault_injector().maybe_fail_write(data_path)
        offset = 0
        with open(data_path, "wb") as f:
            for name, leaf in zip(names, leaves):
                arr = np.asarray(jax.device_get(leaf))
                data = arr.tobytes()
                index.append({"name": name, "offset": offset,
                              "nbytes": len(data), "dtype": str(arr.dtype),
                              "shape": list(arr.shape),
                              "crc32": zlib.crc32(data)})
                f.write(data)
                offset += len(data)
            f.flush()
            os.fsync(f.fileno())

    retry_io(write_data, what=f"checkpoint data write {data_path}")
    _durable_write(index_path, json.dumps(index),
                   what=f"checkpoint index write {index_path}")
    # observability spine: bytes written per save feeds Ckpt/* reporting
    from ..monitor.telemetry import metrics_registry

    metrics_registry.counter("ckpt_bytes_written").incr(
        sum(e["nbytes"] for e in index))


def _load_native(path: str, example, shardings):
    with open(os.path.join(path, INDEX_FILE)) as f:
        index = json.load(f)
    by_name = {e["name"]: e for e in index}
    names = _leaf_paths(example)
    treedef = jax.tree_util.tree_structure(example)
    sh_leaves = jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))
    ex_leaves = jax.tree_util.tree_leaves(example)
    if len(sh_leaves) != len(ex_leaves):
        raise ValueError("sharding tree does not match example tree")
    out = []
    with open(os.path.join(path, DATA_FILE), "rb") as f:
        for name, ex, sh in zip(names, ex_leaves, sh_leaves):
            if name not in by_name:
                # pre-_key_str bracketed formats
                legacy = next((c for c in _legacy_names(name) if c in by_name),
                              None)
                if legacy is not None:
                    name = legacy
                else:
                    raise KeyError(f"checkpoint missing leaf {name!r}")
            e = by_name[name]
            f.seek(e["offset"])
            buf = f.read(e["nbytes"])
            if len(buf) != e["nbytes"]:
                raise CheckpointCorruptionError(
                    path, f"leaf {name!r} torn: wanted {e['nbytes']} bytes at "
                          f"offset {e['offset']}, file had {len(buf)}")
            if "crc32" in e and zlib.crc32(buf) != e["crc32"]:
                raise CheckpointCorruptionError(
                    path, f"leaf {name!r} checksum mismatch "
                          f"(stored {e['crc32']}, got {zlib.crc32(buf)})")
            arr = np.frombuffer(buf,
                                dtype=jnp.dtype(e["dtype"])).reshape(e["shape"])
            if tuple(arr.shape) != tuple(np.shape(ex)):
                raise ValueError(
                    f"shape mismatch for {name!r}: checkpoint {arr.shape} vs "
                    f"model {np.shape(ex)}")
            ex_dtype = getattr(ex, "dtype", None)
            if ex_dtype is not None and arr.dtype != ex_dtype:
                # dtype-changing resume (e.g. an x64-written counter into an i32
                # engine): cast at the boundary so the already-compiled train step
                # sees its expected dtypes instead of recompiling or failing later.
                arr = arr.astype(ex_dtype)
            else:
                # own the memory: frombuffer views the read buffer, and on the
                # CPU backend device_put may alias host memory — which the
                # jitted train step later DONATES. A resumed-then-trained leaf
                # must never share storage with the I/O buffer.
                arr = np.array(arr)
            out.append(jax.device_put(arr, sh))
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------- orbax backend
def _globalize(state):
    """Host-local leaves (uncommitted scalars like loss-scale state, or numpy)
    → fully-replicated global arrays. Orbax refuses host-local jax.Arrays in a
    multi-controller save; every process holds the same value for these, so
    declaring them replicated over the world mesh is exact."""
    from jax.experimental import multihost_utils

    from ..comm.topology import get_world_topology

    mesh = get_world_topology().mesh
    if jax.process_count() == 1:
        return state  # single-controller saves take the native backend anyway

    def fix(x):
        if not hasattr(x, "dtype"):
            return x
        sh = getattr(x, "sharding", None)
        if sh is not None and len(sh.device_set) > 1:
            return x  # already a global (mesh-sharded/replicated) array
        return multihost_utils.host_local_array_to_global_array(
            np.asarray(x), mesh, jax.sharding.PartitionSpec())

    return jax.tree_util.tree_map(fix, state)


def _save_orbax(path: str, state) -> None:
    import orbax.checkpoint as ocp

    ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    try:
        ckptr.save(os.path.join(path, STATE_DIR), _globalize(state),
                   force=True)
    finally:
        ckptr.close()


def _load_orbax(path: str, example, shardings):  # pragma: no cover
    import orbax.checkpoint as ocp

    item = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=s)
        if hasattr(x, "dtype") else x, example, shardings)
    ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    try:
        return ckptr.restore(os.path.join(path, STATE_DIR),
                             args=ocp.args.PyTreeRestore(item=item))
    finally:
        ckptr.close()


# ------------------------------------------------------------ integrity + GC
def verify_tree(path: str, deep: bool = True) -> Tuple[bool, str]:
    """Offline integrity check of one checkpoint directory: meta parses, the
    index is intact, and the data file matches the manifest. Returns
    ``(ok, reason)`` instead of raising so callers can walk past bad tags.

    ``deep=True`` re-reads every byte and checks crc32s — run before a load,
    where a silently bit-rotted tag would poison the resumed run.
    ``deep=False`` checks structure and file sizes only (catches torn
    writes, skips the full re-read) — for hot paths like rotation that run
    on every save and must not re-stream multi-GB state from storage."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return False, "missing directory"
    meta_path = os.path.join(path, META_FILE)
    if not os.path.exists(meta_path):
        return False, f"missing {META_FILE}"
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (ValueError, OSError) as e:
        return False, f"unreadable {META_FILE}: {e}"
    # pod completeness gates BOTH layouts (native and orbax): a tag only a
    # subset of ranks committed must never verify, or a restarted pod would
    # resume half-written state — see pod_commit/pod_complete
    ok_pod, pod_reason = pod_complete(path)
    if not ok_pod:
        return False, pod_reason
    index_path = os.path.join(path, INDEX_FILE)
    if not os.path.exists(index_path):
        # orbax layout: content integrity is orbax's own (per-array
        # checksummed) business; presence of the state dir is all we assert
        if os.path.isdir(os.path.join(path, STATE_DIR)):
            return True, "ok (orbax layout, content not re-verified)"
        return False, f"missing {INDEX_FILE} and {STATE_DIR}/"
    try:
        with open(index_path) as f:
            index = json.load(f)
    except (ValueError, OSError) as e:
        return False, f"unreadable {INDEX_FILE}: {e}"
    data_path = os.path.join(path, DATA_FILE)
    if not os.path.exists(data_path):
        return False, f"missing {DATA_FILE}"
    try:
        expected = max((e["offset"] + e["nbytes"] for e in index), default=0)
        size = os.path.getsize(data_path)
        if size < expected:
            return False, (f"torn {DATA_FILE}: {size} bytes on disk, index "
                           f"expects {expected}")
        manifest = meta.get(INTEGRITY_KEY)
        if manifest:
            for fname, want in manifest.get("files", {}).items():
                p = os.path.join(path, fname)
                if not os.path.exists(p):
                    return False, f"missing {fname}"
                if not deep:
                    size = os.path.getsize(p)
                    if size != want.get("nbytes"):
                        return False, (f"{fname} size mismatch: manifest "
                                       f"says {want.get('nbytes')}, on disk "
                                       f"{size}")
                    continue
                got = _file_digest(p)
                if got != want:
                    return False, (f"{fname} manifest mismatch: stored "
                                   f"{want}, on disk {got}")
        elif deep:
            # pre-manifest checkpoint: fall back to per-leaf crcs if present
            with open(data_path, "rb") as f:
                for e in index:
                    if "crc32" not in e:
                        continue
                    f.seek(e["offset"])
                    if zlib.crc32(f.read(e["nbytes"])) != e["crc32"]:
                        return False, (f"leaf {e['name']!r} checksum "
                                       f"mismatch")
    except (KeyError, TypeError, ValueError, AttributeError, OSError) as e:
        # valid JSON whose entries are damaged (bit rot inside the index or
        # manifest), or a file racing out from under us: that is corruption,
        # never an exception — the fallback walk depends on this function
        # answering, not raising
        return False, f"malformed index/manifest: {e!r}"
    return True, "ok"


def _read_latest(load_dir: str) -> Optional[str]:
    latest = os.path.join(load_dir, LATEST_FILE)
    if not os.path.exists(latest):
        return None
    with open(latest) as f:
        tag = f.read().strip()
    return tag or None


def list_tags(load_dir: str) -> List[str]:
    """Checkpoint tags under ``load_dir``, newest first (by recorded
    ``global_steps``, then mtime — mtime alone lies after restores/copies)."""
    out = []
    try:
        names = os.listdir(load_dir)
    except OSError:
        return []
    for name in names:
        p = os.path.join(load_dir, name)
        if not os.path.isdir(p) or name.startswith(".staging") \
                or _QUARANTINE_RE.search(name):
            continue
        if not (os.path.exists(os.path.join(p, META_FILE))
                or os.path.exists(os.path.join(p, INDEX_FILE))
                or os.path.isdir(os.path.join(p, STATE_DIR))):
            continue
        steps = -1
        try:
            with open(os.path.join(p, META_FILE)) as f:
                steps = int(json.load(f).get("global_steps", -1))
        except (OSError, ValueError, TypeError):
            pass  # torn meta: still a candidate, ranked by mtime only
        try:
            mtime = os.path.getmtime(p)
        except OSError:
            # renamed/deleted out from under the walk — a sibling rank's
            # sweep quarantining a torn-pod tag races this listing
            continue
        out.append((steps, mtime, name))
    out.sort(reverse=True)
    return [name for _, _, name in out]


def _candidate_tags(load_dir: str) -> Tuple[Optional[str], List[str]]:
    """The one candidate ordering every fallback walk shares: whatever
    ``latest`` points at first, then the remaining tags newest-first."""
    pointed = _read_latest(load_dir)
    candidates = [pointed] if pointed is not None else []
    candidates.extend(t for t in list_tags(load_dir) if t != pointed)
    return pointed, candidates


# names produced by quarantine_tag: <tag>.corrupt, <tag>.corrupt.1, ... —
# list_tags must skip every generation or a quarantined tag re-enters the
# candidate walk on the next restart
_QUARANTINE_RE = re.compile(r"\.corrupt(\.\d+)?$")


def quarantine_tag(path: str) -> str:
    """Rename a corrupt tag out of the candidate walk, keeping it on disk as
    forensic evidence. The destination is uniquified — the same tag name can
    be re-saved and re-corrupted across restarts, and ``os.replace`` onto an
    existing non-empty ``.corrupt`` directory raises ENOTEMPTY."""
    dst = path + ".corrupt"
    n = 1
    while os.path.exists(dst):
        dst = f"{path}.corrupt.{n}"
        n += 1
    os.replace(path, dst)
    return dst


def find_latest_valid_tag(load_dir: str, deep: bool = True
                          ) -> Tuple[Optional[str], List[Tuple[str, str]]]:
    """Newest tag that passes :func:`verify_tree`, walking tag history
    backwards past corrupt/torn tags. The ``latest`` pointer is tried first;
    returns ``(tag_or_None, [(skipped_tag, reason), ...])``. ``deep=False``
    skips the crc re-read — right when the caller is about to stream the
    tag anyway (the loader checks per-leaf crc32s itself)."""
    skipped: List[Tuple[str, str]] = []
    _, candidates = _candidate_tags(load_dir)
    for tag in candidates:
        ok, reason = verify_tree(os.path.join(load_dir, tag), deep=deep)
        if ok:
            return tag, skipped
        skipped.append((tag, reason))
    return None, skipped


def promote_last_good(save_dir: str, tag: str) -> None:
    """Durably point ``last_good`` at ``tag``. Called by the training
    sentinel once K healthy steps have been observed *beyond* the tag's save
    step — promotion lagging health observation is the whole point: a tag is
    only "good" once the run proved it trained on past it."""
    path = os.path.join(save_dir, LAST_GOOD_FILE)
    _durable_write(path + f".tmp{os.getpid()}", tag,
                   what=f"last_good pointer -> {tag}", rename_to=path)


def read_last_good(load_dir: str) -> Optional[str]:
    p = os.path.join(load_dir, LAST_GOOD_FILE)
    try:
        with open(p) as f:
            tag = f.read().strip()
    except OSError:
        return None
    return tag or None


def find_last_good_tag(load_dir: str, deep: bool = False
                       ) -> Tuple[Optional[str], List[Tuple[str, str]]]:
    """Newest *health-promoted* tag that passes :func:`verify_tree` — the
    rollback analog of :func:`find_latest_valid_tag`, but gated on the
    sentinel's ``last_good`` pointer instead of ``latest``: candidates are
    the promoted tag itself, then only tags whose recorded ``global_steps``
    is older (an un-promoted newer tag may already hold diverged state).
    Returns ``(tag_or_None, [(skipped_tag, reason), ...])``."""
    skipped: List[Tuple[str, str]] = []
    promoted = read_last_good(load_dir)
    if promoted is None:
        return None, skipped
    steps_of = {}
    for tag in list_tags(load_dir):
        steps = -1
        try:
            with open(os.path.join(load_dir, tag, META_FILE)) as f:
                steps = int(json.load(f).get("global_steps", -1))
        except (OSError, ValueError, TypeError):
            pass
        steps_of[tag] = steps
    cap = steps_of.get(promoted, -1)
    candidates = [promoted] + [
        t for t in list_tags(load_dir)
        if t != promoted and 0 <= steps_of.get(t, -1) <= cap]
    for tag in candidates:
        ok, reason = verify_tree(os.path.join(load_dir, tag), deep=deep)
        if ok:
            return tag, skipped
        skipped.append((tag, reason))
    return None, skipped


def load_latest_valid(load_dir: str, template: Dict[str, Tuple[Any, Any]]
                      ) -> Tuple[Optional[str], Any, Dict[str, Any]]:
    """Load the newest *verified* checkpoint under ``load_dir``, falling back
    through tag history on corruption instead of crashing — a torn newest
    tag costs one save interval, not the run. Returns
    ``(tag, state, meta)``; ``(None, None, {})`` when nothing loadable.

    Candidates are shallow-verified only: ``load_tree`` re-checks every
    leaf's crc32 during the read anyway (raising
    ``CheckpointCorruptionError``, handled below by quarantine + continue),
    so a deep pre-verify would stream each candidate twice."""
    counters = _counters()
    pointed, candidates = _candidate_tags(load_dir)
    skipped_any = False
    for tag in candidates:
        path = os.path.join(load_dir, tag)
        ok, reason = verify_tree(path, deep=False)
        if not ok:
            logger.warning("skipping corrupt checkpoint %s: %s", path, reason)
            counters.incr("corrupt_tags_skipped")
            skipped_any = True
            continue
        try:
            state, meta = load_tree(path, template)
        except CheckpointCorruptionError as e:
            # verified-then-torn race (or unverifiable orbax content):
            # quarantine by renaming so later walks skip it too
            logger.warning("checkpoint %s corrupt on read (%s); quarantining",
                           path, e.reason)
            counters.incr("corrupt_tags_skipped")
            skipped_any = True
            quarantine_tag(path)
            continue
        if tag != pointed or skipped_any:
            counters.incr("fallback_loads")
            logger.warning("fallback load: resumed %s (latest pointer was "
                           "%r)", path, pointed)
        return tag, state, meta
    return None, None, {}


def rotate_checkpoints(save_dir: str, keep_last_n: int) -> List[str]:
    """Garbage-collect old tags, keeping the newest ``keep_last_n``
    *verified* checkpoints. Only ever deletes a verified checkpoint older
    than the newest verified one — corrupt/unverifiable tags are left in
    place (they are forensic evidence, and deleting them can never free the
    rollback target). Returns the deleted tags."""
    if keep_last_n < 1:
        raise ValueError(f"keep_last_n must be >= 1, got {keep_last_n}")
    pointed = _read_latest(save_dir)
    # the sentinel's promoted rollback target is pinned like `latest`:
    # rotation must never free the only tag a divergence can rewind to
    last_good = read_last_good(save_dir)
    # shallow verify: rotation runs after every save, and a deep (full-CRC)
    # pass would re-stream every retained tag's bytes from storage each time
    verified = [t for t in list_tags(save_dir)
                if verify_tree(os.path.join(save_dir, t), deep=False)[0]]
    doomed = [t for t in verified[keep_last_n:]
              if t != pointed and t != last_good]
    for tag in doomed:
        shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
        logger.info("rotated out checkpoint %s", os.path.join(save_dir, tag))
    if doomed:
        _counters().incr("checkpoints_rotated", len(doomed))
    return doomed


def _jsonable(obj):
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()
    return obj
